"""Deterministic, seeded chaos-campaign engine.

Every adversarial scenario this repo grew so far — Apollo-style process
kills, SIGSTOP partitions, per-link drop planes, byzantine strategies,
breaker trips — existed as one-off tests drawing from unseeded RNGs: a
failure that showed up once could not be replayed. This module composes
those primitives into a *campaign*: a matrix of named scenarios where

  * every random draw flows from one ``random.Random(seed)`` (each
    scenario gets a sub-RNG derived as SHA-256(master_seed, name), so
    adding or reordering scenarios never perturbs the others' draws);
  * every scheduled action and draw is appended to an **event log**
    whose canonical-JSON SHA-256 digest is the campaign's identity —
    running the same seed twice yields the identical digest, so a red
    run attaches ``(seed, digest)`` to the bug report and anyone
    replays the exact fault schedule;
  * verdicts, recovery-time stats, and wall-clock live OUTSIDE the
    digest (they are measurements, not schedule).

Two scenario kinds: ``inproc`` (InProcessCluster over the loopback bus —
the tier-1 smoke matrix; seconds per scenario) and ``process`` (real
replica subprocesses via BftTestNetwork with SIGSTOP/SIGKILL and the
per-link fault plane — the full matrix, run by ``bench_chaos.py``).

Recovery invariants asserted by every scenario that crashes something:
exactly-once replay (no double-applied request), no ledger divergence
(all live replicas converge on the same state), and re-convergence
within the scenario's time budget.
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

DEFAULT_SEED = 20260803

# ----------------------------------------------------------------------
# event log + context
# ----------------------------------------------------------------------


class EventLog:
    """Append-only schedule record. Only *scheduled* facts belong here
    (injected faults, seeded draws, logical step order) — never
    wall-clock readings or measured outcomes, which would break the
    replay-digest contract."""

    def __init__(self) -> None:
        self.events: List[dict] = []

    def append(self, scenario: str, action: str, **params) -> None:
        self.events.append({"i": len(self.events), "scenario": scenario,
                            "action": action, **params})

    def digest(self) -> str:
        blob = json.dumps(self.events, sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()


def sub_seed(master: int, name: str) -> int:
    h = hashlib.sha256(f"{master}:{name}".encode()).digest()
    return int.from_bytes(h[:8], "big")


class ScenarioContext:
    """One scenario's handle: its derived RNG, its slice of the event
    log, a scratch dir, and polling helpers."""

    def __init__(self, name: str, master_seed: int, log: EventLog,
                 tmp_root: str) -> None:
        import random
        self.name = name
        self.master_seed = master_seed
        self.rng = random.Random(sub_seed(master_seed, name))
        self._log = log
        self._tmp_root = tmp_root
        self._tmpdir: Optional[str] = None

    # ---- schedule (digested) ----
    def event(self, action: str, **params) -> None:
        self._log.append(self.name, action, **params)

    def randint(self, label: str, a: int, b: int) -> int:
        v = self.rng.randint(a, b)
        self.event("draw", label=label, value=v)
        return v

    def choice(self, label: str, seq):
        v = self.rng.choice(list(seq))
        self.event("draw", label=label, value=v)
        return v

    def cluster_seed(self) -> bytes:
        return f"chaos-{self.name}-{self.master_seed}".encode()

    # ---- scratch ----
    @property
    def tmpdir(self) -> str:
        if self._tmpdir is None:
            self._tmpdir = os.path.join(self._tmp_root,
                                        self.name.replace("/", "_"))
            os.makedirs(self._tmpdir, exist_ok=True)
        return self._tmpdir

    # ---- measurement (NOT digested) ----
    @staticmethod
    def wait_until(pred: Callable[[], bool], timeout: float,
                   poll: float = 0.05, what: str = "condition") -> float:
        """Poll until pred() is truthy; returns elapsed seconds. Raises
        AssertionError on timeout (the scenario's red verdict)."""
        t0 = time.monotonic()
        deadline = t0 + timeout
        while time.monotonic() < deadline:
            if pred():
                return time.monotonic() - t0
            time.sleep(poll)
        raise AssertionError(f"{what} not reached within {timeout:.0f}s")


# ----------------------------------------------------------------------
# scenario specs + campaign runner
# ----------------------------------------------------------------------


@dataclass
class ScenarioSpec:
    name: str
    fn: Callable[[ScenarioContext], dict]
    kind: str                       # "inproc" | "process"
    time_budget_s: float
    tags: tuple = ()


@dataclass
class ScenarioVerdict:
    name: str
    ok: bool
    duration_s: float
    time_budget_s: float
    stats: dict = field(default_factory=dict)
    error: str = ""
    # flight-recorder artifact captured at the moment of a red verdict
    # (rings + kernel profile + slot timings): the timeline that led to
    # the failure rides the bug report, not just the assertion text
    flight_dump: str = ""

    def as_dict(self) -> dict:
        out = {"name": self.name, "ok": self.ok,
               "duration_s": round(self.duration_s, 3),
               "time_budget_s": self.time_budget_s,
               "stats": self.stats, "error": self.error}
        if self.flight_dump:
            out["flight_dump"] = self.flight_dump
        return out


class ChaosCampaign:
    def __init__(self, seed: int = DEFAULT_SEED,
                 specs: Optional[List[ScenarioSpec]] = None,
                 keep_tmp: bool = False) -> None:
        self.seed = seed
        self.specs = specs if specs is not None else smoke_matrix()
        self.keep_tmp = keep_tmp

    def run(self) -> dict:
        log = EventLog()
        verdicts: List[ScenarioVerdict] = []
        tmp_root = tempfile.mkdtemp(prefix="tpubft-chaos-")
        try:
            for spec in self.specs:
                ctx = ScenarioContext(spec.name, self.seed, log, tmp_root)
                ctx.event("begin", kind=spec.kind)
                t0 = time.monotonic()
                try:
                    stats = spec.fn(ctx) or {}
                    dt = time.monotonic() - t0
                    ok = dt <= spec.time_budget_s
                    err = ("" if ok else
                           f"over time budget: {dt:.1f}s > "
                           f"{spec.time_budget_s:.0f}s")
                except Exception as e:  # noqa: BLE001 — red verdict
                    dt = time.monotonic() - t0
                    stats, ok = {}, False
                    err = f"{type(e).__name__}: {e}"
                finally:
                    self._cleanup_globals()
                fdump = ""
                if not ok:
                    # red verdict: capture the flight recorder BEFORE
                    # the next scenario overwrites the rings (the dump
                    # is measurement, not schedule — never digested)
                    from tpubft.utils import flight
                    fdump = flight.dump(
                        reason=f"chaos-red-{spec.name}",
                        extra={"error": err}) or ""
                verdicts.append(ScenarioVerdict(
                    spec.name, ok, dt, spec.time_budget_s, stats, err,
                    flight_dump=fdump))
        finally:
            if not self.keep_tmp:
                shutil.rmtree(tmp_root, ignore_errors=True)
        degraded = [v for v in verdicts if v.stats.get("degraded")]
        artifact = {
            "seed": self.seed,
            "scenarios": [v.as_dict() for v in verdicts],
            "passed": sum(1 for v in verdicts if v.ok),
            "failed": sum(1 for v in verdicts if not v.ok),
            "event_log": log.events,
            "event_log_digest": log.digest(),
            "recovery_s": {v.name: v.stats["recovery_s"]
                           for v in verdicts if "recovery_s" in v.stats},
        }
        if degraded:
            # PR 4's convention: a degraded artifact names WHY, so a
            # reader can tell injected degradation from a perf story
            artifact["degraded"] = True
            artifact["probe_error"] = "; ".join(
                v.stats.get("probe_error", v.name) for v in degraded)
        return artifact

    @staticmethod
    def _cleanup_globals() -> None:
        """Process-wide state a scenario may have mutated must never
        leak into the next scenario (or a later test): disarm
        crashpoints, release parked threads, close the breaker."""
        from tpubft.testing import crashpoints as cp
        cp.disarm_all()
        cp.release_parked()
        try:
            from tpubft.ops.dispatch import device_breaker
            device_breaker().reset()
        except Exception:  # noqa: BLE001 — cleanup is best-effort
            pass
        try:
            # the mesh plane is process-wide too: injected chip faults,
            # eviction state, and the shard-count cap must never leak
            # into the next scenario's crypto traffic
            from tpubft.parallel import sharding
            sharding.clear_chip_faults()
            sharding.mesh_manager().reset()
        except Exception:  # noqa: BLE001 — cleanup is best-effort
            pass
        try:
            # the autotuner's ECDSA crossover override is process-wide
            # (all replicas share the device): a scenario whose
            # controllers moved it must not leak tuned routing into
            # the next scenario's clusters
            from tpubft.crypto import tpu
            tpu.set_ecdsa_crossover(None)
        except Exception:  # noqa: BLE001 — cleanup is best-effort
            pass
        try:
            # the offload pool is process-wide: quarantined helpers,
            # per-helper breaker trips and lease counters from one
            # scenario must not leak into the next one's crypto traffic
            from tpubft.offload.pool import reset_offload_pool
            reset_offload_pool()
        except Exception:  # noqa: BLE001 — cleanup is best-effort
            pass


# ----------------------------------------------------------------------
# smoke matrix (in-process; tier-1 wires this via bench_chaos --smoke)
# ----------------------------------------------------------------------

_FAST_VC = {"view_change_timer_ms": 900}


def _counter_cluster(ctx: ScenarioContext, **kw):
    from tpubft.testing.cluster import InProcessCluster
    kw.setdefault("cfg_overrides", dict(_FAST_VC))
    kw.setdefault("f", 1)
    return InProcessCluster(seed=ctx.cluster_seed(), **kw)


def _persistent_factories(ctx: ScenarioContext):
    from tpubft.apps.counter import PersistentCounterHandler
    from tpubft.consensus.persistent import FilePersistentStorage
    base = ctx.tmpdir

    def storage_factory(r: int):
        return FilePersistentStorage(os.path.join(base, f"r{r}.wal"))

    def handler_factory(r: int):
        return PersistentCounterHandler(os.path.join(base, f"c{r}.state"))

    return storage_factory, handler_factory


def _wait_converged(ctx: ScenarioContext, cluster, expected: int,
                    replicas, timeout: float, what: str) -> float:
    """No-ledger-divergence check for counter clusters: every live
    replica's applied state reaches the same expected value."""
    return ctx.wait_until(
        lambda: all(cluster.handlers[r].value == expected
                    for r in replicas),
        timeout, what=what)


def scenario_wrong_digest_primary(ctx: ScenarioContext) -> dict:
    """Wrong-digest primary (corrupted PrePrepare broadcast): backups
    reject every proposal, view-change away, and the honest quorum
    commits; the byzantine replica still converges as a backup."""
    from tpubft.apps import counter
    amount = ctx.randint("add", 1, 1000)
    ctx.event("byzantine", replica=0, strategy="corrupt-preprepare")
    with _counter_cluster(ctx, byzantine={0: "corrupt-preprepare"}) \
            as cluster:
        cl = cluster.client()
        t0 = time.monotonic()
        reply = cl.send_write(counter.encode_add(amount), timeout_ms=30000)
        recovery = time.monotonic() - t0
        assert counter.decode_reply(reply) == amount
        for r in (1, 2, 3):
            assert cluster.replicas[r].view >= 1, \
                f"replica {r} never left the corrupt primary's view"
        _wait_converged(ctx, cluster, amount, (1, 2, 3), 15,
                        "honest replicas converge")
    return {"recovery_s": round(recovery, 3)}


def scenario_equivocating_primary(ctx: ScenarioContext) -> dict:
    """Truly equivocating primary (both forks validly signed): the
    backups split across two digests, neither can commit, and the
    view change must resolve ONE fork deterministically — the cluster
    commits exactly once, never both forks."""
    from tpubft.apps import counter
    amount = ctx.randint("add", 1, 1000)
    ctx.event("byzantine", replica=0, strategy="equivocate")
    with _counter_cluster(ctx, byzantine={0: "equivocate"}) as cluster:
        cl = cluster.client()
        t0 = time.monotonic()
        reply = cl.send_write(counter.encode_add(amount), timeout_ms=45000)
        recovery = time.monotonic() - t0
        # exactly-once across the fork: the counter reflects ONE apply
        assert counter.decode_reply(reply) == amount
        for r in (1, 2, 3):
            assert cluster.replicas[r].view >= 1, \
                f"replica {r} never left the equivocating primary's view"
        _wait_converged(ctx, cluster, amount, (1, 2, 3), 15,
                        "honest replicas converge on one fork")
    return {"recovery_s": round(recovery, 3)}


def scenario_partition_heal(ctx: ScenarioContext) -> dict:
    """Asymmetric backup partition (2→3 dropped, 3→2 flows): liveness
    must not suffer at all; after heal everyone converges."""
    from tpubft.apps import counter
    frm, to = 2, 3
    ctx.event("partition", frm=frm, to=to, mode="asymmetric")
    healed = threading.Event()

    def drop(s, d, data):
        if not healed.is_set() and s == frm and d == to:
            return None
        return data

    with _counter_cluster(ctx) as cluster:
        cluster.bus.add_hook(drop)
        cl = cluster.client()
        total = 0
        n_writes = ctx.randint("writes", 3, 5)
        for i in range(n_writes):
            delta = ctx.randint(f"add{i}", 1, 50)
            total += delta
            reply = cl.send_write(counter.encode_add(delta),
                                  timeout_ms=20000)
            assert counter.decode_reply(reply) == total, \
                "ordering wedged under a one-way link cut"
        ctx.event("heal", frm=frm, to=to)
        healed.set()
        t0 = time.monotonic()
        _wait_converged(ctx, cluster, total, range(cluster.n), 20,
                        "all replicas converge after heal")
        recovery = time.monotonic() - t0
    return {"recovery_s": round(recovery, 3), "writes": n_writes}


def scenario_breaker_viewchange(ctx: ScenarioContext) -> dict:
    """COMPOUND: the device circuit breaker trips (all replicas of the
    process share the device, PR 5) and the primary dies while the
    plane is degraded — the view change must complete on the scalar
    fallback and ordering must resume, still degraded."""
    from tpubft.apps import counter
    from tpubft.ops.dispatch import device_breaker
    from tpubft.utils.breaker import CLOSED
    b = device_breaker()
    with _counter_cluster(ctx) as cluster:
        cl = cluster.client()
        assert counter.decode_reply(
            cl.send_write(counter.encode_add(3),
                          timeout_ms=30000)) == 3
        ctx.event("breaker_trip", threshold=b.failure_threshold)
        for _ in range(b.failure_threshold):
            b.record_failure(kind="chaos", cause="injected")
        assert b.state != CLOSED, "breaker did not trip"
        ctx.event("kill_primary", replica=0)
        cluster.kill(0)
        t0 = time.monotonic()
        reply = cl.send_write(counter.encode_add(4), timeout_ms=30000)
        recovery = time.monotonic() - t0
        assert counter.decode_reply(reply) == 7
        assert b.state != CLOSED, \
            "breaker silently closed without a probe verdict"
        for r in (1, 2, 3):
            assert cluster.replicas[r].view >= 1
        _wait_converged(ctx, cluster, 7, (1, 2, 3), 15,
                        "survivors converge while degraded")
        trips = b.trips
    return {"recovery_s": round(recovery, 3), "degraded": True,
            "breaker_trips": trips,
            "probe_error": "device breaker tripped by chaos injection "
                           "(%d consecutive failures)" % b.failure_threshold}


def scenario_fused_flush_bad_share(ctx: ScenarioContext) -> dict:
    """Byzantine shares inside fused combine flushes (ISSUE 11): a
    backup corrupts every threshold share it sends while pipelined load
    keeps several slots per flush. Each poisoned combine must fail ONLY
    its own slot (bad-share identification drops the byzantine share
    and the honest 2f+c+1 re-combine lands); sibling slots in the same
    batch commit on schedule, no view change, no divergence."""
    from tpubft.apps import counter
    byz = ctx.choice("byz", (1, 2, 3))
    ctx.event("byzantine", replica=byz, strategy="corrupt-shares")
    n_per_client = 4
    deltas = [[ctx.randint(f"add{c}_{i}", 1, 50)
               for i in range(n_per_client)] for c in (0, 1)]
    with _counter_cluster(ctx, byzantine={byz: "corrupt-shares"},
                          num_clients=2) as cluster:
        # pipelined writers: two clients in parallel so combine flushes
        # carry sibling slots alongside the poisoned shares
        errs = []

        def drive(idx: int) -> None:
            cl = cluster.client(idx)
            try:
                for d in deltas[idx]:
                    cl.send_write(counter.encode_add(d),
                                  timeout_ms=30000)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errs.append(e)

        t0 = time.monotonic()
        threads = [threading.Thread(target=drive, args=(c,))
                   for c in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, f"writes failed under byzantine shares: {errs}"
        total = sum(sum(ds) for ds in deltas)
        recovery = time.monotonic() - t0
        _wait_converged(ctx, cluster, total,
                        [r for r in range(cluster.n) if r != byz], 20,
                        "honest replicas converge despite poisoned "
                        "shares in every flush")
        # sibling-slot schedule: ordering never needed a view change —
        # bad-share identification isolated the byzantine share per
        # slot instead of stalling the pipeline into the VC timer
        for r in range(cluster.n):
            if r != byz:
                assert cluster.replicas[r].view == 0, \
                    f"replica {r} view-changed away under isolated " \
                    f"bad shares"
        # the fused plane was actually exercised on some honest replica
        # (collector roles rotate; at least one honest collector
        # drained flushes)
        batches = sum(cluster.metric(r, "counters", "combine_batches")
                      for r in range(cluster.n) if r != byz)
        assert batches > 0, "fused combine batcher never drained"
    return {"recovery_s": round(recovery, 3),
            "combine_batches": batches}


def scenario_autotune_stability(ctx: ScenarioContext) -> dict:
    """Autotuner control-loop stability (ISSUE 14): a breaker flap plus
    a load step must leave every knob convergent — the degraded rule
    resets tuned knobs to their defaults the moment the breaker opens
    (the controller never fights the degradation plane), tuning resumes
    only after the healthy warmup, and across the whole scenario no
    knob oscillates (bounded direction flips) or leaves its bounds."""
    from tpubft.apps import counter
    from tpubft.ops.dispatch import device_breaker
    from tpubft.utils.breaker import CLOSED
    b = device_breaker()
    # scheduled facts: the operator-style knob nudges the reset must
    # undo, and the load-step deltas
    flush_nudge = ctx.randint("flush_nudge", 600, 1200)
    acc_nudge = ctx.randint("acc_nudge", 2, 6)
    deltas = [[ctx.randint(f"step{c}_{i}", 1, 50) for i in range(4)]
              for c in (0, 1)]
    ctx.event("knob_nudge", combine_flush_us=flush_nudge,
              execution_max_accumulation=acc_nudge)
    ctx.event("breaker_flap", threshold=b.failure_threshold)
    MAX_FLIPS = 4
    with _counter_cluster(ctx, num_clients=2, cfg_overrides={
            "view_change_timer_ms": 2500,
            "autotune_enabled": True,
            "autotune_interval_ms": 40,
            "autotune_cooldown_ms": 80}) as cluster:
        reps = list(cluster.replicas.values())
        assert all(r.tuning is not None for r in reps)
        cl = cluster.client()
        assert counter.decode_reply(
            cl.send_write(counter.encode_add(1), timeout_ms=30000)) == 1
        # operator-style nudges away from the defaults, so the degraded
        # reset has real work to prove
        for r in reps:
            r.tuning.registry.set("combine_flush_us", flush_nudge)
            r.tuning.registry.set("execution_max_accumulation",
                                  acc_nudge)
        # breaker flap: trip OPEN; every controller (all replicas share
        # the process-wide device) must back its knobs off to defaults
        for _ in range(b.failure_threshold):
            b.record_failure(kind="chaos", cause="injected")
        assert b.state != CLOSED, "breaker did not trip"

        def all_reset() -> bool:
            return all(
                r.tuning.registry.get("combine_flush_us")
                == r.cfg.combine_flush_us
                and r.tuning.registry.get("execution_max_accumulation")
                == r.cfg.execution_max_accumulation for r in reps)

        t0 = time.monotonic()
        ctx.wait_until(all_reset, 15,
                       what="degraded reset backs every knob to default")
        reset_s = time.monotonic() - t0
        assert all(r.exec_lane.max_accumulation
                   == r.cfg.execution_max_accumulation for r in reps), \
            "reset reached the registry but not the live actuator"
        b.reset()
        # load step under the restored device: two pipelined writers;
        # the controller may tune, but must not oscillate
        errs: list = []

        def drive(idx: int) -> None:
            c = cluster.client(idx)
            try:
                for d in deltas[idx]:
                    c.send_write(counter.encode_add(d),
                                 timeout_ms=30000)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errs.append(e)

        threads = [threading.Thread(target=drive, args=(c,))
                   for c in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, f"load step failed: {errs}"
        total = 1 + sum(sum(ds) for ds in deltas)
        _wait_converged(ctx, cluster, total, range(cluster.n), 20,
                        "cluster converges through the flap + step")
        # stability: bounded direction flips per knob, values in bounds
        worst_flips = 0
        steps = resets = 0
        for r in reps:
            snap = r.tuning.registry.snapshot()
            for name, k in snap.items():
                assert k["lo"] <= k["value"] <= k["hi"], \
                    f"{name} out of bounds: {k}"
                worst_flips = max(worst_flips, k["direction_flips"])
            assert worst_flips <= MAX_FLIPS, \
                f"knob oscillation on replica {r.id}: {snap}"
            steps += r.tuning.m_steps.value
            resets += r.tuning.m_resets.value
        assert resets >= cluster.n, \
            "not every controller observed the degraded episode"
    return {"recovery_s": round(reset_s, 3),
            "tune_steps": steps, "reset_episodes": resets,
            "max_direction_flips": worst_flips}


def scenario_mesh_chip_fault_flood(ctx: ScenarioContext) -> dict:
    """Multi-chip crypto-plane chaos (ISSUE 16): one mesh chip dies in
    the middle of an ed25519 verification flood. The chip's own breaker
    (`device.chip<N>`) must evict exactly that chip and rebalance the
    flood over the survivors — the plane stays BATCHED (the GLOBAL
    device breaker never trips, so nothing falls back to scalar) and no
    verdict in the flood is dropped or flipped. After the chip heals,
    the cooldown probe re-admits it and the full-width plane verifies
    the same flood byte-identically."""
    import numpy as np
    from tpubft.crypto import cpu
    from tpubft.ops import dispatch
    from tpubft.ops import ed25519 as ops_ed25519
    from tpubft.parallel import sharding
    from tpubft.utils.breaker import CLOSED

    mgr = dispatch.crypto_mesh()
    mgr.reset()
    sharding.clear_chip_faults()
    full = mgr.device_count()
    if full < 2:
        # single-chip host: there is no mesh to degrade — report the
        # run degraded (PR 4's artifact convention) instead of going
        # vacuously green on an unexercised plane
        ctx.event("mesh_unavailable", devices=full)
        return {"recovery_s": 0.0, "degraded": True,
                "probe_error": "single-chip host: mesh plane "
                               "unavailable (%d device)" % full}
    # flood schedule: forged signatures every `stride` items, so every
    # shard of every width carries both valid and forged lanes
    stride = ctx.randint("forge_stride", 3, 9)
    n_batches = ctx.randint("flood_batches", 3, 5)
    n = 64
    signer = cpu.Ed25519Signer.generate(seed=ctx.cluster_seed())
    pk = signer.public_bytes()
    items = []
    for i in range(n):
        m = b"flood-%d" % i
        sig = signer.sign(m)
        if i % stride == 0:
            sig = sig[:4] + bytes([sig[4] ^ 0xFF]) + sig[5:]
        items.append((m, sig, pk))
    want = [i % stride != 0 for i in range(n)]
    # healthy full-width baseline
    assert dispatch.mesh_plan().n == full, "mesh not at full width"
    assert np.asarray(ops_ed25519.verify_batch(items)).tolist() == want
    victim = ctx.choice("victim",
                        [d.id for d in dispatch.mesh_plan().devices])
    ctx.event("chip_fault", device=victim)
    sharding.inject_chip_fault(victim)
    t0 = time.monotonic()
    verdicts = [np.asarray(ops_ed25519.verify_batch(items)).tolist()
                for _ in range(n_batches)]
    recovery = time.monotonic() - t0
    assert all(v == want for v in verdicts), \
        "flood dropped/flipped verdicts across the eviction"
    snap = mgr.snapshot()
    assert snap["evicted"] == [victim], snap
    assert dispatch.mesh_plan().n == full - 1, \
        "plane did not rebalance onto the survivors"
    assert dispatch.device_breaker().state == CLOSED, \
        "global breaker tripped — the plane fell back to scalar"
    # chip heals: the cooldown probe must re-admit it into the plan
    ctx.event("heal", device=victim)
    sharding.clear_chip_faults()
    b = mgr.chip_breaker(victim)
    b.configure(cooldown_s=0.05)
    try:
        ctx.wait_until(lambda: dispatch.mesh_plan().n == full, 10,
                       what="healed chip re-admitted after cooldown")
    finally:
        b.configure(cooldown_s=2.0)
    assert mgr.snapshot()["readmits"] >= 1
    assert np.asarray(ops_ed25519.verify_batch(items)).tolist() == want
    return {"recovery_s": round(recovery, 3),
            "rebalance_ms": snap["last_rebalance_ms"],
            "flood_batches": n_batches,
            "shards_after_eviction": full - 1}


def scenario_offload_byzantine_helper_flood(ctx: ScenarioContext) -> dict:
    """Verified crypto-offload under a lying helper (ISSUE 20): a
    4-replica TPU-backend cluster leases its threshold combines to two
    helpers; mid-way through a 2-client write flood one helper turns
    Byzantine (wrong-but-on-curve points — the strongest lie, it passes
    every shape check). The on-replica soundness check must catch every
    lie BEFORE it can influence a verdict: no write fails, no replica
    view-changes or diverges, the liar is breaker-evicted into
    quarantine (no auto re-admission), and the flood continues on the
    honest helper + local fallback. Replayed with the same seed the
    event-log digest is byte-identical."""
    from tpubft.apps import counter
    from tpubft.offload.helper import HelperServer
    from tpubft.offload.pool import InprocHelper, get_offload_pool
    from tpubft.utils.breaker import get_breaker

    pool = get_offload_pool()
    pool.reset()
    honest = HelperServer("h-honest", strategy="honest")
    liar = HelperServer("h-liar", strategy="honest")   # flips mid-flood
    pool.add_helper(InprocHelper("h-honest", honest))
    pool.add_helper(InprocHelper("h-liar", liar))
    n_per_phase = 2
    deltas = [[ctx.randint(f"add{c}_{i}", 1, 50)
               for i in range(2 * n_per_phase)] for c in (0, 1)]
    ctx.event("helpers", roster=["h-honest", "h-liar"])
    overrides = {"crypto_backend": "tpu", "device_min_verify_batch": 1,
                 # adaptive resolves to multisig-ed25519 at n=4 — pin
                 # the BLS threshold system or there is nothing to lease
                 "threshold_scheme": "threshold-bls",
                 "offload_enabled": True,
                 # generous lease deadline: XLA-CPU pairing checks on a
                 # shared core can take >200ms — a deadline miss would
                 # reclassify the LIAR as merely sick
                 "offload_lease_timeout_ms": 30000,
                 "view_change_timer_ms": 30000}
    with _counter_cluster(ctx, num_clients=2,
                          cfg_overrides=overrides) as cluster:
        errs: list = []

        def drive(idx: int, lo: int, hi: int) -> None:
            cl = cluster.client(idx)
            try:
                for d in deltas[idx][lo:hi]:
                    cl.send_write(counter.encode_add(d),
                                  timeout_ms=60000)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errs.append(e)

        def flood(lo: int, hi: int) -> None:
            threads = [threading.Thread(target=drive, args=(c, lo, hi))
                       for c in (0, 1)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

        t0 = time.monotonic()
        flood(0, n_per_phase)                 # phase 1: both honest
        ctx.event("helper_flip", helper="h-liar",
                  strategy="wrong-on-curve")
        liar.set_strategy("wrong-on-curve")
        flood(n_per_phase, 2 * n_per_phase)   # phase 2: liar active
        recovery = time.monotonic() - t0
        assert not errs, f"writes failed under a lying helper: {errs}"
        total = sum(sum(ds) for ds in deltas)
        _wait_converged(ctx, cluster, total, range(cluster.n), 30,
                        "all replicas converge past the lying helper")
        # no wrong verdict ever surfaced: ordering never needed a view
        # change — every lie was caught by the soundness check and the
        # combine re-ran locally inside the same flush
        for r in range(cluster.n):
            assert cluster.replicas[r].view == 0, \
                f"replica {r} view-changed away under a lying helper"
        snap = pool.snapshot()
        assert snap["quarantined"] == ["h-liar"], snap
        assert snap["counters"]["helper_evicted"] == 1, snap
        assert snap["counters"]["lease_rejected"] >= 1, snap
        # the tier kept working: verified leases continued on the
        # honest helper (phase 1 at minimum, phase 2 once the liar was
        # out of rotation)
        assert snap["counters"]["lease_verified"] >= 1, snap
        assert get_breaker("helper.h-liar").state == "open", \
            "liar's breaker must hold OPEN (no cooldown re-admission)"
        assert get_breaker("helper.h-honest").state == "closed", \
            "honest helper must stay admitted"
        rejected = snap["counters"]["lease_rejected"]
        verified = snap["counters"]["lease_verified"]
    return {"recovery_s": round(recovery, 3),
            "leases_verified": verified,
            "leases_rejected": rejected}


def scenario_crash_restart_replay(ctx: ScenarioContext) -> dict:
    """Plain crash recovery: a backup restarts from its WAL and replays
    to the cluster's state exactly once."""
    from tpubft.apps import counter
    sf, hf = _persistent_factories(ctx)
    victim = ctx.choice("victim", (1, 2, 3))
    with _counter_cluster(ctx, storage_factory=sf,
                          handler_factory=hf) as cluster:
        cl = cluster.client()
        total = 0
        for i in range(2):
            delta = ctx.randint(f"add{i}", 1, 50)
            total += delta
            assert counter.decode_reply(
                cl.send_write(counter.encode_add(delta),
                              timeout_ms=30000)) == total
        ctx.wait_until(lambda: cluster.replicas[victim].last_executed >= 1,
                       10, what="victim executed a prefix")
        ctx.event("crash_restart", replica=victim)
        t0 = time.monotonic()
        rep = cluster.restart(victim)
        assert rep.last_executed >= 1, "WAL recovery lost the prefix"
        delta = ctx.randint("add_post", 1, 50)
        total += delta
        assert counter.decode_reply(
            cl.send_write(counter.encode_add(delta),
                          timeout_ms=30000)) == total
        _wait_converged(ctx, cluster, total, range(cluster.n), 20,
                        "restarted replica replays exactly once")
        recovery = time.monotonic() - t0
    return {"recovery_s": round(recovery, 3)}


def scenario_spec_abort_equivocation(ctx: ScenarioContext) -> dict:
    """Equivocating primary vs speculative execution: replica 0 sends
    two validly-signed forks of every PrePrepare, so honest backups
    accept (and SPECULATE on) conflicting bodies that can never reach a
    commit quorum. The view change must abort every speculative run —
    the overlay is discarded, nothing speculative becomes durable — and
    each slot re-executes from the body committed in the new view:
    exactly one write lands in the ledger, the reply ring holds only
    the committed execution's reply, and the honest replicas converge
    byte-identically."""
    from tpubft.apps import skvbc
    from tpubft.kvbc import KeyValueBlockchain
    from tpubft.storage.memorydb import MemoryDB
    from tpubft.testing.cluster import InProcessCluster
    dbs: dict = {}

    def handler_factory(r):
        db = dbs.setdefault(r, MemoryDB())
        return skvbc.SkvbcHandler(
            KeyValueBlockchain(db, use_device_hashing=False))

    ctx.event("byzantine", replica=0, strategy="equivocate")
    key = b"spec-%d" % ctx.randint("key", 1, 999)
    with InProcessCluster(f=1, seed=ctx.cluster_seed(),
                          cfg_overrides=dict(_FAST_VC),
                          handler_factory=handler_factory,
                          byzantine={0: "equivocate"}) as cluster:
        kv = skvbc.SkvbcClient(cluster.client(0))
        t0 = time.monotonic()
        r = kv.write([(key, b"committed")], timeout_ms=60000)
        recovery = time.monotonic() - t0
        assert r.success, "cluster never committed past the equivocation"
        aborts = sum(cluster.metric(i, "counters", "exec_spec_aborts")
                     for i in (1, 2, 3))
        assert aborts >= 1, (
            "no honest replica aborted a speculative run — the "
            "equivocation either never induced speculation or the "
            "forked overlay was sealed")
        for i in (1, 2, 3):
            assert cluster.replicas[i].view >= 1, \
                f"replica {i} never left the equivocating primary's view"
        # no speculative write reached the ledger: each honest chain is
        # exactly the committed history (1 block for the 1 committed
        # write — an aborted overlay that leaked would add a block or
        # skew the digest), and they are byte-identical
        ctx.wait_until(
            lambda: len({cluster.handlers[i].blockchain.state_digest()
                         for i in (1, 2, 3)}) == 1
            and all(cluster.handlers[i].blockchain.last_block_id == 1
                    for i in (1, 2, 3)),
            20, what="honest ledgers converge on the committed fork")
        # the reply ring holds only the committed execution's reply
        cid = cluster.client(0).cfg.client_id
        for i in (1, 2, 3):
            rep = cluster.replicas[i]
            info = rep.clients._clients[cid]
            assert info.replies, f"replica {i} lost the reply record"
            assert all(rep.clients.was_executed(cid, s)
                       for s in info.replies)
        val = kv.read([key])
        assert val == {key: b"committed"}, val
    return {"recovery_s": round(recovery, 3), "spec_aborts": aborts}


def scenario_optimistic_reply_cert_blackout(ctx: ScenarioContext) -> dict:
    """ISSUE 18: equivocating primary + a full commit-share/certificate
    blackout under `optimistic_replies`. The optimistic plane serves
    clients from f+1 matching INDIVIDUALLY-SIGNED replies — but a
    release still requires a structurally-valid commit certificate, so
    with every commit-path message suppressed no replica executes and a
    strict client must time out rather than accept anything weaker than
    its f+1 signed quorum. After the heal the cluster view-changes away
    from the equivocator, the write commits, the honest replicas
    converge byte-identically, and the optimistic plane re-engages
    (releases fire on the new view's certificates)."""
    from tpubft.apps import skvbc
    from tpubft.bftclient.client import TimeoutError_
    from tpubft.consensus import messages as m
    from tpubft.kvbc import KeyValueBlockchain
    from tpubft.storage.memorydb import MemoryDB
    from tpubft.testing.cluster import InProcessCluster
    dbs: dict = {}

    def handler_factory(r):
        db = dbs.setdefault(r, MemoryDB())
        return skvbc.SkvbcHandler(
            KeyValueBlockchain(db, use_device_hashing=False))

    # every message that can carry commit shares or a formed commit
    # certificate — slow path, fast path, and the PR 17 aggregation
    # overlay (message code = first two LE header bytes)
    cert_codes = {int(c) for c in (
        m.MsgCode.CommitPartial, m.MsgCode.CommitFull,
        m.MsgCode.PartialCommitProof, m.MsgCode.FullCommitProof,
        m.MsgCode.AggregateShare)}
    healed = threading.Event()

    def blackout(s, d, data):
        if not healed.is_set() \
                and int.from_bytes(data[:2], "little") in cert_codes:
            return None
        return data

    cfg = dict(_FAST_VC)
    cfg["optimistic_replies"] = True
    ctx.event("byzantine", replica=0, strategy="equivocate")
    ctx.event("blackout", what="commit-shares+certs")
    key = b"lit-%d" % ctx.randint("key", 1, 999)
    with InProcessCluster(f=1, seed=ctx.cluster_seed(),
                          cfg_overrides=cfg,
                          handler_factory=handler_factory,
                          byzantine={0: "equivocate"}) as cluster:
        cluster.bus.add_hook(blackout)
        kv = skvbc.SkvbcClient(
            cluster.client(0, require_signed_replies=True))
        # dark phase: certs cannot form, so nothing executes and no
        # signed reply exists anywhere — acceptance on anything short of
        # f+1 matching signatures would be the bug this scenario hunts
        try:
            kv.write([(b"dark", b"0")], timeout_ms=2500)
            raise AssertionError(
                "client accepted a write during the cert blackout")
        except TimeoutError_:
            pass
        for i in (1, 2, 3):
            assert cluster.replicas[i].last_executed == 0, (
                f"replica {i} executed without a commit certificate "
                "during the blackout")
            assert cluster.metric(
                i, "counters", "optimistic_releases") == 0, (
                f"replica {i} optimistically released a slot with the "
                "cert plane dark")
        ctx.event("heal")
        healed.set()
        t0 = time.monotonic()
        r = kv.write([(key, b"committed")], timeout_ms=60000)
        recovery = time.monotonic() - t0
        assert r.success, "cluster never recovered from the blackout"
        for i in (1, 2, 3):
            assert cluster.replicas[i].view >= 1, \
                f"replica {i} never left the equivocating primary's view"
        # the optimistic plane re-engages on the new view's certs
        ctx.wait_until(
            lambda: sum(cluster.metric(i, "counters",
                                       "optimistic_releases")
                        for i in (1, 2, 3)) > 0,
            15, what="optimistic releases after heal")
        # honest replicas converge byte-identically (the dark write may
        # or may not have survived in queues — they must only AGREE)
        ctx.wait_until(
            lambda: len({(cluster.handlers[i].blockchain.last_block_id,
                          cluster.handlers[i].blockchain.state_digest())
                         for i in (1, 2, 3)}) == 1,
            20, what="honest ledgers converge after the blackout")
        val = kv.read([key])
        assert val == {key: b"committed"}, val
        releases = sum(cluster.metric(i, "counters",
                                      "optimistic_releases")
                       for i in (1, 2, 3))
    return {"recovery_s": round(recovery, 3), "opt_releases": releases}


def scenario_crashpoint_exec_post_apply(ctx: ScenarioContext) -> dict:
    """Crashpoint drill 1 — exec.post_apply: a replica dies after the
    run's durable apply but before watermark/bookkeeping. Recovery from
    its WAL must replay the committed suffix EXACTLY ONCE (the durable
    at-most-once state dedups) and reach the cluster's value."""
    from tpubft.apps import counter
    from tpubft.comm.loopback import LoopbackBus
    from tpubft.consensus.persistent import FilePersistentStorage
    from tpubft.consensus.replica import Replica
    from tpubft.testing import crashpoints as cp
    from tpubft.utils.config import ReplicaConfig
    sf, hf = _persistent_factories(ctx)
    victim = 2
    hit = threading.Event()

    def crash_here() -> None:
        hit.set()
        cp.park()                 # SIGKILL analog: not one more statement

    with _counter_cluster(ctx, storage_factory=sf,
                          handler_factory=hf) as cluster:
        cl = cluster.client()
        first = ctx.randint("add1", 1, 50)
        assert counter.decode_reply(
            cl.send_write(counter.encode_add(first),
                          timeout_ms=30000)) == first
        ctx.wait_until(lambda: cluster.replicas[victim].last_executed >= 1,
                       10, what="victim applied the baseline")
        ctx.event("arm_crashpoint", point="exec.post_apply",
                  replica=victim)
        cp.arm("exec.post_apply", rid=victim, action=crash_here)
        second = ctx.randint("add2", 1, 50)
        total = first + second
        assert counter.decode_reply(
            cl.send_write(counter.encode_add(second),
                          timeout_ms=20000)) == total
        ctx.wait_until(hit.is_set, 15, what="crashpoint fired")
        ctx.event("crashed", replica=victim, point="exec.post_apply")
        # ---- recovery: restore the victim standalone from its durable
        # state (WAL + counter file + surviving reserved pages) with the
        # lane off, so the committed-suffix replay happens in __init__ —
        # and assert it applied exactly once ----
        t0 = time.monotonic()
        cfg = ReplicaConfig(replica_id=victim, f_val=1,
                            num_of_client_proxies=2,
                            execution_lane=False, **_FAST_VC)
        recovered = Replica(
            cfg, cluster.keys.for_node(victim),
            LoopbackBus().create(victim),
            hf(victim),
            storage=FilePersistentStorage(
                os.path.join(ctx.tmpdir, f"r{victim}.wal")),
            reserved_pages=cluster._pages_dbs[victim])
        recovery = time.monotonic() - t0
        assert recovered.handler.value == total, (
            f"replay divergence: recovered value "
            f"{recovered.handler.value} != {total} (double-applied?)")
        assert recovered.last_executed >= 2, \
            "recovery did not replay the committed suffix"
        # release the parked lane thread BEFORE cluster teardown so the
        # victim's stop() doesn't eat its full join timeout
        cp.disarm_all()
        cp.release_parked()
    return {"recovery_s": round(recovery, 3),
            "recovered_value": total}


def scenario_group_commit_crash(ctx: ScenarioContext) -> dict:
    """Crashpoint drill — dur.group_fsync (ISSUE 15): a replica's
    durability io thread dies between the group's apply and its fsync —
    runs executed, batch maybe-on-disk, watermark never published, no
    reply sent, `last_executed` never advanced. The frozen replica must
    NOT advance its watermark past the unsynced group (a reply can
    never precede its group's fsync), and recovery from the on-disk
    state must replay the committed suffix EXACTLY ONCE (the reserved-
    pages at-most-once state dedups whatever did land) — no double
    apply, no ledger divergence, `last_executed` monotone across the
    crash-restart."""
    from tpubft.apps import counter
    from tpubft.comm.loopback import LoopbackBus
    from tpubft.consensus.persistent import FilePersistentStorage
    from tpubft.consensus.replica import Replica
    from tpubft.testing import crashpoints as cp
    from tpubft.utils.config import ReplicaConfig
    sf, hf = _persistent_factories(ctx)
    victim = ctx.choice("victim", (1, 2, 3))
    hit = threading.Event()

    def crash_here() -> None:
        hit.set()
        cp.park()                 # SIGKILL analog: not one more statement

    with _counter_cluster(ctx, storage_factory=sf,
                          handler_factory=hf) as cluster:
        cl = cluster.client()
        first = ctx.randint("add1", 1, 50)
        assert counter.decode_reply(
            cl.send_write(counter.encode_add(first),
                          timeout_ms=30000)) == first
        ctx.wait_until(lambda: cluster.replicas[victim].last_executed >= 1,
                       10, what="victim's first group landed")
        frozen_at = cluster.replicas[victim].last_executed
        ctx.event("arm_crashpoint", point="dur.group_fsync",
                  replica=victim)
        cp.arm("dur.group_fsync", rid=victim, action=crash_here)
        second = ctx.randint("add2", 1, 50)
        total = first + second
        assert counter.decode_reply(
            cl.send_write(counter.encode_add(second),
                          timeout_ms=20000)) == total
        ctx.wait_until(hit.is_set, 15, what="crashpoint fired")
        ctx.event("crashed", replica=victim, point="dur.group_fsync")
        # the unsynced group must never surface: the frozen replica's
        # watermark (and so last_executed) stays where durability
        # stopped, while the healthy quorum acked the write
        assert cluster.replicas[victim].last_executed == frozen_at, (
            "last_executed advanced past a group that never fsynced — "
            "a reply could have preceded its group's durability")
        # ---- recovery: restore the victim standalone from its durable
        # state (WAL + counter file + surviving reserved pages), lane
        # off so the committed-suffix replay happens in __init__ ----
        t0 = time.monotonic()
        cfg = ReplicaConfig(replica_id=victim, f_val=1,
                            num_of_client_proxies=2,
                            execution_lane=False, **_FAST_VC)
        recovered = Replica(
            cfg, cluster.keys.for_node(victim),
            LoopbackBus().create(victim),
            hf(victim),
            storage=FilePersistentStorage(
                os.path.join(ctx.tmpdir, f"r{victim}.wal")),
            reserved_pages=cluster._pages_dbs[victim])
        recovery = time.monotonic() - t0
        assert recovered.handler.value == total, (
            f"replay divergence after the group-fsync crash: recovered "
            f"value {recovered.handler.value} != {total} "
            f"(double-applied?)")
        assert recovered.last_executed >= 2, \
            "recovery did not replay the committed suffix"
        assert recovered.last_executed >= frozen_at, \
            "last_executed regressed across the crash-restart"
        cp.disarm_all()
        cp.release_parked()
    return {"recovery_s": round(recovery, 3), "recovered_value": total,
            "frozen_at": frozen_at}


def scenario_crashpoint_vc_persist(ctx: ScenarioContext) -> dict:
    """Crashpoint drill 2 — vc.persist: a replica dies after persisting
    its view-change intent but BEFORE broadcasting the ViewChangeMsg.
    With the old primary dead, the view-change quorum NEEDS this
    replica: its restart must resume the change from storage and
    retransmit (the pending_view persistence + _resume_view_change
    path), or the cluster wedges forever."""
    from tpubft.apps import counter
    from tpubft.testing import crashpoints as cp
    sf, hf = _persistent_factories(ctx)
    victim = 2
    hit = threading.Event()

    def crash_here() -> None:
        hit.set()
        cp.park()

    with _counter_cluster(ctx, storage_factory=sf,
                          handler_factory=hf) as cluster:
        cl = cluster.client()
        first = ctx.randint("add1", 1, 50)
        assert counter.decode_reply(
            cl.send_write(counter.encode_add(first),
                          timeout_ms=30000)) == first
        ctx.event("arm_crashpoint", point="vc.persist", replica=victim)
        cp.arm("vc.persist", rid=victim, action=crash_here)
        ctx.event("kill_primary", replica=0)
        cluster.kill(0)
        # complaints (and thus the view change the victim parks inside)
        # only fire while work is in flight — drive a write in the
        # background; it can only complete after the victim recovers,
        # because the view-change quorum (2f+1 = 3) needs all three
        # survivors and the victim crashes before broadcasting its msg
        second = ctx.randint("add2", 1, 50)
        total = first + second
        box: dict = {}

        def drive() -> None:
            try:
                box["reply"] = cl.send_write(counter.encode_add(second),
                                             timeout_ms=60000)
            except Exception as e:  # noqa: BLE001 — asserted below
                box["err"] = e

        th = threading.Thread(target=drive, daemon=True)
        th.start()
        ctx.wait_until(hit.is_set, 30,
                       what="victim crashed at vc.persist")
        ctx.event("crashed", replica=victim, point="vc.persist")
        old = cluster.replicas[victim]       # parked mid-seam
        ctx.event("crash_restart", replica=victim)
        t0 = time.monotonic()
        cluster.crash(victim)                # recover from WAL, rebind bus
        # the resumed view change must complete: 1, 3 and the recovered
        # victim reach the view-change quorum, view >= 1 activates, and
        # ordering resumes with history intact
        th.join(60)
        recovery = time.monotonic() - t0
        assert not th.is_alive() and "err" not in box, \
            f"driver write failed: {box.get('err', 'timed out')}"
        assert counter.decode_reply(box["reply"]) == total, \
            "cluster never recovered from the mid-view-change crash"
        for r in (1, 2, 3):
            assert cluster.replicas[r].view >= 1, \
                f"replica {r} stuck in view 0"
        _wait_converged(ctx, cluster, total, (1, 2, 3), 20,
                        "recovered replica rejoins the new view")
        # let the abandoned pre-crash instance observe its stop flags
        cp.disarm_all()
        cp.release_parked()
        try:
            old.stop()
        except Exception:  # noqa: BLE001 — it crashed; best-effort
            pass
    return {"recovery_s": round(recovery, 3)}


def scenario_thin_replica_failover(ctx: ScenarioContext) -> dict:
    """Read-tier failover: a thin-replica subscriber streams digest-
    verified updates (every block needs f+1 server agreement) while the
    cluster orders PRE-EXECUTED writes; its DATA server's replica is
    killed mid-stream. The client must rotate to a surviving replica
    and catch up — every committed block delivered exactly once, in
    order, with the committed bytes (no gap, no dup, no divergence)."""
    from tpubft.apps import skvbc
    from tpubft.kvbc import KeyValueBlockchain
    from tpubft.storage.memorydb import MemoryDB
    from tpubft.testing.cluster import InProcessCluster
    from tpubft.thinreplica import ThinReplicaClient

    def handler_factory(_r):
        return skvbc.SkvbcHandler(
            KeyValueBlockchain(MemoryDB(), use_device_hashing=False),
            merkle=True)

    n_pre = ctx.randint("writes_before", 3, 5)
    n_post = ctx.randint("writes_after", 3, 5)
    writes = [(b"k%03d" % i, b"v%d" % ctx.randint(f"val{i}", 1, 999))
              for i in range(n_pre + n_post)]
    victim = 1          # the subscriber's data source; NOT the primary —
    # the scenario isolates read-tier failover from ordering failover
    # (the primary-kill paths have their own scenarios)
    ctx.event("kill_data_server", replica=victim)
    overrides = dict(_FAST_VC, thin_replica_enabled=True,
                     pre_execution_enabled=True)
    with InProcessCluster(f=1, seed=ctx.cluster_seed(),
                          handler_factory=handler_factory,
                          cfg_overrides=overrides) as cluster:
        kv = skvbc.SkvbcClient(cluster.client(0))
        got: List[tuple] = []
        # data source = victim first, survivors as hash servers/fallback
        eps = [("127.0.0.1", cluster.replicas[r].thin_replica.port)
               for r in (victim, 2, 3, 0)]
        trc = ThinReplicaClient(eps, f_val=1)
        trc.STALL_TIMEOUT_S = 1.0
        trc.subscribe(lambda b, kvs: got.append((b, dict(kvs))),
                      start_block=1)
        for k, v in writes[:n_pre]:
            assert kv.write([(k, v)], pre_process=True,
                            timeout_ms=30000).success
        ctx.wait_until(lambda: len(got) >= n_pre, 20,
                       what="subscriber streamed the pre-kill blocks")
        cluster.kill(victim)            # SIGKILL analog: server vanishes
        t0 = time.monotonic()
        for k, v in writes[n_pre:]:
            assert kv.write([(k, v)], pre_process=True,
                            timeout_ms=30000).success
        total = len(writes)
        ctx.wait_until(lambda: len(got) >= total, 30,
                       what="subscriber caught up after data-server kill")
        recovery = time.monotonic() - t0
        trc.stop()
        blocks = [b for b, _ in got]
        assert blocks == list(range(1, total + 1)), \
            f"gap/dup/disorder in the resumed stream: {blocks}"
        for i, (k, v) in enumerate(writes):
            assert got[i][1] == {k: v}, \
                f"divergence at block {i + 1}: {got[i][1]}"
        # the pre-execution plane really carried the writes
        agreed = cluster.metric(0, "counters", "preexec_agreed",
                                component="preexec")
    return {"recovery_s": round(recovery, 3), "blocks": total,
            "preexec_agreed": agreed}


# ----------------------------------------------------------------------
# share-aggregation overlay scenarios (ISSUE 17)
# ----------------------------------------------------------------------


class _WanLatency:
    """WAN latency profile over the loopback bus, modeled on
    bench_st.LatencyNet (deliver-time heap + one scheduler thread): the
    bus hook intercepts replica->replica traffic and re-queues it for
    delayed direct delivery to the destination endpoint — the same tail
    the bus pump runs. Client traffic stays instant, so request
    injection is not part of the profile. Per-pair delays come from a
    caller-supplied (sender, dest) -> seconds function, letting a
    scenario shape regions rather than one flat RTT."""

    def __init__(self, bus, n_replicas: int, delay_fn) -> None:
        import heapq
        self._heapq = heapq
        self._bus = bus
        self._n = n_replicas
        self._delay = delay_fn
        self._q: list = []
        self._cv = threading.Condition()
        self._seq = 0
        self._stop = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="wan-latency")
        self._thread.start()
        bus.add_hook(self._hook)

    def _hook(self, s, d, data):
        if s >= self._n or d >= self._n or self._stop:
            return data                 # clients / teardown: instant
        with self._cv:
            self._seq += 1
            self._heapq.heappush(
                self._q, (time.monotonic() + self._delay(s, d),
                          self._seq, s, d, data))
            self._cv.notify()
        return None

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._stop and (
                        not self._q
                        or self._q[0][0] > time.monotonic()):
                    timeout = (max(self._q[0][0] - time.monotonic(), 1e-4)
                               if self._q else None)
                    self._cv.wait(timeout=timeout)
                if self._stop:
                    return
                _, _, s, d, data = self._heapq.heappop(self._q)
            ep = self._bus._endpoints.get(d)
            if ep is not None:
                ep._deliver(s, data)

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._thread.join(timeout=5)


def scenario_agg_tree_node_kill(ctx: ScenarioContext) -> dict:
    """Interior aggregator killed mid-flood: the shares its subtree was
    climbing through stop being forwarded, the children's parent
    timeout re-sends them DIRECT to the collector, and the cluster
    converges WITHOUT a view change — liveness under aggregation is
    never worse than the all-to-all path it replaced. The schedule
    (victim draw included) replays digest-identically."""
    from tpubft.apps import counter
    from tpubft.consensus.aggregation import overlay_for
    overrides = dict(share_aggregation="tree", agg_fanout=2,
                     agg_flush_ms=5, agg_parent_timeout_ms=150,
                     fast_path_timeout_ms=50,
                     # long enough that the fallback, not a view
                     # change, is what restores progress
                     view_change_timer_ms=6000)
    with _counter_cluster(ctx, cfg_overrides=overrides) as cluster:
        n = cluster.n
        # the view-0 overlay is deterministic: pick the interior
        # non-root aggregator every replica agrees on
        ov = overlay_for("tree", n, 2, 0, 0, 1, 16)
        victim = next(r for r in ov.order[1:] if ov.is_interior(r))
        ctx.event("kill", replica=victim, role="interior-aggregator")
        cl = cluster.client()
        total = 0
        for i in range(2):              # flood before the kill
            delta = ctx.randint(f"pre{i}", 1, 50)
            total += delta
            reply = cl.send_write(counter.encode_add(delta),
                                  timeout_ms=20000)
            assert counter.decode_reply(reply) == total
        cluster.kill(victim)
        t0 = time.monotonic()
        for i in range(3):              # flood through the dead branch
            delta = ctx.randint(f"post{i}", 1, 50)
            total += delta
            reply = cl.send_write(counter.encode_add(delta),
                                  timeout_ms=30000)
            assert counter.decode_reply(reply) == total
        recovery = time.monotonic() - t0
        live = [r for r in range(n) if r != victim]
        _wait_converged(ctx, cluster, total, live, 15,
                        "fallback path converges")
        for r in live:
            assert cluster.replicas[r].view == 0, \
                f"replica {r} view-changed; fallback should have held"
        fallbacks = sum(cluster.metric(r, "counters", "agg_fallbacks")
                        for r in live)
        assert fallbacks > 0, "no parent-timeout fallback ever fired"
    return {"recovery_s": round(recovery, 3), "victim": victim,
            "fallbacks": fallbacks}


def scenario_agg_wan_latency(ctx: ScenarioContext) -> dict:
    """Large-n two-region WAN profile (intra 2ms, inter 12ms one-way)
    under gossip aggregation with one dead replica forcing the slow
    path: the overlay keeps every node's share fan-in under the
    collector's all-to-all O(n), and commits flow without a view change
    at WAN timescales."""
    from tpubft.apps import counter
    intra_ms, inter_ms = 2, 12
    # parent timeout must clear the WHOLE slow-path slot latency (WAN
    # hops + flush windows + CPU-host BLS combines), not just one hop:
    # the fallback trigger is "slot not prepared/committed yet", so an
    # undersized value collapses the overlay back to all-to-all with
    # duplicate shares on top. 2s is comfortably past a CPU-host slot
    # and still 4x under the view-change timer.
    overrides = dict(share_aggregation="gossip", agg_fanout=3,
                     agg_flush_ms=10, agg_parent_timeout_ms=2000,
                     agg_rotate_seqs=4, fast_path_timeout_ms=80,
                     view_change_timer_ms=8000)
    ctx.event("latency_profile", intra_ms=intra_ms, inter_ms=inter_ms,
              regions=2)
    with _counter_cluster(ctx, f=3, cfg_overrides=overrides) as cluster:
        n = cluster.n                   # 10
        region = {r: r % 2 for r in range(n)}

        def delay(s, d):
            return (intra_ms if region[s] == region[d] else inter_ms) / 1e3

        wan = _WanLatency(cluster.bus, n, delay)
        try:
            victim = n - 1
            ctx.event("kill", replica=victim, role="fast-path-breaker")
            cluster.kill(victim)
            cl = cluster.client()
            total = 0
            for i in range(5):
                delta = ctx.randint(f"add{i}", 1, 50)
                total += delta
                reply = cl.send_write(counter.encode_add(delta),
                                      timeout_ms=45000)
                assert counter.decode_reply(reply) == total
            live = [r for r in range(n) if r != victim]
            _wait_converged(ctx, cluster, total, live, 30,
                            "WAN cluster converges")
            for r in live:
                assert cluster.replicas[r].view == 0
            rcvd = [cluster.metric(r, "counters", "share_msgs_received")
                    for r in live]
            absorbed = cluster.metric(0, "counters",
                                      "agg_partials_absorbed")
            assert absorbed > 0, "root never absorbed a partial"
            # the whole point: no node carries all-to-all fan-in.
            # 5 slots x 2 kinds x (n-2) senders is the collector's
            # un-aggregated load; the busiest node must sit strictly
            # under it even INCLUDING the first-slot fallback burst
            # (the dead replica seats as an interior node in some
            # rotation, so its orphans route direct from slot 2 on)
            assert max(rcvd) < 5 * 2 * (n - 2), \
                f"fan-in {max(rcvd)} not under all-to-all {5*2*(n-2)}"
        finally:
            wan.stop()
    return {"recovery_s": 0.0, "max_fan_in": max(rcvd),
            "collector_fan_in": rcvd[0], "absorbed": absorbed}


def smoke_matrix() -> List[ScenarioSpec]:
    return [
        ScenarioSpec("wrong-digest-primary", scenario_wrong_digest_primary,
                     "inproc", 60, tags=("byzantine", "view-change")),
        ScenarioSpec("equivocating-primary", scenario_equivocating_primary,
                     "inproc", 90, tags=("byzantine", "view-change")),
        ScenarioSpec("partition-heal", scenario_partition_heal,
                     "inproc", 60, tags=("partition",)),
        ScenarioSpec("breaker-viewchange", scenario_breaker_viewchange,
                     "inproc", 60, tags=("compound", "degraded",
                                         "view-change")),
        ScenarioSpec("spec-abort-equivocation",
                     scenario_spec_abort_equivocation,
                     "inproc", 90, tags=("byzantine", "view-change",
                                         "speculation")),
        ScenarioSpec("optimistic-reply-cert-blackout",
                     scenario_optimistic_reply_cert_blackout,
                     "inproc", 120, tags=("byzantine", "view-change",
                                          "optimistic-replies")),
        ScenarioSpec("fused-flush-bad-share", scenario_fused_flush_bad_share,
                     "inproc", 90, tags=("byzantine", "combine")),
        ScenarioSpec("autotune-stability", scenario_autotune_stability,
                     "inproc", 90, tags=("autotune", "degraded",
                                         "compound")),
        ScenarioSpec("mesh-chip-fault-flood", scenario_mesh_chip_fault_flood,
                     # budget sized for a COLD first run: the full- and
                     # survivor-width kernels compile inside the
                     # scenario on a 1-core host (~90s); warm it is <5s
                     "inproc", 240, tags=("mesh", "crypto", "recovery")),
        ScenarioSpec("offload-byzantine-helper-flood",
                     scenario_offload_byzantine_helper_flood,
                     # budget sized for a COLD first run: the TPU-backend
                     # combine/pairing kernels compile inside the
                     # scenario on a 1-core XLA-CPU host; warm it is
                     # a fraction of this
                     "inproc", 300, tags=("byzantine", "offload",
                                          "crypto", "recovery")),
        ScenarioSpec("crash-restart-replay", scenario_crash_restart_replay,
                     "inproc", 60, tags=("recovery",)),
        ScenarioSpec("thin-replica-failover",
                     scenario_thin_replica_failover,
                     "inproc", 90, tags=("crash", "read-tier",
                                         "pre-execution")),
        ScenarioSpec("crashpoint-exec-post-apply",
                     scenario_crashpoint_exec_post_apply,
                     "inproc", 60, tags=("crashpoint", "recovery")),
        ScenarioSpec("crashpoint-vc-persist",
                     scenario_crashpoint_vc_persist,
                     "inproc", 90, tags=("crashpoint", "view-change",
                                         "recovery")),
        ScenarioSpec("group-commit-crash", scenario_group_commit_crash,
                     "inproc", 60, tags=("crashpoint", "durability",
                                         "recovery")),
        ScenarioSpec("agg-tree-node-kill", scenario_agg_tree_node_kill,
                     "inproc", 90, tags=("aggregation", "crash",
                                         "fallback")),
        ScenarioSpec("agg-wan-latency", scenario_agg_wan_latency,
                     "inproc", 120, tags=("aggregation", "wan",
                                          "large-n")),
    ]


# ----------------------------------------------------------------------
# full matrix (real replica subprocesses; bench_chaos.py without --smoke)
# ----------------------------------------------------------------------


def _net(ctx: ScenarioContext, **kw):
    from tpubft.testing.network import BftTestNetwork
    base_port = ctx.randint("base_port", 210, 479) * 100
    kw.setdefault("view_change_timeout_ms", 2500)
    return BftTestNetwork(f=1, base_port=base_port,
                          db_dir=ctx.tmpdir,
                          seed=ctx.cluster_seed().decode(), **kw)


def _commit(kv, key: bytes, value: bytes, timeout_ms: int = 10000,
            tries: int = 6) -> bool:
    for _ in range(tries):
        try:
            if kv.write([(key, value)], timeout_ms=timeout_ms).success:
                return True
        except Exception:  # noqa: BLE001 — retried
            pass
    return False


def _views(net, replicas) -> dict:
    return {r: net.current_view(r) or 0 for r in replicas}


def proc_crash_primary_mid_viewchange(ctx: ScenarioContext) -> dict:
    """The old primary is isolated, then HARD-CRASHES halfway through
    the view-change window and restarts: the cluster must still
    complete the change, and the restarted ex-primary must rejoin the
    new view with its ledger intact."""
    with _net(ctx) as net:
        kv = net.skvbc_client(0)
        assert _commit(kv, b"pre", b"1"), "baseline write failed"
        ctx.event("isolate", replica=0)
        net.isolate_replica(0)
        # crash the old primary mid-window (half the VC timeout in)
        time.sleep(net.view_change_timeout_ms / 2e3)
        ctx.event("kill", replica=0)
        net.kill_replica(0)
        t0 = time.monotonic()
        assert _commit(kv, b"during", b"2", timeout_ms=15000, tries=8), \
            "cluster never recovered from the crashed primary"
        views = _views(net, (1, 2, 3))
        assert all(v >= 1 for v in views.values()), views
        ctx.event("restart", replica=0)
        net.start_replica(0)
        net.wait_for_replicas_up(replicas=[0])
        net.wait_for(lambda: (net.current_view(0) or 0) >= 1, timeout=60)
        assert _commit(kv, b"post", b"3", timeout_ms=15000)
        recovery = time.monotonic() - t0
        assert kv.read([b"pre", b"during", b"post"]) == {
            b"pre": b"1", b"during": b"2", b"post": b"3"}, \
            "ledger divergence after the mid-view-change crash"
    return {"recovery_s": round(recovery, 3)}


def proc_asymmetric_partition_heal(ctx: ScenarioContext) -> dict:
    """A deaf backup (sends, hears nothing) must not cost liveness;
    after heal it re-converges from retransmissions/state transfer."""
    victim = ctx.choice("victim", (2, 3))
    with _net(ctx) as net:
        kv = net.skvbc_client(0)
        assert _commit(kv, b"a", b"1")
        ctx.event("deafen", replica=victim)
        net.deafen_replica(victim)
        for i in range(3):
            assert _commit(kv, b"k%d" % i, b"v", timeout_ms=15000), \
                "liveness lost to a single deaf backup"
        ctx.event("heal", replica=victim)
        net.heal(victim)
        t0 = time.monotonic()
        target = net.last_executed(0) or 0
        net.wait_for(lambda: (net.last_executed(victim) or 0) >= target,
                     timeout=60)
        recovery = time.monotonic() - t0
        assert _commit(kv, b"b", b"2")
    return {"recovery_s": round(recovery, 3)}


def proc_equivocating_primary(ctx: ScenarioContext) -> dict:
    """Process-grade equivocation: replica 0 runs with the equivocate
    strategy (validly signed forks). The honest quorum must view-change
    away and commit."""
    net = _net(ctx)
    ctx.event("byzantine", replica=0, strategy="equivocate")
    try:
        for r in range(net.n):
            net.start_replica(r, extra_args=(
                ["--strategy", "equivocate"] if r == 0 else None))
        net.wait_for_replicas_up()
        kv = net.skvbc_client(0)
        t0 = time.monotonic()
        assert _commit(kv, b"x", b"1", timeout_ms=15000, tries=10), \
            "honest quorum never committed under an equivocating primary"
        recovery = time.monotonic() - t0
        views = _views(net, (1, 2, 3))
        assert all(v >= 1 for v in views.values()), views
        assert _commit(kv, b"y", b"2", timeout_ms=15000)
        assert kv.read([b"x", b"y"]) == {b"x": b"1", b"y": b"2"}
    finally:
        net.stop_all()
    return {"recovery_s": round(recovery, 3)}


def proc_f_crash_restart_st_catchup(ctx: ScenarioContext) -> dict:
    """f replicas crash simultaneously and restart far behind: they must
    catch back up (state transfer once the window is gone) and the
    cluster re-converges."""
    victim = ctx.choice("victim", (1, 2, 3))
    with _net(ctx, checkpoint_window=10, work_window=20) as net:
        kv = net.skvbc_client(0)
        assert _commit(kv, b"seed", b"1")
        ctx.event("kill", replica=victim)
        net.kill_replica(victim)
        n_writes = 30               # > work_window: forces ST catch-up
        ctx.event("writes_behind", count=n_writes)
        for i in range(n_writes):
            assert _commit(kv, b"w%03d" % i, b"v", timeout_ms=15000), i
        ctx.event("restart", replica=victim)
        net.start_replica(victim)
        net.wait_for_replicas_up(replicas=[victim])
        t0 = time.monotonic()
        target = net.last_executed(0) or 0
        # a lagging replica's ST anchor comes from live CheckpointMsgs
        # beyond its window (reference: ST triggers off checkpoint
        # certificates riding ordering) — an idle cluster gives it no
        # signal to transfer from, so keep traffic flowing while it
        # catches up
        deadline = time.monotonic() + 240
        i = 0
        while time.monotonic() < deadline \
                and (net.last_executed(victim) or 0) < target:
            _commit(kv, b"t%03d" % i, b"v", timeout_ms=10000, tries=2)
            i += 1
            time.sleep(0.2)
        assert (net.last_executed(victim) or 0) >= target, \
            "victim never caught up via state transfer"
        recovery = time.monotonic() - t0
        assert _commit(kv, b"tail", b"2")
    return {"recovery_s": round(recovery, 3), "writes_behind": n_writes}


def proc_crashpoint_exec_drill(ctx: ScenarioContext) -> dict:
    """Process crashpoint drill: a replica restarted with
    TPUBFT_CRASHPOINT=exec.post_apply dies AT the seam (exit code 173,
    proving it was the seam and not a stray fault), restarts clean, and
    must replay exactly once — reads stay consistent clusterwide."""
    from tpubft.testing.crashpoints import CRASH_EXIT_CODE, ENV_VAR
    victim = 2
    with _net(ctx) as net:
        kv = net.skvbc_client(0)
        assert _commit(kv, b"pre", b"1")
        ctx.event("restart_with_crashpoint", replica=victim,
                  point="exec.post_apply")
        net.restart_replica(victim,
                            extra_env={ENV_VAR: "exec.post_apply"})
        net.wait_for_replicas_up(replicas=[victim])
        # the victim dies on its first applied run (recovery replay of
        # the committed suffix counts — it IS a durable apply)
        assert _commit(kv, b"boom", b"2", timeout_ms=15000)
        code = net.wait_exit(victim, timeout=60)
        assert code == CRASH_EXIT_CODE, \
            f"victim exited {code}, not at the crashpoint seam"
        ctx.event("crashed", replica=victim, point="exec.post_apply")
        ctx.event("restart", replica=victim)
        t0 = time.monotonic()
        net.start_replica(victim)           # clean env: no crashpoint
        net.wait_for_replicas_up(replicas=[victim])
        assert _commit(kv, b"post", b"3", timeout_ms=15000)
        target = net.last_executed(0) or 0
        net.wait_for(lambda: (net.last_executed(victim) or 0) >= target,
                     timeout=60)
        recovery = time.monotonic() - t0
        assert kv.read([b"pre", b"boom", b"post"]) == {
            b"pre": b"1", b"boom": b"2", b"post": b"3"}, \
            "ledger divergence after the exec-seam crash"
    return {"recovery_s": round(recovery, 3), "exit_code": code}


def proc_crashpoint_dur_drill(ctx: ScenarioContext) -> dict:
    """Process crashpoint drill (ISSUE 15): a replica restarted with
    TPUBFT_CRASHPOINT=dur.group_fsync dies AT the durability seam —
    group applied, fsync never issued, watermark never published (exit
    code 173 proves it was the seam). A clean restart must replay the
    committed suffix exactly once: reads stay consistent clusterwide
    and the recovered replica catches back up to the quorum's
    watermark, digest-identical."""
    from tpubft.testing.crashpoints import CRASH_EXIT_CODE, ENV_VAR
    victim = ctx.choice("victim", (1, 2, 3))
    with _net(ctx) as net:
        kv = net.skvbc_client(0)
        assert _commit(kv, b"pre", b"1")
        ctx.event("restart_with_crashpoint", replica=victim,
                  point="dur.group_fsync")
        net.restart_replica(victim,
                            extra_env={ENV_VAR: "dur.group_fsync"})
        net.wait_for_replicas_up(replicas=[victim])
        # the victim dies on its first group commit after the restart
        assert _commit(kv, b"boom", b"2", timeout_ms=15000)
        code = net.wait_exit(victim, timeout=60)
        assert code == CRASH_EXIT_CODE, \
            f"victim exited {code}, not at the dur.group_fsync seam"
        ctx.event("crashed", replica=victim, point="dur.group_fsync")
        ctx.event("restart", replica=victim)
        t0 = time.monotonic()
        net.start_replica(victim)           # clean env: no crashpoint
        net.wait_for_replicas_up(replicas=[victim])
        assert _commit(kv, b"post", b"3", timeout_ms=15000)
        target = net.last_executed(0) or 0
        net.wait_for(lambda: (net.last_executed(victim) or 0) >= target,
                     timeout=60)
        recovery = time.monotonic() - t0
        assert kv.read([b"pre", b"boom", b"post"]) == {
            b"pre": b"1", b"boom": b"2", b"post": b"3"}, \
            "ledger divergence after the group-fsync crash"
    return {"recovery_s": round(recovery, 3), "exit_code": code}


def proc_crashpoint_vc_drill(ctx: ScenarioContext) -> dict:
    """Process crashpoint drill: a backup dies at vc.persist while the
    old primary is isolated — after a clean restart it must RESUME the
    persisted view change and retransmit its ViewChangeMsg so the
    quorum completes."""
    from tpubft.testing.crashpoints import CRASH_EXIT_CODE, ENV_VAR
    victim = ctx.choice("victim", (2, 3))
    with _net(ctx) as net:
        kv = net.skvbc_client(0)
        assert _commit(kv, b"pre", b"1")
        ctx.event("restart_with_crashpoint", replica=victim,
                  point="vc.persist")
        net.restart_replica(victim, extra_env={ENV_VAR: "vc.persist"})
        net.wait_for_replicas_up(replicas=[victim])
        ctx.event("isolate", replica=0)
        net.isolate_replica(0)
        # complaints (and the view change the victim dies inside) only
        # fire while work is in flight: drive a write from a background
        # thread. It cannot complete before the victim recovers — the
        # view-change quorum (2f+1 = 3) needs all three survivors and
        # the victim crashes before broadcasting its ViewChangeMsg.
        box: dict = {}

        def drive() -> None:
            box["ok"] = _commit(kv, b"during", b"2", timeout_ms=15000,
                                tries=20)

        th = threading.Thread(target=drive, daemon=True)
        th.start()
        code = net.wait_exit(victim, timeout=90)
        assert code == CRASH_EXIT_CODE, \
            f"victim exited {code}, not at the vc.persist seam"
        ctx.event("crashed", replica=victim, point="vc.persist")
        ctx.event("restart", replica=victim)
        t0 = time.monotonic()
        net.start_replica(victim)           # clean env
        net.wait_for_replicas_up(replicas=[victim])
        th.join(120)
        recovery = time.monotonic() - t0
        assert not th.is_alive() and box.get("ok"), \
            "view change never completed after the vc.persist crash"
        views = _views(net, [r for r in (1, 2, 3)])
        assert all(v >= 1 for v in views.values()), views
        net.heal(0)
        assert _commit(kv, b"post", b"3", timeout_ms=15000)
        assert kv.read([b"pre", b"during", b"post"]) == {
            b"pre": b"1", b"during": b"2", b"post": b"3"}
    return {"recovery_s": round(recovery, 3), "exit_code": code}


def proc_breaker_trip_mid_viewchange(ctx: ScenarioContext) -> dict:
    """COMPOUND at process scale: every replica's device breaker is
    tripped through the fault-control plane, then the primary is
    isolated — the view change and subsequent ordering run entirely
    degraded."""
    from tpubft.testing.faults import fault_command
    with _net(ctx) as net:
        kv = net.skvbc_client(0)
        assert _commit(kv, b"pre", b"1")
        ctx.event("breaker_trip", replicas=list(range(1, net.n)))
        for r in range(1, net.n):
            res = fault_command(net.fault_base + r, cmd="breaker",
                                action="trip")
            assert res and "breaker" in res, f"breaker trip failed on {r}"
        ctx.event("isolate", replica=0)
        net.isolate_replica(0)
        t0 = time.monotonic()
        assert _commit(kv, b"during", b"2", timeout_ms=15000, tries=10), \
            "degraded cluster never completed the view change"
        recovery = time.monotonic() - t0
        views = _views(net, (1, 2, 3))
        assert all(v >= 1 for v in views.values()), views
        snap = fault_command(net.fault_base + 1, cmd="breaker",
                             action="get")
        trips = (snap or {}).get("breaker", {}).get("trips", 0)
        assert trips >= 1, "breaker snapshot lost the injected trip"
        net.heal(0)
        assert _commit(kv, b"post", b"3", timeout_ms=15000)
    return {"recovery_s": round(recovery, 3), "degraded": True,
            "breaker_trips": trips,
            "probe_error": "device breaker tripped via fault-control "
                           "plane during view change"}


def full_matrix() -> List[ScenarioSpec]:
    return smoke_matrix() + [
        ScenarioSpec("proc-crash-primary-mid-viewchange",
                     proc_crash_primary_mid_viewchange, "process", 300,
                     tags=("crash", "view-change")),
        ScenarioSpec("proc-asymmetric-partition-heal",
                     proc_asymmetric_partition_heal, "process", 300,
                     tags=("partition",)),
        ScenarioSpec("proc-equivocating-primary",
                     proc_equivocating_primary, "process", 300,
                     tags=("byzantine", "view-change")),
        ScenarioSpec("proc-f-crash-restart-st-catchup",
                     proc_f_crash_restart_st_catchup, "process", 420,
                     tags=("crash", "state-transfer")),
        ScenarioSpec("proc-crashpoint-exec-drill",
                     proc_crashpoint_exec_drill, "process", 300,
                     tags=("crashpoint", "recovery")),
        ScenarioSpec("proc-crashpoint-vc-drill",
                     proc_crashpoint_vc_drill, "process", 300,
                     tags=("crashpoint", "view-change", "recovery")),
        ScenarioSpec("proc-crashpoint-dur-drill",
                     proc_crashpoint_dur_drill, "process", 300,
                     tags=("crashpoint", "durability", "recovery")),
        ScenarioSpec("proc-breaker-trip-mid-viewchange",
                     proc_breaker_trip_mid_viewchange, "process", 300,
                     tags=("compound", "degraded", "view-change")),
    ]


def matrix_by_name() -> Dict[str, ScenarioSpec]:
    return {s.name: s for s in full_matrix()}
