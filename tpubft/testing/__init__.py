"""In-process test harnesses (reference fake_comm.h + Apollo's BftTestNetwork).

InProcessCluster is exported lazily (PEP 562): submodules like
`tpubft.testing.slowdown` are imported by the consensus engine at module
scope, and an eager cluster import here would close a circular import
back into tpubft.consensus.replica.
"""

__all__ = ["InProcessCluster"]


def __getattr__(name):
    if name == "InProcessCluster":
        from tpubft.testing.cluster import InProcessCluster
        return InProcessCluster
    raise AttributeError(name)
