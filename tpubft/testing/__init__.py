"""In-process test harnesses (reference fake_comm.h + Apollo's BftTestNetwork)."""
from tpubft.testing.cluster import InProcessCluster

__all__ = ["InProcessCluster"]
