"""BftTestNetwork — the system-test harness running REAL replica
processes.

Rebuild of the reference's Apollo core (/root/reference/tests/apollo/
util/bft.py:233 BftTestNetwork): each replica is an OS subprocess of the
actual SKVBC tester replica (subprocess.Popen, bft.py:818), driven from
the test through real UDP clients, observed through each replica's UDP
metrics server (bft_metrics.py), and fault-injected by killing/restarting
processes and by pausing them with SIGSTOP/SIGCONT (the portable stand-in
for Apollo's iptables partitioning — a stopped process neither sends nor
receives, which is exactly a partition from the cluster's viewpoint).
"""
from __future__ import annotations

import json
import os
import random
import signal
import socket
import subprocess
import sys
import time
import weakref
from typing import Dict, List, Optional

from tpubft.apps.simple_test import endpoint_table
from tpubft.apps.skvbc import SkvbcClient
from tpubft.bftclient import BftClient, ClientConfig
from tpubft.comm import CommConfig, PlainUdpCommunication
from tpubft.consensus.keys import ClusterKeys
from tpubft.utils.config import ReplicaConfig

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


class MetricsClient:
    """Polls a replica's UDP metrics server (reference bft_metrics.py)."""

    def __init__(self, port: int, host: str = "127.0.0.1") -> None:
        self.addr = (host, port)

    def snapshot(self, timeout: float = 1.0) -> Optional[dict]:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.settimeout(timeout)
        try:
            s.sendto(b"metrics", self.addr)
            data, _ = s.recvfrom(1 << 20)
            return json.loads(data.decode())
        except (OSError, json.JSONDecodeError):
            return None
        finally:
            s.close()

    def get(self, component: str, kind: str, name: str,
            timeout: float = 1.0):
        snap = self.snapshot(timeout)
        if snap is None:
            return None
        try:
            return snap["components"][component][kind][name]
        except KeyError:
            return None


class BftTestNetwork:
    def __init__(self, f: int = 1, c: int = 0, num_clients: int = 4,
                 num_ro: int = 0,
                 base_port: Optional[int] = None,
                 db_dir: Optional[str] = None,
                 seed: str = "apollo-net",
                 view_change_timeout_ms: int = 3000,
                 crypto_backend: str = "cpu",
                 pre_execution: bool = False,
                 checkpoint_window: int = 150,
                 work_window: int = 300,
                 transport: str = "udp",
                 threshold_scheme: str = "multisig-ed25519",
                 client_sig_scheme: str = "ed25519",
                 device_min_verify_batch: Optional[int] = None,
                 merkle: bool = False,
                 cfg_overrides: Optional[dict] = None) -> None:
        self.f, self.c = f, c
        self.n = 3 * f + 2 * c + 1
        self.num_ro = num_ro
        self.num_clients = num_clients
        self.seed = seed
        self.base_port = base_port or random.randint(20000, 50000)
        self.metrics_base = self.base_port + 1000
        self.fault_base = self.base_port + 2000
        self.trs_base = self.base_port + 3000   # thin-replica servers
        self.diag_base = self.base_port + 4000  # diagnostics admin servers
        self.db_dir = db_dir
        self.view_change_timeout_ms = view_change_timeout_ms
        self.crypto_backend = crypto_backend
        self.pre_execution = pre_execution
        self.checkpoint_window = checkpoint_window
        self.work_window = work_window
        self.transport = transport
        self.threshold_scheme = threshold_scheme
        self.client_sig_scheme = client_sig_scheme
        self.device_min_verify_batch = device_min_verify_batch
        self.merkle = merkle     # BLOCK_MERKLE skvbc state (provable
        # reads for the thin-replica tier)
        # arbitrary ReplicaConfig fields, forwarded to every replica
        # process as --config-override FIELD=VALUE
        self.cfg_overrides = dict(cfg_overrides or {})
        self.certs_dir = None
        if transport in ("tls", "tls-mux"):
            # pinned-cert material for every principal (replicas +
            # clients + operator), like keygen --tls-certs
            assert db_dir, "TLS transport needs db_dir for cert material"
            from tpubft.comm.tls import generate_tls_material
            from tpubft.consensus.replicas_info import ReplicasInfo
            cfg = ReplicaConfig(f_val=f, c_val=c,
                                num_of_client_proxies=num_clients)
            op_id = ReplicasInfo.from_config(cfg).operator_id
            ids = (list(range(self.n))
                   + list(range(self.n, self.n + num_clients)) + [op_id])
            self.certs_dir = os.path.join(db_dir, "tls")
            os.makedirs(self.certs_dir, exist_ok=True)
            generate_tls_material(self.certs_dir, ids, seed=None)
        self.procs: Dict[int, subprocess.Popen] = {}
        self.paused: set = set()
        self._clients: Dict[int, BftClient] = {}
        # teardown guarantee: even when a red assertion (or a crashed
        # test runner) skips __exit__/stop_all, no SIGSTOP'd or live
        # replica subprocess may outlive this harness — a stopped orphan
        # holds its ports and poisons every later test on the host. The
        # finalizer fires at GC or interpreter exit and must not hold a
        # reference to self (it would never fire), so it closes over the
        # mutable dicts only.
        self._finalizer = weakref.finalize(
            self, BftTestNetwork._reap_procs, self.procs, self.paused)

    @staticmethod
    def _reap_procs(procs: Dict[int, subprocess.Popen],
                    paused: set) -> None:
        """Last-resort reaper: SIGCONT anything stopped, SIGKILL, reap.
        (SIGKILL does kill a stopped process, but the SIGCONT keeps the
        behavior uniform with stop_all's graceful path and unsticks any
        descendant blocked on the stopped parent.)"""
        for r, p in list(procs.items()):
            try:
                if p.poll() is None:
                    if r in paused:
                        p.send_signal(signal.SIGCONT)
                    p.kill()
            except OSError:
                pass
        for p in list(procs.values()):
            try:
                p.wait(timeout=5)
            except (subprocess.TimeoutExpired, OSError):
                pass
        paused.clear()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start_all(self, timeout: float = 120.0) -> "BftTestNetwork":
        # 120s: n replica processes pay CONCURRENT contended jax imports
        # (~10-20s each when the 1-core host is busy) — 30s and 60s both
        # flaked under background load; boot time is not what any of
        # these scenarios measure
        try:
            for r in range(self.n):
                self.start_replica(r)
            self.wait_for_replicas_up(timeout=timeout)
        except BaseException:
            # a failed startup must not leak live replica processes (a
            # 31-process orphan herd from one failed start poisons every
            # later measurement on the host)
            self.stop_all()
            raise
        return self

    def start_replica(self, r: int,
                      extra_args: Optional[List[str]] = None,
                      extra_env: Optional[Dict[str, str]] = None) -> None:
        assert r not in self.procs or self.procs[r].poll() is not None
        # persistent kernel cache: device-backend replicas (crypto tpu)
        # otherwise pay a cold XLA compile per process — the dominant
        # source of system-test flakiness
        env = dict(os.environ, PYTHONPATH=_REPO_ROOT, JAX_PLATFORMS="cpu",
                   JAX_COMPILATION_CACHE_DIR=os.path.join(_REPO_ROOT,
                                                          ".jax_cache"),
                   JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="2",
                   **(extra_env or {}))
        args = [sys.executable, "-m", "tpubft.apps.skvbc_replica",
                "--replica", str(r), "--f", str(self.f), "--c", str(self.c),
                "--ro", str(self.num_ro),
                "--clients", str(self.num_clients),
                "--base-port", str(self.base_port),
                "--metrics-port", str(self.metrics_base + r),
                "--seed", self.seed,
                "--view-change-timeout-ms",
                str(self.view_change_timeout_ms),
                "--fault-port", str(self.fault_base + r),
                "--trs-port", str(self.trs_base + r),
                "--diag-port", str(self.diag_base + r),
                "--crypto-backend", self.crypto_backend,
                "--checkpoint-window", str(self.checkpoint_window),
                "--work-window", str(self.work_window),
                "--threshold-scheme", self.threshold_scheme,
                "--client-sig-scheme", self.client_sig_scheme,
                "--transport", self.transport] + (extra_args or [])
        if self.device_min_verify_batch is not None:
            args += ["--device-min-verify-batch",
                     str(self.device_min_verify_batch)]
        for k, v in self.cfg_overrides.items():
            args += ["--config-override", f"{k}={v}"]
        if self.certs_dir:
            args += ["--certs-dir", self.certs_dir]
        if self.pre_execution:
            args += ["--pre-execution"]
        if self.merkle:
            args += ["--merkle"]
        if self.db_dir:
            args += ["--db-dir", self.db_dir]
        # per-replica log files (Apollo keeps logs under
        # build/tests/apollo/logs — CMakeLists.txt:27)
        if self.db_dir:
            log = open(os.path.join(self.db_dir,
                                    f"replica-{r}.log"), "ab")
            out = err = log
        else:
            out = err = subprocess.DEVNULL
        self.procs[r] = subprocess.Popen(args, env=env, stdout=out,
                                         stderr=err)
        if out is not subprocess.DEVNULL:
            out.close()                   # child keeps its own fd

    def start_ro_replica(self, idx: int = 0,
                         extra_args: Optional[List[str]] = None,
                         extra_env: Optional[Dict[str, str]] = None) -> int:
        """Spawn a read-only replica process (id n+idx) — the archival
        follower (reference RO TesterReplica variant). Returns its id."""
        rid = self.n + idx
        assert idx < self.num_ro, "construct the network with num_ro"
        env = dict(os.environ, PYTHONPATH=_REPO_ROOT, JAX_PLATFORMS="cpu",
                   **(extra_env or {}))
        args = [sys.executable, "-m", "tpubft.apps.ro_replica",
                "--replica", str(rid), "--f", str(self.f),
                "--c", str(self.c), "--ro", str(self.num_ro),
                "--clients", str(self.num_clients),
                "--base-port", str(self.base_port),
                "--metrics-port", str(self.metrics_base + rid),
                "--seed", self.seed,
                "--checkpoint-window", str(self.checkpoint_window),
                "--threshold-scheme", self.threshold_scheme,
                "--client-sig-scheme", self.client_sig_scheme,
                "--transport", self.transport] + (extra_args or [])
        if self.certs_dir:
            args += ["--certs-dir", self.certs_dir]
        if self.db_dir:
            log = open(os.path.join(self.db_dir, f"ro-{rid}.log"), "ab")
            out = err = log
        else:
            out = err = subprocess.DEVNULL
        self.procs[rid] = subprocess.Popen(args, env=env, stdout=out,
                                           stderr=err)
        if out is not subprocess.DEVNULL:
            out.close()
        return rid

    def stop_all(self) -> None:
        for r, p in list(self.procs.items()):
            if p.poll() is None:
                # SIGCONT first: a SIGTERM delivered to a stopped process
                # stays pending until it resumes — without this, every
                # paused replica rides the 5s escalation below
                if r in self.paused:
                    p.send_signal(signal.SIGCONT)
                p.send_signal(signal.SIGTERM)
        for p in list(self.procs.values()):
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
                try:
                    p.wait(timeout=5)   # actually reap — no zombies
                except subprocess.TimeoutExpired:
                    pass
        self.paused.clear()
        for cl in self._clients.values():
            try:
                cl.stop()
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass

    # ------------------------------------------------------------------
    # fault injection (Apollo kill/restart + partition analogs)
    # ------------------------------------------------------------------
    def kill_replica(self, r: int) -> None:
        """Hard crash (SIGKILL) — Apollo bft.py stop_replica."""
        p = self.procs[r]
        if p.poll() is None:
            p.send_signal(signal.SIGKILL)
            p.wait()
        self.paused.discard(r)       # a dead process is no longer paused

    def wait_exit(self, r: int, timeout: float = 30.0) -> int:
        """Block until replica r's process exits on its own (crashpoint
        drills assert the exit CODE to prove the seam fired)."""
        return self.procs[r].wait(timeout=timeout)

    def restart_replica(self, r: int,
                        extra_args: Optional[List[str]] = None,
                        extra_env: Optional[Dict[str, str]] = None) -> None:
        self.kill_replica(r)
        self.start_replica(r, extra_args=extra_args, extra_env=extra_env)

    def pause_replica(self, r: int) -> None:
        """SIGSTOP: the replica is partitioned from the cluster (alive,
        silent) — analog of Apollo's iptables isolation."""
        self.procs[r].send_signal(signal.SIGSTOP)
        self.paused.add(r)

    def resume_replica(self, r: int) -> None:
        self.procs[r].send_signal(signal.SIGCONT)
        self.paused.discard(r)

    # ---- per-link faults (Apollo bft_network_partitioning.py analog,
    # via the in-process FaultControlServer instead of iptables) ----
    def drop_link(self, frm: int, to: int) -> None:
        """Asymmetric partition: frm stops SENDING to `to` (traffic
        to→frm still flows)."""
        from tpubft.testing.faults import fault_command
        state = fault_command(self.fault_base + frm, cmd="get") or {}
        drops = set(state.get("drop_to", [])) | {to}
        assert fault_command(self.fault_base + frm, cmd="set",
                             drop_to=sorted(drops)) is not None

    def isolate_replica(self, r: int, peers: Optional[List[int]] = None
                        ) -> None:
        """Symmetric isolation of r from `peers` (default: all replicas)
        without stopping the process — unlike SIGSTOP the replica keeps
        running (timers fire, complaints accumulate)."""
        from tpubft.testing.faults import fault_command
        others = [p for p in (peers if peers is not None
                              else range(self.n)) if p != r]
        assert fault_command(self.fault_base + r, cmd="set",
                             drop_to=others, drop_from=others) is not None

    def deafen_replica(self, r: int) -> None:
        """The classic view-change liveness trap (reference apollo
        partitioning's one-direction iptables DROP): replica r keeps
        SENDING — status beacons, PrePrepares, shares all flow out, so it
        looks alive to naive failure detection — but receives NOTHING
        (peers, clients, operator). If r is the primary, the cluster must
        view-change away despite the heartbeats."""
        from tpubft.consensus.replicas_info import ReplicasInfo
        from tpubft.testing.faults import fault_command
        op_id = ReplicasInfo.from_config(self._node_cfg()).operator_id
        everyone = [i for i in
                    list(range(self.n + self.num_ro + self.num_clients))
                    + [op_id] if i != r]
        assert fault_command(self.fault_base + r, cmd="set",
                             drop_from=everyone) is not None

    def set_loss(self, r: int, loss: float) -> None:
        """Uniform probabilistic message loss at replica r."""
        from tpubft.testing.faults import fault_command
        assert fault_command(self.fault_base + r, cmd="set",
                             loss=loss) is not None

    def set_delay(self, r: int, delay_ms: float,
                  jitter_ms: float = 0.0) -> None:
        """Latency shaping at replica r: every outbound message is held
        delay_ms ± jitter_ms before hitting the wire (the Apollo
        bft_network_traffic_control.py tc/netem role)."""
        from tpubft.testing.faults import fault_command
        assert fault_command(self.fault_base + r, cmd="set",
                             delay_ms=delay_ms,
                             jitter_ms=jitter_ms) is not None

    def heal(self, r: Optional[int] = None) -> None:
        """Clear all injected faults (for one replica or all)."""
        from tpubft.testing.faults import fault_command
        for rr in ([r] if r is not None else list(range(self.n))):
            fault_command(self.fault_base + rr, cmd="clear")

    # ------------------------------------------------------------------
    # observation
    # ------------------------------------------------------------------
    def metrics(self, r: int) -> MetricsClient:
        return MetricsClient(self.metrics_base + r)

    def wait_for_replicas_up(self, timeout: float = 30.0,
                             replicas: Optional[List[int]] = None) -> None:
        pending = set(replicas if replicas is not None
                      else range(self.n)) - self.paused
        deadline = time.monotonic() + timeout
        while pending and time.monotonic() < deadline:
            for r in list(pending):
                if self.metrics(r).snapshot(timeout=0.3) is not None:
                    pending.discard(r)
            if pending:
                time.sleep(0.2)
        if pending:
            raise TimeoutError(f"replicas never came up: {sorted(pending)}")

    def wait_for(self, predicate, timeout: float = 30.0,
                 poll: float = 0.2):
        """Apollo-style polling assertion helper."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            v = predicate()
            if v:
                return v
            time.sleep(poll)
        raise TimeoutError("condition never satisfied")

    def last_executed(self, r: int) -> Optional[int]:
        return self.metrics(r).get("replica", "gauges", "last_executed_seq")

    def current_view(self, r: int) -> Optional[int]:
        return self.metrics(r).get("replica", "gauges", "view")

    # ------------------------------------------------------------------
    # clients
    # ------------------------------------------------------------------
    def _node_cfg(self) -> ReplicaConfig:
        return ReplicaConfig(f_val=self.f, c_val=self.c,
                             num_ro_replicas=self.num_ro,
                             num_of_client_proxies=self.num_clients,
                             threshold_scheme=self.threshold_scheme,
                             client_sig_scheme=self.client_sig_scheme)

    def _make_comm(self, node_id: int, eps):
        if self.transport in ("tls", "tls-mux"):
            from tpubft.comm import create_communication
            from tpubft.comm.multiplex import client_floor
            from tpubft.comm.tls import TlsConfig
            floor = (client_floor(self.n, self.num_ro)
                     if self.transport == "tls-mux" else None)
            return create_communication(
                TlsConfig(self_id=node_id, endpoints=eps,
                          certs_dir=self.certs_dir,
                          mux_client_floor=floor), self.transport)
        return PlainUdpCommunication(CommConfig(self_id=node_id,
                                                endpoints=eps))

    def client(self, idx: int = 0, **cfg_kw) -> BftClient:
        client_id = self.n + self.num_ro + idx
        cl = self._clients.get(client_id)
        if cl is None:
            cfg = self._node_cfg()
            keys = ClusterKeys.generate(
                cfg, self.num_clients,
                seed=self.seed.encode()).for_node(client_id)
            eps = endpoint_table(self.base_port, self.n + self.num_ro,
                                 self.num_clients)
            comm = self._make_comm(client_id, eps)
            cl = BftClient(ClientConfig(client_id=client_id, f_val=self.f,
                                        c_val=self.c, **cfg_kw), keys, comm)
            cl.start()
            self._clients[client_id] = cl
        return cl

    def skvbc_client(self, idx: int = 0, **cfg_kw) -> SkvbcClient:
        return SkvbcClient(self.client(idx, **cfg_kw))

    def operator_client(self, **cfg_kw):
        """Operator principal over the real transport (reconfiguration
        commands: wedge, key rotation, pruning — reference TesterCRE/
        concord-ctl roles)."""
        from tpubft.consensus.replicas_info import ReplicasInfo
        from tpubft.reconfiguration import OperatorClient
        cfg = self._node_cfg()
        op_id = ReplicasInfo.from_config(cfg).operator_id
        cl = self._clients.get(op_id)
        if cl is None:
            keys = ClusterKeys.generate(
                cfg, self.num_clients,
                seed=self.seed.encode()).for_node(op_id)
            eps = endpoint_table(self.base_port, self.n + self.num_ro,
                                 self.num_clients, operator_id=op_id)
            comm = self._make_comm(op_id, eps)
            cl = BftClient(ClientConfig(client_id=op_id, f_val=self.f,
                                        c_val=self.c, **cfg_kw), keys, comm)
            cl.start()
            self._clients[op_id] = cl
        return OperatorClient(cl)

    def __enter__(self) -> "BftTestNetwork":
        return self.start_all()

    def __exit__(self, *exc) -> None:
        self.stop_all()
