"""Byzantine strategies — fault-injecting communication wrappers.

Rebuild of the reference's TesterReplica strategy framework
(/root/reference/tests/simpleKVBC/TesterReplica/strategy/,
WrapCommunication.cpp): an otherwise-honest replica is wrapped so its
*outgoing* messages are dropped, delayed, corrupted, or misdirected.
Strategies are selected by name (`--strategy` on the tester replica, or
passed to the in-process cluster) so system tests can inject faults
without touching protocol code.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, Iterable, Optional

from tpubft.comm.interfaces import (ConnectionStatus, ICommunication,
                                    IReceiver, NodeNum)


class WrapCommunication(ICommunication):
    """Delegates to an inner transport, routing sends through a mutator:
    mutate(dest, data) -> data | None (None = drop)."""

    def __init__(self, inner: ICommunication,
                 mutate: Callable[[NodeNum, bytes], Optional[bytes]]) -> None:
        self._inner = inner
        self._mutate = mutate

    def start(self, receiver: IReceiver) -> None:
        self._inner.start(receiver)

    def stop(self) -> None:
        self._inner.stop()

    def is_running(self) -> bool:
        return self._inner.is_running()

    def send(self, dest: NodeNum, data: bytes) -> None:
        out = self._mutate(dest, data)
        if out is not None:
            self._inner.send(dest, out)

    def get_connection_status(self, node: NodeNum) -> ConnectionStatus:
        return self._inner.get_connection_status(node)

    @property
    def max_message_size(self) -> int:
        return self._inner.max_message_size

    def flush(self) -> None:
        """Pass the dispatcher's end-of-iteration flush through to a
        batching inner transport (udp sendmmsg plane)."""
        inner_flush = getattr(self._inner, "flush", None)
        if inner_flush is not None:
            inner_flush()


def _msg_code(data: bytes) -> int:
    """Peek the consensus msg code without a full parse (every packed
    consensus message starts with a little-endian u16 MsgCode)."""
    import struct
    return struct.unpack_from("<H", data)[0] if len(data) >= 2 else -1


def _drop_all(dest: NodeNum, data: bytes) -> Optional[bytes]:
    return None


def _silent_preprepare(dest: NodeNum, data: bytes) -> Optional[bytes]:
    from tpubft.consensus.messages import MsgCode
    return None if _msg_code(data) == int(MsgCode.PrePrepare) else data


def _corrupt_shares(dest: NodeNum, data: bytes) -> Optional[bytes]:
    """Flip a byte INSIDE the signature share of every outgoing share
    message — exercises share verification + bad-share isolation. The
    flipped byte must be within `sig`: PartialCommitProofMsg carries a
    trailing path u8 AFTER the signature (messages.py SPEC), so flipping
    the last wire byte would only make the message unparseable (a silent
    replica, not a byzantine share)."""
    from tpubft.consensus.messages import MsgCode
    code = _msg_code(data)
    if code in (int(MsgCode.PreparePartial), int(MsgCode.CommitPartial),
                int(MsgCode.PartialCommitProof)):
        b = bytearray(data)
        # b[-1] is `path` on PartialCommitProof and the sig tail on the
        # others; b[-3] is inside the >=48-byte signature on all three
        b[-3] ^= 0xFF
        return bytes(b)
    return data


class _Equivocate:
    """A genuinely equivocating primary: odd-id destinations receive a
    validly re-signed VARIANT of every outgoing PrePrepare (the batch's
    last request dropped, requests_digest recomputed), even-id
    destinations the original. Both proposals verify, so the backups
    split across two digests for the same (view, seq) — no digest can
    reach a commit quorum and the cluster must view-change away without
    ever committing both. Needs the replica's signer (the in-process
    cluster and the tester replica both have it); without one the
    variant keeps the stale signature and degrades to a wrong-digest
    primary (receivers reject the fork outright)."""

    def __init__(self, signer=None) -> None:
        self._signer = signer

    def __call__(self, dest: NodeNum, data: bytes) -> Optional[bytes]:
        from tpubft.consensus import messages as cm
        if _msg_code(data) != int(cm.MsgCode.PrePrepare) \
                or int(dest) % 2 == 0:
            return data
        try:
            pp = cm.unpack(data)
        except cm.MsgError:
            return data
        reqs = list(pp.requests[:-1])
        fork = cm.PrePrepareMsg(
            sender_id=pp.sender_id, view=pp.view, seq_num=pp.seq_num,
            first_path=pp.first_path, time=pp.time,
            requests_digest=cm.PrePrepareMsg.compute_requests_digest(reqs),
            requests=reqs, signature=pp.signature, epoch=pp.epoch)
        if self._signer is not None:
            fork.signature = self._signer.sign(fork.signed_payload())
        return fork.pack()


def _corrupt_preprepare(dest: NodeNum, data: bytes) -> Optional[bytes]:
    """Wrong-digest primary: every outgoing PrePrepare's requests_digest
    is bit-flipped while the (now stale) signature rides along. Receivers
    must reject it at parse/verify — from the cluster's viewpoint the
    primary proposes garbage and must be view-changed away."""
    from tpubft.consensus import messages as cm
    if _msg_code(data) != int(cm.MsgCode.PrePrepare):
        return data
    try:
        pp = cm.unpack(data)
    except cm.MsgError:
        return data
    bad = bytes([pp.requests_digest[0] ^ 0xFF]) + pp.requests_digest[1:]
    fork = cm.PrePrepareMsg(
        sender_id=pp.sender_id, view=pp.view, seq_num=pp.seq_num,
        first_path=pp.first_path, time=pp.time, requests_digest=bad,
        requests=pp.requests, signature=pp.signature, epoch=pp.epoch)
    return fork.pack()


class _RandomDrop:
    def __init__(self, rate: float, seed: int = 0xBF7) -> None:
        self._rate = rate
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def __call__(self, dest: NodeNum, data: bytes) -> Optional[bytes]:
        with self._lock:
            roll = self._rng.random()
        return None if roll < self._rate else data


class _Delay:
    """Delays every send via one worker thread draining a time-ordered
    queue (send stays non-blocking; stop() cancels pending sends)."""

    def __init__(self, delay_s: float) -> None:
        self._delay = delay_s
        self._inner: Optional[ICommunication] = None
        self._queue: list = []
        self._cv = threading.Condition()
        self._stopped = False
        self._thread: Optional[threading.Thread] = None

    def bind(self, inner: ICommunication) -> None:
        self._inner = inner
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="byz-delay")
        self._thread.start()

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._queue.clear()
            self._cv.notify()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._stopped and (
                        not self._queue
                        or self._queue[0][0] > time.monotonic()):
                    wait = (self._queue[0][0] - time.monotonic()
                            if self._queue else None)
                    self._cv.wait(timeout=wait)
                if self._stopped:
                    return
                _, dest, data = self._queue.pop(0)
            try:
                if self._inner and self._inner.is_running():
                    self._inner.send(dest, data)
            except Exception:
                pass

    def __call__(self, dest: NodeNum, data: bytes) -> Optional[bytes]:
        with self._cv:
            if not self._stopped:
                self._queue.append((time.monotonic() + self._delay,
                                    dest, data))
                self._cv.notify()
        return None


STRATEGIES: Dict[str, Callable[..., Callable]] = {
    # reference strategy analogs (ByzantineStrategy.hpp implementations);
    # every factory takes an optional signer=... (the wrapped replica's
    # own signing key) — only the re-signing strategies use it
    "silent": lambda signer=None: _drop_all,           # mute replica
    "silent-preprepare":
        lambda signer=None: _silent_preprepare,        # primary withholds PP
    "corrupt-shares":
        lambda signer=None: _corrupt_shares,           # bad threshold shares
    "corrupt-preprepare":
        lambda signer=None: _corrupt_preprepare,       # wrong-digest primary
    "equivocate":
        lambda signer=None: _Equivocate(signer),       # two-faced primary
    "drop-20": lambda signer=None: _RandomDrop(0.2),   # lossy links
    "drop-50": lambda signer=None: _RandomDrop(0.5),
}


def strategy_wrapper(name: str) -> Callable[..., ICommunication]:
    """Returns wrap(inner, signer=None): the per-name communication
    wrapper. `signer` is the wrapped replica's own signing key, forwarded
    to strategies that need to re-sign mutated messages (equivocate)."""
    if name.startswith("delay-"):
        delay_ms = int(name.split("-", 1)[1])

        def wrap_delay(inner: ICommunication,
                       signer=None) -> ICommunication:
            d = _Delay(delay_ms / 1000.0)
            d.bind(inner)

            class _DelayedComm(WrapCommunication):
                def stop(self) -> None:
                    d.stop()
                    super().stop()

            return _DelayedComm(inner, d)
        return wrap_delay
    if name not in STRATEGIES:
        raise ValueError(f"unknown byzantine strategy {name!r}; "
                         f"have {sorted(STRATEGIES)} + delay-<ms>")

    def wrap(inner: ICommunication, signer=None) -> ICommunication:
        return WrapCommunication(inner, STRATEGIES[name](signer=signer))
    return wrap
