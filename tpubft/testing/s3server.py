"""Tiny in-repo S3-compatible HTTP server (test double).

The role of the reference's fake-S3 test setup (its S3 tests run against
a local MinIO/fake endpoint — bftengine/tests/s3): an in-memory
bucket store speaking the REST subset `S3ObjectStore` uses — PUT/GET/
HEAD/DELETE object and ListObjectsV2 with continuation tokens — and
*verifying* AWS SigV4 signatures when credentials are configured, so the
client's signing path is exercised end-to-end, not mocked out.

Usage:
    srv = S3TestServer(access_key="ak", secret_key="sk")
    srv.start()                      # serves on 127.0.0.1:<port>
    store = S3ObjectStore(srv.endpoint, "bucket", "ak", "sk")
"""
from __future__ import annotations

import datetime
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from xml.sax.saxutils import escape

from tpubft.storage.s3 import sigv4_headers


class S3TestServer:
    def __init__(self, access_key: str = "", secret_key: str = "",
                 max_keys: int = 1000, port: int = 0):
        self._objs: Dict[str, bytes] = {}      # "bucket/key" -> raw blob
        self._lock = threading.Lock()
        self.access_key, self.secret_key = access_key, secret_key
        self.max_keys = max_keys
        self.fail_next = 0                      # test hook: N transport 500s
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):          # quiet
                pass

            def _deny(self, code: int, msg: str) -> None:
                body = msg.encode()
                self.send_response(code)
                self.send_header("content-length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _auth_ok(self, body: bytes) -> bool:
                if not outer.secret_key:
                    return True
                auth = self.headers.get("authorization", "")
                amz_date = self.headers.get("x-amz-date", "")
                if not auth or not amz_date:
                    return False
                try:
                    now = datetime.datetime.strptime(
                        amz_date, "%Y%m%dT%H%M%SZ").replace(
                            tzinfo=datetime.timezone.utc)
                except ValueError:
                    return False
                path, _, query = self.path.partition("?")
                path = urllib.parse.unquote(path)
                want = sigv4_headers(
                    self.command, self.headers.get("host", ""), path,
                    query, body, outer.access_key, outer.secret_key,
                    now=now)["authorization"]
                return want == auth

            def _object_key(self) -> str:
                path, _, _ = self.path.partition("?")
                return urllib.parse.unquote(path).lstrip("/")

            def _read_body(self) -> bytes:
                n = int(self.headers.get("content-length", "0") or 0)
                return self.rfile.read(n) if n else b""

            def _maybe_fail(self) -> bool:
                with outer._lock:
                    if outer.fail_next > 0:
                        outer.fail_next -= 1
                        return True
                return False

            def do_PUT(self):
                body = self._read_body()
                if self._maybe_fail():
                    return self._deny(500, "injected failure")
                if not self._auth_ok(body):
                    return self._deny(403, "SignatureDoesNotMatch")
                with outer._lock:
                    outer._objs[self._object_key()] = body
                self.send_response(200)
                self.send_header("content-length", "0")
                self.end_headers()

            def do_GET(self):
                body = self._read_body()
                if self._maybe_fail():
                    return self._deny(500, "injected failure")
                if not self._auth_ok(body):
                    return self._deny(403, "SignatureDoesNotMatch")
                path, _, query = self.path.partition("?")
                qs = urllib.parse.parse_qs(query)
                if "list-type" in qs:
                    return self._list(path.lstrip("/"), qs)
                with outer._lock:
                    blob = outer._objs.get(self._object_key())
                if blob is None:
                    return self._deny(404, "NoSuchKey")
                self.send_response(200)
                self.send_header("content-length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            def do_HEAD(self):
                if not self._auth_ok(b""):
                    return self._deny(403, "SignatureDoesNotMatch")
                with outer._lock:
                    present = self._object_key() in outer._objs
                self.send_response(200 if present else 404)
                self.send_header("content-length", "0")
                self.end_headers()

            def do_DELETE(self):
                if not self._auth_ok(b""):
                    return self._deny(403, "SignatureDoesNotMatch")
                with outer._lock:
                    outer._objs.pop(self._object_key(), None)
                self.send_response(204)
                self.send_header("content-length", "0")
                self.end_headers()

            def _list(self, bucket: str, qs) -> None:
                prefix = qs.get("prefix", [""])[0]
                after = qs.get("continuation-token", [""])[0]
                full_prefix = f"{bucket}/{prefix}"
                with outer._lock:
                    keys = sorted(
                        k[len(bucket) + 1:] for k in outer._objs
                        if k.startswith(full_prefix))
                keys = [k for k in keys if k > after] if after else keys
                page, rest = keys[:outer.max_keys], keys[outer.max_keys:]
                parts = ["<?xml version='1.0'?><ListBucketResult>"]
                parts += [f"<Contents><Key>{escape(k)}</Key></Contents>"
                          for k in page]
                parts.append(
                    f"<IsTruncated>{'true' if rest else 'false'}"
                    "</IsTruncated>")
                if rest:
                    parts.append(f"<NextContinuationToken>"
                                 f"{escape(page[-1])}"
                                 f"</NextContinuationToken>")
                parts.append("</ListBucketResult>")
                body = "".join(parts).encode()
                self.send_response(200)
                self.send_header("content-type", "application/xml")
                self.send_header("content-length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def endpoint(self) -> str:
        host, port = self._server.server_address[:2]
        return f"{host}:{port}"

    def start(self) -> "S3TestServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="s3-test-server")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def corrupt(self, bucket_key: str) -> None:
        """Flip a byte of a stored object (integrity seal must catch it)."""
        with self._lock:
            blob = bytearray(self._objs[bucket_key])
            blob[-1] ^= 0xFF
            self._objs[bucket_key] = bytes(blob)

    def __enter__(self) -> "S3TestServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
