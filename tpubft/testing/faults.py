"""Runtime-controllable per-link fault injection for replica processes.

Rebuild of Apollo's network partitioning layer
(/root/reference/tests/apollo/util/bft_network_partitioning.py:52 —
iptables per-link DROP rules) without requiring iptables/root: the fault
plane lives INSIDE the replica process as a transport wrapper
(the reference's WrapCommunication.cpp role) whose drop sets are mutated
at runtime through a tiny UDP control server. This gives the harness
ASYMMETRIC partitions (A→B dropped while B→A flows), full isolation, and
probabilistic loss per link — per replica, per direction.

Control protocol (JSON over UDP, one datagram per command):
  {"cmd": "set", "drop_to": [ids], "drop_from": [ids], "loss": 0.3}
  {"cmd": "clear"}
  {"cmd": "get"}
Every command answers with the current fault state.
"""
from __future__ import annotations

import heapq
import json
import random
import socket
import threading
import time
from typing import Optional, Set

from tpubft.comm.interfaces import ICommunication, IReceiver, NodeNum
from tpubft.testing.byzantine import WrapCommunication


class _DelayScheduler:
    """Single-thread delayed-send executor (the tc/netem delay queue):
    callbacks fire in due-time order, so a larger jitter draw can reorder
    deliveries exactly like netem does."""

    def __init__(self) -> None:
        self._heap = []                # (due, seq, fn)
        self._seq = 0
        self._cv = threading.Condition()
        self._running = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="delay-sched")
        self._thread.start()

    def schedule(self, delay_s: float, fn) -> None:
        with self._cv:
            heapq.heappush(self._heap,
                           (time.monotonic() + delay_s, self._seq, fn))
            self._seq += 1
            self._cv.notify()

    def _run(self) -> None:
        while True:
            with self._cv:
                if not self._running:
                    return
                if not self._heap:
                    self._cv.wait(timeout=0.5)
                    continue
                due, _, fn = self._heap[0]
                now = time.monotonic()
                if due > now:
                    self._cv.wait(timeout=min(due - now, 0.5))
                    continue
                heapq.heappop(self._heap)
            try:
                fn()
            except Exception:  # noqa: BLE001 — transport may be stopping
                pass

    def stop(self) -> None:
        with self._cv:
            self._running = False
            self._cv.notify()


class FaultyComm(WrapCommunication):
    """Transport wrapper with runtime-mutable drop sets: outbound drops by
    destination, inbound drops by transport sender, uniform probabilistic
    loss (both directions), and per-send delay with jitter (the
    bft_network_traffic_control.py tc/netem role)."""

    def __init__(self, inner: ICommunication) -> None:
        super().__init__(inner, self._mutate_send)
        self.drop_to: Set[int] = set()
        self.drop_from: Set[int] = set()
        self.loss = 0.0
        self.delay_ms = 0.0
        self.jitter_ms = 0.0
        self._rng = random.Random(0xFA017)
        self._sched: Optional[_DelayScheduler] = None

    def _mutate_send(self, dest: NodeNum, data: bytes) -> Optional[bytes]:
        if int(dest) in self.drop_to:
            return None
        if self.loss and self._rng.random() < self.loss:
            return None
        return data

    def send(self, dest: NodeNum, data: bytes) -> None:
        out = self._mutate_send(dest, data)
        if out is None:
            return
        if self.delay_ms or self.jitter_ms:
            delay = max(0.0, (self.delay_ms + self._rng.uniform(
                -self.jitter_ms, self.jitter_ms)) / 1e3)
            if self._sched is None:
                self._sched = _DelayScheduler()
            self._sched.schedule(delay,
                                 lambda: self._inner.send(dest, out))
            return
        self._inner.send(dest, out)

    def start(self, receiver: IReceiver) -> None:
        self._inner.start(_FilteringReceiver(self, receiver))

    def stop(self) -> None:
        if self._sched is not None:
            self._sched.stop()
        super().stop()

    # control-server entry
    def configure(self, drop_to=None, drop_from=None,
                  loss: Optional[float] = None,
                  delay_ms: Optional[float] = None,
                  jitter_ms: Optional[float] = None) -> None:
        if drop_to is not None:
            self.drop_to = {int(x) for x in drop_to}
        if drop_from is not None:
            self.drop_from = {int(x) for x in drop_from}
        if loss is not None:
            self.loss = float(loss)
        if delay_ms is not None:
            self.delay_ms = float(delay_ms)
        if jitter_ms is not None:
            self.jitter_ms = float(jitter_ms)

    def state(self) -> dict:
        return {"drop_to": sorted(self.drop_to),
                "drop_from": sorted(self.drop_from), "loss": self.loss,
                "delay_ms": self.delay_ms, "jitter_ms": self.jitter_ms}


class _FilteringReceiver(IReceiver):
    def __init__(self, faults: FaultyComm, inner: IReceiver) -> None:
        self._faults = faults
        self._inner = inner

    def on_new_message(self, sender: NodeNum, data: bytes) -> None:
        f = self._faults
        if int(sender) in f.drop_from:
            return
        if f.loss and f._rng.random() < f.loss:
            return
        self._inner.on_new_message(sender, data)

    def on_connection_status_change(self, node, status) -> None:
        fn = getattr(self._inner, "on_connection_status_change", None)
        if fn is not None:
            fn(node, status)


class FaultControlServer:
    """One-datagram-per-command UDP control endpoint mutating a
    FaultyComm's drop state (the harness's handle into the process)."""

    def __init__(self, faults: FaultyComm, port: int = 0,
                 host: str = "127.0.0.1") -> None:
        self._faults = faults
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]
        self._running = False
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fault-ctl")
        self._thread.start()

    def _run(self) -> None:
        self._sock.settimeout(0.5)
        while self._running:
            try:
                data, addr = self._sock.recvfrom(1 << 16)
            except socket.timeout:
                continue
            except OSError:
                return
            # the control thread is the harness's only handle into the
            # process: it must answer EVERY datagram — malformed JSON, a
            # non-object payload, unknown commands, bad field types — with
            # an error dict rather than dying silently (a dead control
            # thread turns every later heal()/set into a mystery timeout)
            try:
                reply = json.dumps(self._handle(data)).encode()
            except Exception as e:  # noqa: BLE001 — never kill the thread
                reply = json.dumps({"error": f"{type(e).__name__}: {e}"}
                                   ).encode()
            try:
                self._sock.sendto(reply, addr)
            except OSError:
                pass

    def _handle(self, data: bytes) -> dict:
        cmd = json.loads(data.decode())
        if not isinstance(cmd, dict):
            return {"error": "command must be a JSON object"}
        op = cmd.get("cmd")
        if op == "clear":
            self._faults.configure(drop_to=(), drop_from=(), loss=0,
                                   delay_ms=0, jitter_ms=0)
        elif op == "set":
            self._faults.configure(cmd.get("drop_to"),
                                   cmd.get("drop_from"),
                                   cmd.get("loss"),
                                   cmd.get("delay_ms"),
                                   cmd.get("jitter_ms"))
        elif op == "breaker":
            # chaos handle into the degradation plane: trip or reset the
            # process-wide device breaker so campaigns can compose
            # device-degraded modes with protocol faults (a breaker that
            # trips mid-view-change is the compound failure a real
            # cluster sees when a chip dies under load)
            from tpubft.ops.dispatch import device_breaker
            b = device_breaker()
            action = cmd.get("action")
            if action == "trip":
                for _ in range(b.failure_threshold):
                    b.record_failure(kind="chaos", cause="injected")
            elif action == "reset":
                b.reset()
            elif action != "get":
                return {"error": f"unknown breaker action {action!r}"}
            return {"breaker": b.snapshot()}
        elif op != "get":
            return {"error": f"unknown cmd {op!r}"}
        return self._faults.state()

    def stop(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=2)
        self._sock.close()


def fault_command(port: int, timeout: float = 2.0, **cmd) -> Optional[dict]:
    """Harness side: send one control command, return the replica's fault
    state (None on timeout — e.g. the process is paused)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.settimeout(timeout)
    try:
        s.sendto(json.dumps(cmd).encode(), ("127.0.0.1", port))
        data, _ = s.recvfrom(1 << 16)
        return json.loads(data.decode())
    except (OSError, ValueError):
        return None
    finally:
        s.close()
