"""SlowdownManager — phase-tagged delay/drop fault injection.

Rebuild of /root/reference/performance/include/SlowdownManager.hpp:32-145
(compile-time-gated there via BUILD_SLOWDOWN; runtime-gated here): named
pipeline phases consult the process-wide manager, which is a no-op unless
a policy was installed. Tests install policies to simulate slow storage,
slow pre-execution, or message-drop pressure without touching protocol
code.
"""
from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional

# phase names (SlowdownPhase enum in the reference)
PHASE_CLIENT_REQUEST = "client_request"
PHASE_PRE_EXECUTE = "pre_execute"
PHASE_COMMIT = "commit"
PHASE_EXECUTE = "execute"
PHASE_STORAGE_WRITE = "storage_write"


@dataclass
class SlowdownPolicy:
    delay_ms: float = 0.0
    jitter_ms: float = 0.0
    drop_rate: float = 0.0   # probability a phase reports "drop this"


class SlowdownManager:
    def __init__(self) -> None:
        self._policies: Dict[str, SlowdownPolicy] = {}
        self._rng = random.Random(5160)
        self._lock = threading.Lock()
        self.enabled = False

    def install(self, phase: str, policy: SlowdownPolicy) -> None:
        with self._lock:
            self._policies[phase] = policy
            self.enabled = True

    def clear(self) -> None:
        with self._lock:
            self._policies.clear()
            self.enabled = False

    def delay_only(self, phase: str) -> None:
        """Apply only the delay component — for phases where dropping is
        not meaningful (e.g. ordered execution, which must stay
        deterministic across replicas)."""
        if not self.enabled:
            return
        with self._lock:
            policy = self._policies.get(phase)
            if policy is None:
                return
            jitter = self._rng.random() * policy.jitter_ms
        if policy.delay_ms or jitter:
            time.sleep((policy.delay_ms + jitter) / 1000.0)

    def delay(self, phase: str) -> bool:
        """Apply the phase's policy. Returns True if the operation should
        be DROPPED (delay already applied otherwise)."""
        if not self.enabled:
            return False
        with self._lock:
            policy = self._policies.get(phase)
            if policy is None:
                return False
            roll = self._rng.random()
            jitter = self._rng.random() * policy.jitter_ms
        if policy.delay_ms or jitter:
            time.sleep((policy.delay_ms + jitter) / 1000.0)
        return roll < policy.drop_rate


_manager = SlowdownManager()


def get_slowdown_manager() -> SlowdownManager:
    return _manager
