"""BCStateTran-equivalent: the state-transfer protocol state machine.

Rebuild of /root/reference/bftengine/src/bcstatetransfer/BCStateTran.cpp
(destination fetch loop + source serving) with RVBManager's duties folded
into the RangeValidationTree and a SourceSelector grown into a per-source
scoreboard. Runs entirely on the consensus dispatcher thread
(handle_message + tick), so no internal locking is needed — mirroring the
reference's single-threaded ST handler invoked from the replica loop.

Flow (SURVEY §3.4), destination side PIPELINED:
  lag detected → AskForCheckpointSummaries (all replicas) → f+1 matching
  summaries = agreed target (seq, digest, last_block, rvt_root) → the
  span [head+1, target] is split into ranges of `fetch_batch_blocks`
  blocks and up to `window_ranges` ranges are kept in flight at once,
  each assigned to a different live source (aggregated-gossip insight:
  spread dissemination cost over the quorum, not one link). Ranges
  complete OUT OF ORDER; a completed range's leaf digests are hashed as
  ONE device batch (ops/sha256, hashlib below the cutoff / without a
  device), its RVT proofs checked per window, and its blocks staged in
  one WriteBatch; the contiguous staged prefix links in one atomic
  batch. A stalled or lying source is charged on its scoreboard and only
  ITS range is re-assigned to the next-best source — in-flight ranges on
  other sources survive. head == target → verify digest →
  on_transfer_complete upcall into consensus.
  source: answers summaries from its latest stable checkpoint; streams
  chunked ItemData with RVT proofs; RejectFetching when pruned/behind.
"""
from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from tpubft.kvbc.blockchain import BlockchainError, KeyValueBlockchain
from tpubft.statetransfer import messages as stm
from tpubft.statetransfer.rvt import RangeValidationTree, RvtProof
from tpubft.testing.crashpoints import crashpoint
from tpubft.utils import serialize as ser
from tpubft.utils.metrics import Aggregator, Component, Meter
from tpubft.utils.tracing import Span, get_tracer

_META_FAMILY = b"st.meta"
_K_STABLE = b"stable"

# destination states
_IDLE = "idle"
_SUMMARIES = "summaries"
_FETCHING = "fetching"
_RESPAGES = "respages"


@dataclass
class StConfig:
    fetch_batch_blocks: int = 16        # blocks per range
    max_chunk_bytes: int = 24 * 1024
    retry_timeout_s: float = 1.0
    # concurrent ranges in flight (1 = the old stop-and-wait loop)
    window_ranges: int = 4
    # a completed window with >= this many blocks hashes its leaf digests
    # through the batched device kernel (ops/sha256); smaller windows and
    # no-device runs stay on hashlib
    device_digest_threshold: int = 16
    # None = follow the blockchain's use_device_hashing; explicit
    # True/False overrides (tests, CPU-only deployments)
    use_device_digests: Optional[bool] = None
    # plausibility ceiling for byzantine chunk metadata: chunks are only
    # buffered while total_chunks and the range's cumulative payload stay
    # under what this block-size bound allows — a lying source gets
    # punished instead of streaming unbounded data into reassembly
    max_block_bytes: int = 64 << 20


@dataclass
class _SourceStats:
    failures: int = 0           # consecutive — cleared when a range LINKS
    outstanding: int = 0        # ranges currently assigned
    bytes: int = 0
    first_byte_at: float = 0.0
    last_byte_at: float = 0.0
    abandoned: bool = False

    def rate(self) -> float:
        dt = self.last_byte_at - self.first_byte_at
        return self.bytes / dt if dt > 0 else 0.0


class SourceSelector:
    """Per-source scoreboard (reference: bcstatetransfer/SourceSelector.hpp
    grown for the pipelined fetch loop): bytes/sec, outstanding ranges,
    and a consecutive-failure budget per candidate. pick() returns the
    best usable source, preferring ones with no range in flight so the
    window stripes across the quorum; RETRY_BUDGET consecutive failures
    abandon a source; when every candidate is abandoned pick() returns
    None and the manager restarts from checkpoint summaries."""

    RETRY_BUDGET = 3

    def __init__(self) -> None:
        self._stats: Dict[int, _SourceStats] = {}

    def reset(self, candidates: List[int]) -> None:
        self._stats = {c: _SourceStats() for c in candidates}

    def live(self) -> List[int]:
        return [s for s, st in sorted(self._stats.items())
                if not st.abandoned]

    def stats(self, src: int) -> Optional[_SourceStats]:
        return self._stats.get(src)

    def pick(self, avoid: Optional[set] = None) -> Optional[int]:
        """Best live source: fewest outstanding ranges first (stripe the
        window), then measured throughput, then fewest failures. `avoid`
        is a soft preference — only honored while other candidates
        remain (fewer live sources than window slots is legal: sources
        then serve several ranges)."""
        live = self.live()
        if not live:
            return None
        pool = [s for s in live if s not in (avoid or ())] or live
        return min(pool, key=lambda s: (self._stats[s].outstanding,
                                        -self._stats[s].rate(),
                                        self._stats[s].failures, s))

    def note_bytes(self, src: int, n: int) -> None:
        st = self._stats.get(src)
        if st is None:
            return
        now = time.monotonic()
        if st.first_byte_at == 0.0:
            st.first_byte_at = now
        st.last_byte_at = now
        st.bytes += n

    def note_success(self, src: int) -> None:
        """A range served by `src` verified AND linked: clear its
        consecutive failures so sporadic timeouts across a long transfer
        don't accumulate into abandonment (reference SourceSelector
        resets the retry counter on successful replies). Deliberately NOT
        called at verify time — a lying agreed group makes every source's
        blocks verify then fail linking, and clearing at verify would
        livelock instead of exhausting into a summaries restart."""
        st = self._stats.get(src)
        if st is not None:
            st.failures = 0

    def fail(self, src: int) -> None:
        """Charge one failure (stall, corrupt data, reject, link
        mismatch); the source is abandoned once its budget is spent."""
        st = self._stats.get(src)
        if st is None:
            return
        st.failures += 1
        if st.failures >= self.RETRY_BUDGET:
            st.abandoned = True

    def inc_outstanding(self, src: int) -> None:
        st = self._stats.get(src)
        if st is not None:
            st.outstanding += 1

    def dec_outstanding(self, src: int) -> None:
        st = self._stats.get(src)
        if st is not None and st.outstanding > 0:
            st.outstanding -= 1


@dataclass
class _Range:
    """One in-flight block range [lo, hi] assigned to one source."""
    msg_id: int
    lo: int
    hi: int
    source: int
    last_activity: float
    chunks: Dict[int, Dict[int, bytes]] = field(default_factory=dict)
    totals: Dict[int, int] = field(default_factory=dict)
    proofs: Dict[int, RvtProof] = field(default_factory=dict)
    raws: Dict[int, bytes] = field(default_factory=dict)
    bytes_rcvd: int = 0
    span: Optional[Span] = None

    @property
    def n_blocks(self) -> int:
        return self.hi - self.lo + 1


class StateTransferManager:
    def __init__(self, replica_id: int, blockchain: KeyValueBlockchain,
                 cfg: Optional[StConfig] = None,
                 reserved_pages=None,
                 aggregator: Optional[Aggregator] = None) -> None:
        self.id = replica_id
        self.bc = blockchain
        self.cfg = cfg or StConfig()
        self._db = blockchain._db
        self.rvt = RangeValidationTree(self._db)
        self.sources = SourceSelector()
        self.pages = reserved_pages  # ReservedPages (set via bind/replica)
        if self.cfg.use_device_digests is None:
            self._use_device = bool(getattr(blockchain, "_use_device",
                                            False))
        else:
            self._use_device = self.cfg.use_device_digests

        # observability (issue: st_blocks_per_sec, st_bytes_per_sec,
        # inflight_ranges, source_failovers + spans per range)
        self.metrics = Component("state_transfer", aggregator)
        self.m_blocks = self.metrics.register_counter("blocks_fetched")
        self.m_bytes = self.metrics.register_counter("bytes_fetched")
        self.m_failovers = self.metrics.register_counter("source_failovers")
        self.m_device_batches = self.metrics.register_counter(
            "device_digest_batches")
        self.m_scalar_digests = self.metrics.register_counter(
            "scalar_digests")
        self.m_requeued = self.metrics.register_counter("ranges_requeued")
        self.m_inflight = self.metrics.register_gauge("inflight_ranges")
        self.m_blocks_rate = self.metrics.register_gauge("st_blocks_per_sec")
        self.m_bytes_rate = self.metrics.register_gauge("st_bytes_per_sec")
        self._blocks_meter = Meter()
        self._bytes_meter = Meter()

        # wiring (bind() before start)
        self._send: Callable[[int, bytes], None] = lambda d, p: None
        self._complete: Callable[[int, bytes], None] = lambda s, d: None
        self._replica_ids: List[int] = []
        self._quorum = 1  # f+1

        # source-side stable checkpoint info, persisted across restarts
        raw = self._db.get(_K_STABLE, _META_FAMILY)
        self._stable: Optional[Tuple[int, bytes, int]] = None
        self._serving_pages: list = []
        if raw:
            seq = int.from_bytes(raw[:8], "big")
            last_block = int.from_bytes(raw[8:16], "big")
            self._stable = (seq, raw[16:48], last_block)
            snap = self._load_snapshot(seq)
            if snap is not None and snap[1] == self._stable[1]:
                self._serving_pages = snap[2]

        # destination-side state
        self.state = _IDLE
        self._msg_id = 0
        self._summaries: Dict[int, stm.CheckpointSummary] = {}
        self._agreed: Optional[stm.CheckpointSummary] = None
        self._min_seq = 0
        self._certified: Dict[int, bytes] = {}  # seq -> certified digest
        self._ranges: Dict[int, _Range] = {}    # msg_id -> in-flight range
        self._requeue: List[Tuple[int, int]] = []
        self._next_lo = 0
        self._staged_src: Dict[int, int] = {}   # staged block -> source
        self._refilling = False
        self._refill_more = False
        self._transfer_span: Optional[Span] = None
        self._page_chunks: Dict[int, list] = {}
        self._page_total = 0
        self._pages_src: Optional[int] = None
        self._last_activity = 0.0

    # ------------------------------------------------------------------
    # wiring
    # ------------------------------------------------------------------
    def bind(self, send_fn: Callable[[int, bytes], None],
             complete_fn: Callable[[int, bytes], None],
             replica_ids: List[int], f_val: int) -> None:
        self._send = send_fn
        self._complete = complete_fn
        self._replica_ids = [r for r in replica_ids if r != self.id]
        self._quorum = f_val + 1

    @property
    def is_fetching(self) -> bool:
        return self.state != _IDLE

    @property
    def last_activity(self) -> float:
        """Monotonic timestamp of the fetch plane's last send/receive —
        the health watchdog's progress pulse while `is_fetching`."""
        return self._last_activity

    # ------------------------------------------------------------------
    # consensus upcalls (dispatcher thread)
    # ------------------------------------------------------------------
    def on_checkpoint_created(self, seq: int, state_digest: bytes) -> None:
        """Called at the moment the replica sends its CheckpointMsg for
        `seq` — i.e. right after executing seq, when live state EQUALS the
        digests being certified. Snapshot what a certificate would bind:
        last_block and the reserved pages. The cluster keeps executing
        while the certificate forms, so serving live state instead would
        livelock every destination (digests never match the certificate)."""
        pages = self.pages.all_pages() if self.pages is not None else []
        buf = bytearray()
        buf += self.bc.last_block_id.to_bytes(8, "big")
        buf += state_digest
        ser.write_uvarint(buf, len(pages))
        for k, v in pages:
            ser.write_bytes(buf, k)
            ser.write_bytes(buf, v)
        self._db.put(b"snap" + seq.to_bytes(8, "big"), bytes(buf),
                     _META_FAMILY)
        # GC old snapshots (keep the last few in-flight checkpoints)
        for k, _ in list(self._db.range_iter(_META_FAMILY, start=b"snap")):
            if k.startswith(b"snap") and len(k) == 12 \
                    and int.from_bytes(k[4:], "big") + 4 < seq:
                self._db.delete(k, _META_FAMILY)

    def _load_snapshot(self, seq: int):
        raw = self._db.get(b"snap" + seq.to_bytes(8, "big"), _META_FAMILY)
        if raw is None:
            return None
        mv = memoryview(raw)
        last_block = int.from_bytes(mv[:8], "big")
        state_digest = bytes(mv[8:40])
        n, off = ser.read_uvarint(mv, 40)
        pages = []
        for _ in range(n):
            k, off = ser.read_bytes(mv, off)
            v, off = ser.read_bytes(mv, off)
            pages.append((k, v))
        return last_block, state_digest, pages

    def on_checkpoint_stable(self, seq: int, state_digest: bytes) -> None:
        """A certificate formed for checkpoint `seq`: promote the snapshot
        taken at creation time to the serving point
        (RVBManager::setNewSourceCheckpoint duty) and grow the RVT."""
        snap = self._load_snapshot(seq)
        if snap is None or snap[1] != state_digest:
            # no matching snapshot (e.g. we just state-transferred in):
            # live state IS the certified state right now
            snap = (self.bc.last_block_id, state_digest,
                    self.pages.all_pages() if self.pages is not None else [])
        last_block, _, pages = snap
        try:
            self.rvt.sync_to(self.bc)
        except BlockchainError:
            return  # digest gap (shouldn't happen); keep old serving point
        self._stable = (seq, state_digest, last_block)
        self._serving_pages = pages
        self._db.put(
            _K_STABLE,
            seq.to_bytes(8, "big") + last_block.to_bytes(8, "big")
            + state_digest, _META_FAMILY)

    def start_collecting(self, min_checkpoint_seq: int,
                         certified: Optional[Dict[int, bytes]] = None
                         ) -> None:
        """Lag detected by consensus — begin (or retarget) a transfer.
        `certified` maps checkpoint seq -> signature-quorum-verified state
        digest; ST sub-messages are unauthenticated, so summaries are only
        accepted when they match one of these anchors (an attacker who can
        spoof sender ids still cannot steer us to a state whose head
        digest isn't certificate-backed)."""
        if certified:
            self._certified.update(certified)
        if self.state == _FETCHING:
            return
        self._min_seq = max(self._min_seq, min_checkpoint_seq)
        if self.state == _SUMMARIES:
            return
        from tpubft.utils.logging import get_logger
        get_logger("statetransfer").info(
            "starting state transfer toward checkpoint >= %d", self._min_seq)
        self.state = _SUMMARIES
        self._summaries.clear()
        self._agreed = None
        self._transfer_span = get_tracer().start_span(
            "state_transfer", tags={"r": self.id, "min_seq": self._min_seq})
        self._ask_summaries()

    def tick(self) -> None:
        if self.state == _IDLE:
            return
        now = time.monotonic()
        if self.state == _FETCHING:
            # per-range stall detection: only the stalled range's source
            # is charged and only that range re-assigned — other in-flight
            # ranges keep streaming
            stalled = [rng for rng in list(self._ranges.values())
                       if now - rng.last_activity >= self.cfg.retry_timeout_s]
            for rng in stalled:
                if rng.msg_id in self._ranges:      # not dropped meanwhile
                    self._punish_range(rng, "stalled")
            # a link deferred by an open speculative accumulation
            # (link_st_chain returns without adopting while the exec
            # lane holds the staging lock) leaves a contiguous staged
            # block waiting — retry it here once the speculation
            # resolved, or the transfer would wedge on already-verified
            # blocks
            if self._staged_src \
                    and self.bc.has_st_block(self.bc.last_block_id + 1):
                self._try_link()
            self._refill_ranges()
            self._update_rates()
            return
        if now - self._last_activity < self.cfg.retry_timeout_s:
            return
        if self.state == _SUMMARIES:
            self._ask_summaries()
        elif self.state == _RESPAGES:
            if self._pages_src is not None:
                self.sources.fail(self._pages_src)
                self.m_failovers.inc()
            self._request_res_pages()

    def _update_rates(self) -> None:
        self.m_blocks_rate.set(int(self._blocks_meter.rate()))
        self.m_bytes_rate.set(int(self._bytes_meter.rate()))

    # ------------------------------------------------------------------
    # message dispatch
    # ------------------------------------------------------------------
    def handle_message(self, sender: int, payload: bytes) -> None:
        try:
            msg = stm.unpack(payload)
        except ser.SerializeError:
            return
        if isinstance(msg, stm.AskForCheckpointSummaries):
            self._on_ask_summaries(sender, msg)
        elif isinstance(msg, stm.CheckpointSummary):
            self._on_summary(sender, msg)
        elif isinstance(msg, stm.FetchBlocks):
            self._on_fetch_blocks(sender, msg)
        elif isinstance(msg, stm.ItemData):
            self._on_item_data(sender, msg)
        elif isinstance(msg, stm.RejectFetching):
            self._on_reject(sender, msg)
        elif isinstance(msg, stm.FetchResPages):
            self._on_fetch_res_pages(sender, msg)
        elif isinstance(msg, stm.ResPagesData):
            self._on_res_pages_data(sender, msg)

    # ------------------------------------------------------------------
    # source side
    # ------------------------------------------------------------------
    def _on_ask_summaries(self, sender: int,
                          msg: stm.AskForCheckpointSummaries) -> None:
        if self._stable is None:
            return
        seq, digest, last_block = self._stable
        if seq < msg.min_checkpoint_seq or last_block == 0:
            return
        try:
            root = self.rvt.root(last_block)
        except ValueError:
            return
        from tpubft.consensus.reserved_pages import ReservedPages
        self._send(sender, stm.pack(stm.CheckpointSummary(
            reply_to=msg.msg_id, checkpoint_seq=seq, state_digest=digest,
            last_block=last_block, rvt_root=root,
            res_pages_digest=(ReservedPages.digest_of(self._serving_pages)
                              if self.pages is not None else b""))))

    def _on_fetch_res_pages(self, sender: int,
                            msg: stm.FetchResPages) -> None:
        all_pages = self._serving_pages
        groups: List[list] = [[]]
        size = 0
        for k, v in all_pages:
            if size + len(k) + len(v) > self.cfg.max_chunk_bytes \
                    and groups[-1]:
                groups.append([])
                size = 0
            groups[-1].append((k, v))
            size += len(k) + len(v)
        for ci, group in enumerate(groups):
            self._send(sender, stm.pack(stm.ResPagesData(
                reply_to=msg.msg_id, chunk_idx=ci,
                total_chunks=len(groups), pages=group)))

    def _on_fetch_blocks(self, sender: int, msg: stm.FetchBlocks) -> None:
        if (self._stable is None or msg.from_block > msg.to_block
                or msg.from_block < 1
                or msg.to_block > msg.target_last_block
                or msg.target_last_block > self._stable[2]
                or msg.to_block - msg.from_block
                >= 4 * self.cfg.fetch_batch_blocks):
            self._send(sender, stm.pack(stm.RejectFetching(
                reply_to=msg.msg_id, reason="range unavailable")))
            return
        if msg.from_block < self.bc.genesis_block_id:
            self._send(sender, stm.pack(stm.RejectFetching(
                reply_to=msg.msg_id, reason="pruned")))
            return
        # prove at the requester's agreed leaf count, NOT our own stable
        # point — ours may have advanced past the agreed summary mid-transfer
        rvt_leaves = msg.target_last_block
        for bid in range(msg.from_block, msg.to_block + 1):
            raw = self.bc.get_raw_block(bid)
            if raw is None:
                self._send(sender, stm.pack(stm.RejectFetching(
                    reply_to=msg.msg_id, reason=f"missing {bid}")))
                return
            proof = self.rvt.prove(bid - 1, rvt_leaves)
            chunks = [raw[i:i + self.cfg.max_chunk_bytes]
                      for i in range(0, len(raw), self.cfg.max_chunk_bytes)] \
                or [b""]
            for ci, chunk in enumerate(chunks):
                self._send(sender, stm.pack(stm.ItemData(
                    reply_to=msg.msg_id, block_id=bid, chunk_idx=ci,
                    total_chunks=len(chunks), payload=chunk, proof=proof,
                    last_in_response=(bid == msg.to_block
                                      and ci == len(chunks) - 1))))

    # ------------------------------------------------------------------
    # destination side — summaries
    # ------------------------------------------------------------------
    def _ask_summaries(self) -> None:
        self._msg_id += 1
        self._last_activity = time.monotonic()
        ask = stm.pack(stm.AskForCheckpointSummaries(
            msg_id=self._msg_id, min_checkpoint_seq=self._min_seq))
        for r in self._replica_ids:
            self._send(r, ask)

    def _on_summary(self, sender: int, msg: stm.CheckpointSummary) -> None:
        if self.state != _SUMMARIES or msg.reply_to != self._msg_id:
            return
        if msg.checkpoint_seq < self._min_seq or msg.last_block == 0:
            return
        if sender not in self._replica_ids:
            return
        # only certificate-anchored targets are acceptable
        if self._certified.get(msg.checkpoint_seq) \
                != (msg.state_digest, msg.res_pages_digest):
            return
        self._summaries[sender] = msg
        groups: Dict[tuple, List[int]] = {}
        for r, s in self._summaries.items():
            groups.setdefault(s.key(), []).append(r)
        for key, senders in groups.items():
            if len(senders) >= self._quorum:
                self._agreed = next(s for s in self._summaries.values()
                                    if s.key() == key)
                self.sources.reset(sorted(senders))
                self.state = _FETCHING
                self._ranges.clear()
                self._requeue.clear()
                self._staged_src.clear()
                self._next_lo = self.bc.last_block_id + 1
                self._refill_ranges()
                return

    # ------------------------------------------------------------------
    # destination side — the pipelined fetch window
    # ------------------------------------------------------------------
    def _restart_from_summaries(self) -> None:
        """No usable sources left (or agreed digest mismatch) — drop all
        in-flight state and start over from checkpoint summaries."""
        for rng in list(self._ranges.values()):
            self._drop_range(rng, "aborted")
        self._requeue.clear()
        self._staged_src.clear()
        self.state = _SUMMARIES
        self._summaries.clear()
        self._agreed = None
        self._ask_summaries()

    def _refill_ranges(self) -> None:
        """Keep up to `window_ranges` ranges in flight, preferring a
        distinct source per range. Re-entrant-safe: over a synchronous
        transport every send can complete a whole range inline, which
        would otherwise recurse one stack level per range."""
        if self.state != _FETCHING:
            return
        if self._refilling:
            self._refill_more = True
            return
        self._refilling = True
        try:
            while True:
                self._refill_more = False
                if self.state != _FETCHING:
                    break
                assert self._agreed is not None
                target = self._agreed.last_block
                if (not self._ranges and not self._requeue
                        and self._next_lo > target):
                    # everything fetched; _finish validates the head (the
                    # staged suffix links as its prefix arrives, so a
                    # clean run is fully linked here). Over a synchronous
                    # transport _finish may restart the transfer inline —
                    # the outer loop re-checks instead of returning.
                    self._finish()
                else:
                    while (len(self._ranges) < self.cfg.window_ranges
                           and self.state == _FETCHING):
                        span: Optional[Tuple[int, int]] = None
                        if self._requeue:
                            span = self._requeue.pop(0)
                        elif self._next_lo <= target:
                            lo = self._next_lo
                            hi = min(lo + self.cfg.fetch_batch_blocks - 1,
                                     target)
                            span = (lo, hi)
                            self._next_lo = hi + 1
                        if span is None:
                            break
                        busy = {r.source for r in self._ranges.values()}
                        src = self.sources.pick(avoid=busy)
                        if src is None:
                            self._restart_from_summaries()
                            break
                        self._send_fetch(span, src)      # may re-enter
                if not self._refill_more:
                    break
        finally:
            self._refilling = False

    def _send_fetch(self, span: Tuple[int, int], src: int) -> None:
        assert self._agreed is not None
        self._msg_id += 1
        now = time.monotonic()
        rng = _Range(msg_id=self._msg_id, lo=span[0], hi=span[1],
                     source=src, last_activity=now)
        parent = (self._transfer_span.context
                  if self._transfer_span is not None else None)
        rng.span = get_tracer().start_span(
            "st_range", parent=parent,
            tags={"lo": rng.lo, "hi": rng.hi, "source": src})
        self._ranges[rng.msg_id] = rng
        self.sources.inc_outstanding(src)
        self.m_inflight.set(len(self._ranges))
        self._last_activity = now
        self._send(src, stm.pack(stm.FetchBlocks(
            msg_id=rng.msg_id, from_block=rng.lo, to_block=rng.hi,
            target_last_block=self._agreed.last_block)))

    def _drop_range(self, rng: _Range, outcome: str) -> None:
        self._ranges.pop(rng.msg_id, None)
        self.sources.dec_outstanding(rng.source)
        self.m_inflight.set(len(self._ranges))
        if rng.span is not None:
            rng.span.set_tag("outcome", outcome)
            rng.span.finish()
            rng.span = None

    def _punish_range(self, rng: _Range, reason: str) -> None:
        """Bad or stalled range: charge ONLY the serving source, re-queue
        the span for the next-best source. Other in-flight ranges are
        untouched; source exhaustion falls back to summaries (in
        _refill_ranges)."""
        self._drop_range(rng, reason)
        self.sources.fail(rng.source)
        self.m_failovers.inc()
        self.m_requeued.inc()
        self._requeue.append((rng.lo, rng.hi))
        self._refill_ranges()

    def _on_item_data(self, sender: int, msg: stm.ItemData) -> None:
        if self.state != _FETCHING or self._agreed is None:
            return
        rng = self._ranges.get(msg.reply_to)
        if rng is None or sender != rng.source:
            return
        if not rng.lo <= msg.block_id <= rng.hi:
            return
        if not 0 <= msg.chunk_idx < msg.total_chunks:
            return
        if msg.block_id in rng.raws:
            return                              # duplicate, already whole
        # plausibility caps BEFORE buffering anything: reassembly and RVT
        # checks only run once all claimed chunks arrive, so an uncapped
        # total_chunks (or endless payload stream) would let a byzantine
        # source grow rng.chunks without bound while each chunk refreshes
        # the stall timer. Chunks smaller than 4 KiB only arise as a
        # block's tail, so max_block_bytes/4Ki bounds any honest count.
        if msg.total_chunks > self.cfg.max_block_bytes // 4096 + 1:
            self._punish_range(rng, "implausible chunk count")
            return
        if rng.bytes_rcvd + len(msg.payload) \
                > rng.n_blocks * self.cfg.max_block_bytes:
            self._punish_range(rng, "range overweight")
            return
        # a source flipping total_chunks or the proof between chunks of
        # the SAME block is malformed — don't let it confuse reassembly
        prev_total = rng.totals.get(msg.block_id)
        if prev_total is not None and msg.total_chunks != prev_total:
            self._punish_range(rng, "chunk-total flip")
            return
        prev_proof = rng.proofs.get(msg.block_id)
        if prev_proof is not None and msg.proof != prev_proof:
            self._punish_range(rng, "proof flip")
            return
        now = time.monotonic()
        rng.last_activity = now
        self._last_activity = now
        rng.totals[msg.block_id] = msg.total_chunks
        rng.proofs[msg.block_id] = msg.proof
        parts = rng.chunks.setdefault(msg.block_id, {})
        if msg.chunk_idx not in parts:
            rng.bytes_rcvd += len(msg.payload)
        parts[msg.chunk_idx] = msg.payload
        self.sources.note_bytes(sender, len(msg.payload))
        self.m_bytes.inc(len(msg.payload))
        self._bytes_meter.mark(len(msg.payload))
        if len(parts) == msg.total_chunks:
            rng.raws[msg.block_id] = b"".join(parts[i]
                                              for i in range(msg.total_chunks))
            del rng.chunks[msg.block_id]
            if len(rng.raws) == rng.n_blocks:
                self._complete_range(rng)

    def _window_digests(self, raws: List[bytes]) -> List[bytes]:
        """Leaf digests for a completed window: one batched device call
        (ops/sha256) above the cutoff, hashlib otherwise or when the
        device path fails."""
        if (self._use_device
                and len(raws) >= self.cfg.device_digest_threshold):
            try:
                from tpubft.ops.sha256 import sha256_batch_mixed
                out = sha256_batch_mixed(raws)
                self.m_device_batches.inc()
                return out
            except Exception:  # noqa: BLE001 — device loss degrades, not fails
                pass
        self.m_scalar_digests.inc(len(raws))
        return [hashlib.sha256(r).digest() for r in raws]

    def _complete_range(self, rng: _Range) -> None:
        """All blocks of a range reassembled: verify the whole window —
        leaf digests in one batch, RVT proofs per block — then stage it
        in one WriteBatch and link whatever prefix became contiguous."""
        assert self._agreed is not None
        raws = [rng.raws[b] for b in range(rng.lo, rng.hi + 1)]
        leaves = self._window_digests(raws)
        if not RangeValidationTree.verify_window(
                self._agreed.rvt_root, rng.lo - 1, self._agreed.last_block,
                leaves, [rng.proofs[b] for b in range(rng.lo, rng.hi + 1)]):
            self._punish_range(rng, "rvt mismatch")
            return
        crashpoint("st.window_adopt", rid=self.id)
        self.bc.add_raw_st_blocks(rng.raws)
        for b in rng.raws:
            self._staged_src[b] = rng.source
        if rng.span is not None:
            rng.span.set_tag("bytes", sum(len(r) for r in raws))
        self.m_blocks.inc(rng.n_blocks)
        self._blocks_meter.mark(rng.n_blocks)
        self._drop_range(rng, "verified")
        self._try_link()
        self._update_rates()
        self._refill_ranges()

    def _try_link(self) -> None:
        """Adopt the contiguous staged prefix (one atomic WriteBatch in
        the blockchain). A link failure after RVT verification means the
        block's CONTENT doesn't re-execute to its recorded digests —
        charge the source that served it and re-fetch just that block."""
        try:
            self.bc.link_st_chain()
        except Exception:  # noqa: BLE001 — any staged-block defect
            failed = self.bc.last_block_id + 1
            src = self._staged_src.pop(failed, None)
            if src is not None:
                self.sources.fail(src)
                self.m_failovers.inc()
            self.m_requeued.inc()
            self._requeue.append((failed, failed))
        # linked blocks: clear blame AND credit their sources (see
        # SourceSelector.note_success for why credit waits for the link)
        linked = [b for b in self._staged_src
                  if b <= self.bc.last_block_id]
        for b in linked:
            self.sources.note_success(self._staged_src.pop(b))

    def _on_reject(self, sender: int, msg: stm.RejectFetching) -> None:
        if self.state != _FETCHING:
            return
        rng = self._ranges.get(msg.reply_to)
        if rng is None or sender != rng.source:
            return
        self._punish_range(rng, f"rejected: {msg.reason}")

    def _finish(self) -> None:
        assert self._agreed is not None
        agreed = self._agreed
        if self.bc.last_block_id != agreed.last_block \
                or self.bc.state_digest() != agreed.state_digest:
            # chain incomplete or digest mismatch — the agreed group lied
            # or we hit a bug; restart from scratch
            self._restart_from_summaries()
            return
        # reserved pages next (reference: FetchResPagesMsg after blocks)
        if self.pages is not None \
                and self.pages.digest() != agreed.res_pages_digest:
            self.state = _RESPAGES
            self._request_res_pages()
            return
        self._complete_transfer()

    # ------------------------------------------------------------------
    # destination side — reserved pages
    # ------------------------------------------------------------------
    def _request_res_pages(self) -> None:
        self._last_activity = time.monotonic()
        src = self.sources.pick()
        if src is None:
            self._restart_from_summaries()
            return
        self._pages_src = src
        self._msg_id += 1
        self._page_chunks.clear()
        self._send(src, stm.pack(stm.FetchResPages(msg_id=self._msg_id)))

    def _on_res_pages_data(self, sender: int, msg: stm.ResPagesData) -> None:
        if (self.state != _RESPAGES or self._agreed is None
                or sender != self._pages_src
                or msg.reply_to != self._msg_id
                or not 0 <= msg.chunk_idx < msg.total_chunks):
            return
        # a source switching total_chunks mid-response is malformed
        if self._page_chunks and msg.total_chunks != self._page_total:
            self._fail_res_pages()
            return
        self._page_total = msg.total_chunks
        self._last_activity = time.monotonic()
        self._page_chunks[msg.chunk_idx] = msg.pages
        if any(ci not in self._page_chunks
               for ci in range(msg.total_chunks)):
            return
        pages = [kv for ci in range(msg.total_chunks)
                 for kv in self._page_chunks[ci]]
        from tpubft.consensus.reserved_pages import ReservedPages
        if ReservedPages.digest_of(pages) != self._agreed.res_pages_digest:
            self._fail_res_pages()
            return
        self.pages.replace_all(pages)
        self._complete_transfer()

    def _fail_res_pages(self) -> None:
        self._page_chunks.clear()
        if self._pages_src is not None:
            self.sources.fail(self._pages_src)
            self.m_failovers.inc()
        self._request_res_pages()

    def _complete_transfer(self) -> None:
        agreed = self._agreed
        from tpubft.utils.logging import get_logger
        get_logger("statetransfer").info(
            "state transfer complete at checkpoint %d", agreed.checkpoint_seq)
        self.state = _IDLE
        self._agreed = None
        self._summaries.clear()
        self._page_chunks.clear()
        self._pages_src = None
        self._staged_src.clear()
        self._update_rates()
        if self._transfer_span is not None:
            self._transfer_span.set_tag("checkpoint", agreed.checkpoint_seq)
            self._transfer_span.set_tag("last_block", self.bc.last_block_id)
            self._transfer_span.finish()
            self._transfer_span = None
        self._certified = {s: d for s, d in self._certified.items()
                           if s > agreed.checkpoint_seq}
        # we are now a valid source for this checkpoint
        self.on_checkpoint_stable(agreed.checkpoint_seq, agreed.state_digest)
        self._complete(agreed.checkpoint_seq, agreed.state_digest)
