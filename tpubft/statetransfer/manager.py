"""BCStateTran-equivalent: the state-transfer protocol state machine.

Rebuild of /root/reference/bftengine/src/bcstatetransfer/BCStateTran.cpp
(destination fetch loop + source serving) with RVBManager's duties folded
into the RangeValidationTree and a SourceSelector for rotating away from
slow/Byzantine sources. Runs entirely on the consensus dispatcher thread
(handle_message + tick), so no internal locking is needed — mirroring the
reference's single-threaded ST handler invoked from the replica loop.

Flow (SURVEY §3.4):
  destination: lag detected → AskForCheckpointSummaries (all replicas)
    → f+1 matching summaries = agreed target (seq, digest, last_block,
    rvt_root) → FetchBlocks batches from selected source → per-block RVT
    proof check → stage + link into the blockchain → head == target →
    verify digest → on_transfer_complete upcall into consensus.
  source: answers summaries from its latest stable checkpoint; streams
    chunked ItemData with RVT proofs; RejectFetching when pruned/behind.
"""
from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from tpubft.kvbc.blockchain import BlockchainError, KeyValueBlockchain
from tpubft.statetransfer import messages as stm
from tpubft.statetransfer.rvt import RangeValidationTree, RvtProof
from tpubft.utils import serialize as ser

_META_FAMILY = b"st.meta"
_K_STABLE = b"stable"

# destination states
_IDLE = "idle"
_SUMMARIES = "summaries"
_FETCHING = "fetching"
_RESPAGES = "respages"


@dataclass
class StConfig:
    fetch_batch_blocks: int = 16
    max_chunk_bytes: int = 24 * 1024
    retry_timeout_s: float = 1.0


class SourceSelector:
    """Rotates through candidate sources, abandoning ones that exhaust a
    per-source retry budget (reference: bcstatetransfer/SourceSelector.hpp).
    Once every candidate is abandoned, current() returns None and the
    manager restarts from checkpoint summaries."""

    RETRY_BUDGET = 3

    def __init__(self) -> None:
        self._candidates: List[int] = []
        self._failures: Dict[int, int] = {}
        self._idx = 0

    def reset(self, candidates: List[int]) -> None:
        self._candidates = list(candidates)
        self._failures = {c: 0 for c in candidates}
        self._idx = 0

    def current(self) -> Optional[int]:
        if not self._candidates:
            return None
        return self._candidates[self._idx % len(self._candidates)]

    def note_success(self) -> None:
        """A batch from the current source verified and linked: clear its
        failure count so sporadic timeouts across a long transfer don't
        accumulate into abandonment (reference SourceSelector resets the
        retry counter on successful replies)."""
        cur = self.current()
        if cur is not None:
            self._failures[cur] = 0

    def fail_current(self) -> Optional[int]:
        """Charge the current source one failure; drop it once its budget
        is spent, then move to the next (None when all are exhausted)."""
        cur = self.current()
        if cur is None:
            return None
        self._failures[cur] = self._failures.get(cur, 0) + 1
        if self._failures[cur] >= self.RETRY_BUDGET:
            pos = self._candidates.index(cur)
            self._candidates.pop(pos)
            if self._candidates:
                self._idx = pos % len(self._candidates)
        else:
            self._idx += 1
        return self.current()


class StateTransferManager:
    def __init__(self, replica_id: int, blockchain: KeyValueBlockchain,
                 cfg: Optional[StConfig] = None,
                 reserved_pages=None) -> None:
        self.id = replica_id
        self.bc = blockchain
        self.cfg = cfg or StConfig()
        self._db = blockchain._db
        self.rvt = RangeValidationTree(self._db)
        self.sources = SourceSelector()
        self.pages = reserved_pages  # ReservedPages (set via bind/replica)

        # wiring (bind() before start)
        self._send: Callable[[int, bytes], None] = lambda d, p: None
        self._complete: Callable[[int, bytes], None] = lambda s, d: None
        self._replica_ids: List[int] = []
        self._quorum = 1  # f+1

        # source-side stable checkpoint info, persisted across restarts
        raw = self._db.get(_K_STABLE, _META_FAMILY)
        self._stable: Optional[Tuple[int, bytes, int]] = None
        self._serving_pages: list = []
        if raw:
            seq = int.from_bytes(raw[:8], "big")
            last_block = int.from_bytes(raw[8:16], "big")
            self._stable = (seq, raw[16:48], last_block)
            snap = self._load_snapshot(seq)
            if snap is not None and snap[1] == self._stable[1]:
                self._serving_pages = snap[2]

        # destination-side state
        self.state = _IDLE
        self._msg_id = 0
        self._summaries: Dict[int, stm.CheckpointSummary] = {}
        self._agreed: Optional[stm.CheckpointSummary] = None
        self._min_seq = 0
        self._certified: Dict[int, bytes] = {}  # seq -> certified digest
        self._chunks: Dict[int, Dict[int, bytes]] = {}  # block -> idx -> part
        self._chunk_totals: Dict[int, int] = {}
        self._proofs: Dict[int, RvtProof] = {}
        self._page_chunks: Dict[int, list] = {}
        self._page_total = 0
        self._last_activity = 0.0
        self._fetch_from = 0

    # ------------------------------------------------------------------
    # wiring
    # ------------------------------------------------------------------
    def bind(self, send_fn: Callable[[int, bytes], None],
             complete_fn: Callable[[int, bytes], None],
             replica_ids: List[int], f_val: int) -> None:
        self._send = send_fn
        self._complete = complete_fn
        self._replica_ids = [r for r in replica_ids if r != self.id]
        self._quorum = f_val + 1

    @property
    def is_fetching(self) -> bool:
        return self.state != _IDLE

    # ------------------------------------------------------------------
    # consensus upcalls (dispatcher thread)
    # ------------------------------------------------------------------
    def on_checkpoint_created(self, seq: int, state_digest: bytes) -> None:
        """Called at the moment the replica sends its CheckpointMsg for
        `seq` — i.e. right after executing seq, when live state EQUALS the
        digests being certified. Snapshot what a certificate would bind:
        last_block and the reserved pages. The cluster keeps executing
        while the certificate forms, so serving live state instead would
        livelock every destination (digests never match the certificate)."""
        pages = self.pages.all_pages() if self.pages is not None else []
        buf = bytearray()
        buf += self.bc.last_block_id.to_bytes(8, "big")
        buf += state_digest
        ser.write_uvarint(buf, len(pages))
        for k, v in pages:
            ser.write_bytes(buf, k)
            ser.write_bytes(buf, v)
        self._db.put(b"snap" + seq.to_bytes(8, "big"), bytes(buf),
                     _META_FAMILY)
        # GC old snapshots (keep the last few in-flight checkpoints)
        for k, _ in list(self._db.range_iter(_META_FAMILY, start=b"snap")):
            if k.startswith(b"snap") and len(k) == 12 \
                    and int.from_bytes(k[4:], "big") + 4 < seq:
                self._db.delete(k, _META_FAMILY)

    def _load_snapshot(self, seq: int):
        raw = self._db.get(b"snap" + seq.to_bytes(8, "big"), _META_FAMILY)
        if raw is None:
            return None
        mv = memoryview(raw)
        last_block = int.from_bytes(mv[:8], "big")
        state_digest = bytes(mv[8:40])
        n, off = ser.read_uvarint(mv, 40)
        pages = []
        for _ in range(n):
            k, off = ser.read_bytes(mv, off)
            v, off = ser.read_bytes(mv, off)
            pages.append((k, v))
        return last_block, state_digest, pages

    def on_checkpoint_stable(self, seq: int, state_digest: bytes) -> None:
        """A certificate formed for checkpoint `seq`: promote the snapshot
        taken at creation time to the serving point
        (RVBManager::setNewSourceCheckpoint duty) and grow the RVT."""
        snap = self._load_snapshot(seq)
        if snap is None or snap[1] != state_digest:
            # no matching snapshot (e.g. we just state-transferred in):
            # live state IS the certified state right now
            snap = (self.bc.last_block_id, state_digest,
                    self.pages.all_pages() if self.pages is not None else [])
        last_block, _, pages = snap
        try:
            self.rvt.sync_to(self.bc)
        except BlockchainError:
            return  # digest gap (shouldn't happen); keep old serving point
        self._stable = (seq, state_digest, last_block)
        self._serving_pages = pages
        self._db.put(
            _K_STABLE,
            seq.to_bytes(8, "big") + last_block.to_bytes(8, "big")
            + state_digest, _META_FAMILY)

    def start_collecting(self, min_checkpoint_seq: int,
                         certified: Optional[Dict[int, bytes]] = None
                         ) -> None:
        """Lag detected by consensus — begin (or retarget) a transfer.
        `certified` maps checkpoint seq -> signature-quorum-verified state
        digest; ST sub-messages are unauthenticated, so summaries are only
        accepted when they match one of these anchors (an attacker who can
        spoof sender ids still cannot steer us to a state whose head
        digest isn't certificate-backed)."""
        if certified:
            self._certified.update(certified)
        if self.state == _FETCHING:
            return
        self._min_seq = max(self._min_seq, min_checkpoint_seq)
        if self.state == _SUMMARIES:
            return
        from tpubft.utils.logging import get_logger
        get_logger("statetransfer").info(
            "starting state transfer toward checkpoint >= %d", self._min_seq)
        self.state = _SUMMARIES
        self._summaries.clear()
        self._agreed = None
        self._ask_summaries()

    def tick(self) -> None:
        if self.state == _IDLE:
            return
        if time.monotonic() - self._last_activity < self.cfg.retry_timeout_s:
            return
        if self.state == _SUMMARIES:
            self._ask_summaries()
        elif self.state == _FETCHING:
            # stalled source: charge it a failure and re-request; when every
            # candidate's budget is spent, _request_next_batch restarts from
            # summaries
            self.sources.fail_current()
            self._request_next_batch()
        elif self.state == _RESPAGES:
            self.sources.fail_current()
            self._request_res_pages()

    # ------------------------------------------------------------------
    # message dispatch
    # ------------------------------------------------------------------
    def handle_message(self, sender: int, payload: bytes) -> None:
        try:
            msg = stm.unpack(payload)
        except ser.SerializeError:
            return
        if isinstance(msg, stm.AskForCheckpointSummaries):
            self._on_ask_summaries(sender, msg)
        elif isinstance(msg, stm.CheckpointSummary):
            self._on_summary(sender, msg)
        elif isinstance(msg, stm.FetchBlocks):
            self._on_fetch_blocks(sender, msg)
        elif isinstance(msg, stm.ItemData):
            self._on_item_data(sender, msg)
        elif isinstance(msg, stm.RejectFetching):
            self._on_reject(sender, msg)
        elif isinstance(msg, stm.FetchResPages):
            self._on_fetch_res_pages(sender, msg)
        elif isinstance(msg, stm.ResPagesData):
            self._on_res_pages_data(sender, msg)

    # ------------------------------------------------------------------
    # source side
    # ------------------------------------------------------------------
    def _on_ask_summaries(self, sender: int,
                          msg: stm.AskForCheckpointSummaries) -> None:
        if self._stable is None:
            return
        seq, digest, last_block = self._stable
        if seq < msg.min_checkpoint_seq or last_block == 0:
            return
        try:
            root = self.rvt.root(last_block)
        except ValueError:
            return
        from tpubft.consensus.reserved_pages import ReservedPages
        self._send(sender, stm.pack(stm.CheckpointSummary(
            reply_to=msg.msg_id, checkpoint_seq=seq, state_digest=digest,
            last_block=last_block, rvt_root=root,
            res_pages_digest=(ReservedPages.digest_of(self._serving_pages)
                              if self.pages is not None else b""))))

    def _on_fetch_res_pages(self, sender: int,
                            msg: stm.FetchResPages) -> None:
        all_pages = self._serving_pages
        groups: List[list] = [[]]
        size = 0
        for k, v in all_pages:
            if size + len(k) + len(v) > self.cfg.max_chunk_bytes \
                    and groups[-1]:
                groups.append([])
                size = 0
            groups[-1].append((k, v))
            size += len(k) + len(v)
        for ci, group in enumerate(groups):
            self._send(sender, stm.pack(stm.ResPagesData(
                reply_to=msg.msg_id, chunk_idx=ci,
                total_chunks=len(groups), pages=group)))

    def _on_fetch_blocks(self, sender: int, msg: stm.FetchBlocks) -> None:
        if (self._stable is None or msg.from_block > msg.to_block
                or msg.from_block < 1
                or msg.to_block > msg.target_last_block
                or msg.target_last_block > self._stable[2]
                or msg.to_block - msg.from_block
                >= 4 * self.cfg.fetch_batch_blocks):
            self._send(sender, stm.pack(stm.RejectFetching(
                reply_to=msg.msg_id, reason="range unavailable")))
            return
        if msg.from_block < self.bc.genesis_block_id:
            self._send(sender, stm.pack(stm.RejectFetching(
                reply_to=msg.msg_id, reason="pruned")))
            return
        # prove at the requester's agreed leaf count, NOT our own stable
        # point — ours may have advanced past the agreed summary mid-transfer
        rvt_leaves = msg.target_last_block
        for bid in range(msg.from_block, msg.to_block + 1):
            raw = self.bc.get_raw_block(bid)
            if raw is None:
                self._send(sender, stm.pack(stm.RejectFetching(
                    reply_to=msg.msg_id, reason=f"missing {bid}")))
                return
            proof = self.rvt.prove(bid - 1, rvt_leaves)
            chunks = [raw[i:i + self.cfg.max_chunk_bytes]
                      for i in range(0, len(raw), self.cfg.max_chunk_bytes)] \
                or [b""]
            for ci, chunk in enumerate(chunks):
                self._send(sender, stm.pack(stm.ItemData(
                    reply_to=msg.msg_id, block_id=bid, chunk_idx=ci,
                    total_chunks=len(chunks), payload=chunk, proof=proof,
                    last_in_response=(bid == msg.to_block
                                      and ci == len(chunks) - 1))))

    # ------------------------------------------------------------------
    # destination side
    # ------------------------------------------------------------------
    def _ask_summaries(self) -> None:
        self._msg_id += 1
        self._last_activity = time.monotonic()
        ask = stm.pack(stm.AskForCheckpointSummaries(
            msg_id=self._msg_id, min_checkpoint_seq=self._min_seq))
        for r in self._replica_ids:
            self._send(r, ask)

    def _on_summary(self, sender: int, msg: stm.CheckpointSummary) -> None:
        if self.state != _SUMMARIES or msg.reply_to != self._msg_id:
            return
        if msg.checkpoint_seq < self._min_seq or msg.last_block == 0:
            return
        if sender not in self._replica_ids:
            return
        # only certificate-anchored targets are acceptable
        if self._certified.get(msg.checkpoint_seq) \
                != (msg.state_digest, msg.res_pages_digest):
            return
        self._summaries[sender] = msg
        groups: Dict[tuple, List[int]] = {}
        for r, s in self._summaries.items():
            groups.setdefault(s.key(), []).append(r)
        for key, senders in groups.items():
            if len(senders) >= self._quorum:
                self._agreed = next(s for s in self._summaries.values()
                                    if s.key() == key)
                self.sources.reset(sorted(senders))
                self.state = _FETCHING
                self._chunks.clear()
                self._chunk_totals.clear()
                self._proofs.clear()
                self._request_next_batch()
                return

    def _request_next_batch(self) -> None:
        assert self._agreed is not None
        self._last_activity = time.monotonic()
        nxt = self.bc.last_block_id + 1
        if nxt > self._agreed.last_block:
            self._finish()
            return
        src = self.sources.current()
        if src is None:
            # no usable sources left — start over from summaries
            self.state = _SUMMARIES
            self._summaries.clear()
            self._agreed = None
            self._ask_summaries()
            return
        self._msg_id += 1
        self._fetch_from = nxt
        to = min(nxt + self.cfg.fetch_batch_blocks - 1,
                 self._agreed.last_block)
        self._send(src, stm.pack(stm.FetchBlocks(
            msg_id=self._msg_id, from_block=nxt, to_block=to,
            target_last_block=self._agreed.last_block)))

    def _on_item_data(self, sender: int, msg: stm.ItemData) -> None:
        if (self.state != _FETCHING or self._agreed is None
                or sender != self.sources.current()
                or msg.reply_to != self._msg_id):
            return
        if not (self._fetch_from <= msg.block_id
                <= self._agreed.last_block):
            return
        if not 0 <= msg.chunk_idx < msg.total_chunks:
            return
        self._last_activity = time.monotonic()
        parts = self._chunks.setdefault(msg.block_id, {})
        parts[msg.chunk_idx] = msg.payload
        self._chunk_totals[msg.block_id] = msg.total_chunks
        self._proofs[msg.block_id] = msg.proof
        if len(parts) == msg.total_chunks:
            raw = b"".join(parts[i] for i in range(msg.total_chunks))
            if not self._adopt_block(msg.block_id, raw):
                return
        if msg.last_in_response:
            self._try_link_and_continue()

    def _adopt_block(self, block_id: int, raw: bytes) -> bool:
        """RVT-check one reassembled block and stage it."""
        assert self._agreed is not None
        leaf = hashlib.sha256(raw).digest()
        proof = self._proofs.get(block_id)
        if proof is None or not RangeValidationTree.verify(
                self._agreed.rvt_root, block_id - 1,
                self._agreed.last_block, leaf, proof):
            self._punish_source()
            return False
        self.bc.add_raw_st_block(block_id, raw)
        self._chunks.pop(block_id, None)
        self._chunk_totals.pop(block_id, None)
        self._proofs.pop(block_id, None)
        return True

    def _try_link_and_continue(self) -> None:
        try:
            self.bc.link_st_chain()
        except Exception:
            self._punish_source()
            return
        self.sources.note_success()
        self._request_next_batch()

    def _punish_source(self) -> None:
        """Bad data: charge the source and retry the batch from the next
        one; source exhaustion falls back to summaries (in
        _request_next_batch)."""
        self._chunks.clear()
        self._chunk_totals.clear()
        self._proofs.clear()
        self.sources.fail_current()
        self._request_next_batch()

    def _on_reject(self, sender: int, msg: stm.RejectFetching) -> None:
        if self.state != _FETCHING or sender != self.sources.current():
            return
        if msg.reply_to != self._msg_id:
            return
        self._punish_source()

    def _finish(self) -> None:
        assert self._agreed is not None
        agreed = self._agreed
        if self.bc.state_digest() != agreed.state_digest:
            # chain linked but digest mismatch — the agreed group lied or
            # we hit a bug; restart from scratch
            self.state = _SUMMARIES
            self._summaries.clear()
            self._agreed = None
            self._ask_summaries()
            return
        # reserved pages next (reference: FetchResPagesMsg after blocks)
        if self.pages is not None \
                and self.pages.digest() != agreed.res_pages_digest:
            self.state = _RESPAGES
            self._request_res_pages()
            return
        self._complete_transfer()

    def _request_res_pages(self) -> None:
        self._last_activity = time.monotonic()
        src = self.sources.current()
        if src is None:
            self.state = _SUMMARIES
            self._summaries.clear()
            self._agreed = None
            self._ask_summaries()
            return
        self._msg_id += 1
        self._page_chunks.clear()
        self._send(src, stm.pack(stm.FetchResPages(msg_id=self._msg_id)))

    def _on_res_pages_data(self, sender: int, msg: stm.ResPagesData) -> None:
        if (self.state != _RESPAGES or self._agreed is None
                or sender != self.sources.current()
                or msg.reply_to != self._msg_id
                or not 0 <= msg.chunk_idx < msg.total_chunks):
            return
        # a source switching total_chunks mid-response is malformed
        if self._page_chunks and msg.total_chunks != self._page_total:
            self._page_chunks.clear()
            self.sources.fail_current()
            self._request_res_pages()
            return
        self._page_total = msg.total_chunks
        self._last_activity = time.monotonic()
        self._page_chunks[msg.chunk_idx] = msg.pages
        if any(ci not in self._page_chunks
               for ci in range(msg.total_chunks)):
            return
        pages = [kv for ci in range(msg.total_chunks)
                 for kv in self._page_chunks[ci]]
        from tpubft.consensus.reserved_pages import ReservedPages
        if ReservedPages.digest_of(pages) != self._agreed.res_pages_digest:
            self._page_chunks.clear()
            self.sources.fail_current()
            self._request_res_pages()
            return
        self.pages.replace_all(pages)
        self._complete_transfer()

    def _complete_transfer(self) -> None:
        agreed = self._agreed
        from tpubft.utils.logging import get_logger
        get_logger("statetransfer").info(
            "state transfer complete at checkpoint %d", agreed.checkpoint_seq)
        self.state = _IDLE
        self._agreed = None
        self._summaries.clear()
        self._page_chunks.clear()
        self._certified = {s: d for s, d in self._certified.items()
                           if s > agreed.checkpoint_seq}
        # we are now a valid source for this checkpoint
        self.on_checkpoint_stable(agreed.checkpoint_seq, agreed.state_digest)
        self._complete(agreed.checkpoint_seq, agreed.state_digest)
