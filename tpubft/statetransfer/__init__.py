"""State transfer — bringing lagging/new replicas to the cluster's state.

Rebuild of /root/reference/bftengine/src/bcstatetransfer/ (BCStateTran,
RVBManager + RangeValidationTree, SourceSelector): checkpoint-summary
agreement (f+1 matching), sourced block fetching with chunking, and
per-block integrity proofs against an append-only digest tree so a
Byzantine source is caught on the first bad block, not at the end.
"""
from tpubft.statetransfer.manager import StateTransferManager
from tpubft.statetransfer.rvt import RangeValidationTree

__all__ = ["StateTransferManager", "RangeValidationTree"]
