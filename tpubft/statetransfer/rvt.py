"""Range Validation Tree — append-only Merkle commitment over block digests.

Rebuild of the reference's RangeValidationTree
(/root/reference/bftengine/src/bcstatetransfer/RangeValidationTree.cpp,
RVBManager.hpp:31-59): the source advertises one root in its checkpoint
summary; every fetched block then carries a membership proof, so a
Byzantine source is rejected at the first bad block instead of DOSing the
destination with a long bogus chain.

Design here is a Merkle Mountain Range (append-only, O(log n) proofs,
persistable as a flat pos→hash map) rather than the reference's fixed-
arity RVB tree — same duties, simpler append path, and old roots stay
provable because node positions never move.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from tpubft.storage.interfaces import IDBClient, WriteBatch

_PARENT = b"\x02"
_BAG = b"\x03"
_ROOT = b"\x04"


def _h_parent(left: bytes, right: bytes) -> bytes:
    return hashlib.sha256(_PARENT + left + right).digest()


def _pos_height(pos: int) -> int:
    """Height of the node at 0-based MMR position `pos`."""
    pos += 1
    while pos & (pos + 1):  # until all-ones
        pos -= (1 << (pos.bit_length() - 1)) - 1
    return pos.bit_length() - 1


def _leaf_pos(i: int) -> int:
    """MMR position of the i-th (0-based) leaf."""
    return 2 * i - bin(i).count("1")


def _mmr_size(n_leaves: int) -> int:
    return 2 * n_leaves - bin(n_leaves).count("1")


def _mountains(n_leaves: int) -> List[Tuple[int, int, int]]:
    """-> [(height, first_leaf, pos_start)] per mountain, left to right."""
    out = []
    leaf_off = 0
    pos_off = 0
    for bit in reversed(range(n_leaves.bit_length())):
        if n_leaves >> bit & 1:
            out.append((bit, leaf_off, pos_off))
            leaf_off += 1 << bit
            pos_off += (1 << (bit + 1)) - 1
    return out


def _node_pos(pos_start: int, mountain_h: int, local_leaf: int,
              k: int) -> int:
    """Position of the height-k ancestor of `local_leaf` inside a mountain
    of height `mountain_h` whose nodes start at `pos_start` (post-order)."""
    lo, hi = 0, 1 << mountain_h
    pos = pos_start + (1 << (mountain_h + 1)) - 2  # mountain root
    cur = mountain_h
    while cur > k:
        mid = (lo + hi) // 2
        if local_leaf < mid:
            pos = pos - 1 - ((1 << cur) - 1)  # left child root
            hi = mid
        else:
            pos = pos - 1                      # right child root
            lo = mid
        cur -= 1
    return pos


@dataclass
class RvtProof:
    """Climb siblings (bottom-up) + the other mountains' peaks (left to
    right, ours excluded). Positions are derived from (leaf_i, n_leaves)
    at verify time, so only hashes travel."""
    path: List[bytes] = field(default_factory=list)
    peaks: List[bytes] = field(default_factory=list)

    SPEC = [("path", ("list", "bytes")), ("peaks", ("list", "bytes"))]


class RangeValidationTree:
    """Leaves are block digests; leaf i = block_id i+1. Backed by an
    IDBClient family so the source's tree survives restarts and keeps
    growing lazily as blocks are added."""

    def __init__(self, db: IDBClient, family: bytes = b"rvt") -> None:
        self._db = db
        self._family = family
        raw = db.get(b"n", family + b".meta")
        self._n_leaves = int.from_bytes(raw, "big") if raw else 0

    @property
    def n_leaves(self) -> int:
        return self._n_leaves

    def _get(self, pos: int) -> bytes:
        v = self._db.get(pos.to_bytes(8, "big"), self._family)
        if v is None:
            raise ValueError(f"missing RVT node {pos}")
        return v

    def append(self, leaf_hash: bytes) -> None:
        wb = WriteBatch()
        size = _mmr_size(self._n_leaves)
        pos = size
        wb.put(pos.to_bytes(8, "big"), leaf_hash, self._family)
        written = {pos: leaf_hash}
        size += 1
        height = 0
        while _pos_height(size) > height:
            right_pos = pos
            left_pos = pos - ((1 << (height + 1)) - 1)
            left = written.get(left_pos) or self._get(left_pos)
            right = written[right_pos]
            pos = size
            parent = _h_parent(left, right)
            wb.put(pos.to_bytes(8, "big"), parent, self._family)
            written[pos] = parent
            size += 1
            height += 1
        self._n_leaves += 1
        wb.put(b"n", self._n_leaves.to_bytes(8, "big"),
               self._family + b".meta")
        self._db.write(wb)

    def _peaks(self, n_leaves: int) -> List[bytes]:
        return [self._get(ps + (1 << (h + 1)) - 2)
                for h, _lf, ps in _mountains(n_leaves)]

    def root(self, n_leaves: Optional[int] = None) -> bytes:
        """Root commitment at a historical leaf count (append-only ⇒ old
        node positions are still live)."""
        n = self._n_leaves if n_leaves is None else n_leaves
        if n == 0 or n > self._n_leaves:
            raise ValueError(f"bad leaf count {n} (have {self._n_leaves})")
        return self.compute_root(n, self._peaks(n))

    @staticmethod
    def compute_root(n_leaves: int, peaks: List[bytes]) -> bytes:
        acc = peaks[-1]
        for p in reversed(peaks[:-1]):
            acc = hashlib.sha256(_BAG + p + acc).digest()
        return hashlib.sha256(
            _ROOT + n_leaves.to_bytes(8, "big") + acc).digest()

    def prove(self, leaf_i: int, n_leaves: Optional[int] = None) -> RvtProof:
        n = self._n_leaves if n_leaves is None else n_leaves
        if not 0 <= leaf_i < n or n > self._n_leaves:
            raise ValueError(f"bad proof request leaf={leaf_i} n={n}")
        proof = RvtProof()
        for h, first_leaf, ps in _mountains(n):
            if first_leaf <= leaf_i < first_leaf + (1 << h):
                local = leaf_i - first_leaf
                for k in range(h):
                    sib_local = (local >> k) ^ 1
                    proof.path.append(self._get(
                        _node_pos(ps, h, sib_local << k, k)))
            else:
                proof.peaks.append(self._get(ps + (1 << (h + 1)) - 2))
        return proof

    @staticmethod
    def verify(root: bytes, leaf_i: int, n_leaves: int, leaf_hash: bytes,
               proof: RvtProof) -> bool:
        if not 0 <= leaf_i < n_leaves:
            return False
        peaks: List[bytes] = []
        path_iter = iter(proof.path)
        peak_iter = iter(proof.peaks)
        try:
            for h, first_leaf, _ps in _mountains(n_leaves):
                if first_leaf <= leaf_i < first_leaf + (1 << h):
                    local = leaf_i - first_leaf
                    acc = leaf_hash
                    for k in range(h):
                        sib = next(path_iter)
                        if local >> k & 1:
                            acc = _h_parent(sib, acc)
                        else:
                            acc = _h_parent(acc, sib)
                    peaks.append(acc)
                else:
                    peaks.append(next(peak_iter))
        except StopIteration:
            return False
        if (next(path_iter, None) is not None
                or next(peak_iter, None) is not None):
            return False
        return RangeValidationTree.compute_root(n_leaves, peaks) == root

    @staticmethod
    def verify_window(root: bytes, first_leaf_i: int, n_leaves: int,
                      leaf_hashes: List[bytes],
                      proofs: List[RvtProof]) -> bool:
        """Verify a contiguous window of leaves against one root — the
        per-window proof check of the pipelined state transfer (leaf
        digests arrive pre-batched from the device hash kernel)."""
        if len(leaf_hashes) != len(proofs):
            return False
        return all(
            RangeValidationTree.verify(root, first_leaf_i + k, n_leaves,
                                       lh, pr)
            for k, (lh, pr) in enumerate(zip(leaf_hashes, proofs)))

    def sync_to(self, blockchain) -> None:
        """Lazily extend with digests of blocks appended since last sync
        (the RVBManager 'add pending blocks on checkpoint' duty)."""
        while self._n_leaves < blockchain.last_block_id:
            self.append(blockchain.block_digest(self._n_leaves + 1))
