"""State-transfer wire messages, carried inside the consensus-level
StateTransferMsg envelope (reference: bcstatetransfer/Messages.hpp —
AskForCheckpointSummariesMsg, CheckpointSummaryMsg, FetchBlocksMsg,
ItemDataMsg, RejectFetchingMsg).

Concurrency contract: the destination may keep SEVERAL FetchBlocks
ranges outstanding at once, each under its own `msg_id` and each against
a different source (the pipelined fetch window). `reply_to` is therefore
the range identity — a source answers with the msg_id it was asked
under, and late/stray ItemData for a range that was re-assigned simply
misses the window and is dropped. Sources need no new state: each
FetchBlocks is still served independently."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

from tpubft.statetransfer.rvt import RvtProof
from tpubft.utils import serialize as ser


@dataclass
class AskForCheckpointSummaries:
    ID = 1
    msg_id: int = 0              # nonce echoed in replies
    min_checkpoint_seq: int = 0
    SPEC = [("msg_id", "u64"), ("min_checkpoint_seq", "u64")]


@dataclass
class CheckpointSummary:
    ID = 2
    reply_to: int = 0
    checkpoint_seq: int = 0
    state_digest: bytes = b""
    last_block: int = 0
    rvt_root: bytes = b""
    res_pages_digest: bytes = b""
    SPEC = [("reply_to", "u64"), ("checkpoint_seq", "u64"),
            ("state_digest", "bytes"), ("last_block", "u64"),
            ("rvt_root", "bytes"), ("res_pages_digest", "bytes")]

    def key(self):
        return (self.checkpoint_seq, self.state_digest, self.last_block,
                self.rvt_root, self.res_pages_digest)


@dataclass
class FetchBlocks:
    """Block-range fetch. `target_last_block` is the AGREED summary's last
    block: the source must build RVT proofs at that historical leaf count
    (the append-only MMR supports old sizes), not its own — its stable
    checkpoint may advance mid-transfer, and proofs built at the newer size
    would never verify against the agreed root (destination pins the
    agreed (root, n) for the whole transfer)."""
    ID = 3
    msg_id: int = 0
    from_block: int = 0
    to_block: int = 0
    target_last_block: int = 0
    SPEC = [("msg_id", "u64"), ("from_block", "u64"), ("to_block", "u64"),
            ("target_last_block", "u64")]


@dataclass
class ItemData:
    """One chunk of one block. INVARIANT (enforced by the destination):
    every chunk of the same block must carry the same `total_chunks` and
    the same `proof` — a source flipping either mid-block is malformed
    and is punished, so byzantine metadata can never confuse reassembly
    or smuggle a second proof past the window verification."""
    ID = 4
    reply_to: int = 0
    block_id: int = 0
    chunk_idx: int = 0
    total_chunks: int = 1
    payload: bytes = b""
    # membership proof of the whole block's digest at the agreed rvt size
    proof: RvtProof = field(default_factory=RvtProof)
    last_in_response: bool = False
    SPEC = [("reply_to", "u64"), ("block_id", "u64"), ("chunk_idx", "u32"),
            ("total_chunks", "u32"), ("payload", "bytes"),
            ("proof", ("msg", RvtProof)), ("last_in_response", "bool")]


@dataclass
class RejectFetching:
    ID = 5
    reply_to: int = 0
    reason: str = ""
    SPEC = [("reply_to", "u64"), ("reason", "str")]


@dataclass
class FetchResPages:
    """Reserved-pages fetch, after blocks are linked (reference
    FetchResPagesMsg)."""
    ID = 6
    msg_id: int = 0
    SPEC = [("msg_id", "u64")]


@dataclass
class ResPagesData:
    ID = 7
    reply_to: int = 0
    chunk_idx: int = 0
    total_chunks: int = 1
    pages: List = field(default_factory=list)  # [(page_key, page_bytes)]
    SPEC = [("reply_to", "u64"), ("chunk_idx", "u32"),
            ("total_chunks", "u32"),
            ("pages", ("list", ("pair", "bytes", "bytes")))]


_TYPES = {cls.ID: cls for cls in
          (AskForCheckpointSummaries, CheckpointSummary, FetchBlocks,
           ItemData, RejectFetching, FetchResPages, ResPagesData)}


def pack(msg) -> bytes:
    return bytes([msg.ID]) + ser.encode_msg(msg)


def unpack(data: bytes):
    if not data or data[0] not in _TYPES:
        raise ser.SerializeError(f"unknown ST msg id {data[:1]!r}")
    return ser.decode_msg(data[1:], _TYPES[data[0]])
