"""Cluster topology arithmetic.

Rebuild of the reference's ReplicasInfo
(/root/reference/bftengine/src/bftengine/ReplicasInfo.hpp): replica/client
id ranges, primary-of-view, and collector selection for threshold shares.
"""
from __future__ import annotations

from dataclasses import dataclass

from tpubft.utils.config import ReplicaConfig


@dataclass(frozen=True)
class ReplicasInfo:
    n: int
    f: int
    c: int
    num_ro: int = 0
    num_clients: int = 16

    @classmethod
    def from_config(cls, cfg: ReplicaConfig) -> "ReplicasInfo":
        return cls(n=cfg.n_val, f=cfg.f_val, c=cfg.c_val,
                   num_ro=cfg.num_ro_replicas,
                   num_clients=cfg.num_of_client_proxies)

    # ---- id ranges (reference convention: replicas, then RO, then clients)
    @property
    def replica_ids(self) -> range:
        return range(self.n)

    @property
    def first_client_id(self) -> int:
        return self.n + self.num_ro

    def is_replica(self, node: int) -> bool:
        return 0 <= node < self.n

    @property
    def ro_replica_ids(self) -> range:
        """Read-only replicas (reference ReadOnlyReplica): ST-only nodes
        squeezed between the voting set and the clients."""
        return range(self.n, self.n + self.num_ro)

    def is_ro_replica(self, node: int) -> bool:
        return self.n <= node < self.n + self.num_ro

    def is_client(self, node: int) -> bool:
        return node >= self.first_client_id

    # ---- internal clients (reference InternalBFTClient principals) ----
    @property
    def first_internal_client_id(self) -> int:
        return self.first_client_id + self.num_clients

    def internal_client_of(self, replica_id: int) -> int:
        return self.first_internal_client_id + replica_id

    def is_internal_client(self, node: int) -> bool:
        return (self.first_internal_client_id <= node
                < self.first_internal_client_id + self.n)

    def owner_of_internal_client(self, node: int) -> int:
        return node - self.first_internal_client_id

    @property
    def operator_id(self) -> int:
        """The operator principal (reconfiguration commands must carry its
        signature — reference: operator key validation in
        reconfiguration/src/reconfiguration_handler.cpp)."""
        return self.first_internal_client_id + self.n

    def all_client_ids(self) -> range:
        """External clients + one internal client per replica + operator.
        The id space is contiguous by construction (externals, then one
        internal per replica, then the operator), so the universe is a
        `range` — O(1) membership with O(1) memory, which is what keeps
        million-principal topologies from materializing million-entry
        sets in every consumer (ClientsManager, admission gates)."""
        return range(self.first_client_id, self.operator_id + 1)

    def other_replicas(self, me: int) -> list:
        return [r for r in self.replica_ids if r != me]

    # ---- roles ----
    def primary_of_view(self, view: int) -> int:
        return view % self.n

    def collector_for(self, view: int, seq_num: int) -> int:
        """Collector of threshold shares for (view, seq). The reference
        supports rotating collectors (getCollectorsForPartialProofs); the
        primary is the default collector."""
        return self.primary_of_view(view)

    # ---- quorums ----
    @property
    def slow_quorum(self) -> int:
        return 2 * self.f + self.c + 1

    @property
    def fast_threshold_quorum(self) -> int:
        return 3 * self.f + self.c + 1

    @property
    def optimistic_quorum(self) -> int:
        return self.n

    @property
    def checkpoint_quorum(self) -> int:
        """2f + c + 1 matching signed CheckpointMsgs make a checkpoint
        STABLE (reference CheckpointInfo.hpp MsgsCertificate): with at most
        f Byzantine confirmers, stability implies f+1 honest replicas hold
        the state, so the window can be GC'd safely. f+1 matching digests
        (st_anchor_quorum) are enough only as a state-transfer trust
        anchor — at least one honest signer vouches for the digest."""
        return 2 * self.f + self.c + 1

    @property
    def st_anchor_quorum(self) -> int:
        return self.f + 1

    @property
    def view_change_quorum(self) -> int:
        """2f + 2c + 1 ViewChangeMsgs form a new-view certificate
        (reference ViewsManager)."""
        return 2 * self.f + 2 * self.c + 1

    @property
    def complaint_quorum(self) -> int:
        """f + 1 ReplicaAsksToLeaveView complaints trigger a view change."""
        return self.f + 1
