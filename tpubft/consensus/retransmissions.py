"""Ack-tracked retransmission with dynamic per-destination timeouts.

Rebuild of the reference's RetransmissionsManager
(/root/reference/bftengine/src/bftengine/RetransmissionsManager.cpp,
consumed via sendRetransmittableMsgToReplica, ReplicaImp.cpp:2531) and
its DynamicUpperLimitWithSimpleFilter RTT model: protocol messages whose
loss stalls consensus (shares to the collector, the primary's
PrePrepares, the collector's combined certificates) are tracked per
(destination, msg code, seqnum); the receiver acks with SimpleAckMsg;
unacked entries are re-sent with exponentially backed-off timeouts
derived from a per-destination RTT estimate, and dropped once the seqnum
stabilizes, the view changes, or attempts run out (at which point the
status-beacon gap resend and view-change liveness take over).

Acks are unauthenticated (as in the reference): a spoofed ack can only
suppress a retransmission — the same power a packet-dropping network
attacker already has; safety never depends on retransmission.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Tuple

from tpubft.utils.logging import get_logger

log = get_logger("retransmissions")


class RttEstimator:
    """EWMA of observed ack round-trips with a clamped dynamic timeout
    (the DynamicUpperLimitWithSimpleFilter role)."""

    def __init__(self, min_timeout_s: float, max_timeout_s: float):
        self._min = min_timeout_s
        self._max = max_timeout_s
        self._ewma: float = 0.0
        self._have = False

    def observe(self, rtt_s: float) -> None:
        if not self._have:
            self._ewma, self._have = rtt_s, True
        else:
            self._ewma = 0.8 * self._ewma + 0.2 * rtt_s

    def timeout_s(self) -> float:
        if not self._have:
            return self._max / 4
        return min(self._max, max(self._min, 3.0 * self._ewma))


@dataclass
class _Entry:
    raw: bytes
    view: int
    first_sent: float
    next_due: float
    attempts: int = 0


class RetransmissionsManager:
    MAX_ATTEMPTS = 10
    MAX_TRACKED = 5000                 # memory bound (reference PARM)

    def __init__(self, comm, min_timeout_ms: int = 20,
                 max_timeout_ms: int = 1000):
        self._comm = comm
        self._min_s = min_timeout_ms / 1e3
        self._max_s = max_timeout_ms / 1e3
        # (dest, msg_code, seq) -> entry; mutated on the dispatcher thread
        self._entries: Dict[Tuple[int, int, int], _Entry] = {}
        self._rtt: Dict[int, RttEstimator] = {}
        self._lock = threading.Lock()
        self.total_retransmitted = 0

    def _est(self, dest: int) -> RttEstimator:
        est = self._rtt.get(dest)
        if est is None:
            est = self._rtt[dest] = RttEstimator(self._min_s, self._max_s)
        return est

    def track(self, dest: int, code: int, seq: int, view: int,
              raw: bytes, now: float) -> None:
        """Register a just-sent retransmittable message."""
        with self._lock:
            if len(self._entries) >= self.MAX_TRACKED:
                return
            self._entries[(dest, code, seq)] = _Entry(
                raw=raw, view=view, first_sent=now,
                next_due=now + self._est(dest).timeout_s())

    def on_ack(self, dest: int, code: int, seq: int, now: float) -> None:
        with self._lock:
            e = self._entries.pop((dest, code, seq), None)
            if e is not None and e.attempts == 0:
                # only un-retransmitted messages give a clean RTT sample
                self._est(dest).observe(now - e.first_sent)

    def tick(self, now: float) -> None:
        """Resend overdue entries (exponential backoff per attempt)."""
        due = []
        with self._lock:
            for key, e in self._entries.items():
                if now >= e.next_due:
                    e.attempts += 1
                    if e.attempts > self.MAX_ATTEMPTS:
                        due.append((key, None))
                        continue
                    backoff = self._est(key[0]).timeout_s() * (2 ** e.attempts)
                    e.next_due = now + min(backoff, self._max_s)
                    due.append((key, e.raw))
            for key, raw in due:
                if raw is None:
                    del self._entries[key]
        for (dest, code, seq), raw in due:
            if raw is not None:
                self.total_retransmitted += 1
                self._comm.send(dest, raw)

    def gc_stable(self, stable_seq: int) -> None:
        """A stabilized seqnum no longer needs its messages delivered."""
        with self._lock:
            for key in [k for k in self._entries if k[2] <= stable_seq]:
                del self._entries[key]

    def clear_view(self, view: int) -> None:
        """View changed: in-flight ordering messages of older views are
        dead letters."""
        with self._lock:
            for key in [k for k, e in self._entries.items()
                        if e.view < view]:
                del self._entries[key]

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._entries)

    def is_pending(self, dest: int, code: int, seq: int) -> bool:
        """True while a tracked send has not been acked — the aggregation
        fallback uses this as dead-parent evidence: a parent that acked
        the share is alive (the slot is just slow) and must not be
        routed around."""
        with self._lock:
            return (dest, code, seq) in self._entries
