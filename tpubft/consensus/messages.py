"""Consensus wire messages.

Rebuild of /root/reference/bftengine/src/bftengine/messages/ (MsgCode.hpp:24,
MessageBase.hpp, PrePrepareMsg.hpp:33-53, SignedShareMsgs.hpp,
FullCommitProofMsg.hpp, CheckpointMsg.hpp, ViewChangeMsg.hpp, …).

Instead of hand-packed C structs, every message is a dataclass serialized
with the canonical codec (tpubft.utils.serialize); the wire envelope is
  u16 msg_code | body
Signed messages carry their signature as the last field; `signed_payload()`
is the canonical encoding of everything before it, so signing and verifying
never disagree about byte layout.

`sender_id` is part of the body (as in the reference's MessageBase header,
MessageBase.hpp senderId) — receivers must check it against the transport's
reported sender before trusting it.
"""
from __future__ import annotations

import enum
import struct
from dataclasses import dataclass, field
from typing import ClassVar, Dict, List, Optional, Tuple, Type

from tpubft.crypto.digest import calc_combination, digest as sha256
from tpubft.utils import serialize as ser


class MsgCode(enum.IntEnum):
    """Wire discriminants (reference MsgCode.hpp:24-; values are ours)."""
    ClientRequest = 1
    ClientReply = 2
    PrePrepare = 3
    StartSlowCommit = 4
    PreparePartial = 5
    PrepareFull = 6
    CommitPartial = 7
    CommitFull = 8
    PartialCommitProof = 9
    FullCommitProof = 10
    Checkpoint = 11
    SimpleAck = 12
    ViewChange = 13
    NewView = 14
    ReqMissingData = 15
    ReplicaStatus = 16
    ReplicaAsksToLeaveView = 17
    StateTransfer = 18
    ReplicaRestartReady = 19
    RestartProof = 20
    PreProcessRequest = 21
    PreProcessReply = 22
    ReqViewPrePrepare = 23
    ClientBatchRequest = 24
    PreProcessBatchRequest = 25
    PreProcessBatchReply = 26
    AskForCheckpoint = 27
    TimeOpinion = 28
    AggregateShare = 29


class RequestFlag(enum.IntFlag):
    """ClientRequestMsg flags (reference ClientMsgs.hpp)."""
    EMPTY = 0
    READ_ONLY = 1
    PRE_PROCESS = 2
    HAS_PRE_PROCESSED = 4
    KEY_EXCHANGE = 8
    INTERNAL = 16
    RECONFIG = 32
    TICK = 64


class CommitPath(enum.IntEnum):
    """The three commit paths (reference ReplicaConfig / PrePrepareMsg
    firstPath): OPTIMISTIC_FAST needs n sigs, FAST_WITH_THRESHOLD needs
    3f+c+1, SLOW is two PBFT-like rounds of 2f+c+1."""
    OPTIMISTIC_FAST = 0
    FAST_WITH_THRESHOLD = 1
    SLOW = 2


_REGISTRY: Dict[int, Type["ConsensusMsg"]] = {}


def register(cls: Type["ConsensusMsg"]) -> Type["ConsensusMsg"]:
    assert cls.CODE not in _REGISTRY, cls
    _REGISTRY[int(cls.CODE)] = cls
    return cls


class MsgError(Exception):
    """Structurally invalid message (reference throws from validate())."""


class ConsensusMsg:
    """Mixin for dataclass messages; subclasses set CODE and SPEC."""
    CODE: ClassVar[MsgCode]
    SPEC: ClassVar[list]

    def pack(self) -> bytes:
        buf = bytearray(struct.pack("<H", int(self.CODE)))
        ser.encode_msg_into(buf, self)
        return bytes(buf)

    def signed_payload(self) -> bytes:
        """Canonical bytes covered by this message's signature: the msg
        code + every field before the trailing `signature`."""
        assert self.SPEC and self.SPEC[-1][0] == "signature", type(self)
        buf = bytearray(struct.pack("<H", int(self.CODE)))
        for name, spec in self.SPEC[:-1]:
            ser.encode_value(buf, spec, getattr(self, name))
        return bytes(buf)

    def validate(self) -> None:
        """Structural checks; raises MsgError. Signature checks live in
        SigManager/collector paths where keys are known."""


def unpack(data: bytes) -> ConsensusMsg:
    if len(data) < 2:
        raise MsgError("short message")
    (code,) = struct.unpack_from("<H", data)
    cls = _REGISTRY.get(code)
    if cls is None:
        raise MsgError(f"unknown msg code {code}")
    try:
        msg = ser.decode_msg(data[2:], cls)
    except MsgError:
        raise
    except Exception as e:  # noqa: BLE001 — untrusted bytes: any decode
        # failure (SerializeError, UnicodeDecodeError, …) is a bad message,
        # never an exception that may kill the receive path
        raise MsgError(f"{cls.__name__}: {e}") from e
    msg.validate()
    return msg


# ---------------- client <-> replica ----------------

@register
@dataclass
class ClientRequestMsg(ConsensusMsg):
    """Reference ClientRequestMsg.hpp: client-signed command submission."""
    CODE = MsgCode.ClientRequest
    sender_id: int
    req_seq_num: int
    flags: int
    request: bytes
    cid: str                      # correlation id (reference spanContext/cid)
    signature: bytes
    SPEC = [("sender_id", "u32"), ("req_seq_num", "u64"), ("flags", "u32"),
            ("request", "bytes"), ("cid", "str"), ("signature", "bytes")]

    def digest(self) -> bytes:
        return sha256(self.signed_payload())

    def validate(self) -> None:
        if not self.request and not self.flags & RequestFlag.READ_ONLY:
            raise MsgError("empty write request")


@register
@dataclass
class ClientBatchRequestMsg(ConsensusMsg):
    """Reference preprocessor/messages/ClientBatchRequestMsg.hpp: several
    individually-signed ClientRequestMsgs from ONE client ride a single
    wire message. The replica unpacks and admits each element; their
    signatures then verify as one cross-request device batch in the
    admission plane, so client batching composes with the TPU seam."""
    CODE = MsgCode.ClientBatchRequest
    sender_id: int
    cid: str
    requests: list                # packed ClientRequestMsg frames
    signature: bytes              # unused — authenticity is per element
    SPEC = [("sender_id", "u32"), ("cid", "str"),
            ("requests", ("list", "bytes")), ("signature", "bytes")]

    # also sizes the per-client reply cache (clients_manager) — every
    # element of an executed batch must stay regenerable for
    # retransmission recovery, so the cache covers one full batch
    MAX_BATCH: ClassVar[int] = 64

    def validate(self) -> None:
        if not self.requests:
            raise MsgError("empty client batch")
        if len(self.requests) > self.MAX_BATCH:
            raise MsgError(
                f"client batch of {len(self.requests)} > {self.MAX_BATCH}")


@register
@dataclass
class ClientReplyMsg(ConsensusMsg):
    """Reference ClientReplyMsg.hpp: execution result returned to client."""
    CODE = MsgCode.ClientReply
    sender_id: int                # replying replica
    req_seq_num: int
    current_primary: int
    reply: bytes
    replica_specific_info: bytes  # RSI — differs per replica, excluded from
                                  # quorum matching (reference rsiLength)
    # per-replica signature over the preceding fields (trailing, so
    # signed_payload() covers everything before it). Empty on the
    # certificate-backed path; populated under optimistic replies
    # (ReplicaConfig.optimistic_replies), where the client's f+1
    # matching quorum rests on these individual signatures instead of
    # the threshold certificate (arXiv 2407.12172). The canonical
    # persisted reply-ring form always zeroes it, so ledger/page bytes
    # are identical with the mode on or off.
    signature: bytes = b""
    SPEC = [("sender_id", "u32"), ("req_seq_num", "u64"),
            ("current_primary", "u32"), ("reply", "bytes"),
            ("replica_specific_info", "bytes"), ("signature", "bytes")]

    def matching_digest(self) -> bytes:
        """Digest over the parts that must match across replicas (the
        per-replica signature and RSI are excluded)."""
        return sha256(struct.pack("<Q", self.req_seq_num) + self.reply)


# ---------------- ordering ----------------

@register
@dataclass
class PrePrepareMsg(ConsensusMsg):
    """Reference PrePrepareMsg.hpp:33-53: the primary's batch proposal.

    `requests` holds packed ClientRequestMsgs; `requests_digest` commits to
    them; `time` is the primary's timestamp voted on by the time service.
    """
    CODE = MsgCode.PrePrepare
    sender_id: int
    view: int
    seq_num: int
    first_path: int               # CommitPath the primary starts on
    time: int                     # microseconds since epoch
    requests_digest: bytes
    requests: List[bytes]
    signature: bytes
    # reconfiguration era (reference PrePrepareMsg epochNum, stamped from
    # EpochManager); inside the signed payload, rejected on mismatch
    epoch: int = 0
    SPEC = [("sender_id", "u32"), ("view", "u64"), ("seq_num", "u64"),
            ("first_path", "u8"), ("time", "u64"),
            ("requests_digest", "bytes"), ("requests", ("list", "bytes")),
            ("epoch", "u64"), ("signature", "bytes")]

    @staticmethod
    def compute_requests_digest(requests: List[bytes]) -> bytes:
        h = bytearray()
        for r in requests:
            h += sha256(r)
        return sha256(bytes(h))

    def digest(self) -> bytes:
        """Digest of the proposal identity (digestOfRequests + seq/view),
        the value threshold signatures commit to."""
        return calc_combination(self.requests_digest, self.view, self.seq_num)

    def validate(self) -> None:
        if self.first_path not in (0, 1, 2):
            raise MsgError("bad commit path")
        if self.requests_digest != self.compute_requests_digest(self.requests):
            raise MsgError("requests digest mismatch")

    def client_requests(self) -> List[ClientRequestMsg]:
        # memoized: the batch is parsed once (by the admission plane when
        # it is on, by the first handler otherwise) and every later
        # consumer — structural checks, barrier classification, execution
        # — reuses the same objects. Safe because `requests` is never
        # mutated after construction/decode.
        cached = getattr(self, "_reqs_cache", None)
        if cached is not None:
            return cached
        out = []
        for raw in self.requests:
            m = unpack(raw)
            if not isinstance(m, ClientRequestMsg):
                raise MsgError("non-request in PrePrepare batch")
            out.append(m)
        self._reqs_cache = out
        return out


def commit_digest(view: int, seq_num: int, pp_digest: bytes) -> bytes:
    """Digest::calcCombination(ppDigest, view, seq) equivalent
    (reference ReplicaImp.cpp:1344): the value signed by commit-path
    threshold shares."""
    return calc_combination(pp_digest, view, seq_num)


@register
@dataclass
class StartSlowCommitMsg(ConsensusMsg):
    """Reference StartSlowCommitMsg.hpp: primary demotes seq to slow path."""
    CODE = MsgCode.StartSlowCommit
    sender_id: int
    view: int
    seq_num: int
    epoch: int = 0
    SPEC = [("sender_id", "u32"), ("view", "u64"), ("seq_num", "u64"),
            ("epoch", "u64")]


@dataclass
class _SignedShareBase(ConsensusMsg):
    """Reference SignedShareMsgs.hpp SignedShareBase: a threshold-signature
    share (or combined signature) over the commit digest for (view, seq)."""
    sender_id: int
    view: int
    seq_num: int
    digest: bytes                 # share_digest(kind, epoch, view, seq, ppD)
    sig: bytes                    # share (Partial) or combined (Full)
    epoch: int = 0                # reconfiguration era (SignedShareMsgs
                                  # carry epochNum in the reference too).
                                  # The era is ALSO bound inside `digest`
                                  # (replica.share_digest), so the gate on
                                  # these messages is authenticated — this
                                  # wire field is a fast-drop hint only
    SPEC = [("sender_id", "u32"), ("view", "u64"), ("seq_num", "u64"),
            ("digest", "bytes"), ("sig", "bytes"), ("epoch", "u64")]

    def validate(self) -> None:
        if len(self.digest) != 32:
            raise MsgError("bad digest length")
        if not self.sig:
            raise MsgError("empty signature share")


@register
@dataclass
class PreparePartialMsg(_SignedShareBase):
    CODE = MsgCode.PreparePartial


@register
@dataclass
class PrepareFullMsg(_SignedShareBase):
    CODE = MsgCode.PrepareFull


@register
@dataclass
class CommitPartialMsg(_SignedShareBase):
    CODE = MsgCode.CommitPartial


@register
@dataclass
class CommitFullMsg(_SignedShareBase):
    CODE = MsgCode.CommitFull


@register
@dataclass
class PartialCommitProofMsg(_SignedShareBase):
    """Fast-path share (reference PartialCommitProofMsg.hpp); `path` tells
    the collector which quorum size applies."""
    CODE = MsgCode.PartialCommitProof
    path: int = int(CommitPath.OPTIMISTIC_FAST)
    SPEC = _SignedShareBase.SPEC + [("path", "u8")]

    def validate(self) -> None:
        super().validate()
        if self.path not in (0, 1):
            raise MsgError("bad fast path")


@register
@dataclass
class FullCommitProofMsg(_SignedShareBase):
    """Fast-path combined proof (reference FullCommitProofMsg.hpp) —
    possession is a commit certificate."""
    CODE = MsgCode.FullCommitProof


@register
@dataclass
class AggregateShareMsg(ConsensusMsg):
    """A PARTIAL AGGREGATE climbing the share-aggregation overlay
    (ISSUE 17, arXiv 1911.04698): an interior node's sum of its
    subtree's Prepare/Commit shares, self-authenticating via the
    contributor bitmap inside `agg` (crypto/systems.pack_agg_cert —
    the root verifies it against the bitmap's aggregate public key, so
    a forged partial indicts exactly the forwarding subtree). `kind`
    is the share family ("prepare"=0 / "commit"=1); fast-path shares
    never aggregate (they are already one datagram to the collector).
    NOT relay-safe: the transport sender is the accountable forwarder
    for retransmission/ack and bad-subtree isolation."""
    CODE = MsgCode.AggregateShare
    sender_id: int
    view: int
    seq_num: int
    kind: int                     # 0 = prepare share family, 1 = commit
    digest: bytes                 # share_digest(kind, epoch, view, seq, ppD)
    agg: bytes                    # pack_agg_cert: u64 bitmap + 48B G1 sum
    epoch: int = 0
    SPEC = [("sender_id", "u32"), ("view", "u64"), ("seq_num", "u64"),
            ("kind", "u8"), ("digest", "bytes"), ("agg", "bytes"),
            ("epoch", "u64")]

    def validate(self) -> None:
        if self.kind not in (0, 1):
            raise MsgError("bad aggregate share kind")
        if len(self.digest) != 32:
            raise MsgError("bad digest length")
        if len(self.agg) != 56:
            raise MsgError("bad partial aggregate length")


# ---------------- checkpointing ----------------

@register
@dataclass
class AskForCheckpointMsg(ConsensusMsg):
    """Any node → a replica: please (re)send your latest self
    CheckpointMsg (reference AskForCheckpointMsg.hpp — sent periodically
    by read-only replicas so a late joiner doesn't wait a whole
    checkpoint window for the next broadcast). Unsigned: the reply is
    bounded, already-signed traffic."""
    CODE = MsgCode.AskForCheckpoint
    sender_id: int
    SPEC = [("sender_id", "u32")]


@register
@dataclass
class CheckpointMsg(ConsensusMsg):
    """Reference CheckpointMsg.hpp: signed app-state digest at a checkpoint
    seqnum (every checkpointWindowSize=150); f+1 matching ⇒ stable."""
    CODE = MsgCode.Checkpoint
    sender_id: int
    seq_num: int
    state_digest: bytes
    is_stable: bool
    # reserved-pages digest is part of the signed certificate (reference
    # CheckpointMsg carries stateDigest + reservedPagesDigest + rvbDigest)
    res_pages_digest: bytes = b""
    signature: bytes = b""
    # era of the certifying replica: lower-epoch checkpoints are stale
    # and dropped; higher-epoch ones are evidence this replica lags a
    # reconfiguration and feed state-transfer catch-up
    epoch: int = 0
    SPEC = [("sender_id", "u32"), ("seq_num", "u64"),
            ("state_digest", "bytes"), ("is_stable", "bool"),
            ("res_pages_digest", "bytes"), ("epoch", "u64"),
            ("signature", "bytes")]


@register
@dataclass
class SimpleAckMsg(ConsensusMsg):
    """Reference SimpleAckMsg.hpp: ack for retransmittable msgs."""
    CODE = MsgCode.SimpleAck
    sender_id: int
    seq_num: int
    view: int
    acked_msg_code: int
    epoch: int = 0
    SPEC = [("sender_id", "u32"), ("seq_num", "u64"), ("view", "u64"),
            ("acked_msg_code", "u16"), ("epoch", "u64")]


@register
@dataclass
class TimeOpinionMsg(ConsensusMsg):
    """A replica's signed clock reading (time-service voting extension of
    the reference TimeServiceManager.hpp model, where each replica only
    bounds the primary's stamp against its LOCAL clock): collecting f+1
    fresh opinions lets every replica bound the primary against the
    CLUSTER's median clock, so one fast primary + one fast backup clock
    cannot drift the agreed time."""
    CODE = MsgCode.TimeOpinion
    sender_id: int
    t_ms: int                     # sender's clock, ms since epoch
    signature: bytes
    epoch: int = 0
    SPEC = [("sender_id", "u32"), ("t_ms", "u64"), ("epoch", "u64"),
            ("signature", "bytes")]


# ---------------- pre-execution (reference src/preprocessor/messages) ----

@register
@dataclass
class PreProcessRequestMsg(ConsensusMsg):
    """Primary → all replicas: speculatively execute this client request
    (reference PreProcessRequestMsg.hpp)."""
    CODE = MsgCode.PreProcessRequest
    sender_id: int              # the primary
    client_id: int
    req_seq_num: int
    retry_id: int
    request: bytes              # packed original ClientRequestMsg
    SPEC = [("sender_id", "u32"), ("client_id", "u32"),
            ("req_seq_num", "u64"), ("retry_id", "u64"),
            ("request", "bytes")]


@register
@dataclass
class PreProcessBatchRequestMsg(ConsensusMsg):
    """Primary → all replicas: a GROUP of PreProcessRequestMsgs for one
    client, one wire message (reference PreProcessBatchRequestMsg.hpp —
    the wire-level half of client batching: per-element sessions,
    grouped transport)."""
    CODE = MsgCode.PreProcessBatchRequest
    sender_id: int              # the primary
    client_id: int
    batch_id: int               # primary-local group id for reply folding
    requests: list              # packed PreProcessRequestMsg frames
    SPEC = [("sender_id", "u32"), ("client_id", "u32"),
            ("batch_id", "u64"), ("requests", ("list", "bytes"))]

    def validate(self) -> None:
        if not self.requests:
            raise MsgError("empty preprocess batch")
        if len(self.requests) > ClientBatchRequestMsg.MAX_BATCH:
            raise MsgError("preprocess batch too large")


@register
@dataclass
class PreProcessBatchReplyMsg(ConsensusMsg):
    """Replica → primary: all of a batch's speculative-result replies
    folded into one wire message (reference PreProcessBatchReplyMsg.hpp)."""
    CODE = MsgCode.PreProcessBatchReply
    sender_id: int
    client_id: int
    batch_id: int
    replies: list               # packed PreProcessReplyMsg frames
    SPEC = [("sender_id", "u32"), ("client_id", "u32"),
            ("batch_id", "u64"), ("replies", ("list", "bytes"))]

    def validate(self) -> None:
        if not self.replies:
            raise MsgError("empty preprocess batch reply")
        if len(self.replies) > ClientBatchRequestMsg.MAX_BATCH:
            raise MsgError("preprocess batch reply too large")


@register
@dataclass
class PreProcessReplyMsg(ConsensusMsg):
    """Replica → primary: signed digest of its speculative result
    (reference PreProcessReplyMsg.hpp)."""
    CODE = MsgCode.PreProcessReply
    sender_id: int
    client_id: int
    req_seq_num: int
    retry_id: int
    result_digest: bytes
    status: int                 # 0 = ok, 1 = rejected/unsupported
    signature: bytes            # over preexec_digest binding below
    SPEC = [("sender_id", "u32"), ("client_id", "u32"),
            ("req_seq_num", "u64"), ("retry_id", "u64"),
            ("result_digest", "bytes"), ("status", "u8"),
            ("signature", "bytes")]


@dataclass
class PreProcessResult:
    """The ordered artifact replacing the raw request: original request +
    agreed speculative result + f+1 replica signatures (reference
    PreProcessResultMsg.hpp — a ClientRequestMsg subclass on the wire;
    here it is the wrapper request's payload)."""
    original: bytes             # packed original ClientRequestMsg
    result: bytes
    signatures: list            # [(replica_id, sig)]
    SPEC = [("original", "bytes"), ("result", "bytes"),
            ("signatures", ("list", ("pair", "u32", "bytes")))]


def preexec_digest(client_id: int, req_seq: int, original: bytes,
                   result: bytes) -> bytes:
    """What PreProcessReply signatures cover: the binding of a concrete
    request to its speculative result."""
    return sha256(b"preexec" + struct.pack("<IQ", client_id, req_seq)
                  + sha256(original) + sha256(result))


# ---------------- view change ----------------

@dataclass
class PreparedCertificate:
    """Evidence inside ViewChangeMsg that a seqnum may have committed in an
    earlier view (reference ViewChangeMsg element + PrepareFull proof).

    Carries only the PrePrepare DIGEST, not the batch body — the reference
    ships digests and fetches missing PrePrepares during view entry
    (ReplicaImp.cpp:1078 addPotentiallyMissingPP); embedding bodies made a
    ViewChangeMsg O(batch x window) bytes."""
    seq_num: int
    view: int                     # view in which it was prepared
    kind: int                     # which threshold system signed it
                                  # (view_change.CERT_* constants)
    pp_digest: bytes
    combined_sig: bytes           # PrepareFull/FullCommitProof combined sig
    SPEC = [("seq_num", "u64"), ("view", "u64"), ("kind", "u8"),
            ("pp_digest", "bytes"), ("combined_sig", "bytes")]


@register
@dataclass
class ViewChangeMsg(ConsensusMsg):
    """Reference ViewChangeMsg.hpp: replica's signed statement entering a
    new view: last stable checkpoint + prepared certificates in-flight."""
    CODE = MsgCode.ViewChange
    sender_id: int
    new_view: int
    last_stable_seq: int
    prepared: List[PreparedCertificate]
    signature: bytes
    # a dead-era ViewChangeMsg must not count toward a live-era f+1
    # view-change threshold — epoch rides the signed payload like the
    # other ordering messages
    epoch: int = 0
    SPEC = [("sender_id", "u32"), ("new_view", "u64"),
            ("last_stable_seq", "u64"),
            ("prepared", ("list", ("msg", PreparedCertificate))),
            ("epoch", "u64"), ("signature", "bytes")]

    def digest(self) -> bytes:
        return sha256(self.signed_payload())


@dataclass
class ReplicaDigest:
    """(replica id, digest-or-signature bytes) pair used in certificates."""
    replica: int
    digest: bytes
    SPEC = [("replica", "u32"), ("digest", "bytes")]


@register
@dataclass
class NewViewMsg(ConsensusMsg):
    """Reference NewViewMsg.hpp: new primary's certificate — digests of the
    2f+2c+1 ViewChangeMsgs it built the new view from."""
    CODE = MsgCode.NewView
    sender_id: int
    new_view: int
    view_change_digests: List[ReplicaDigest]
    signature: bytes
    epoch: int = 0
    SPEC = [("sender_id", "u32"), ("new_view", "u64"),
            ("view_change_digests", ("list", ("msg", ReplicaDigest))),
            ("epoch", "u64"), ("signature", "bytes")]


@register
@dataclass
class ReplicaAsksToLeaveViewMsg(ConsensusMsg):
    """Reference ReplicaAsksToLeaveViewMsg.hpp: signed view-change
    complaint; f+1 of these start an actual view change."""
    CODE = MsgCode.ReplicaAsksToLeaveView
    sender_id: int
    view: int
    reason: int                   # enum: timeout=0, primary-misbehavior=1…
    signature: bytes
    epoch: int = 0                # dead-era complaints must not count
                                  # toward a live-era f+1 threshold
    SPEC = [("sender_id", "u32"), ("view", "u64"), ("reason", "u8"),
            ("epoch", "u64"), ("signature", "bytes")]


# ---------------- recovery / status ----------------

@register
@dataclass
class ReqMissingDataMsg(ConsensusMsg):
    """Reference ReqMissingDataMsg.hpp: ask a peer for missing protocol
    msgs for a seqnum (bitmask of what's needed)."""
    CODE = MsgCode.ReqMissingData
    sender_id: int
    view: int
    seq_num: int
    missing: int                  # bitmask: 1=PrePrepare, 2=PrepareFull,
                                  # 4=CommitFull, 8=FullCommitProof
    SPEC = [("sender_id", "u32"), ("view", "u64"), ("seq_num", "u64"),
            ("missing", "u32")]


@register
@dataclass
class ReqViewPrePrepareMsg(ConsensusMsg):
    """Fetch an old-view PrePrepare body referenced (by digest) from a
    view-change restriction (reference addPotentiallyMissingPP,
    ReplicaImp.cpp:1078): ViewChangeMsgs carry digests only, so a replica
    entering `new_view` must obtain any batch body it lacks before it can
    re-propose or validate re-proposals. Unsigned like ReqMissingData —
    a spoofed request costs a bounded resend. The response is the raw
    packed original PrePrepareMsg; the requester authenticates it by
    digest, which the threshold certificate already certifies."""
    CODE = MsgCode.ReqViewPrePrepare
    sender_id: int
    new_view: int                 # view being entered (routing/context)
    seq_num: int
    pp_digest: bytes
    SPEC = [("sender_id", "u32"), ("new_view", "u64"), ("seq_num", "u64"),
            ("pp_digest", "bytes")]


# Capability bits advertised in ReplicaStatusMsg.capabilities (ROADMAP
# 4a, first half): a wire-visible declaration of optional planes so
# mixed clusters are DETECTABLE — peers record what each replica
# advertises (surfaced via `status get health`), clients infer the
# optimistic plane from signed replies. No negotiation logic rides
# these bits yet; they are observability, not protocol.
CAP_OPT_REPLIES = 1 << 0     # optimistic reply plane active
CAP_OFFLOAD = 1 << 1         # verified crypto-offload tier configured


@register
@dataclass
class ReplicaStatusMsg(ConsensusMsg):
    """Reference ReplicaStatusMsg.hpp: periodic gap-detection beacon.
    Carries the sender's capability bitmap (see CAP_*): status beacons
    reach every peer on a timer, making them the natural place to
    advertise optional planes without a new message type."""
    CODE = MsgCode.ReplicaStatus
    sender_id: int
    view: int
    last_stable_seq: int
    last_executed_seq: int
    in_view_change: bool
    capabilities: int = 0
    SPEC = [("sender_id", "u32"), ("view", "u64"), ("last_stable_seq", "u64"),
            ("last_executed_seq", "u64"), ("in_view_change", "bool"),
            ("capabilities", "u32")]


@register
@dataclass
class StateTransferMsg(ConsensusMsg):
    """Opaque envelope for the state-transfer module's own messages
    (reference StateTransferMsg.hpp → BCStateTran wire msgs)."""
    CODE = MsgCode.StateTransfer
    sender_id: int
    payload: bytes
    SPEC = [("sender_id", "u32"), ("payload", "bytes")]


@register
@dataclass
class ReplicaRestartReadyMsg(ConsensusMsg):
    """Reference ReplicaRestartReadyMsg.hpp: signed 'ready to restart' vote
    (n/n super-stable wedge for upgrades)."""
    CODE = MsgCode.ReplicaRestartReady
    sender_id: int
    seq_num: int
    reason: int
    signature: bytes
    epoch: int = 0
    SPEC = [("sender_id", "u32"), ("seq_num", "u64"), ("reason", "u8"),
            ("epoch", "u64"), ("signature", "bytes")]


@register
@dataclass
class RestartProofMsg(ConsensusMsg):
    """Reference RestartProofMsg: n ReplicaRestartReady sigs combined."""
    CODE = MsgCode.RestartProof
    sender_id: int
    seq_num: int
    signatures: List[ReplicaDigest]
    SPEC = [("sender_id", "u32"), ("seq_num", "u64"),
            ("signatures", ("list", ("msg", ReplicaDigest)))]


# Messages carrying their own end-to-end signature (replica sig or
# threshold combined sig, verified in their handlers): relay-safe — the
# transport sender may legitimately differ from sender_id (gap-resend +
# ReqMissingData flows forward them on the original's behalf). Shared by
# the dispatcher's anti-spoofing gate and the admission plane's
# stateless pre-drop, so the two can never disagree.
RELAY_SAFE = (PrePrepareMsg, PrepareFullMsg, CommitFullMsg,
              FullCommitProofMsg, ViewChangeMsg, NewViewMsg, CheckpointMsg)


def known_code(code: int) -> bool:
    """True iff `code` is a registered wire discriminant (the admission
    plane's cheapest pre-parse drop for garbage datagrams)."""
    return code in _REGISTRY


def client_request_admissible(req: ClientRequestMsg, info) -> bool:
    """Topology-static flag gates for a wire client request: the
    INTERNAL flag and internal-client principals must correspond
    (external clients can't smuggle internal ops and vice versa),
    ordered (non-READ_ONLY) RECONFIG commands only from the operator,
    and HAS_PRE_PROCESSED may only be minted by the preprocessor (it
    enters via _admit_request, never from the wire). Shared by the
    dispatcher's client-request handler and the admission plane's
    pre-verify drop so the two can never disagree — an admission-side
    drop is final, so drift between copies would silently lose
    messages only when admission is on."""
    if bool(req.flags & RequestFlag.INTERNAL) \
            != info.is_internal_client(req.sender_id):
        return False
    if req.flags & RequestFlag.RECONFIG \
            and not req.flags & RequestFlag.READ_ONLY \
            and req.sender_id != info.operator_id:
        return False
    if req.flags & RequestFlag.HAS_PRE_PROCESSED:
        return False
    return True


def parse_batch_elements(batch: ClientBatchRequestMsg):
    """Structural element checks for a client batch (reference
    ClientBatchRequestMsg::checkElements): every element must decode to
    a ClientRequestMsg from the SAME principal; a malformed element
    rejects the whole batch. Returns the parsed elements, or None.
    Shared by the admission plane and the dispatcher's legacy inline
    path so the two can never disagree about batch structure."""
    inners = []
    for raw in batch.requests:
        try:
            inner = unpack(raw)
        except MsgError:
            return None
        if not isinstance(inner, ClientRequestMsg) \
                or inner.sender_id != batch.sender_id:
            return None
        inners.append(inner)
    return inners
