"""Execution lane — committed-slot execution off the dispatcher thread.

The reference separates ordering from post-execution (concord-bft's
post-execution separation + block accumulation: PostExecJob queues and
the accumulated-block commit in kv_blockchain): the dispatcher thread
marks slots committed and hands them over; a single executor thread
drains *runs* of consecutive committed slots in seqnum order and applies
each run as ONE coalesced commit:

  * one ledger commit per run — the handler's add_block calls stage into
    a shared WriteBatch via KeyValueBlockchain.begin/end_accumulation
    (read-your-writes overlay, PR 2's _StagedReadView), so N blocks cost
    one DB write instead of N;
  * one reserved-pages batch per run for the reply ring / at-most-once
    markers (folded into the ledger batch when pages share its DB —
    apply is then atomic across ledger and reply state);
  * replies are handed back to the dispatcher, whose send loop already
    rides the transport batcher.

Safety rules enforced here and in the replica wiring:

  * `last_executed` advances on the DISPATCHER, only after the run's
    durable apply (the completed-run handoff) — a crash between commit
    and apply replays the committed suffix, deduplicated by the
    reserved-pages at-most-once state;
  * runs never cross a checkpoint-window boundary, and the boundary
    run's state/pages digests are snapshotted HERE, before the next run
    can mutate state — checkpoint certificates stay comparable
    cluster-wide;
  * batches carrying INTERNAL/RECONFIG requests never reach the lane:
    the dispatcher drains it and executes them inline (they mutate
    dispatcher-owned subsystems: key exchange, cron, wedge control);
  * view change, wedge announcement, and state-transfer completion all
    drain the lane first (Replica._drain_exec_lane).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from tpubft.storage.interfaces import WriteBatch
from tpubft.testing.crashpoints import crashpoint
from tpubft.utils import flight
from tpubft.utils.logging import get_logger, mdc_scope
from tpubft.utils.racecheck import get_watchdog, make_lock

log = get_logger("execlane")


@dataclass
class CompletedRun:
    """A durably-applied run, ready for the dispatcher to integrate."""
    first: int
    last: int
    n_requests: int                       # executed (non-dedup) requests
    replies: List[Tuple[int, bytes]] = field(default_factory=list)
    reply_keys: List[Tuple[int, int]] = field(default_factory=list)
    # (seq, state_digest, pages_digest) when `last` is a checkpoint
    # boundary — snapshotted at the boundary, before the next run ran
    checkpoint: Optional[Tuple[int, bytes, bytes]] = None


class ExecutionLane:
    """Single executor thread + the dispatcher↔executor handoff.

    Dispatcher-side API: submit / drain / pop_completed / depth.
    All protocol state stays dispatcher-owned; the lane touches only
    thread-safe surfaces (handler execution, ClientsManager, reserved
    pages, the blockchain's accumulation bracket)."""

    RETRY_DELAY_S = 0.5                   # backoff after a failed run

    def __init__(self, replica, max_accumulation: int,
                 checkpoint_window: int) -> None:
        self._r = replica
        self._max_acc = max(1, max_accumulation)
        self._ckpt_window = checkpoint_window
        self._mu = make_lock("exec_lane")
        self._cond = threading.Condition(self._mu)
        self._pending: "deque[Tuple[int, object]]" = deque()
        self._completed: "deque[CompletedRun]" = deque()
        self._busy = False
        self._held = False                # test hook: freeze execution
        self._retry_at = 0.0
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._name = f"exec-{replica.id}"

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=self._name)
        self._thread.start()

    def stop(self) -> None:
        """Stop WITHOUT draining: pending slots are committed state that
        recovery replays — stop is crash-equivalent by design."""
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        get_watchdog().unregister(self._name)

    # ------------------------------------------------------------------
    # dispatcher-side API
    # ------------------------------------------------------------------
    def submit(self, seq: int, pre_prepare) -> None:
        """Hand a committed slot to the lane. The dispatcher submits in
        strictly increasing consecutive seq order."""
        with self._cond:
            if self._pending and seq != self._pending[-1][0] + 1:
                raise RuntimeError(
                    f"non-consecutive lane submit: {seq} after "
                    f"{self._pending[-1][0]}")
            self._pending.append((seq, pre_prepare))
            self._cond.notify_all()
        self._r.m_exec_lane_depth.set(self.depth)

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until every submitted slot has been applied (pending
        empty AND no run in flight). Returns False on timeout — the
        caller decides whether proceeding is safe. The executor never
        waits on the dispatcher, so this cannot deadlock."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._pending or self._busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.2))
        return True

    def pop_completed(self) -> List[CompletedRun]:
        out = []
        with self._cond:
            while self._completed:
                out.append(self._completed.popleft())
        return out

    @property
    def depth(self) -> int:
        return len(self._pending)

    def idle(self) -> bool:
        with self._cond:
            return not self._pending and not self._busy

    # test hooks: freeze/unfreeze the lane so crash-window tests can
    # create "committed persisted, not yet applied" states determinately
    def hold(self) -> None:
        with self._cond:
            self._held = True

    def release(self) -> None:
        with self._cond:
            self._held = False
            self._cond.notify_all()

    # ------------------------------------------------------------------
    # executor thread
    # ------------------------------------------------------------------
    def _loop(self) -> None:
        watchdog = get_watchdog()
        # health-probe semantics are PROGRESS, not thread liveness: the
        # beat fires when the lane is idle (fresh age when work arrives)
        # and after each durable apply — depth > 0 with no apply for
        # execution_drain_timeout_ms reads as a stall (a wedged handler,
        # a run stuck behind a dead DB, or a held lane), even while this
        # thread is alive and waiting
        health = getattr(self._r, "health", None)
        flight.set_thread_rid(self._r.id)
        with mdc_scope(r=self._r.id):
            while True:
                watchdog.beat(self._name)
                with self._cond:
                    while self._running and (
                            not self._pending or self._held
                            or time.monotonic() < self._retry_at):
                        if health is not None and not self._pending:
                            health.beat("exec_lane")
                        self._cond.wait(0.2)
                        watchdog.beat(self._name)
                    if not self._running:
                        return
                    run = self._take_run_locked()
                    self._busy = True
                try:
                    self._execute_run(run)
                    if health is not None:
                        health.beat("exec_lane")      # durable apply
                except Exception:  # noqa: BLE001 — retry, as inline did
                    log.exception("run [%d..%d] failed; will retry",
                                  run[0][0], run[-1][0])
                    with self._cond:
                        self._pending.extendleft(reversed(run))
                        self._retry_at = (time.monotonic()
                                          + self.RETRY_DELAY_S)
                finally:
                    with self._cond:
                        self._busy = False
                        self._cond.notify_all()
                self._r.m_exec_lane_depth.set(self.depth)

    def _take_run_locked(self) -> List[Tuple[int, object]]:
        """Pop the next run: consecutive pending slots, capped at
        execution_max_accumulation, always breaking AFTER a checkpoint
        boundary so digests are computed at cluster-agreed points."""
        run: List[Tuple[int, object]] = []
        while self._pending and len(run) < self._max_acc:
            seq, pp = self._pending[0]
            if run and seq != run[-1][0] + 1:
                break                      # defensive: never skip a gap
            run.append(self._pending.popleft())
            if seq % self._ckpt_window == 0:
                break
        return run

    def _execute_run(self, run: List[Tuple[int, object]]) -> None:
        r = self._r
        from tpubft.utils.tracing import get_tracer
        blockchain = getattr(r.handler, "blockchain", None)
        can_accumulate = (blockchain is not None
                          and hasattr(blockchain, "begin_accumulation"))
        pages_wb = WriteBatch()
        result = CompletedRun(first=run[0][0], last=run[-1][0],
                              n_requests=0)
        # ClientsManager updates deferred to AFTER the durable commit:
        # an aborted run retries, and the at-most-once state must not
        # claim requests whose staged effects were discarded. _run_seen
        # is the run-local dedup (a byzantine primary re-batching one
        # request into two of the run's slots).
        executed_now: List[Tuple[int, int, object]] = []
        self._run_seen = set()
        span = get_tracer().start_span("execute")
        span.set_tag("r", r.id).set_tag("first", result.first) \
            .set_tag("run_len", len(run))
        acc = False
        if can_accumulate:
            blockchain.begin_accumulation()
            acc = True
        try:
            for seq, pp in run:
                self._execute_slot(seq, pp, pages_wb, result,
                                   executed_now)
        except BaseException:
            if acc:
                blockchain.abort_accumulation()
            span.set_tag("error", True)
            span.finish()
            raise
        # ---- coalesced durable apply: ONE ledger commit + ONE pages
        # batch per run (a single atomic batch when they share a DB).
        # Everything up to and including the LEDGER write is retriable
        # (end_accumulation rolls the head back on failure); everything
        # AFTER it is the point of no return — a post-commit exception
        # must never requeue the run, or the retry would re-execute
        # requests whose blocks are already durable (duplicate blocks,
        # permanent state divergence). ----
        crashpoint("exec.pre_apply", rid=r.id)
        t0 = time.perf_counter()
        folded = False
        if acc:
            folded = (pages_wb.ops
                      and r.res_pages.shares_db(
                          getattr(blockchain, "_base_db", None)))
            blockchain.end_accumulation(extra=pages_wb if folded else None)
        try:
            if not folded:
                # without accumulation the handler's effects applied
                # irreversibly during execution, and with it the ledger
                # just committed — either way a pages failure here is
                # logged, never retried (in-memory at-most-once still
                # dedups; the at-risk window is a crash before the next
                # run persists the ring)
                try:
                    r.res_pages.write_batch(pages_wb)
                except Exception:  # noqa: BLE001
                    log.exception("run [%d..%d]: reply-pages batch "
                                  "failed post point-of-no-return",
                                  result.first, result.last)
            crashpoint("exec.post_apply", rid=r.id)
            commit_ms = (time.perf_counter() - t0) * 1e3
            # durable-apply flight events, one per slot (the `exec`
            # stage's end anchor; `reply` runs from here to the
            # dispatcher's integration)
            for seq, _pp in run:
                flight.record(flight.EV_EXEC_APPLY, seq=seq,
                              arg=len(run))
            # the run is durable: NOW the at-most-once/reply-cache
            # records become visible (crash before this point replays
            # the suffix; the persisted ring deduplicates it)
            for client, req_seq, reply in executed_now:
                r.clients.on_request_executed(client, req_seq, reply)
            # checkpoint-boundary snapshot: digests taken now, before
            # the next run mutates state
            if result.last % self._ckpt_window == 0:
                try:
                    state_digest = r.handler.state_digest()
                    if r.state_transfer is not None:
                        r.state_transfer.on_checkpoint_created(
                            result.last, state_digest)
                    result.checkpoint = (result.last, state_digest,
                                         r.res_pages.digest())
                except Exception:  # noqa: BLE001 — skip OUR checkpoint
                    # vote for this boundary; peers' quorum can still
                    # certify it, and re-executing the run would be
                    # strictly worse (duplicate blocks)
                    log.exception("checkpoint snapshot failed at %d",
                                  result.last)
            span.set_tag("commit_ms", round(commit_ms, 3))
            span.finish()
            r.record_exec_run(len(run), commit_ms)
        except Exception:  # noqa: BLE001 — the run is durable: a
            # post-commit bookkeeping failure must be SWALLOWED, never
            # reach _loop's requeue path (re-executing a committed run
            # appends duplicate blocks — permanent divergence)
            log.exception("post-commit bookkeeping failed for run "
                          "[%d..%d] (run still completes)",
                          result.first, result.last)
        finally:
            # the run IS completed (durably applied) no matter what the
            # post-commit bookkeeping did — hand it to the dispatcher
            with self._cond:
                self._completed.append(result)
            r.incoming.push_internal_once("exec_done")

    def _execute_slot(self, seq: int, pp, pages_wb: WriteBatch,
                      result: CompletedRun,
                      executed_now: List[Tuple[int, int, object]]) -> None:
        """One slot's requests, in order. Only plain / pre-processed
        client requests reach the lane (barrier batches run inline on
        the dispatcher)."""
        r = self._r
        seen = self._run_seen
        for req in pp.client_requests():
            client = req.sender_id
            key = (client, req.req_seq_num)
            if key in seen or r.clients.was_executed(client,
                                                     req.req_seq_num):
                cached = r.clients.cached_reply(client, req.req_seq_num)
                if cached is not None:
                    result.replies.append((client, cached.pack()))
                continue
            if r._slowdown.enabled:
                from tpubft.testing.slowdown import PHASE_EXECUTE
                r._slowdown.delay(PHASE_EXECUTE)
            payload = r._execute_request(req, seq)
            result.n_requests += 1
            reply, wire = r._build_reply(client, req.req_seq_num,
                                         payload, pages_wb)
            executed_now.append((client, req.req_seq_num, reply))
            seen.add(key)
            result.reply_keys.append(key)
            if wire is not None:
                result.replies.append((client, wire))
        if r.cfg.time_service_enabled and pp.time:
            # agreed-time page writes must stay seq-ordered with the
            # reply pages for checkpoint digest determinism
            r.time_service.on_executed(pp.time)
