"""Execution lane — committed-slot execution off the dispatcher thread.

The reference separates ordering from post-execution (concord-bft's
post-execution separation + block accumulation: PostExecJob queues and
the accumulated-block commit in kv_blockchain): the dispatcher thread
marks slots committed and hands them over; a single executor thread
drains *runs* of consecutive committed slots in seqnum order and applies
each run as ONE coalesced commit:

  * one ledger commit per run — the handler's add_block calls stage into
    a shared WriteBatch via KeyValueBlockchain.begin/end_accumulation
    (read-your-writes overlay, PR 2's _StagedReadView), so N blocks cost
    one DB write instead of N;
  * one reserved-pages batch per run for the reply ring / at-most-once
    markers (folded into the ledger batch when pages share its DB —
    apply is then atomic across ledger and reply state);
  * replies are handed back to the dispatcher, whose send loop already
    rides the transport batcher.

SPECULATIVE runs (ReplicaConfig.speculative_execution): the dispatcher
hands a slot over at prepare-quorum (slow path) or PrePrepare
acceptance (fast paths) — before its commit certificate exists. The
lane executes it inside an OPEN speculative accumulation (staged
WriteBatch + staged reply pages, nothing durable, overlay visible only
to this thread) and then parks, overlapping execution with the
threshold combine that used to serialize ahead of it. When the
dispatcher confirms every slot's commit with the SAME digest the run
speculated on, the lane SEALS it — one end_accumulation, the normal
durable-apply tail — and only then do replies and `last_executed`
advance (strictly post-commit, exactly as before). On an abort request
(view change, barrier batch, state-transfer adoption, digest
surprise), the lane discards the overlay via abort_accumulation; the
slots re-execute later from their committed PrePrepares through the
normal path. A crash mid-speculation leaves NO trace (the overlay was
never durable); a crash at the seal seam (`exec.spec_seal`) replays
the committed suffix exactly once.

Safety rules enforced here and in the replica wiring:

  * `last_executed` advances on the DISPATCHER, only after the run's
    durable apply (the completed-run handoff) — a crash between commit
    and apply replays the committed suffix, deduplicated by the
    reserved-pages at-most-once state;
  * runs never cross a checkpoint-window boundary, and the boundary
    run's state/pages digests are snapshotted HERE, before the next run
    can mutate state — checkpoint certificates stay comparable
    cluster-wide;
  * batches carrying INTERNAL/RECONFIG requests never reach the lane:
    the dispatcher drains it and executes them inline (they mutate
    dispatcher-owned subsystems: key exchange, cron, wedge control) —
    and never speculate;
  * view change, wedge announcement, and state-transfer completion all
    abort any open speculation and drain the lane first
    (Replica._drain_exec_lane).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tpubft.storage.interfaces import WriteBatch
from tpubft.testing.crashpoints import crashpoint
from tpubft.utils import flight
from tpubft.utils.logging import get_logger, mdc_scope
from tpubft.utils.racecheck import get_watchdog, make_lock

log = get_logger("execlane")


@dataclass
class CompletedRun:
    """A durably-applied run, ready for the dispatcher to integrate."""
    first: int
    last: int
    n_requests: int                       # executed (non-dedup) requests
    replies: List[Tuple[int, bytes]] = field(default_factory=list)
    reply_keys: List[Tuple[int, int]] = field(default_factory=list)
    # optimistic-reply mode with the durability pipeline: replies built
    # UNSIGNED during execution; the io thread signs the whole sealed
    # group in one batched sign at the group boundary and appends the
    # packed wire bytes to `replies` before the group burst
    unsigned: List[Tuple[int, object]] = field(default_factory=list)
    # set by the durability pipeline when it already pushed `replies`
    # as part of the group-boundary send burst — the dispatcher's
    # integration pass must not send them a second time
    replies_sent: bool = False
    # (seq, state_digest, pages_digest, block_id) when `last` is a
    # checkpoint boundary — snapshotted at the boundary, before the
    # next run ran. block_id is the ledger height the state digest
    # binds (None for non-ledger handlers) — the thin-replica anchor
    # needs it to resolve a certified digest to a block row.
    checkpoint: Optional[Tuple[int, bytes, bytes, Optional[int]]] = None


@dataclass
class _SpecRun:
    """An OPEN speculative run: already executed into a never-durable
    accumulation, parked until every slot's commit is confirmed (seal)
    or an abort is requested. All mutation happens under the lane's
    condition; the accumulation bracket itself is touched only by the
    lane thread (begin at staging, end at seal, abort on request)."""
    first: int
    last: int
    pps: Dict[int, object]                # seq -> PrePrepare speculated
    digests: Dict[int, bytes]             # seq -> its digest at submit
    result: CompletedRun
    pages_wb: WriteBatch
    executed_now: List[Tuple[int, int, object]]
    t_open: float                         # monotonic: staging began
    seen: set = field(default_factory=set)
    confirmed: set = field(default_factory=set)
    t_confirmed: float = 0.0              # monotonic: last commit in
    abort: bool = False
    acc: bool = False                     # accumulation bracket open
    span: Optional[object] = None
    # checkpoint-boundary digests PRECOMPUTED at staging (ISSUE 18a):
    # (seq, state_digest, head) — the lane parks between staging and
    # seal, so the handler state cannot move; riding them on the
    # speculation overlaps the expensive state digest with the combine
    # window instead of paying it synchronously at the seal
    ckpt_pre: Optional[Tuple[int, bytes, Optional[int]]] = None


class ExecutionLane:
    """Single executor thread + the dispatcher↔executor handoff.

    Dispatcher-side API: submit / confirm / abort_speculation / drain /
    pop_completed / depth. All protocol state stays dispatcher-owned;
    the lane touches only thread-safe surfaces (handler execution,
    ClientsManager, reserved pages, the blockchain's accumulation
    bracket)."""

    RETRY_DELAY_S = 0.5                   # backoff after a failed run

    def __init__(self, replica, max_accumulation: int,
                 checkpoint_window: int) -> None:
        self._r = replica
        self._max_acc = max(1, max_accumulation)
        self._ckpt_window = checkpoint_window
        self._mu = make_lock("exec_lane")
        self._cond = threading.Condition(self._mu)
        # entries are (seq, pre_prepare, speculative)
        self._pending: "deque[Tuple[int, object, bool]]" = deque()
        self._completed: "deque[CompletedRun]" = deque()
        self._busy = False
        self._held = False                # test hook: freeze execution
        self._retry_at = 0.0
        # durability-pipeline dedup bridge: (client, req_seq) -> reply
        # for requests executed in SEALED runs whose group fsync has
        # not landed yet. The at-most-once ClientsManager state only
        # becomes visible post-fsync (a retransmit must never be
        # answered from a run that could still be lost), but the LANE
        # must still dedup across back-to-back runs — the same request
        # re-proposed into a later slot (view change after an
        # equivocation, primary retry) would otherwise execute twice
        # before the first run's group lands: duplicate block,
        # permanent divergence. Written by the lane thread at seal,
        # erased by the io thread at completion (strictly AFTER
        # on_request_executed makes the ClientsManager entry visible,
        # so there is no uncovered window).
        self._inflight: Dict[Tuple[int, int], object] = {}
        self._spec: Optional[_SpecRun] = None
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._name = f"exec-{replica.id}"

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=self._name)
        self._thread.start()

    def stop(self) -> None:
        """Stop WITHOUT draining: pending slots are committed state that
        recovery replays — stop is crash-equivalent by design. An open
        speculation is aborted (never made durable) on the way out."""
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        get_watchdog().unregister(self._name)

    def set_max_accumulation(self, n: int) -> None:
        """Autotuner actuator: retune the run-coalescing cap live. The
        lane thread reads it once per run pop (under the condition), so
        the new cap applies from the next run."""
        with self._cond:
            self._max_acc = max(1, int(n))

    @property
    def max_accumulation(self) -> int:
        return self._max_acc

    # ------------------------------------------------------------------
    # dispatcher-side API
    # ------------------------------------------------------------------
    def submit(self, seq: int, pre_prepare,
               speculative: bool = False) -> None:
        """Hand a slot to the lane. The dispatcher submits in strictly
        increasing consecutive seq order; `speculative` slots arrive at
        prepare-quorum / acceptance, before their commit certificate."""
        with self._cond:
            if self._pending and seq != self._pending[-1][0] + 1:
                raise RuntimeError(
                    f"non-consecutive lane submit: {seq} after "
                    f"{self._pending[-1][0]}")
            self._pending.append((seq, pre_prepare, speculative))
            self._cond.notify_all()
        self._r.m_exec_lane_depth.set(self.depth)

    def confirm(self, seq: int, digest: bytes) -> bool:
        """Dispatcher: slot `seq`'s commit certificate landed over
        `digest`. Returns True when the lane's speculation for it
        matches (a still-pending speculative entry simply becomes a
        normal committed slot; a slot of the open run counts toward the
        seal). False = mismatch, abort in flight, or the lane does not
        know the slot — the dispatcher must abort speculation and
        resubmit through the normal committed path."""
        with self._cond:
            sp = self._spec
            if sp is not None and sp.first <= seq <= sp.last:
                if sp.abort or sp.digests.get(seq) != digest:
                    return False
                sp.confirmed.add(seq)
                if len(sp.confirmed) == sp.last - sp.first + 1 \
                        and not sp.t_confirmed:
                    sp.t_confirmed = time.monotonic()
                    self._cond.notify_all()
                return True
            for i in range(len(self._pending)):
                s, pp, spec = self._pending[i]
                if s != seq:
                    continue
                if not spec:
                    return True           # already a committed entry
                if pp.digest() != digest:
                    return False
                self._pending[i] = (s, pp, False)
                self._cond.notify_all()
                return True
            return False

    def abort_speculation(self, wait: float = 5.0) -> List[int]:
        """Dispatcher: discard ALL speculation — the open run's overlay
        (aborted on the lane thread; this call waits up to `wait` for
        the accumulation to actually roll back) and every pending entry
        from the first speculative one onward (later entries depend on
        the speculated prefix's execution order). Returns the removed
        seqs so the caller can roll back its submission bookkeeping and
        resubmit the committed ones through the normal path."""
        removed: List[int] = []
        with self._cond:
            sp = self._spec
            if sp is not None:
                sp.abort = True
                removed.extend(range(sp.first, sp.last + 1))
                # everything still pending sits AFTER the open run
                removed.extend(s for s, _pp, _f in self._pending)
                self._pending.clear()
            else:
                idx = next((i for i, e in enumerate(self._pending)
                            if e[2]), None)
                if idx is not None:
                    kept = deque()
                    for i, e in enumerate(self._pending):
                        if i < idx:
                            kept.append(e)
                        else:
                            removed.append(e[0])
                    self._pending = kept
            if not removed:
                return []
            self._cond.notify_all()
            deadline = time.monotonic() + wait
            while self._spec is not None and self._running \
                    and time.monotonic() < deadline:
                self._cond.wait(0.2)
        self._r.m_exec_lane_depth.set(self.depth)
        return sorted(set(removed))

    @property
    def speculating(self) -> bool:
        with self._cond:
            return self._spec is not None \
                or any(spec for _s, _pp, spec in self._pending)

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until every submitted slot has been applied (pending
        empty, no run in flight, no open speculation). Returns False on
        timeout — the caller decides whether proceeding is safe. A
        speculative run cannot drain (it waits on commits only the
        dispatcher can confirm): callers abort speculation first
        (Replica._drain_exec_lane does). The executor never waits on
        the dispatcher, so this cannot deadlock."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._pending or self._busy or self._spec is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.2))
        return True

    def complete_durable(self, run: CompletedRun) -> None:
        """Durability-pipeline completion hop (io thread): the run's
        group fsync landed — only now does it reach the dispatcher's
        integration queue (replies, `last_executed`, checkpoint votes).
        The caller (the pipeline) made the ClientsManager at-most-once
        entries visible FIRST, so dropping the in-flight dedup entries
        here leaves no uncovered window. On the legacy path _apply_run
        appends directly."""
        with self._cond:
            for key in run.reply_keys:
                self._inflight.pop(key, None)
            self._completed.append(run)
            self._cond.notify_all()

    def pop_completed(self) -> List[CompletedRun]:
        out = []
        with self._cond:
            while self._completed:
                out.append(self._completed.popleft())
        return out

    @property
    def depth(self) -> int:
        return len(self._pending)

    def idle(self) -> bool:
        with self._cond:
            return not self._pending and not self._busy \
                and self._spec is None

    # test hooks: freeze/unfreeze the lane so crash-window tests can
    # create "committed persisted, not yet applied" states determinately
    def hold(self) -> None:
        with self._cond:
            self._held = True

    def release(self) -> None:
        with self._cond:
            self._held = False
            self._cond.notify_all()

    # ------------------------------------------------------------------
    # executor thread
    # ------------------------------------------------------------------
    def _next_action_locked(self) -> Optional[str]:
        sp = self._spec
        if sp is not None and sp.abort:
            return "abort"                # even while held: stop-clean
        if self._held:
            return None
        if sp is not None:
            if len(sp.confirmed) == sp.last - sp.first + 1:
                return "seal"
            return None
        if self._pending and time.monotonic() >= self._retry_at:
            return "run"
        return None

    def _loop(self) -> None:
        watchdog = get_watchdog()
        # health-probe semantics are PROGRESS, not thread liveness: the
        # beat fires when the lane is idle (fresh age when work arrives)
        # and after each durable apply — depth > 0 with no apply for
        # execution_drain_timeout_ms reads as a stall (a wedged handler,
        # a run stuck behind a dead DB, or a held lane), even while this
        # thread is alive and waiting. An OPEN speculation counts as
        # busy: it resolves within a commit round trip or a view-change
        # abort, both far under the stall threshold.
        health = getattr(self._r, "health", None)
        flight.set_thread_rid(self._r.id)
        with mdc_scope(r=self._r.id):
            while True:
                watchdog.beat(self._name)
                action = None
                run: List[Tuple[int, object, bool]] = []
                sp: Optional[_SpecRun] = None
                with self._cond:
                    while self._running:
                        action = self._next_action_locked()
                        if action is not None:
                            break
                        if health is not None and not self._pending \
                                and self._spec is None:
                            health.beat("exec_lane")
                        self._cond.wait(0.2)
                        watchdog.beat(self._name)
                    if not self._running:
                        sp = self._spec
                        if sp is None:
                            return
                        sp.abort = True
                        action = "abort"
                    if action == "abort":
                        sp = self._spec
                    elif action == "seal":
                        sp, self._spec = self._spec, None
                        self._busy = True
                    else:                          # "run"
                        run = self._take_run_locked()
                        if run and run[0][2]:
                            # publish the speculation UNDER THIS LOCK
                            # HOLD: from the moment the entry left
                            # _pending, confirm() must be able to find
                            # it — a commit landing between pop and a
                            # later publication would read as
                            # unknown-slot, spuriously abort on the
                            # dispatcher, and leave an untracked open
                            # speculation wedging the lane
                            sp = self._publish_spec_locked(run)
                        else:
                            self._busy = True
                # ---- outside the condition ----
                if action == "abort":
                    self._abort_spec(sp, "stop" if not self._running
                                     else "request")
                    if not self._running:
                        return
                    continue
                if action == "seal":
                    try:
                        self._seal_spec_run(sp)
                        if health is not None:
                            health.beat("exec_lane")   # durable apply
                    except Exception:  # noqa: BLE001 — pre-durability
                        # seal failed before anything became durable
                        # (end_accumulation rolled the head back): the
                        # slots ARE committed — requeue them as normal
                        # entries and retry through the standard path
                        log.exception("spec seal [%d..%d] failed; "
                                      "requeueing as committed run",
                                      sp.first, sp.last)
                        with self._cond:
                            self._pending.extendleft(reversed(
                                [(s, sp.pps[s], False)
                                 for s in range(sp.first, sp.last + 1)]))
                            self._retry_at = (time.monotonic()
                                              + self.RETRY_DELAY_S)
                    finally:
                        with self._cond:
                            self._busy = False
                            self._cond.notify_all()
                    self._r.m_exec_lane_depth.set(self.depth)
                    continue
                if run and run[0][2]:
                    self._stage_into_spec(sp, [(s, pp)
                                               for s, pp, _f in run])
                    self._r.m_exec_lane_depth.set(self.depth)
                    continue
                plain = [(s, pp) for s, pp, _f in run]
                try:
                    self._execute_run(plain)
                    if health is not None:
                        health.beat("exec_lane")      # durable apply
                except Exception:  # noqa: BLE001 — retry, as inline did
                    log.exception("run [%d..%d] failed; will retry",
                                  plain[0][0], plain[-1][0])
                    with self._cond:
                        self._pending.extendleft(reversed(run))
                        self._retry_at = (time.monotonic()
                                          + self.RETRY_DELAY_S)
                finally:
                    with self._cond:
                        self._busy = False
                        self._cond.notify_all()
                self._r.m_exec_lane_depth.set(self.depth)

    def _take_run_locked(self) -> List[Tuple[int, object, bool]]:
        """Pop the next run. Committed runs coalesce: consecutive
        pending slots, capped at execution_max_accumulation, always
        breaking AFTER a checkpoint boundary so digests are computed at
        cluster-agreed points. SPECULATIVE runs are single-slot by
        design: a multi-slot speculation could only seal when its LAST
        slot commits, coupling the first slot's reply to later slots'
        combines — exactly the serialization speculation exists to
        remove. (Throughput coalescing is preserved anyway: under load
        commits land before the lane reaches pending speculative
        entries, flipping them into normal coalesced runs.)"""
        run: List[Tuple[int, object, bool]] = []
        while self._pending and len(run) < self._max_acc:
            seq, pp, spec = self._pending[0]
            if spec and not run:
                return [self._pending.popleft()]
            if run and (seq != run[-1][0] + 1 or spec):
                break                      # gap or speculation boundary
            run.append(self._pending.popleft())
            if seq % self._ckpt_window == 0:
                break
        return run

    # ------------------------------------------------------------------
    # speculative run machinery (lane thread)
    # ------------------------------------------------------------------
    def _publish_spec_locked(self,
                             run: List[Tuple[int, object, bool]]
                             ) -> _SpecRun:
        """Create + publish the _SpecRun for a just-popped speculative
        run. Caller holds the condition: the publication is atomic with
        the pop, so confirm() can never observe the slot in neither
        place (the window that wedged the lane on a racing commit)."""
        result = CompletedRun(first=run[0][0], last=run[-1][0],
                              n_requests=0)
        sp = _SpecRun(first=run[0][0], last=run[-1][0],
                      pps={s: pp for s, pp, _f in run},
                      digests={s: pp.digest() for s, pp, _f in run},
                      result=result, pages_wb=WriteBatch(),
                      executed_now=[], t_open=time.monotonic())
        self._spec = sp
        return sp

    def _stage_into_spec(self, sp: _SpecRun,
                         slots: List[Tuple[int, object]]) -> None:
        """Execute `slots` into the open speculative accumulation
        (opened here on the first batch). Nothing becomes durable; a
        failure aborts the whole speculation and requeues its slots."""
        from tpubft.utils.tracing import get_tracer
        r = self._r
        blockchain = getattr(r.handler, "blockchain", None)
        if sp.span is None:
            sp.span = get_tracer().start_span("execute")
            sp.span.set_tag("r", r.id).set_tag("first", sp.first) \
                .set_tag("spec", True)
        self._run_seen = sp.seen          # one logical run across extends
        try:
            if not sp.acc:
                blockchain.begin_accumulation(speculative=True)
                sp.acc = True
            for seq, pp in slots:
                self._execute_slot(seq, pp, sp.pages_wb, sp.result,
                                   sp.executed_now)
                sp.result.last = seq
            if sp.result.last % self._ckpt_window == 0:
                # checkpoint boundary: precompute the state digest NOW,
                # inside the combine window, instead of at the seal.
                # Read-your-writes: the owner thread sees the overlay's
                # state and speculative head, which the seal commits
                # unchanged (the lane parks in between). res_pages
                # digest stays at the seal — pages_wb is not applied yet
                sp.ckpt_pre = (sp.result.last, r.handler.state_digest(),
                               getattr(blockchain, "last_block_id", None))
        except BaseException:  # noqa: BLE001 — discard + retry
            log.exception("speculative staging [%d..%d] failed; "
                          "overlay discarded", sp.first, sp.last)
            self._spec_failure(sp)

    def _spec_failure(self, sp: _SpecRun) -> None:
        """Staging raised: roll the accumulation back and requeue the
        run's slots — already-confirmed ones as committed entries (their
        commit certificates will not be re-announced), the rest still
        speculative (the dispatcher keeps confirming them)."""
        blockchain = getattr(self._r.handler, "blockchain", None)
        if sp.acc:
            try:
                blockchain.abort_accumulation()
            except Exception:  # noqa: BLE001 — already failing
                log.exception("abort_accumulation after staging failure")
        if sp.span is not None:
            sp.span.set_tag("error", True)
            sp.span.finish()
        with self._cond:
            if self._spec is sp:
                self._spec = None
            if not sp.abort:
                self._pending.extendleft(reversed(
                    [(s, sp.pps[s], s not in sp.confirmed)
                     for s in range(sp.first, sp.last + 1)]))
                self._retry_at = time.monotonic() + self.RETRY_DELAY_S
            self._cond.notify_all()

    def _abort_spec(self, sp: _SpecRun, cause: str) -> None:
        """Abort request honored (lane thread): discard the overlay.
        The dispatcher already rolled back its submission bookkeeping —
        the slots re-execute from their committed PrePrepares through
        the normal path once their certificates land."""
        blockchain = getattr(self._r.handler, "blockchain", None)
        if sp.acc:
            try:
                blockchain.abort_accumulation()
            except Exception:  # noqa: BLE001 — abort must not wedge stop
                log.exception("spec abort_accumulation failed")
        if sp.span is not None:
            sp.span.set_tag("aborted", cause)
            sp.span.finish()
        log.info("speculative run [%d..%d] aborted (%s): overlay "
                 "discarded, slots re-execute post-commit",
                 sp.first, sp.last, cause)
        with self._cond:
            if self._spec is sp:
                self._spec = None
            self._cond.notify_all()

    def _seal_spec_run(self, sp: _SpecRun) -> None:
        """Every slot's commit confirmed over the speculated digest:
        make the run durable. From here the path is byte-identical to a
        normal run's apply tail — replies and watermark advancement
        stay strictly post-commit."""
        overlap_ms = max(0.0, (sp.t_confirmed - sp.t_open) * 1e3)
        if sp.span is not None:
            sp.span.set_tag("run_len", sp.last - sp.first + 1)
        blockchain = getattr(self._r.handler, "blockchain", None)
        self._apply_run(sp.last - sp.first + 1, sp.result, sp.pages_wb,
                        sp.executed_now, blockchain, sp.acc, sp.span,
                        spec_overlap_ms=overlap_ms, ckpt_pre=sp.ckpt_pre)

    # ------------------------------------------------------------------
    # normal (committed) run execution
    # ------------------------------------------------------------------
    def _execute_run(self, run: List[Tuple[int, object]]) -> None:
        r = self._r
        from tpubft.utils.tracing import get_tracer
        blockchain = getattr(r.handler, "blockchain", None)
        can_accumulate = (blockchain is not None
                          and hasattr(blockchain, "begin_accumulation"))
        pages_wb = WriteBatch()
        result = CompletedRun(first=run[0][0], last=run[-1][0],
                              n_requests=0)
        # ClientsManager updates deferred to AFTER the durable commit:
        # an aborted run retries, and the at-most-once state must not
        # claim requests whose staged effects were discarded. _run_seen
        # is the run-local dedup (a byzantine primary re-batching one
        # request into two of the run's slots).
        executed_now: List[Tuple[int, int, object]] = []
        self._run_seen = set()
        span = get_tracer().start_span("execute")
        span.set_tag("r", r.id).set_tag("first", result.first) \
            .set_tag("run_len", len(run))
        acc = False
        if can_accumulate:
            blockchain.begin_accumulation()
            acc = True
        try:
            for seq, pp in run:
                self._execute_slot(seq, pp, pages_wb, result,
                                   executed_now)
        except BaseException:
            if acc:
                blockchain.abort_accumulation()
            span.set_tag("error", True)
            span.finish()
            raise
        self._apply_run(len(run), result, pages_wb, executed_now,
                        blockchain, acc, span)

    def _apply_run(self, run_len: int, result: CompletedRun,
                   pages_wb: WriteBatch, executed_now, blockchain,
                   acc: bool, span,
                   spec_overlap_ms: Optional[float] = None,
                   ckpt_pre: Optional[Tuple[int, bytes,
                                            Optional[int]]] = None) -> None:
        """Coalesced apply: ONE ledger commit + ONE pages batch per run
        (a single atomic batch when they share a DB). Everything up to
        and including the LEDGER commit point is retriable
        (end_accumulation rolls the head back on failure); everything
        AFTER it is the point of no return — a post-commit exception
        must never requeue the run, or the retry would re-execute
        requests whose blocks are already committed (duplicate blocks,
        permanent state divergence).

        With the durability pipeline (ReplicaConfig.durability_pipeline,
        the default) the run's batch is SEALED, not written: the
        overlay moves into the pending store (still readable by every
        thread), the io thread group-commits it across runs with one
        fsync per group, and only then do replies, `last_executed` and
        the at-most-once cache advance — this thread never touches the
        disk and moves straight to the next run. Without the pipeline
        the legacy per-run write + immediate completion path runs
        byte-identically to before."""
        r = self._r
        pipe = getattr(r, "durability", None)
        if spec_overlap_ms is not None:
            # the speculative seal seam: a SIGKILL here — run fully
            # commit-confirmed, nothing yet durable — must replay the
            # committed suffix exactly once on recovery
            crashpoint("exec.spec_seal", rid=r.id)
        crashpoint("exec.pre_apply", rid=r.id)
        t0 = time.perf_counter()
        folded = False
        deferred = None                   # (run_no, batch, raw base db)
        if acc:
            folded = (pages_wb.ops
                      and r.res_pages.shares_db(
                          getattr(blockchain, "_base_db", None)))
            # deferral requires the WHOLE run to ride one deferred
            # batch: with reply pages in a SEPARATE store (not folded)
            # the pages write would land at seal while the ledger batch
            # waited in memory — a crash in that window persists
            # "request executed" without its block, and replay would
            # skip it forever. Fall back to the immediate apply there
            # (ledger first, pages second, same thread — the legacy
            # order); the seal below still groups the fsyncs.
            defer = (pipe is not None
                     and getattr(blockchain, "durability_attached", False)
                     and (folded or not pages_wb.ops))
            blockchain.end_accumulation(
                extra=pages_wb if folded else None, defer=defer)
            if defer:
                deferred = blockchain.take_deferred()
        try:
            if not folded:
                # without accumulation the handler's effects applied
                # irreversibly during execution, and with it the ledger
                # just committed — either way a pages failure here is
                # logged, never retried (in-memory at-most-once still
                # dedups; the at-risk window is a crash before the next
                # run persists the ring)
                try:
                    r.res_pages.write_batch(pages_wb)
                except Exception:  # noqa: BLE001
                    log.exception("run [%d..%d]: reply-pages batch "
                                  "failed post point-of-no-return",
                                  result.first, result.last)
            crashpoint("exec.post_apply", rid=r.id)
            commit_ms = (time.perf_counter() - t0) * 1e3
            # durable-apply flight events, one per slot (the `exec`
            # stage's end anchor; `reply` runs from here to the
            # dispatcher's integration). Sealed speculations also mark
            # each slot so the tracker folds its spec_overlap stage.
            for seq in range(result.first, result.last + 1):
                flight.record(flight.EV_EXEC_APPLY, seq=seq, arg=run_len)
                if spec_overlap_ms is not None:
                    flight.record(flight.EV_SPEC_SEAL, seq=seq,
                                  arg=run_len)
            # LEGACY path: the run is durable — NOW the at-most-once/
            # reply-cache records become visible (crash before this
            # point replays the suffix; the persisted ring deduplicates
            # it). With the pipeline that visibility moves to the io
            # thread, strictly AFTER the group's fsync.
            if pipe is None:
                for client, req_seq, reply in executed_now:
                    r.clients.on_request_executed(client, req_seq, reply)
            # checkpoint-boundary snapshot: digests taken now, before
            # the next run mutates state
            if result.last % self._ckpt_window == 0:
                try:
                    if ckpt_pre is not None and ckpt_pre[0] == result.last:
                        # digests rode the speculation (precomputed at
                        # staging while the combine was still in flight):
                        # the boundary no longer forces a synchronous
                        # state walk at the seal
                        _, state_digest, head = ckpt_pre
                    else:
                        state_digest = r.handler.state_digest()
                        # ledger height snapshotted WITH the digest
                        # (same thread, same boundary): resolves the
                        # certified digest to a block for the
                        # thin-replica anchor
                        head = getattr(blockchain, "last_block_id", None)
                    if r.state_transfer is not None:
                        r.state_transfer.on_checkpoint_created(
                            result.last, state_digest)
                    result.checkpoint = (result.last, state_digest,
                                         r.res_pages.digest(), head)
                except Exception:  # noqa: BLE001 — skip OUR checkpoint
                    # vote for this boundary; peers' quorum can still
                    # certify it, and re-executing the run would be
                    # strictly worse (duplicate blocks)
                    log.exception("checkpoint snapshot failed at %d",
                                  result.last)
            span.set_tag("commit_ms", round(commit_ms, 3))
            span.finish()
            r.record_exec_run(run_len, commit_ms)
            if spec_overlap_ms is not None:
                r.record_spec_seal(run_len, spec_overlap_ms)
        except Exception:  # noqa: BLE001 — the run is durable: a
            # post-commit bookkeeping failure must be SWALLOWED, never
            # reach _loop's requeue path (re-executing a committed run
            # appends duplicate blocks — permanent divergence)
            log.exception("post-commit bookkeeping failed for run "
                          "[%d..%d] (run still completes)",
                          result.first, result.last)
        finally:
            # the run IS committed no matter what the post-commit
            # bookkeeping did — hand it over: to the durability
            # pipeline (completion follows its group fsync) or, on the
            # legacy path, straight to the dispatcher
            if pipe is not None:
                from tpubft.durability import SealedRun
                from tpubft.kvbc.blockchain import raw_base
                sync_dbs = []
                if deferred is None and blockchain is not None:
                    # nothing deferred (empty batch, or a ledger
                    # without the accumulation bracket whose writes
                    # applied directly): the base still holds unsynced
                    # buffers the group fsync must land
                    db = raw_base(getattr(blockchain, "_db", None))
                    if db is not None:
                        sync_dbs.append(db)
                if not folded and pages_wb.ops:
                    pdb = raw_base(r.res_pages.db)
                    if not any(pdb is d for d in sync_dbs):
                        sync_dbs.append(pdb)
                run_no, batch, target = (deferred if deferred is not None
                                         else (None, None, None))
                # publish the in-flight dedup entries BEFORE the seal:
                # from the moment the pipeline owns the run, the next
                # run may execute — it must already see these
                with self._cond:
                    for client, req_seq, reply in executed_now:
                        self._inflight[(client, req_seq)] = reply
                pipe.seal(SealedRun(
                    run=result, executed_now=list(executed_now),
                    batch=batch, run_no=run_no, db=target,
                    sync_dbs=tuple(sync_dbs)))
            else:
                with self._cond:
                    self._completed.append(result)
                r.incoming.push_internal_once("exec_done")

    def _execute_slot(self, seq: int, pp, pages_wb: WriteBatch,
                      result: CompletedRun,
                      executed_now: List[Tuple[int, int, object]]) -> None:
        """One slot's requests, in order. Only plain / pre-processed
        client requests reach the lane (barrier batches run inline on
        the dispatcher)."""
        r = self._r
        seen = self._run_seen
        # batched reply signing (optimistic replies + durability
        # pipeline): per-reply scalar signs during execution serialize
        # ~100µs of comb math behind every request — defer them to the
        # io thread, which signs the sealed GROUP in one batch at its
        # fsync boundary (the reply cannot leave before that boundary
        # anyway, so the deferral adds zero client-visible latency)
        defer = getattr(r, "_opt_replies", False) \
            and getattr(r, "durability", None) is not None
        for req in pp.client_requests():
            client = req.sender_id
            key = (client, req.req_seq_num)
            # sealed-but-not-durable dedup (pipeline mode): the request
            # already executed in a run awaiting its group fsync — the
            # ClientsManager entry is deliberately not visible yet, but
            # executing again would append a duplicate block. Re-issue
            # the stashed reply with THIS run (it rides this run's own
            # durability gate). READ ORDER MATTERS: the io thread
            # publishes the ClientsManager entry BEFORE popping the
            # in-flight entry, so checking _inflight FIRST and the
            # manager second can never observe the uncovered
            # none-visible-yet window (checking the manager first
            # could: miss there, completion lands, miss here too).
            # GIL-atomic read; see _inflight.
            stashed = self._inflight.get(key)
            if stashed is not None:
                if defer and not stashed.signature:
                    # the stashed reply's own group has not signed it
                    # yet — route the re-issue through THIS run's batch
                    # sign instead of packing unsigned bytes (ed25519
                    # signing is deterministic, so a double sign from
                    # both groups lands identical bytes)
                    result.unsigned.append((client, stashed))
                else:
                    result.replies.append((client, stashed.pack()))
                continue
            if key in seen or r.clients.was_executed(client,
                                                     req.req_seq_num):
                cached = r.clients.cached_reply(client, req.req_seq_num)
                if cached is not None:
                    result.replies.append((client, cached.pack()))
                continue
            if r._slowdown.enabled:
                from tpubft.testing.slowdown import PHASE_EXECUTE
                r._slowdown.delay(PHASE_EXECUTE)
            payload = r._execute_request(req, seq)
            result.n_requests += 1
            reply, wire = r._build_reply(client, req.req_seq_num,
                                         payload, pages_wb,
                                         defer_sign=defer)
            executed_now.append((client, req.req_seq_num, reply))
            seen.add(key)
            result.reply_keys.append(key)
            if wire is not None:
                result.replies.append((client, wire))
            elif defer and not r.info.is_internal_client(client):
                result.unsigned.append((client, reply))
        if r.cfg.time_service_enabled and pp.time:
            # agreed-time page writes must stay seq-ordered with the
            # reply pages for checkpoint digest determinism
            r.time_service.on_executed(pp.time)
