"""Epoch numbering across reconfigurations.

Rebuild of the reference's EpochManager
(/root/reference/bftengine/include/bftengine/EpochManager.hpp:21-82): a
monotone era counter that separates message traffic from before and
after a reconfiguration (addRemoveWithWedge / coordinated restart).
Without it, a replica restarted into a new configuration cannot tell
same-view-different-era messages apart.

Two numbers, as in the reference:
- the GLOBAL epoch lives in a reserved page, so it is part of every
  checkpoint certificate and rides state transfer to lagging/new
  replicas;
- the SELF epoch is what this process stamps on (and requires of)
  protocol messages. It is loaded from the global page at boot and only
  re-adopted at boot / state-transfer completion — live replicas keep
  ordering in their current era until the wedge+restart boundary.
"""
from __future__ import annotations

from tpubft.consensus.reserved_pages import ReservedPagesClient


class EpochManager:
    CATEGORY = "epoch"

    def __init__(self, pages: ReservedPagesClient) -> None:
        self._pages = pages
        self.self_epoch = self.global_epoch()

    # page layout: epoch u64 | bump command seq u64 | effective seq u64
    # (the wedge stop point at which the new era begins)
    def _read(self):
        raw = self._pages.load(index=0)
        if not raw or len(raw) < 24:
            return 0, 0, 0
        return (int.from_bytes(raw[0:8], "little"),
                int.from_bytes(raw[8:16], "little"),
                int.from_bytes(raw[16:24], "little"))

    def global_epoch(self) -> int:
        return self._read()[0]

    def bump_global_at(self, cmd_seq: int, effective_seq: int) -> int:
        """Executed inside an ordered reconfiguration command — every
        replica writes the same value at the same seq, so the page digest
        stays part of the agreed state. Keyed on the command's seq to be
        IDEMPOTENT: crash-recovery replays re-execute committed commands,
        and a read-modify-write bump would double-count and diverge this
        replica's page digest from the cluster. The guard is MONOTONE
        (any cmd_seq at or below the stored one is a replay), not an
        equality check: two bump commands in one replayed window would
        otherwise double-bump — the older replay sees the newer stored
        seq, mismatches, and bumps again (ADVICE r5)."""
        epoch, seq, eff = self._read()
        if cmd_seq != 0 and cmd_seq <= seq:
            return epoch                # replay of an already-bumped cmd
        nxt = epoch + 1
        self._pages.save(index=0, data=(nxt.to_bytes(8, "little")
                                        + cmd_seq.to_bytes(8, "little")
                                        + effective_seq.to_bytes(8, "little")))
        return nxt

    def boot_adopt(self, last_executed: int) -> int:
        """Boot: adopt the persisted global era ONLY if this replica
        already executed past the era's effective point (the wedge stop).
        A replica that crashed and rebooted mid-era — after the bump
        command executed but before the wedge boundary — must keep
        speaking the old era with its peers, or it strands itself: their
        traffic fails its gate and its traffic fails theirs."""
        epoch, _seq, eff = self._read()
        if epoch > 0 and last_executed < eff:
            self.self_epoch = epoch - 1
        else:
            self.self_epoch = epoch
        return self.self_epoch

    def adopt_global(self) -> int:
        """Post-state-transfer: the fetched pages are part of a certified
        checkpoint at/past the era boundary — speak the persisted era."""
        self.self_epoch = self.global_epoch()
        return self.self_epoch
