"""Cluster key material generation and per-replica key views.

Rebuild of the reference's key tooling (tools/GenerateConcordKeys.cpp +
KeyfileIOUtils.cpp) and CryptoManager's per-path threshold systems
(bftengine/include/bftengine/CryptoManager.hpp:109-111: slow path signs
with threshold 2f+c+1, fast-with-threshold 3f+c+1, optimistic n).

Deterministic from a seed so tests and multi-process harnesses can derive
identical key material without shipping files; real deployments serialize
`ClusterKeys.to_json()` per replica (private material included only in each
replica's own view).
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpubft.crypto.cpu import make_signer, make_verifier
from tpubft.crypto.interfaces import (Cryptosystem, ISigner,
                                      IThresholdSigner, IThresholdVerifier,
                                      IVerifier)
from tpubft.utils.config import ReplicaConfig


def _derive_seed(root: bytes, *labels) -> bytes:
    h = hashlib.sha256(root)
    for lab in labels:
        h.update(str(lab).encode())
        h.update(b"|")
    return h.digest()


@dataclass
class ClusterKeys:
    """All public material + this node's private material."""
    n: int
    f: int
    c: int
    threshold_scheme: str
    # per-principal-class signature schemes (reference SigManager builds a
    # scheme-specific verifier per principal from the keyfile — RSA/EdDSA
    # for replicas, optionally ECDSA-secp256k1 for clients, the BASELINE
    # config-3/5 mix): replicas sign consensus msgs with one scheme,
    # external clients (and the operator) may use another
    replica_sig_scheme: str = "ed25519"
    client_sig_scheme: str = "ed25519"
    # per-message signing (SigManager principals)
    replica_pubkeys: Dict[int, bytes] = field(default_factory=dict)
    client_pubkeys: Dict[int, bytes] = field(default_factory=dict)
    # private: only for this node
    my_id: Optional[int] = None
    my_sign_seed: Optional[bytes] = None
    operator_id: Optional[int] = None
    # threshold cryptosystems per commit path (shared public material;
    # secret shares live inside — prune for untrusted serialization)
    slow_path_system: Optional[Cryptosystem] = None
    commit_path_system: Optional[Cryptosystem] = None
    optimistic_system: Optional[Cryptosystem] = None

    @classmethod
    def generate(cls, cfg: ReplicaConfig, num_clients: int,
                 seed: bytes = b"tpubft-test-cluster") -> "ClusterKeys":
        """Generate the full cluster's material (test/keygen-tool path —
        the reference's GenerateConcordKeys writes one file per replica)."""
        n, f, c = cfg.n_val, cfg.f_val, cfg.c_val
        # "adaptive" resolves HERE, once, from cluster size: the scheme
        # is baked into the generated key material, so every replica and
        # every carried certificate (view change, state transfer) agrees
        # by construction (crypto/systems.resolve_threshold_scheme)
        from tpubft.crypto.systems import resolve_threshold_scheme
        scheme = resolve_threshold_scheme(
            cfg.threshold_scheme, n,
            getattr(cfg, "threshold_scheme_crossover_n", 0),
            aggregation=getattr(cfg, "share_aggregation", "off"))
        ck = cls(n=n, f=f, c=c, threshold_scheme=scheme,
                 replica_sig_scheme=cfg.replica_sig_scheme,
                 client_sig_scheme=cfg.client_sig_scheme)
        for r in range(n):
            s = make_signer(ck.replica_sig_scheme,
                            seed=_derive_seed(seed, "replica", r))
            ck.replica_pubkeys[r] = s.public_bytes()
        first_client = n + cfg.num_ro_replicas
        for cl in range(first_client, first_client + num_clients):
            s = make_signer(ck.client_sig_scheme,
                            seed=_derive_seed(seed, "client", cl))
            ck.client_pubkeys[cl] = s.public_bytes()
        # operator principal (reconfiguration commands): its id must match
        # ReplicasInfo.operator_id, which derives from the CONFIG's client
        # count — not this function's num_clients parameter (callers may
        # generate extra client keys). Distinct seed label so no client
        # enumeration can ever mint the operator's keypair.
        operator_id = first_client + cfg.num_of_client_proxies + n
        s = make_signer(ck.client_sig_scheme,
                        seed=_derive_seed(seed, "operator", operator_id))
        ck.client_pubkeys[operator_id] = s.public_bytes()
        ck.operator_id = operator_id
        ck.slow_path_system = Cryptosystem(
            scheme, 2 * f + c + 1, n, seed=_derive_seed(seed, "slow"))
        ck.commit_path_system = Cryptosystem(
            scheme, 3 * f + c + 1, n, seed=_derive_seed(seed, "fastthresh"))
        ck.optimistic_system = Cryptosystem(
            scheme, n, n, seed=_derive_seed(seed, "optimistic"))
        ck._seed = seed
        return ck

    def for_node(self, node_id: int) -> "ClusterKeys":
        """This node's private view (sign seed derivation)."""
        if node_id == self.operator_id:
            kind = "operator"
        elif node_id < self.n:
            kind = "replica"
        else:
            kind = "client"
        me = ClusterKeys(
            n=self.n, f=self.f, c=self.c,
            threshold_scheme=self.threshold_scheme,
            replica_sig_scheme=self.replica_sig_scheme,
            client_sig_scheme=self.client_sig_scheme,
            replica_pubkeys=self.replica_pubkeys,
            client_pubkeys=self.client_pubkeys,
            my_id=node_id, operator_id=self.operator_id,
            my_sign_seed=_derive_seed(self._seed, kind, node_id),
            slow_path_system=self.slow_path_system,
            commit_path_system=self.commit_path_system,
            optimistic_system=self.optimistic_system)
        me._seed = self._seed
        return me

    # ---- accessors ----
    def scheme_of(self, node: int) -> str:
        """Signature scheme for a principal: replicas (incl. read-only ids
        below the first client) sign with the replica scheme, every client
        principal (operator included) with the client scheme."""
        return (self.replica_sig_scheme if node in self.replica_pubkeys
                else self.client_sig_scheme)

    def my_signer(self) -> ISigner:
        assert self.my_sign_seed is not None
        return make_signer(self.scheme_of(self.my_id)
                           if self.my_id is not None
                           else self.replica_sig_scheme,
                           seed=self.my_sign_seed)

    def verifier_of(self, node: int) -> IVerifier:
        pk = self.replica_pubkeys.get(node) or self.client_pubkeys.get(node)
        if pk is None:
            raise KeyError(f"no public key for node {node}")
        return make_verifier(self.scheme_of(node), pk)

    def threshold_signer(self, system: Cryptosystem,
                         replica_id: int) -> IThresholdSigner:
        """Threshold signer ids are 1-based in the reference."""
        return system.create_threshold_signer(replica_id + 1)

    def threshold_verifier(self, system: Cryptosystem,
                           backend: str = "cpu",
                           min_device_batch: int = 1) -> IThresholdVerifier:
        """Backend-selected threshold verifier over the same key material
        (reference: Cryptosystem::createThresholdVerifier,
        ThresholdSignaturesTypes.cpp:183 — the TPU backend slots in behind
        the identical boundary)."""
        if backend == "tpu":
            from tpubft.crypto import tpu as tpu_backend
            return tpu_backend.make_threshold_verifier(
                system.type_name, system.threshold_, system.num_signers,
                system.public_key, system.share_public_keys,
                min_device_batch)
        return system.create_threshold_verifier()
