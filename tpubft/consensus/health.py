"""Replica health plane: stall watchdog + aggregate degradation verdict.

PRs 3-4 moved execution and admission onto dedicated threads; this
module is the runtime answer to one of those planes *stopping*. A
`HealthMonitor` per replica tracks liveness probes —

  * `dispatcher`      — the consensus thread's tick age (a 0.2s timer
                        beats it; a wedged handler or deadlocked
                        dispatcher stops the beats);
  * `exec_lane`       — executor-thread progress, thresholded at
                        `execution_drain_timeout_ms` (the same budget
                        the dispatcher-side drain barrier uses, so a
                        drain that WOULD time out is reported, not
                        silently eaten); busy only while slots are
                        pending/in flight;
  * `admission`       — worker-loop beats, busy only while the ingest
                        queue holds traffic;
  * `state_transfer`  — the fetch plane's last-activity pulse, busy
                        only while fetching

— and folds them with the device circuit-breaker registry
(tpubft/utils/breaker.py) and any registered degradation flags (e.g.
admission overload shedding) into one verdict:

    healthy   — all busy probes beating, breakers CLOSED, no shedding
    degraded  — live, but a breaker is OPEN/HALF_OPEN or a subsystem
                is load-shedding (the measured mode, not an outage)
    stalled   — a busy probe's beat age exceeded its threshold

The verdict rides the existing diagnostics server as `status get
health` (JSON: verdict + per-probe ages + breaker snapshots + shed
flags) and the metrics aggregator as a `health` component. On a probe's
transition into `stalled`, the monitor dumps every Python thread's
stack plus queue depths and breaker states to the log ONCE (re-armed
when the probe beats again) — the post-hoc diagnosability the
racecheck StallWatchdog provides for tests, promoted to an always-on
replica subsystem.
"""
from __future__ import annotations

import json
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional

from tpubft.utils import breaker as breaker_mod
from tpubft.utils import flight
from tpubft.utils.logging import get_logger
from tpubft.utils.metrics import Aggregator, Component

log = get_logger("health")

HEALTHY = "healthy"
DEGRADED = "degraded"
STALLED = "stalled"


class _Probe:
    __slots__ = ("name", "threshold_s", "busy_fn", "detail_fn", "last_fn",
                 "last_beat", "reported")

    def __init__(self, name: str, threshold_s: float,
                 busy_fn: Optional[Callable[[], bool]],
                 detail_fn: Optional[Callable[[], object]],
                 last_fn: Optional[Callable[[], float]],
                 now: float) -> None:
        self.name = name
        self.threshold_s = threshold_s
        self.busy_fn = busy_fn            # None = always considered busy
        self.detail_fn = detail_fn        # queue depths etc. for dumps
        self.last_fn = last_fn            # pulse source overriding beats
        self.last_beat = now
        self.reported = False             # stall dumped (re-armed on beat)


class HealthMonitor:
    """One per replica. Probes beat from their own threads; a daemon
    poll thread computes verdicts and fires stall dumps. `render()` is
    also safe to call inline (the diagnostics status handler does)."""

    def __init__(self, name: str, aggregator: Optional[Aggregator] = None,
                 poll_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._name = name
        self._clock = clock
        self.poll_s = poll_s
        self._mu = threading.Lock()
        self._probes: Dict[str, _Probe] = {}
        self._degraded_flags: Dict[str, Callable[[], bool]] = {}
        self._info_sections: Dict[str, Callable[[], object]] = {}
        self._running = False
        self._thread: Optional[threading.Thread] = None

        self.metrics = Component("health", aggregator)
        self.m_verdict = self.metrics.register_status("verdict", HEALTHY)
        self.m_breakers = self.metrics.register_status("breakers", "")
        self.m_stall_dumps = self.metrics.register_counter("stall_dumps")
        self.m_stalled_probes = self.metrics.register_gauge("stalled_probes")
        self.m_flight_dumps = self.metrics.register_counter("flight_dumps")
        self._age_gauges: Dict[str, object] = {}
        # flight-dump plane: the verdict seen by the LAST poll, so a
        # transition into degraded/stalled writes exactly one artifact
        # per episode (re-armed when the verdict recovers). A flapping
        # source (e.g. a breaker cycling through half-open probes)
        # oscillates the verdict every few seconds — the min-interval
        # throttle keeps that from writing an artifact per flap, while
        # flight.MAX_DUMPS bounds total disk either way.
        self._last_verdict = HEALTHY
        self.last_flight_dump: Optional[str] = None
        self.dump_min_interval_s = 10.0
        self._last_dump_at: Optional[float] = None

    # ------------------------------------------------------------------
    # registration + beats (any thread)
    # ------------------------------------------------------------------
    def register_probe(self, name: str, threshold_s: float,
                       busy_fn: Optional[Callable[[], bool]] = None,
                       detail_fn: Optional[Callable[[], object]] = None,
                       last_fn: Optional[Callable[[], float]] = None
                       ) -> None:
        with self._mu:
            self._probes[name] = _Probe(name, threshold_s, busy_fn,
                                        detail_fn, last_fn, self._clock())
        self._age_gauges[name] = self.metrics.register_gauge(
            f"{name}_age_ms")

    def unregister_probe(self, name: str) -> None:
        with self._mu:
            self._probes.pop(name, None)

    def register_degraded_flag(self, name: str,
                               fn: Callable[[], bool]) -> None:
        """A boolean degradation source (e.g. admission shed mode): True
        pulls the aggregate verdict to `degraded` while set."""
        with self._mu:
            self._degraded_flags[name] = fn

    def register_info_section(self, name: str,
                              fn: Callable[[], object]) -> None:
        """An informational payload merged into verdict() under `name`.
        Purely additive observability — sections never move the
        aggregate verdict (that is what probes/flags/breakers are for);
        a raising section reports its error string instead of taking
        the monitor down."""
        with self._mu:
            self._info_sections[name] = fn

    def beat(self, name: str) -> None:
        now = self._clock()
        with self._mu:
            p = self._probes.get(name)
            if p is not None:
                p.last_beat = now
                p.reported = False

    # ------------------------------------------------------------------
    # verdicts
    # ------------------------------------------------------------------
    def _probe_states(self) -> List[Dict]:
        now = self._clock()
        with self._mu:
            probes = list(self._probes.values())
        out = []
        for p in probes:
            last = p.last_beat
            if p.last_fn is not None:
                try:
                    last = max(last, p.last_fn())
                except Exception:  # noqa: BLE001 — a probe source must
                    pass           # not take down the monitor
            age = max(0.0, now - last)
            busy = True
            if p.busy_fn is not None:
                try:
                    busy = bool(p.busy_fn())
                except Exception:  # noqa: BLE001
                    busy = True
            stalled = busy and age > p.threshold_s
            detail = None
            if p.detail_fn is not None:
                try:
                    detail = p.detail_fn()
                except Exception:  # noqa: BLE001
                    detail = "<detail error>"
            out.append({"name": p.name, "age_ms": round(age * 1e3, 1),
                        "threshold_ms": round(p.threshold_s * 1e3, 1),
                        "state": (STALLED if stalled
                                  else "ok" if busy else "idle"),
                        "detail": detail})
        return out

    def verdict(self) -> Dict:
        probes = self._probe_states()
        breakers = breaker_mod.snapshot_all()
        with self._mu:
            flags = list(self._degraded_flags.items())
        degraded = {}
        for name, fn in flags:
            try:
                degraded[name] = bool(fn())
            except Exception:  # noqa: BLE001
                degraded[name] = False
        stalled = [p["name"] for p in probes if p["state"] == STALLED]
        if stalled:
            agg = STALLED
        elif any(b["state"] != breaker_mod.CLOSED
                 for b in breakers.values()) or any(degraded.values()):
            agg = DEGRADED
        else:
            agg = HEALTHY
        out = {"verdict": agg, "stalled": stalled, "probes": probes,
               "breakers": breakers, "degraded": degraded}
        # mesh summary (ISSUE 16): chip inventory / evictions ride the
        # health payload so a shrunken crypto plane is visible without
        # decoding the per-chip `device.chip<N>` breaker rows. Only
        # reported once the mesh manager exists — constructing it here
        # would force a jax backend probe on chip-less deployments.
        from tpubft.parallel import sharding as _sh
        if _sh._MESH_MGR is not None:
            out["mesh"] = _sh._MESH_MGR.snapshot()
        # offload summary (ISSUE 20): helper roster / quarantine set /
        # lease counters, same rationale as the mesh section — visible
        # without decoding per-helper `helper.<id>` breaker rows. Gated
        # on the module being live so chip-less or offload-off
        # deployments pay nothing (pool construction registers a flight
        # dump provider; don't force that from a read path).
        _off = sys.modules.get("tpubft.offload.pool")
        if _off is not None and _off._POOL is not None:
            out["offload"] = _off._POOL.snapshot()
        with self._mu:
            sections = list(self._info_sections.items())
        for name, fn in sections:
            try:
                out[name] = fn()
            except Exception as e:  # noqa: BLE001 — a section source
                out[name] = f"<error: {e}>"  # must not kill the monitor
        return out

    def render(self) -> str:
        """`status get health` payload."""
        return json.dumps(self.verdict(), sort_keys=True)

    # ------------------------------------------------------------------
    # poll thread
    # ------------------------------------------------------------------
    def start(self) -> None:
        with self._mu:
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"health-{self._name}")
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        t = self._thread
        if t is not None:
            t.join(timeout=2)
            self._thread = None

    def _run(self) -> None:
        while self._running:
            time.sleep(self.poll_s)
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the watchdog must outlive
                log.exception("health poll failed")   # anything it watches

    def poll_once(self) -> Dict:
        """One verdict pass: refresh metrics, fire stall dumps for
        probes newly past their threshold. Public for tests (and usable
        without the thread)."""
        v = self.verdict()
        self.m_verdict.set(v["verdict"])
        self.m_stalled_probes.set(len(v["stalled"]))
        # flight-dump plane: every transition INTO a non-healthy
        # verdict captures the timeline that led there (rings + kernel
        # profile + lock hold stats + queue depths ride the artifact)
        if v["verdict"] != self._last_verdict:
            flight.record(flight.EV_HEALTH,
                          arg={HEALTHY: 0, DEGRADED: 1,
                               STALLED: 2}.get(v["verdict"], 0))
            now = self._clock()
            throttled = (self._last_dump_at is not None
                         and now - self._last_dump_at
                         < self.dump_min_interval_s)
            if v["verdict"] in (DEGRADED, STALLED) and not throttled:
                self._last_dump_at = now
                path = flight.dump(
                    reason=f"{self._name}-{v['verdict']}",
                    extra={"probes": v["probes"],
                           "breakers": v["breakers"],
                           "degraded": v["degraded"],
                           "stalled": v["stalled"]})
                if path is not None:
                    self.last_flight_dump = path
                    self.m_flight_dumps.inc()
                    log.warning("%s: verdict %s -> %s; flight dump "
                                "written to %s", self._name,
                                self._last_verdict, v["verdict"], path)
            self._last_verdict = v["verdict"]
        self.m_breakers.set(json.dumps(
            {n: b["state"] for n, b in v["breakers"].items()},
            sort_keys=True))
        for p in v["probes"]:
            g = self._age_gauges.get(p["name"])
            if g is not None:
                g.set(int(p["age_ms"]))
        fresh = []
        with self._mu:
            for name in v["stalled"]:
                p = self._probes.get(name)
                if p is not None and not p.reported:
                    p.reported = True
                    fresh.append(name)
        if fresh:
            self.m_stall_dumps.inc(len(fresh))
            self._dump(fresh, v)
        return v

    def _dump(self, stalled: List[str], v: Dict) -> None:
        lines = [f"{self._name}: STALL verdict — no progress from "
                 f"{stalled} past threshold; state and all thread "
                 f"stacks follow",
                 "probes: " + json.dumps(v["probes"]),
                 "breakers: " + json.dumps(v["breakers"]),
                 "degraded: " + json.dumps(v["degraded"])]
        if self.last_flight_dump:
            lines.append(f"flight dump: {self.last_flight_dump}")
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in frames.items():
            lines.append(f"--- thread {names.get(ident, ident)} ---")
            lines.append("".join(traceback.format_stack(frame)))
        log.error("%s", "\n".join(lines))
