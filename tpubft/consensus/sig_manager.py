"""Per-message signing and verification.

Rebuild of the reference's SigManager singleton
(/root/reference/bftengine/src/bftengine/SigManager.hpp:32; verifySig
SigManager.cpp:197, sign :240): holds this replica's signer plus a verifier
per principal (replicas + clients), with verified/failed metrics.

TPU-first delta: ALL verification flows through one batched plane.
`verify` is a batch of one; `BatchVerifier` coalesces async admission
traffic into fixed-size batches; `verify_batch` front-runs everything
with a bounded LRU memo of already-verified (principal, digest, sig)
triples (retransmissions and view-change re-validation re-present
identical items), then dispatches the residue as per-curve kernel calls
(tpubft.ops.ed25519 / ops.ecdsa via the configured batch_fn) or the
per-principal scalar fallback. Per-path counters (`memo_hits`,
`batched_verifies`, `scalar_fallbacks`) ride the metrics component.
This takes the per-message sig check off the dispatcher thread, the
reference's RequestThreadPool role.
"""
from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from tpubft.consensus.keys import ClusterKeys
from tpubft.crypto.interfaces import IVerifier
from tpubft.ops.dispatch import BreakerOpen, device_breaker
from tpubft.utils.logging import get_logger
from tpubft.utils.metrics import Aggregator, Component

log = get_logger("sigmgr")


class SigManager:
    def __init__(self, keys: ClusterKeys,
                 aggregator: Optional[Aggregator] = None,
                 verifier_factory: Optional[Callable[[bytes], IVerifier]] = None,
                 alias_fn: Optional[Callable[[int], int]] = None,
                 grace_seq_window: int = 300,
                 batch_fn: Optional[Callable[
                     [Sequence[Tuple[bytes, bytes, bytes]]],
                     List[bool]]] = None,
                 device_min_batch: int = 1,
                 memo_capacity: int = 4096,
                 verifier_cache_max: int = 4096):
        self._keys = keys
        # cross-principal batch backend: [(scheme, pubkey, data, sig)] ->
        # verdicts in ONE dispatch per scheme (the TPU path; None =
        # per-principal loop)
        self._batch_fn = batch_fn
        # batches smaller than this verify on the per-principal CPU
        # verifiers — a device dispatch only pays off once it amortizes
        # over enough signatures (SURVEY §7 hard part 6)
        self.device_min_batch = device_min_batch
        # a superseded key only verifies messages whose consensus seqnum
        # is at most rotation_seq + this window (callers pass the
        # config's work_window_size: everything deeper in flight than the
        # work window cannot order anyway)
        self.grace_seq_window = grace_seq_window
        # own copies: key exchange rotates keys per-replica-process, and the
        # shared ClusterKeys dicts must not leak one node's view to others.
        # Client keys are exempt — rotation never mutates them, so a
        # virtual keyspace (a lazy Mapping deriving 1M principals' keys
        # on demand, the bench_dispatch --principals shape) is kept by
        # reference instead of being materialized into a 1M-entry dict.
        self._replica_pubkeys: Dict[int, bytes] = dict(keys.replica_pubkeys)
        cpk = keys.client_pubkeys
        self._client_pubkeys = dict(cpk) if type(cpk) is dict else cpk
        # rotation grace keys: principal -> (old pubkey, rotated_at)
        self._prev_pubkeys: Dict[int, Tuple[bytes, float]] = {}
        self._signer = keys.my_signer() if keys.my_sign_seed else None
        # bounded verifier cache: touched principals would otherwise pin
        # one IVerifier each forever — O(principals) resident at scale
        self._verifiers: "OrderedDict[int, IVerifier]" = OrderedDict()
        self._verifier_cache_max = max(1, verifier_cache_max)
        self._prev_verifiers: Dict[int, IVerifier] = {}
        # verify() runs on the dispatcher AND on collector-pool workers
        # (async PP batches); key rotation + grace-key expiry mutate the
        # shared dicts, so those sections take this lock
        self._lock = threading.Lock()
        self._verifier_factory = verifier_factory
        # maps alias principals (e.g. internal-client ids) onto the
        # replica principal whose key signs for them
        self._alias = alias_fn or (lambda p: p)
        self.metrics = Component("signature_manager", aggregator)
        self.sigs_verified = self.metrics.register_counter("sigs_verified")
        self.sig_failures = self.metrics.register_counter("sig_failures")
        self.sigs_signed = self.metrics.register_counter("sigs_signed")
        # signatures dispatched through the cross-principal device batch
        # (dispatch count, not verdicts — failures land in sig_failures)
        self.sigs_device_dispatched = self.metrics.register_counter(
            "sigs_device_dispatched")
        # of those, items whose ride went out over a multi-chip mesh
        # (ISSUE 16): sharded == dispatched on a healthy mesh, so
        # dispatched-minus-sharded exposes single-chip regressions
        # (evictions, capped `crypto_shard_count`) on live telemetry
        self.mesh_sharded_verifies = self.metrics.register_counter(
            "mesh_sharded_verifies")
        # verified-signature memo: bounded LRU of (principal, current
        # pubkey, sha256(data), sig) that already verified under the
        # CURRENT key. Retransmissions and view-change re-validation
        # re-present identical triples; a hit short-circuits the full
        # kernel/scalar cost. Keying on the pubkey makes rotation safe
        # for free: a rotated principal's entries simply stop matching
        # (and sigs accepted only via a grace key are never memoized).
        self._memo: "OrderedDict[Tuple, None]" = OrderedDict()
        self._memo_capacity = memo_capacity
        self._memo_lock = threading.Lock()
        # per-path counters (ROADMAP: make the batched plane *the* hot
        # path and prove it) — memo short-circuits, items verified
        # through the coalesced cross-principal batch, and items that
        # fell back to the per-principal scalar loop
        self.memo_hits = self.metrics.register_counter("memo_hits")
        # entries LRU-evicted from the bounded memo. At steady state a
        # high eviction rate alongside a falling memo hit-rate means the
        # live principal population outruns memo_capacity — the signal
        # (with the client-table and comb-cache eviction counters) that
        # distinguishes "cache too small" from "population churned"
        # at million-principal scale (docs/OPERATIONS.md client-plane
        # scaling section)
        self.memo_evictions = self.metrics.register_counter(
            "memo_evictions")
        # per-principal verifier objects LRU-evicted from the bounded
        # cache (re-created on next touch from the pubkey — an eviction
        # costs one verifier construction, never correctness)
        self.verifier_evictions = self.metrics.register_counter(
            "verifier_evictions")
        self.batched_verifies = self.metrics.register_counter(
            "batched_verifies")
        self.scalar_fallbacks = self.metrics.register_counter(
            "scalar_fallbacks")
        # items rerouted device→scalar at RUNTIME (device exception or a
        # tripped circuit breaker) — a nonzero value means the system ran
        # in degraded verification mode; the breaker snapshot says why
        self.degraded_verifies = self.metrics.register_counter(
            "degraded_verifies")
        # ECDSA two-tier sensors (ROADMAP item 8 autotuner inputs): the
        # device tier's batch stats flow through the kernel profiler
        # (device_section("ecdsa")); the host tier is counted here —
        # items through crypto/scalar.ecdsa_verify_batch (attributed to
        # THIS manager via the thread-local stats sink wrapped around
        # its verification, so every route it takes is covered) and
        # pubkey-decode memo hits (decode + on-curve check paid once per
        # key, not per retransmitted verify)
        self.ecdsa_batched_host = self.metrics.register_counter(
            "ecdsa_batched_host")
        self.pubkey_memo_hits = self.metrics.register_counter(
            "pubkey_memo_hits")
        # bounded-LRU evictions in the scalar engine's per-principal
        # caches (pubkey-decode entries / hot comb tables) attributed to
        # this manager's verifies — read next to pubkey_memo_hits: a
        # high eviction rate with a falling hit-rate means the worker's
        # principal population outruns TPUBFT_ECDSA_PK_CACHE
        self.ecdsa_pk_evictions = self.metrics.register_counter(
            "ecdsa_pk_evictions")
        self.ecdsa_comb_evictions = self.metrics.register_counter(
            "ecdsa_comb_evictions")
        # cumulative wall time the batched host engine spent on THIS
        # manager's items (µs) — with ecdsa_batched_host this yields the
        # host tier's per-item cost, the sensor the autotuner compares
        # against the kernel profiler's `ecdsa` device tier to place
        # the crossover knob
        self.ecdsa_host_us = self.metrics.register_counter(
            "ecdsa_host_us")
        from tpubft.diagnostics import get_registrar
        # replica-scoped (PR 11's replica<id>.combine_batch_size
        # convention) so in-process multi-replica topologies don't
        # co-mingle batch-shape samples
        who = "" if keys.my_id is None else keys.my_id
        self._h_ecdsa_host_batch = get_registrar().histogram(
            f"sigmgr{who}.ecdsa_host_batch", unit="items")

    # ---- signing ----
    def sign(self, data: bytes) -> bytes:
        assert self._signer is not None, "no private key on this node"
        self.sigs_signed.inc()
        return self._signer.sign(data)

    def sign_batch(self, datas: Sequence[bytes]) -> List[bytes]:
        """Sign many payloads under this node's key in one call. Signers
        exposing a native batch (the scalar ed25519 engine's lockstep
        comb walk + Montgomery batch inversion) amortize the per-item
        field inversions across the batch; others degrade to a loop.
        The durability pipeline signs each sealed group's reply burst
        through here — one batched sign per group instead of one scalar
        sign per request (ROADMAP item 4b)."""
        assert self._signer is not None, "no private key on this node"
        if not datas:
            return []
        self.sigs_signed.inc(len(datas))
        batch = getattr(self._signer, "sign_batch", None)
        if batch is not None:
            return batch(datas)
        return [self._signer.sign(d) for d in datas]

    @property
    def my_id(self) -> Optional[int]:
        return self._keys.my_id

    # ---- key rotation (KeyExchangeManager upcalls) ----
    # wall-clock backstop used ONLY for rotations without a seqnum
    # context; seq-scoped rotations expire by CHECKPOINT ERA instead —
    # on_stable() drops a superseded key once stability passes its grace
    # window (the reference's per-checkpoint-era CryptoManager lookup,
    # CryptoManager.hpp:109)
    GRACE_WINDOW_S = 30.0

    def set_replica_key(self, replica_id: int, new_pubkey: bytes,
                        rotation_seq: Optional[int] = None) -> None:
        """Swap a replica's public key. The previous key is kept only for
        verifying messages at seqnums ordered before (or immediately
        around) the exchange at `rotation_seq`; verifications that carry
        no seqnum context never fall back to it."""
        with self._lock:
            old = self._replica_pubkeys.get(replica_id)
            if old == new_pubkey:
                return
            if old is not None:
                self._prev_pubkeys[replica_id] = (old, time.monotonic(),
                                                  rotation_seq)
                self._prev_verifiers.pop(replica_id, None)
            self._replica_pubkeys[replica_id] = new_pubkey
            self._verifiers.pop(replica_id, None)

    def set_my_signer(self, signer) -> None:
        self._signer = signer

    def on_stable(self, stable_seq: int) -> None:
        """Checkpoint-era expiry: once stability passes a rotation's
        grace window, nothing signed under the old key can order anymore
        — drop it (callers: replica._on_seq_stable)."""
        with self._lock:
            for p in [p for p, (_, _, rot_seq) in self._prev_pubkeys.items()
                      if rot_seq is not None
                      and stable_seq >= rot_seq + self.grace_seq_window]:
                self._prev_pubkeys.pop(p, None)
                self._prev_verifiers.pop(p, None)

    # ---- verification ----
    def _scheme_of(self, principal: int) -> str:
        """Per-principal signature scheme (reference SigManager builds a
        scheme-specific verifier per principal from the keyfile; BASELINE
        configs 3/5 mix secp256k1 clients with EdDSA replicas)."""
        scheme = getattr(self._keys, "scheme_of", None)
        return scheme(principal) if scheme is not None else "ed25519"

    def _make_verifier(self, pk: bytes, principal: int) -> IVerifier:
        if self._verifier_factory is not None:
            return self._verifier_factory(pk)
        from tpubft.crypto.cpu import make_verifier
        return make_verifier(self._scheme_of(principal), pk)

    def _pubkey_of(self, principal: int) -> Optional[bytes]:
        return (self._replica_pubkeys.get(principal)
                or self._client_pubkeys.get(principal))

    def _verifier(self, principal: int) -> IVerifier:
        # the whole get-or-create holds the lock: a worker thread must not
        # read a pre-rotation pubkey, lose the CPU to the dispatcher's
        # set_replica_key, then cache a verifier for the rotated-away key
        principal = self._alias(principal)
        evicted = 0
        with self._lock:
            v = self._verifiers.get(principal)
            if v is not None:
                self._verifiers.move_to_end(principal)
                return v
            pk = self._pubkey_of(principal)
            if pk is None:
                raise KeyError(f"no public key for principal {principal}")
            v = self._verifiers[principal] = self._make_verifier(
                pk, principal)
            while len(self._verifiers) > self._verifier_cache_max:
                self._verifiers.popitem(last=False)
                evicted += 1
        if evicted:
            self.verifier_evictions.inc(evicted)
        return v

    def _grace_verifier(self, principal: int, seq: Optional[int],
                        view_scoped: bool = False) -> Optional[IVerifier]:
        """Old-key verifier for in-flight consensus messages only: scoped
        to seqnums at most rotation_seq + grace_seq_window, or (for
        view-change-family messages, which carry views not seqnums) to the
        wall-clock window. Verifications with neither context — e.g.
        client requests — never accept a rotated-away key (a compromised
        pre-rotation key must not keep authenticating arbitrary traffic)."""
        principal = self._alias(principal)
        with self._lock:
            entry = self._prev_pubkeys.get(principal)
            if entry is None:
                return None
            pk, rotated_at, rotation_seq = entry
            expired_wallclock = (time.monotonic() - rotated_at
                                 > self.GRACE_WINDOW_S)
            if rotation_seq is None and expired_wallclock:
                # no seqnum scope exists: the wall clock is the only
                # bound, and past it the leaked/old key must stop
                # verifying — that's the point of rotating
                self._prev_pubkeys.pop(principal, None)
                self._prev_verifiers.pop(principal, None)
                return None
            if seq is None:
                # view-change-family messages have no seqnum to scope by,
                # so the wall clock ALWAYS bounds them — a sustained view
                # change (no checkpoints stabilizing, on_stable never
                # firing) must not let a leaked key authenticate
                # view-scoped traffic indefinitely. The entry itself
                # survives for seq-scoped lookups until on_stable.
                if not view_scoped or expired_wallclock:
                    return None
            elif rotation_seq is not None \
                    and seq > rotation_seq + self.grace_seq_window:
                return None
            v = self._prev_verifiers.get(principal)
            if v is None:
                v = self._prev_verifiers[principal] = self._make_verifier(
                    pk, principal)
            return v

    def has_principal(self, principal: int) -> bool:
        return self._pubkey_of(self._alias(principal)) is not None

    # ---- verified-signature memo ----
    # entries are (aliased principal, CURRENT pubkey, sha256(data), sig);
    # keys are built inline in _verify_items from one batched pubkey
    # resolution. No entry exists for unknown principals, and
    # memo_capacity=0 disables the memo (benchmarks measuring the raw
    # engine).
    def _memo_hit(self, key: Tuple) -> bool:
        with self._memo_lock:
            if key in self._memo:
                self._memo.move_to_end(key)
                return True
        return False

    def _memo_add(self, key: Tuple) -> None:
        evicted = 0
        with self._memo_lock:
            self._memo[key] = None
            self._memo.move_to_end(key)
            while len(self._memo) > self._memo_capacity:
                self._memo.popitem(last=False)
                evicted += 1
        if evicted:
            self.memo_evictions.inc(evicted)

    def verify(self, principal: int, data: bytes, sig: bytes,
               seq: Optional[int] = None,
               view_scoped: bool = False) -> bool:
        """Verify one signature — a thin wrapper over the batched plane
        (a batch of one), so every hot-path verify shares the memo and
        the coalescing machinery. `seq` is the consensus seqnum the
        message belongs to, when it has one; `view_scoped` marks
        view-change-family messages (no seqnum, still in-flight protocol
        traffic). One of the two is required for the post-rotation grace
        fallback — verifications without protocol context never accept a
        rotated-away key."""
        return self._verify_items([(principal, data, sig)], seq,
                                  view_scoped)[0]

    def verify_batch(self, items: Sequence[Tuple[int, bytes, bytes]],
                     seq: Optional[int] = None,
                     view_scoped: bool = False) -> List[bool]:
        """Verify [(principal, data, sig)] — the batch-plane entry the
        BatchVerifier/collector workers drain into (kept as the public
        seam: tests and wrappers intercept it to shape the async plane
        without touching inline dispatcher verifies)."""
        return self._verify_items(items, seq, view_scoped)

    def _verify_items(self, items: Sequence[Tuple[int, bytes, bytes]],
                      seq: Optional[int],
                      view_scoped: bool) -> List[bool]:
        """The one verification path: memo short-circuit first, then ONE
        cross-principal dispatch (per-curve kernel calls) when a batch
        backend is configured (TPU) and the residue is big enough to
        amortize it, otherwise grouped per principal with each verifier
        free to vectorize. Fresh verdicts verified under the current key
        are memoized for retransmit/duplicate traffic."""
        out: List[bool] = [False] * len(items)
        keys: List[Optional[Tuple]] = [None] * len(items)
        pending: List[int] = []
        # ONE lock acquisition resolves every principal's current pubkey;
        # the list feeds both the memo keys and the cross-batch dispatch
        # (per-item locking on a 1000-item admission batch is pure
        # overhead, and dispatch must not see a different key epoch than
        # the memo did)
        aliased = [self._alias(p) for p, _, _ in items]
        with self._lock:
            pks = [self._pubkey_of(a) for a in aliased]
        memo_on = self._memo_capacity > 0
        for i, ((p, data, sig), a, pk) in enumerate(zip(items, aliased,
                                                        pks)):
            key = ((a, pk, hashlib.sha256(data).digest(), bytes(sig))
                   if memo_on and pk is not None else None)
            if key is not None and self._memo_hit(key):
                out[i] = True
                self.memo_hits.inc()
            else:
                keys[i] = key
                pending.append(i)
        if pending:
            from tpubft.crypto import scalar as scalar_engine
            # thread-local attribution scope: the shared module-level
            # scalar engine records ECDSA batch/memo events into THIS
            # manager's sink (verification runs synchronously on this
            # thread), so per-replica metrics stay exact even when
            # several in-process replicas share the engine's caches
            sink = scalar_engine.new_stats_sink()
            with scalar_engine.attribute_stats(sink):
                self._verify_pending(items, pending, out, keys, aliased,
                                     pks, seq, view_scoped)
            self._fold_ecdsa_stats(sink)
        for ok in out:
            (self.sigs_verified if ok else self.sig_failures).inc()
        return out

    def _verify_pending(self, items, pending: List[int], out: List[bool],
                        keys: List[Optional[Tuple]], aliased, pks,
                        seq: Optional[int], view_scoped: bool) -> None:
        """Memo-miss residue: one cross-principal device dispatch when
        configured and the sub-batch is big enough, else the grouped
        host path. Successful current-key verdicts are memoized."""
        sub = [items[i] for i in pending]
        verdicts = None
        use_device = (self._batch_fn is not None
                      and len(sub) >= self.device_min_batch)
        if use_device and not device_breaker().allow():
            # non-mutating preview: while the breaker is OPEN, skip
            # building the device batch entirely instead of paying
            # list construction + a BreakerOpen round-trip on every
            # degraded verify (attempt() below still guards the
            # admitted path — a lost race just raises as before)
            self.degraded_verifies.inc(len(sub))
        elif use_device:
            try:
                verdicts, via_grace = self._verify_batch_cross(
                    sub, seq, view_scoped,
                    aliased=[aliased[i] for i in pending],
                    pks=[pks[i] for i in pending])
                self.batched_verifies.inc(len(sub))
            except BreakerOpen:
                # breaker tripped: fast-fail BEFORE the device — the
                # scalar engines carry the load until the half-open
                # probe re-admits the device
                self.degraded_verifies.inc(len(sub))
            except Exception:  # noqa: BLE001 — a device failure must
                # degrade verification, never fail it: the breaker
                # recorded the failure (trip after N consecutive)
                log.warning("device verify batch failed (%d items); "
                            "rerouting to scalar engines",
                            len(sub), exc_info=True)
                self.degraded_verifies.inc(len(sub))
        if verdicts is None:
            verdicts, via_grace = self._verify_batch_grouped(
                sub, seq, view_scoped)
            self.scalar_fallbacks.inc(len(sub))
        for i, ok, grace in zip(pending, verdicts, via_grace):
            out[i] = ok
            # grace-key acceptances are deliberately NOT memoized:
            # the memo must never outlive the grace window
            if ok and not grace and keys[i] is not None:
                self._memo_add(keys[i])

    def _fold_ecdsa_stats(self, sink) -> None:
        """Fold this manager's attributed scalar-engine events into its
        metrics component + batch-shape histogram (covers BOTH host
        routes — the grouped fallback and verify_batch_mixed's
        below-crossover ride, the default on a cpu backend). The drain
        is atomic per sink (StatsSink.drain swaps under the sink lock),
        so concurrent drains — two replicas' managers, or a drain
        racing a straggler increment — never lose or double-count."""
        stats = sink.drain()
        if stats["host_items"]:
            self.ecdsa_batched_host.inc(stats["host_items"])
        if stats["host_ns"]:
            self.ecdsa_host_us.inc(stats["host_ns"] // 1000)
        if stats["hits"]:
            self.pubkey_memo_hits.inc(stats["hits"])
        if stats["evictions"]:
            self.ecdsa_pk_evictions.inc(stats["evictions"])
        if stats["comb_evictions"]:
            self.ecdsa_comb_evictions.inc(stats["comb_evictions"])
        for size in stats["host_sizes"]:
            self._h_ecdsa_host_batch.record(size)

    def _verify_batch_grouped(self, items: Sequence[Tuple[int, bytes, bytes]],
                              seq: Optional[int], view_scoped: bool
                              ) -> Tuple[List[bool], List[bool]]:
        """Per-principal fallback: group items, let each verifier
        vectorize its group. Returns (verdicts, accepted-via-grace-key)."""
        by_principal: Dict[int, List[int]] = {}
        for i, (p, _, _) in enumerate(items):
            by_principal.setdefault(p, []).append(i)
        out = [False] * len(items)
        via_grace = [False] * len(items)
        for p, idxs in by_principal.items():
            try:
                verifier = self._verifier(p)
            except KeyError:
                continue
            results = verifier.verify_batch(
                [(items[i][1], items[i][2]) for i in idxs])
            grace = self._grace_verifier(p, seq, view_scoped)
            for i, ok in zip(idxs, results):
                if not ok and grace is not None \
                        and grace.verify(items[i][1], items[i][2]):
                    ok = via_grace[i] = True
                out[i] = ok
        return out, via_grace

    def _verify_batch_cross(self, items: Sequence[Tuple[int, bytes, bytes]],
                            seq: Optional[int], view_scoped: bool,
                            aliased: List[int],
                            pks: List[Optional[bytes]]
                            ) -> Tuple[List[bool], List[bool]]:
        """Run the whole batch through the backend in one call (one
        device dispatch per scheme present); failed items retry against
        grace keys. `aliased`/`pks` carry the caller's already-resolved
        principals (resolved under the lock — a worker must not race a
        key rotation into treating the rotated-away key as current).
        Returns (verdicts, accepted-via-grace-key)."""
        entries = []
        keyed = []
        for i, ((p, data, sig), a, pk) in enumerate(zip(items, aliased,
                                                        pks)):
            if pk is not None:
                entries.append((self._scheme_of(a), pk, data, sig))
                keyed.append(i)
        # the device ride runs under the circuit breaker: exceptions and
        # latency-SLO breaches count against the failure budget, an OPEN
        # breaker raises BreakerOpen before building any device work
        # (nested ops-level sections are pass-through — one failure is
        # one failure), and a short/garbage verdict vector classifies as
        # a device failure instead of silently truncating into drops
        with device_breaker().attempt("sig_verify"):
            verdicts = self._batch_fn(entries)
            if len(verdicts) != len(entries):
                raise RuntimeError(
                    f"batch backend returned {len(verdicts)} verdicts "
                    f"for {len(entries)} items")
        # counts only what actually reached the device dispatch
        self.sigs_device_dispatched.inc(len(entries))
        from tpubft.ops.dispatch import mesh_shards
        if mesh_shards() > 1:
            self.mesh_sharded_verifies.inc(len(entries))
        out = [False] * len(items)
        via_grace = [False] * len(items)
        for i, ok in zip(keyed, verdicts):
            if not ok:
                grace = self._grace_verifier(items[i][0], seq, view_scoped)
                if grace is not None and grace.verify(items[i][1],
                                                      items[i][2]):
                    ok = via_grace[i] = True
            out[i] = ok
        return out, via_grace


class PendingVerdict:
    """Future-like handle for one async verification."""
    __slots__ = ("_evt", "_ok")

    def __init__(self) -> None:
        self._evt = threading.Event()
        self._ok: Optional[bool] = None

    def set(self, ok: bool) -> None:
        if self._evt.is_set():
            return                    # first write wins: a late failure
                                      # path must not flip a delivered
                                      # verdict under a woken waiter
        self._ok = ok
        self._evt.set()

    def done(self) -> bool:
        return self._evt.is_set()

    def result(self, timeout: Optional[float] = None) -> bool:
        if not self._evt.wait(timeout):
            raise TimeoutError("verification not complete")
        return bool(self._ok)


class BatchVerifier:
    """Batching dispatcher: accumulates verify requests into fixed-size
    batches with a timeout flush, drains each batch in one
    `SigManager.verify_batch` call on a worker thread.

    This is the TPU seam (SURVEY §7 hard part 6): batch dispatch amortizes
    the host→TPU round trip; batch size/flush window come from
    ReplicaConfig.verify_batch_size / verify_batch_flush_us.
    """

    def __init__(self, sig_manager: SigManager, batch_size: int = 256,
                 flush_us: int = 200):
        from tpubft.utils.batcher import FlushBatcher
        self._sm = sig_manager
        self._batcher = FlushBatcher(
            self._drain, batch_size=batch_size, flush_us=flush_us,
            on_drop=lambda item: item[3](False),  # waiters must not hang
            name="batch-verifier")

    def submit(self, principal: int, data: bytes, sig: bytes) -> PendingVerdict:
        verdict = PendingVerdict()
        self._batcher.submit((principal, data, sig, verdict.set))
        return verdict

    def submit_nowait(self, principal: int, data: bytes, sig: bytes,
                      resolve) -> None:
        """Callback-style submission: `resolve(ok)` fires on the worker
        thread once the batch containing this item drains (False if the
        batch is dropped or the batcher is stopped). This is the
        non-blocking entry the replica's admission path uses — the
        dispatcher thread never waits on a verdict."""
        self._batcher.submit((principal, data, sig, resolve))

    def reconfigure(self, batch_size: int = None,
                    flush_us: int = None) -> None:
        """Autotuner actuator: retune the verify batch cap / flush
        window live (ReplicaConfig seeds the defaults; the knob
        registry owns them after startup)."""
        self._batcher.reconfigure(batch_size=batch_size,
                                  flush_us=flush_us)

    def _drain(self, batch) -> None:
        verdicts = self._sm.verify_batch([(p, d, s) for p, d, s, _ in batch])
        for (_, _, _, resolve), ok in zip(batch, verdicts):
            try:
                resolve(ok)
            except Exception:  # noqa: BLE001 — one bad callback must not
                pass           # fail the whole batch (double-resolving it)

    def stop(self) -> None:
        self._batcher.stop()
