"""Persistent consensus metadata — crash-recovery write-ahead state.

Rebuild of the reference's PersistentStorageImp
(/root/reference/bftengine/src/bftengine/PersistentStorageImp.cpp) +
ReplicaLoader (ReplicaLoader.cpp): transactional `begin/end_write_tran`
bracketing, descriptors (lastView, lastExecutedSeq, lastStableSeq), and
the seqnum-window contents (PrePrepare / full certificates) so a crashed
replica rejoins mid-protocol safely.

Two backends: InMemoryPersistentStorage (tests, NullStateTransfer-style)
and FilePersistentStorage (append-only JSON-lines WAL with atomic snapshot
compaction — the MetadataStorage-over-IDBClient role).
"""
from __future__ import annotations

import json
import os
import tempfile
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from tpubft.consensus import messages as m


@dataclass
class PersistedSeqState:
    pre_prepare: Optional[bytes] = None        # packed PrePrepareMsg
    prepare_full: Optional[bytes] = None       # packed PrepareFullMsg
    commit_full: Optional[bytes] = None        # packed CommitFullMsg
    full_commit_proof: Optional[bytes] = None  # packed FullCommitProofMsg
    slow_started: bool = False


class _TrackingSeqStates(dict):
    """seq_states dict that records per-seq dirt/deletions so incremental
    backends (DBPersistentStorage) persist only what changed in a
    transaction instead of re-encoding the full window every commit."""

    __slots__ = ("owner",)

    def __init__(self, owner: "PersistedState"):
        super().__init__()
        self.owner = owner

    def __setitem__(self, k, v):
        super().__setitem__(k, v)
        self.owner.dirty_seqs.add(k)
        self.owner.deleted_seqs.discard(k)

    def __delitem__(self, k):
        super().__delitem__(k)
        self.owner.dirty_seqs.discard(k)
        self.owner.deleted_seqs.add(k)

    def pop(self, k, *default):
        if k in self:
            self.owner.dirty_seqs.discard(k)
            self.owner.deleted_seqs.add(k)
        return super().pop(k, *default)

    def clear(self):
        self.owner.deleted_seqs.update(self.keys())
        self.owner.dirty_seqs.clear()
        super().clear()

    def update(self, *args, **kwargs):
        for k, v in dict(*args, **kwargs).items():
            self[k] = v                 # route through tracking

    def setdefault(self, k, default=None):
        if k not in self:
            self[k] = default           # route through tracking
        return super().__getitem__(k)


@dataclass
class PersistedState:
    """Everything needed to rejoin safely after a crash."""
    last_view: int = 0
    last_executed_seq: int = 0
    last_stable_seq: int = 0
    in_view_change: bool = False
    # target view of an in-flight view change (0 = none): a replica that
    # crashes between persisting in_view_change and completing the change
    # must know WHICH view it was moving to, or it cannot rebuild and
    # retransmit its ViewChangeMsg on restart (the quorum may be counting
    # on it)
    pending_view: int = 0
    seq_states: Dict[int, PersistedSeqState] = None  # set in __post_init__
    # view-change safety state (reference PersistentStorageDescriptors):
    # packed view_change.Restriction / messages.PreparedCertificate blobs
    restrictions: List[bytes] = field(default_factory=list)
    carried_certs: List[bytes] = field(default_factory=list)
    # packed PrePrepare bodies for the digests in carried_certs — certs
    # travel digest-only, so the bodies that must survive a crash live here
    carried_bodies: List[bytes] = field(default_factory=list)

    def __post_init__(self):
        # change-tracking for incremental backends; a seq appears in at
        # most one of the two sets. Backends drain both at commit.
        self.dirty_seqs: set = set()
        self.deleted_seqs: set = set()
        states = _TrackingSeqStates(self)
        if self.seq_states:                 # dataclasses.replace paths
            states.update(self.seq_states)
        self.seq_states = states

    def seq(self, seq_num: int) -> PersistedSeqState:
        st = self.seq_states.get(seq_num)
        if st is None:
            st = self.seq_states[seq_num] = PersistedSeqState()
        else:
            # the caller got a mutable entry: assume it changes
            self.dirty_seqs.add(seq_num)
        return st

    def clear_tracking(self) -> None:
        self.dirty_seqs.clear()
        self.deleted_seqs.clear()


class PersistentStorage:
    """Interface (reference PersistentStorage.hpp). Mutations must happen
    inside begin/end_write_tran; end commits atomically."""

    def begin_write_tran(self) -> PersistedState:
        raise NotImplementedError

    def end_write_tran(self) -> None:
        raise NotImplementedError

    def load(self) -> PersistedState:
        raise NotImplementedError


class InMemoryPersistentStorage(PersistentStorage):
    def __init__(self) -> None:
        self._state = PersistedState()
        self._depth = 0

    def begin_write_tran(self) -> PersistedState:
        self._depth += 1
        return self._state

    def end_write_tran(self) -> None:
        assert self._depth > 0
        self._depth -= 1
        if self._depth == 0:
            self._state.clear_tracking()    # whole state is live anyway

    def load(self) -> PersistedState:
        return self._state


class FilePersistentStorage(PersistentStorage):
    """Append-only WAL of state deltas with whole-state snapshots.

    Simple but crash-consistent: every end_write_tran appends one fsynced
    JSON line holding the FULL descriptor state + dirty seq entries;
    recovery replays the last complete line. Compaction rewrites the file
    atomically (tempfile + rename) when it grows past `compact_bytes`.
    """

    def __init__(self, path: str, compact_bytes: int = 4 << 20):
        self._path = path
        self._compact_bytes = compact_bytes
        self._state = self._recover()
        self._depth = 0
        self._fh = open(self._path, "ab")

    # ---- transactions ----
    def begin_write_tran(self) -> PersistedState:
        self._depth += 1
        return self._state

    def end_write_tran(self) -> None:
        assert self._depth > 0
        self._depth -= 1
        if self._depth == 0:
            self._state.clear_tracking()    # full-state WAL line follows
            line = json.dumps(self._encode(self._state),
                              separators=(",", ":")) + "\n"
            self._fh.write(line.encode())
            self._fh.flush()
            os.fsync(self._fh.fileno())
            if self._fh.tell() > self._compact_bytes:
                self._compact()

    def load(self) -> PersistedState:
        return self._state

    def close(self) -> None:
        self._fh.close()

    # ---- encoding ----
    @staticmethod
    def _encode(st: PersistedState) -> Dict[str, Any]:
        def b64(x: Optional[bytes]) -> Optional[str]:
            import base64
            return base64.b64encode(x).decode() if x is not None else None
        return {
            "v": st.last_view, "e": st.last_executed_seq,
            "s": st.last_stable_seq, "ivc": st.in_view_change,
            "pv": st.pending_view,
            "seqs": {str(k): {
                "pp": b64(v.pre_prepare), "pf": b64(v.prepare_full),
                "cf": b64(v.commit_full), "fcp": b64(v.full_commit_proof),
                "slow": v.slow_started,
            } for k, v in st.seq_states.items()},
            "restr": [b64(r) for r in st.restrictions],
            "certs": [b64(c) for c in st.carried_certs],
            "bodies": [b64(c) for c in st.carried_bodies],
        }

    @staticmethod
    def _decode(d: Dict[str, Any]) -> PersistedState:
        import base64

        def unb64(x: Optional[str]) -> Optional[bytes]:
            return base64.b64decode(x) if x is not None else None
        st = PersistedState(last_view=d["v"], last_executed_seq=d["e"],
                            last_stable_seq=d["s"], in_view_change=d["ivc"],
                            pending_view=d.get("pv", 0),
                            restrictions=[unb64(r)
                                          for r in d.get("restr", [])],
                            carried_certs=[unb64(c)
                                           for c in d.get("certs", [])],
                            carried_bodies=[unb64(c)
                                            for c in d.get("bodies", [])])
        for k, v in d.get("seqs", {}).items():
            st.seq_states[int(k)] = PersistedSeqState(
                pre_prepare=unb64(v["pp"]), prepare_full=unb64(v["pf"]),
                commit_full=unb64(v["cf"]),
                full_commit_proof=unb64(v["fcp"]), slow_started=v["slow"])
        return st

    def _recover(self) -> PersistedState:
        if not os.path.exists(self._path):
            return PersistedState()
        last = None
        with open(self._path, "rb") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    last = json.loads(line)
                except json.JSONDecodeError:
                    break  # torn tail write: stop at last complete line
        return self._decode(last) if last else PersistedState()

    def _compact(self) -> None:
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(self._path) or ".")
        with os.fdopen(fd, "wb") as out:
            out.write((json.dumps(self._encode(self._state),
                                  separators=(",", ":")) + "\n").encode())
            out.flush()
            os.fsync(out.fileno())
        os.replace(tmp, self._path)
        self._fh.close()
        self._fh = open(self._path, "ab")


def restore_replica_state(storage: PersistentStorage):
    """ReplicaLoader::loadReplica equivalent — returns the PersistedState
    plus unpacked window messages ready to seed a Replica."""
    st = storage.load()
    unpacked = {}
    for seq, entry in st.seq_states.items():
        if seq <= st.last_stable_seq:
            continue
        row = {}
        for name, raw in (("pre_prepare", entry.pre_prepare),
                          ("prepare_full", entry.prepare_full),
                          ("commit_full", entry.commit_full),
                          ("full_commit_proof", entry.full_commit_proof)):
            if raw is not None:
                try:
                    row[name] = m.unpack(raw)
                except m.MsgError:
                    row[name] = None
        row["slow_started"] = entry.slow_started
        unpacked[seq] = row
    return st, unpacked
