"""The replica: SBFT protocol state machine (slow path first).

Rebuild of the reference's ReplicaImp
(/root/reference/bftengine/src/bftengine/ReplicaImp.{hpp,cpp}): message
handlers per MsgCode (onMessage<ClientRequestMsg> :397,
onMessage<PrePrepareMsg> :1047, tryToSendPrePrepareMsg :657,
sendPreparePartial :1373, sendCommitPartial :1399,
executeNextCommittedRequests :5720), driven by the single dispatcher
thread; threshold combine/verify jobs run on the collector pool and
re-enter as internal msgs, exactly the reference's
CollectorOfThresholdSignatures round trip.

Commit flow implemented here (slow path, the PBFT-like 2-round core):
  ClientRequest → [primary] batch → PrePrepare
  → every replica sends PreparePartial (threshold share) to the collector
  → collector combines 2f+c+1 shares → PrepareFull broadcast → prepared
  → every replica sends CommitPartial → collector → CommitFull → committed
  → execute in seqnum order → ClientReply
Fast-path (PartialCommitProof/FullCommitProof) arrives in the fast-path
module; this replica already persists + window-manages for it.
"""
from __future__ import annotations

import abc
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from tpubft.comm.interfaces import ICommunication, IReceiver
from tpubft.consensus import messages as m
from tpubft.consensus.aggregation import overlay_for
from tpubft.consensus.clients_manager import ClientsManager
from tpubft.consensus.collectors import (ByzTelemetry, CollectorPool,
                                         CombineResult, ShareCollector)
from tpubft.consensus.controller import CommitPathController
from tpubft.consensus.epoch import EpochManager
from tpubft.consensus.incoming import Dispatcher, IncomingMsgsStorage
from tpubft.consensus.keys import ClusterKeys
from tpubft.consensus.persistent import (InMemoryPersistentStorage,
                                         PersistentStorage,
                                         restore_replica_state)
from tpubft.consensus.replicas_info import ReplicasInfo
from tpubft.consensus.seq_num_info import ActiveWindow, SeqNumInfo
from tpubft.consensus.sig_manager import SigManager
from tpubft.consensus.view_change import (CERT_COMMIT, CERT_FAST_OPT,
                                          CERT_FAST_THR, CERT_PREPARE,
                                          CERT_SIGNED, Restriction,
                                          ViewChangeState,
                                          build_certificates,
                                          compute_restrictions, pack_cert,
                                          pack_restriction, unpack_cert,
                                          unpack_restriction,
                                          validate_certificate)
from tpubft.crypto.digest import digest as sha256
# hot-loop imports hoisted to module scope: the execution path used to
# re-run these per request per slot (function-level `import` still pays
# a sys.modules lookup + binding on every execution)
from tpubft.diagnostics import TimeRecorder
from tpubft.testing.crashpoints import crashpoint
from tpubft.testing.slowdown import PHASE_EXECUTE
from tpubft.utils import flight
from tpubft.utils.config import ReplicaConfig
from tpubft.utils.logging import get_logger, mdc_scope
from tpubft.utils.metrics import Aggregator, Component
from tpubft.utils.racecheck import make_lock

log = get_logger("replica")


def share_digest(kind: str, epoch: int, view: int, seq_num: int,
                 pp_digest: bytes) -> bytes:
    """Domain-separated digest each threshold share signs: 'prepare' and
    'commit' rounds must not be cross-replayable (the reference separates
    them by message type inside the signed blob). The reconfiguration ERA
    is bound into the signed bytes, so the era gate on Prepare/Commit
    shares and FullCommitProof no longer rests on the unauthenticated
    `epoch` wire field — a share signed in a dead era can never combine
    into (or validate as) a certificate for the current one."""
    return sha256(kind.encode() + b"|"
                  + struct.pack("<QQQ", epoch, view, seq_num) + pp_digest)


class IRequestsHandler(abc.ABC):
    """Execution upcall (reference IRequestsHandler.hpp / RequestHandler)."""

    @abc.abstractmethod
    def execute(self, client_id: int, req_seq: int, flags: int,
                request: bytes) -> bytes: ...

    def read(self, client_id: int, request: bytes) -> bytes:
        """Read-only query — must not mutate state."""
        return b""

    def state_digest(self) -> bytes:
        """Digest of app state for checkpoint agreement."""
        return b"\x00" * 32

    # ---- pre-execution (reference IRequestsHandler PRE_PROCESS flag) ----
    def pre_execute(self, client_id: int, req_seq: int,
                    request: bytes) -> Optional[bytes]:
        """Speculative, side-effect-free execution. The returned bytes
        must be DETERMINISTIC across replicas regardless of their current
        state height (they are hashed for f+1 agreement). None =
        unsupported → the request falls back to normal ordering."""
        return None

    def apply_pre_executed(self, client_id: int, req_seq: int, flags: int,
                           original_request: bytes,
                           result: bytes) -> bytes:
        """Commit a pre-executed result, re-checking conflicts against
        current state. Default: execute the original normally."""
        return self.execute(client_id, req_seq, flags, original_request)

    def pre_exec_conflicted(self, client_id: int, req_seq: int,
                            original_request: bytes,
                            result: bytes) -> bool:
        """Commit-time conflict check for a pre-executed result: True
        when the result's read set is stale against CURRENT state (it
        was computed over an older snapshot) — the replica then falls
        back to ordering the original request normally in the same
        slot. Must be side-effect free. Default: never conflicted."""
        return False


class Replica(IReceiver):
    def __init__(self, cfg: ReplicaConfig, keys: ClusterKeys,
                 comm: ICommunication, handler: IRequestsHandler,
                 storage: Optional[PersistentStorage] = None,
                 aggregator: Optional[Aggregator] = None,
                 reserved_pages=None):
        cfg.validate()
        self.cfg = cfg
        self.id = cfg.replica_id
        self.info = ReplicasInfo.from_config(cfg)
        self.keys = keys
        self.comm = comm
        self.handler = handler
        self.storage = storage or InMemoryPersistentStorage()
        self.aggregator = aggregator or Aggregator()

        # crypto backend selection (the project's north star: the same
        # plugin boundaries the reference routes to CPU crypto —
        # SigManager.cpp:197, IThresholdVerifier.h:23 — route to the
        # batched TPU kernels when crypto_backend == "tpu"; "auto"
        # probes for a real device safely and picks for you)
        # --- degradation plane: device circuit breaker + health
        # watchdog (utils/breaker.py + consensus/health.py). The breaker
        # is process-wide (one accelerator per process); every replica
        # pushes its config — last writer wins, and all replicas of one
        # process share the verdicts, which matches sharing the device.
        from tpubft.consensus.health import HealthMonitor
        from tpubft.ops.dispatch import device_breaker
        device_breaker().configure(
            failure_threshold=cfg.breaker_failure_threshold,
            cooldown_s=cfg.breaker_cooldown_ms / 1e3,
            latency_slo_s=cfg.breaker_latency_slo_ms / 1e3,
            max_cooldown_s=cfg.breaker_cooldown_ms / 1e3 * 16)
        # --- verified crypto-offload tier (tpubft/offload/): lease the
        # heavy MSM/combine work to untrusted helper processes; every
        # returned result passes the constant-size soundness check
        # on-replica before it can influence any verdict. The pool is
        # process-wide like the device breaker (helpers serve the
        # process, not one replica); endpoint list is additive so
        # in-process tests can pre-register InprocHelper transports.
        if cfg.offload_enabled:
            from tpubft.ops.dispatch import offload_pool
            pool = offload_pool()
            pool.configure(enabled=True,
                           lease_timeout_ms=cfg.offload_lease_timeout_ms,
                           max_inflight=cfg.offload_max_inflight)
            for ep in filter(None, cfg.offload_helpers.split(",")):
                hid, addr = ep.split("=", 1)
                host, port = addr.rsplit(":", 1)
                pool.add_endpoint(hid.strip(), host.strip(), int(port))
        self.health = HealthMonitor(f"replica{cfg.replica_id}",
                                    self.aggregator,
                                    poll_s=cfg.health_poll_ms / 1e3)
        self.health.register_probe(
            "dispatcher", cfg.health_stall_ms / 1e3,
            detail_fn=lambda: {
                "external_q": self.incoming.external_depth,
                "internal_q": self.incoming.internal_depth})

        from tpubft.crypto.backend import resolve_backend
        backend = self.crypto_backend = resolve_backend(cfg.crypto_backend)
        # write the RESOLVED backend back: every later consumer of the
        # config (device hashing in kvbc, the startup log, metrics) must
        # see "cpu"/"tpu", never the unresolved "auto"
        cfg.crypto_backend = backend
        batch_fn = None
        if backend == "tpu":
            from tpubft.crypto import tpu as tpu_backend
            batch_fn = tpu_backend.verify_batch_mixed
        # singleton verifies stay on the CPU verifiers even with the TPU
        # backend (latency-critical, can't amortize a dispatch); batches
        # of >= device_min_verify_batch ride the device kernel
        self.sig = SigManager(
            keys, self.aggregator,
            alias_fn=lambda p: (self.info.owner_of_internal_client(p)
                                if self.info.is_internal_client(p) else p),
            grace_seq_window=cfg.work_window_size,
            batch_fn=batch_fn,
            device_min_batch=cfg.device_min_verify_batch)
        # threshold machinery per commit path (CryptoManager.hpp:109-111):
        # slow = 2f+c+1, fast-with-threshold = 3f+c+1, optimistic = n
        min_dev = cfg.device_min_verify_batch
        self.slow_signer = keys.threshold_signer(keys.slow_path_system,
                                                 self.id)
        self.slow_verifier = keys.threshold_verifier(keys.slow_path_system,
                                                     backend, min_dev)
        self.thr_signer = keys.threshold_signer(keys.commit_path_system,
                                                self.id)
        self.thr_verifier = keys.threshold_verifier(keys.commit_path_system,
                                                    backend, min_dev)
        self.opt_signer = keys.threshold_signer(keys.optimistic_system,
                                                self.id)
        self.opt_verifier = keys.threshold_verifier(keys.optimistic_system,
                                                    backend, min_dev)
        self.controller = CommitPathController(cfg.f_val, cfg.c_val)

        # --- share-aggregation overlay (consensus/aggregation.py) ---
        # Active only when the RESOLVED threshold scheme supports partial
        # aggregation (multisig-bls: unweighted G1 sums compose; Shamir
        # shares cannot — interfaces.IThresholdAccumulator.add_partial).
        # A pinned incompatible scheme degrades to "off" rather than
        # refusing to start; config.validate rejects the loud cases.
        self._agg_mode = (cfg.share_aggregation
                          if getattr(keys, "threshold_scheme", "")
                          == "multisig-bls" else "off")
        self._agg_fanout = max(2, cfg.agg_fanout)
        # interior-node banking: (view, seq, kind, digest) -> {entry_key:
        # raw 48B share | 56B partial}, entry keys 1-based (signer id for
        # raw, forwarding child + 1 for partials) — the same keying the
        # root's ShareCollector uses, so bad-entry isolation composes
        self._agg_buffers: Dict[tuple, Dict[int, bytes]] = {}
        self._agg_buffer_born: Dict[tuple, float] = {}
        # membership snapshot of the last flush per buffer: flushes are
        # CUMULATIVE — a buffer re-flushes (as a superset partial that
        # supersedes the previous one upstream) whenever new members
        # arrived, so an early age-based flush never strands the
        # children that were still in flight
        self._agg_flushed: Dict[tuple, frozenset] = {}
        # leaf/interior liveness floor: (view, seq, kind) -> (deadline,
        # share msg, collector id); on parent timeout the original share
        # re-sends DIRECT to the collector — aggregation can delay a
        # slot by at most agg_parent_timeout_ms, never lose it
        self._agg_fallback: Dict[tuple, tuple] = {}
        # parent id -> view in which its edge proved dead: shares route
        # around a sick parent for the rest of that view (the overlay
        # reshuffles at the view change, which implicitly pardons it)
        self._agg_sick: Dict[int, int] = {}

        # --- protocol state (dispatcher-thread only) ---
        st, window_msgs = restore_replica_state(self.storage)
        self.view = st.last_view
        self.last_executed = st.last_executed_seq
        self.last_stable = st.last_stable_seq
        self.primary_next_seq = max(st.last_executed_seq,
                                    st.last_stable_seq) + 1
        self.window: ActiveWindow[SeqNumInfo] = ActiveWindow(
            cfg.work_window_size, SeqNumInfo)
        self.window.advance(st.last_stable_seq)
        # bounded client table (million-principal shape): resident
        # records LRU-capped at client_table_max, cold clients demand-
        # paged back from their reply-ring reserved pages (the pager
        # replays the per-client restart rule). 0 = legacy unbounded
        # table with eager boot restore.
        self.clients = ClientsManager(
            self.info.all_client_ids(),
            max_resident=cfg.client_table_max,
            pager=(self._page_in_client
                   if cfg.client_table_max > 0 else None))
        self.pending_requests: List[m.ClientRequestMsg] = []
        self.checkpoints: Dict[int, Dict[int, m.CheckpointMsg]] = {}
        # highest checkpoint seq stored per sender (memory bound: the
        # checkpoints dict holds at most one message per replica)
        self._ck_latest_seq: Dict[int, int] = {}
        # quorum-certified checkpoints ahead of us: seq -> state digest
        # (the trust anchor handed to state transfer)
        self.certified_checkpoints: Dict[int, bytes] = {}

        # --- view change state (ViewsManager equivalent) ---
        self.vc = ViewChangeState(self.info.complaint_quorum,
                                  self.info.view_change_quorum)
        self.in_view_change = st.in_view_change
        # restore the in-flight target too: a crash after vc.persist must
        # resume the SAME view change (start() retransmits the rebuilt
        # ViewChangeMsg — peers may be counting this replica toward the
        # view-change quorum)
        self.pending_view: Optional[int] = (
            st.pending_view if st.in_view_change and st.pending_view
            else None)
        # safety state surviving crashes mid-view-change (the reference
        # persists view-change descriptors, PersistentStorageDescriptors):
        # restrictions = what the current view's primary must re-propose;
        # carried_certs = evidence from earlier views, keyed by
        # (seq, is_signed_element) — a threshold cert and our own SIGNED
        # report can coexist for one seqnum
        self.restrictions: Dict[int, Restriction] = {
            r.seq_num: r for r in map(unpack_restriction, st.restrictions)}
        self.carried_certs: Dict[tuple, m.PreparedCertificate] = {}
        for raw in st.carried_certs:
            cert = unpack_cert(raw)
            self.carried_certs[(cert.seq_num, cert.kind == CERT_SIGNED)] = cert
        # pp_digest -> packed PrePrepare for every digest-only certificate
        # we hold evidence for: certs travel without bodies (the VERDICT's
        # O(batch x window) ViewChangeMsg fix), so bodies live here to
        # resolve our own restrictions and answer peers' fetches
        self.vc_bodies: Dict[bytes, bytes] = {}
        for raw in st.carried_bodies:
            pp = m.unpack(raw)
            self.vc_bodies[pp.digest()] = raw
        # (new_view, restrictions, missing pp_digest set) when view entry
        # is blocked on fetching restricted batch bodies
        self._pending_entry: Optional[tuple] = None
        self._my_vc_msg: Optional[m.ViewChangeMsg] = None
        # proof of the view we're in, kept for status-driven retransmission
        # to lagging peers (reference: RetransmissionsManager + status)
        self._entered_view_proof: Optional[tuple] = None
        self._complained_views: set = set()
        self._vc_started_at = 0.0
        self._last_progress = time.monotonic()
        self._forwarded: Dict[tuple, float] = {}   # (client, req_seq) -> time
        # client -> (head req_seq of last relayed batch, relay time):
        # backup batch-relay suppression (see _dispatch_external)
        self._batch_relayed: Dict[int, Tuple[int, float]] = {}
        self._ck_asked: Dict[int, float] = {}      # AskForCheckpoint rate
        self._self_ck_latest: Optional[m.CheckpointMsg] = None

        # --- pipeline ---
        self.incoming = IncomingMsgsStorage()
        self.dispatcher = Dispatcher(self.incoming, name=f"replica-{self.id}",
                                     thread_mdc={"r": self.id})
        comm_flush = getattr(comm, "flush", None)
        if comm_flush is not None:
            # batched-send transports hold the dispatcher's datagrams and
            # put them on the wire in one syscall per iteration
            self.dispatcher.set_post_hook(comm_flush)
        self.dispatcher.set_external_handler(self._on_external)
        self.dispatcher.register_internal("combine", self._on_combine_result)
        self.dispatcher.register_internal("pp_verified", self._on_pp_verified)
        self.dispatcher.register_internal("cert_verified",
                                          self._on_cert_verified)
        if self._agg_mode != "off":
            # interior-node partials re-enter from the collector pool
            # (the sum job) exactly like combine verdicts do
            self.dispatcher.register_internal("agg_partial",
                                              self._on_agg_partials)
            self.dispatcher.add_timer(max(cfg.agg_flush_ms, 5) / 1000.0,
                                      self._agg_flush_tick)
            self.dispatcher.add_timer(
                cfg.agg_parent_timeout_ms / 1000.0 / 2,
                self._agg_fallback_tick)
        self.dispatcher.add_timer(cfg.batch_flush_period_ms / 1000.0,
                                  self._try_send_pre_prepare)
        self.dispatcher.add_timer(cfg.fast_path_timeout_ms / 1000.0 / 4,
                                  self._check_fast_path_timeouts)
        self.dispatcher.add_timer(cfg.view_change_timer_ms / 1000.0 / 4,
                                  self._check_view_change_timer)
        self.dispatcher.add_timer(cfg.status_report_timer_ms / 1000.0,
                                  self._send_status)
        # dispatcher liveness beat: fires every loop iteration it is due
        # (messages AND idle timeouts both reach the timer pass), so the
        # beat age is the consensus thread's tick age
        self.dispatcher.add_timer(0.2,
                                  lambda: self.health.beat("dispatcher"))
        # fused cross-slot combine plane: due collectors across seqnums
        # and kinds drain into ONE combine_batch call per flush (BLS:
        # one segmented multi-MSM launch + one RLC pairing check for
        # the whole batch) instead of one combine job per slot
        # per-origin Byzantine evidence rollup (bad shares identified by
        # the combine plane, deferred-cert failures from the async
        # verify path) — surfaced via `status get health` and flight
        # dumps so a repeat offender is attributable, not just counted
        self.byz_telemetry = ByzTelemetry()
        self.health.register_info_section("byzantine",
                                          self.byz_telemetry.snapshot)
        flight.register_dump_provider(f"byzantine.r{self.id}",
                                      self.byz_telemetry.snapshot)
        # wire-visible capability advertisement (satellite of ISSUE 20):
        # peers' CAP_* bitmaps as recorded off their status beacons, so
        # a mixed cluster (some replicas running the optimistic reply
        # plane, some not) is detectable from any one replica's health
        # payload. Observability only — nothing negotiates off this.
        self.peer_capabilities: Dict[int, int] = {}
        self.health.register_info_section(
            "capabilities",
            lambda: {"self": self._my_capabilities(),
                     "peers": dict(self.peer_capabilities)})
        self.collector_pool = CollectorPool(
            lambda res: self.incoming.push_internal("combine", res),
            fused=cfg.fused_combine,
            flush_us=cfg.combine_flush_us,
            max_batch=cfg.combine_batch_max,
            on_flush=self._on_combine_flush,
            rid=self.id)
        # cross-seqnum combined-cert verification batcher: certs arriving
        # within a flush window verify in ONE aggregated check per
        # verifier (BLS: single RLC'd pairing check)
        from tpubft.consensus.collectors import CertBatchVerifier
        self.cert_batcher = CertBatchVerifier(
            lambda cookie, ok: self.incoming.push_internal(
                "cert_verified", (cookie[0], cookie[1], ok)),
            flush_us=cfg.verify_batch_flush_us)
        # admission verification batcher: ClientRequest signature checks
        # leave the dispatcher thread and verify in cross-request batches
        # (ONE device dispatch per flush window with the TPU backend) —
        # under a client flood the primary's dispatcher is no longer the
        # serial per-sig bottleneck (reference: RequestThreadPool role in
        # onMessage<ClientRequestMsg>, ReplicaImp.cpp:397)
        self.req_batcher = None
        self._req_verifying: set = set()
        if cfg.async_verification:
            from tpubft.consensus.sig_manager import BatchVerifier
            self.req_batcher = BatchVerifier(
                self.sig, batch_size=cfg.verify_batch_size,
                flush_us=cfg.verify_batch_flush_us)
            self.dispatcher.register_internal("req_verified",
                                              self._on_req_verified)
        # admission plane (transport → dispatcher): workers parse and
        # verify every external message off the dispatcher, coalescing
        # the drain's signatures into one verify_batch; the dispatcher
        # receives AdmittedMsg objects and its handlers consult the
        # attached verdict instead of re-verifying (admission.py docs).
        # 0 workers = legacy inline path (raw bytes to the dispatcher).
        self.admission = None
        if cfg.admission_workers > 0:
            from tpubft.consensus.admission import AdmissionPipeline
            self.admission = AdmissionPipeline(
                sig=self.sig, info=self.info,
                sink=self.incoming.push_external_obj,
                epoch_fn=lambda: self.epoch_mgr.self_epoch,
                view_fn=lambda: self.view,
                stable_fn=lambda: self.last_stable,
                workers=cfg.admission_workers,
                drain_max=cfg.admission_drain_max,
                aggregator=self.aggregator,
                name=f"admission-{self.id}",
                ckpt_window=cfg.checkpoint_window_size,
                high_watermark=cfg.admission_high_watermark,
                low_watermark=cfg.admission_low_watermark,
                beat_fn=lambda: self.health.beat("admission"),
                rid=cfg.replica_id,
                shard_by_key=cfg.admission_key_sharding)
            self.dispatcher.set_admitted_handler(self._on_admitted)
            self.health.register_probe(
                "admission", cfg.health_stall_ms / 1e3,
                busy_fn=lambda: self.admission.depth > 0,
                detail_fn=lambda: {"depth": self.admission.depth,
                                   "shedding": self.admission.shedding})
            self.health.register_degraded_flag(
                "admission_shedding", lambda: self.admission.shedding)

        # retransmissions (reference RetransmissionsManager +
        # sendRetransmittableMsgToReplica, ReplicaImp.cpp:2531)
        self.retrans = None
        if cfg.retransmissions_enabled:
            from tpubft.consensus.retransmissions import \
                RetransmissionsManager
            self.retrans = RetransmissionsManager(
                comm, min_timeout_ms=cfg.retransmission_timer_ms // 2 or 10,
                max_timeout_ms=cfg.retransmission_timer_ms * 20)
            self.dispatcher.add_timer(
                cfg.retransmission_timer_ms / 1000.0, self._retrans_tick)
            self.dispatcher.add_timer(
                cfg.retransmission_timer_ms * 4 / 1000.0,
                self._check_missing_data)
        # ReqMissingData bookkeeping: seq -> (first_noticed, asks_sent)
        self._missing_since: Dict[int, list] = {}
        # restart-ready votes per wedge point (ReplicaRestartReadyMsg);
        # keyed by point so a later re-wedge starts a fresh election
        self._restart_announced: Optional[int] = None
        self._my_restart_vote: Optional[m.ReplicaRestartReadyMsg] = None
        self._restart_votes: Dict[int, set] = {}

        # --- metrics (names mirror the reference's replica component) ---
        self.metrics = Component("replica", self.aggregator)
        self.m_executed = self.metrics.register_counter("executed_requests")
        self.m_preprepares = self.metrics.register_counter("sent_preprepares")
        self.m_fast_commits = self.metrics.register_counter("fast_path_commits")
        self.m_slow_commits = self.metrics.register_counter("slow_path_commits")
        self.m_slow_starts = self.metrics.register_counter("slow_path_starts")
        self.m_view = self.metrics.register_gauge("view")
        self.m_last_executed = self.metrics.register_gauge("last_executed_seq")
        self.m_last_stable = self.metrics.register_gauge("last_stable_seq")
        self.m_retransmitted = self.metrics.register_gauge(
            "retransmitted_total")
        self.m_epoch = self.metrics.register_gauge("epoch")
        self.m_epoch_dropped = self.metrics.register_counter(
            "epoch_mismatch_dropped")
        # execution-lane observability: queue depth (committed slots not
        # yet applied), runs completed, and slots coalesced into runs
        self.m_exec_lane_depth = self.metrics.register_gauge(
            "exec_lane_depth")
        self.m_exec_runs = self.metrics.register_counter("exec_runs")
        self.m_exec_run_slots = self.metrics.register_counter(
            "exec_run_slots")
        # speculative execution: sealed runs (executed ahead of their
        # commit certificate and made durable at commit), abort events
        # (view change / barrier / digest surprise — the overlay was
        # discarded and the slots re-executed post-commit), and the last
        # sealed run's reclaimed combine-window overlap
        self.m_exec_spec_runs = self.metrics.register_counter(
            "exec_spec_runs")
        self.m_exec_spec_aborts = self.metrics.register_counter(
            "exec_spec_aborts")
        self.m_exec_spec_overlap = self.metrics.register_gauge(
            "exec_spec_overlap_ms")
        # optimistic reply plane: slots released to the client-visible
        # path on a structurally-valid commit cert before its pairing
        # verify landed, and deferred verifies that came back BAD on a
        # slot already released (poisons the plane for the view)
        self.m_opt_replies = self.metrics.register_counter(
            "optimistic_releases")
        self.m_cert_async_fails = self.metrics.register_counter(
            "cert_async_failures")
        # fused combine plane: flushes drained and slots combined —
        # combined_slots / combine_batches is the amortization factor
        # (the `status get kernels` bls_msm batch stats show the same
        # win device-side); the ROADMAP-8 autotuner's flush-window sensor
        self.m_combine_batches = self.metrics.register_counter(
            "combine_batches")
        self.m_combined_slots = self.metrics.register_counter(
            "combined_slots")
        # aggregation overlay: Prepare/Commit share datagrams RECEIVED
        # from peers (raw shares + climbing partials; fast-path shares
        # excluded — they never aggregate), the fan-in bench_scaling
        # --agg-ab reads at the hottest replica; partials forwarded up the
        # tree, partials absorbed at the root, and parent-timeout
        # fallbacks (each one is a direct re-send, the liveness floor)
        self.m_share_msgs_rcvd = self.metrics.register_counter(
            "share_msgs_received")
        self.m_agg_forwarded = self.metrics.register_counter(
            "agg_partials_forwarded")
        self.m_agg_absorbed = self.metrics.register_counter(
            "agg_partials_absorbed")
        self.m_agg_fallbacks = self.metrics.register_counter(
            "agg_fallbacks")
        # external-queue backpressure drops (IncomingMsgsStorage bound),
        # refreshed by the status timer — paired with the admission
        # component's counters for the full ingest picture
        self.m_dropped_external = self.metrics.register_gauge(
            "dropped_external")
        # a recovered replica must REPORT its recovered position — these
        # gauges otherwise read 0 until the next execution, making an
        # idle-after-restart replica look like it lost its state
        self.m_view.set(self.view)
        self.m_last_executed.set(self.last_executed)
        self.m_last_stable.set(self.last_stable)

        # state transfer (attached by the kvbc layer via set_state_transfer;
        # reference: ReplicaForStateTransfer owning an IStateTransfer)
        self.state_transfer = None

        # pre-execution (reference src/preprocessor/, gated on config).
        # The `preexec` metrics component exists whenever the plane can
        # be exercised — conflict/fallback counters tick from the
        # execution path even on replicas that only APPLY pre-executed
        # results
        self.preexec_metrics = Component("preexec", self.aggregator)
        self.m_preexec_conflicts = self.preexec_metrics.register_counter(
            "preexec_conflicts")
        self.m_preexec_applied = self.preexec_metrics.register_counter(
            "preexec_applied")
        self.preprocessor = None
        if cfg.pre_execution_enabled:
            from tpubft.preprocessor import PreProcessor
            self.preprocessor = PreProcessor(
                self, num_threads=cfg.preexec_threads)

        # thin-replica read tier (reference thin-replica-server, gated
        # on config): reads/subscriptions served off the consensus path,
        # fed once per sealed run from the ledger commit stream, with
        # the f+1-signed checkpoint anchor published from
        # _store_checkpoint so clients can digest-verify every read.
        # The anchor snapshot crosses threads (dispatcher publishes,
        # thin-replica handler threads serve) — guarded by _trs_mu.
        self.thin_replica = None
        self._trs_mu = make_lock("trs.anchor")
        self._trs_anchor: Optional[tuple] = None
        # state_digest -> ledger height at our own checkpoint boundaries
        # (bounded; resolves a certified digest to a servable block row)
        self._ckpt_blocks: Dict[bytes, int] = {}
        if cfg.thin_replica_enabled:
            self.attach_thin_replica(port=cfg.thin_replica_port)

        # reserved pages + the subsystems riding them (internal client,
        # key exchange, time service, cron)
        from tpubft.ccron import CronTable, TicksGenerator
        from tpubft.consensus.internal import (InternalBFTClient,
                                               KeyExchangeManager,
                                               TimeServiceManager)
        from tpubft.consensus.reserved_pages import (ReservedPages,
                                                     ReservedPagesClient)
        if reserved_pages is None:
            from tpubft.storage.memorydb import MemoryDB
            reserved_pages = ReservedPages(MemoryDB())
        self.res_pages = reserved_pages
        self.internal_client = InternalBFTClient(self)
        self.key_exchange = KeyExchangeManager(
            self, ReservedPagesClient(self.res_pages,
                                      KeyExchangeManager.CATEGORY))
        self.time_service = TimeServiceManager(
            ReservedPagesClient(self.res_pages, TimeServiceManager.CATEGORY),
            max_skew_ms=cfg.time_max_skew_ms)
        if cfg.time_service_enabled:
            # replica time voting: broadcast our signed clock reading and
            # bound the primary against the cluster's median. 2f+1 clocks
            # (incl. self) so the median is bracketed by honest values
            # even with f faulty opinions present.
            self.time_service.opinion_quorum = 2 * cfg.f_val + 1
            self.dispatcher.add_timer(1.0, self._broadcast_time_opinion)
        from tpubft.consensus.control import ControlStateManager
        self.control = ControlStateManager(
            ReservedPagesClient(self.res_pages,
                                ControlStateManager.CATEGORY))
        self.epoch_mgr = EpochManager(
            ReservedPagesClient(self.res_pages, EpochManager.CATEGORY))
        self.m_epoch.set(self.epoch_mgr.boot_adopt(self.last_executed))
        self.reconfig = None  # ReconfigurationDispatcher (kvbc wiring)
        self.cron_table = CronTable(
            ReservedPagesClient(self.res_pages, CronTable.CATEGORY))
        self.ticks_generator = TicksGenerator(self, self.cron_table)
        self.dispatcher.add_timer(0.25, self.ticks_generator.poll)
        self.key_exchange.load_from_pages()
        self._load_client_replies_from_pages()

        # diagnostics (reference: Registrar status handlers + per-stage
        # histograms, diagnostics.h / performance_handler.h)
        from tpubft.diagnostics import get_registrar
        self._diag = get_registrar()
        self._h_execute = self._diag.histogram(f"replica{self.id}.execute")
        self._h_verify = self._diag.histogram(f"replica{self.id}.verify")
        # run-shape histograms: slots per execution run and the coalesced
        # commit's duration (ms → recorded in µs like the others)
        self._h_exec_run_len = self._diag.histogram(
            f"replica{self.id}.exec_run_len")
        self._h_exec_commit_ms = self._diag.histogram(
            f"replica{self.id}.exec_commit_ms")
        # per-sealed-run reclaimed overlap (ms → recorded in µs)
        self._h_spec_overlap = self._diag.histogram(
            f"replica{self.id}.exec_spec_overlap_ms")
        # slots per fused combine flush (1 = no cross-slot amortization)
        self._h_combine_batch = self._diag.histogram(
            f"replica{self.id}.combine_batch_size", unit="slots")
        self._diag.register_status(
            f"replica{self.id}",
            lambda: (f"view={self.view} last_executed={self.last_executed} "
                     f"last_stable={self.last_stable} "
                     f"in_view_change={self.in_view_change} "
                     f"{self.control.status()}"))
        # aggregate degradation verdict (`status get health`): probes +
        # breaker snapshots + shed flags as JSON. The bare "health" key
        # is the one-replica-per-process operator entry; in-process
        # clusters also get the per-replica key.
        self._diag.register_status(f"replica{self.id}.health",
                                   self.health.render)
        self._diag.register_status("health", self.health.render)
        # flight recorder surfaces (`status get flight|slots|kernels`)
        flight.install_diagnostics(self._diag)
        from tpubft.testing.slowdown import get_slowdown_manager
        self._slowdown = get_slowdown_manager()

        # --- execution lane (post-commit pipelining off the dispatcher;
        # reference: post-execution separation + block accumulation) ---
        self.exec_lane = None
        # highest seq handed to the lane (or executed inline via the
        # lane's barrier path); dispatcher-thread only
        self._exec_enqueued = self.last_executed
        # speculatively-submitted slots whose commit certificate has not
        # confirmed yet, in seq order; dispatcher-thread only
        self._spec_inflight: List[int] = []
        # --- optimistic reply plane (ISSUE 18 / ROADMAP item 4) ---
        # replies go out on a STRUCTURALLY-valid commit cert while the
        # pairing verify runs behind; requires async verification (the
        # deferred check IS the async job) and is reply-visibility only
        self._opt_replies = bool(cfg.optimistic_replies
                                 and cfg.async_verification)
        # a deferred verify that fails on an already-released slot
        # poisons the plane until the next view change (forged certs
        # mean an active equivocator — stop trusting structure alone)
        self._opt_poisoned = False
        # contiguous frontier of slots whose commit certificate has
        # VERIFIED (not just structurally accepted): in optimistic mode
        # the persisted last_executed watermark is clamped to this, so a
        # restart never resumes past evidence that was still in flight
        self._verified_upto = self.last_executed
        # speculation needs a rollback substrate: the lane, an
        # accumulation-capable ledger behind the handler (handlers
        # without one — e.g. the counter app — apply irreversibly during
        # execution), and the time service off (its agreed-time page
        # writes bypass the staged pages batch)
        _bc = getattr(handler, "blockchain", None)
        self._spec_enabled = bool(
            cfg.speculative_execution and cfg.execution_lane
            and not cfg.time_service_enabled
            and _bc is not None and hasattr(_bc, "begin_accumulation"))
        self.durability = None
        if cfg.execution_lane:
            from tpubft.consensus.execution import ExecutionLane
            self.exec_lane = ExecutionLane(
                self, cfg.execution_max_accumulation,
                cfg.checkpoint_window_size)
            self.dispatcher.register_internal("exec_done",
                                              self._apply_exec_runs)
            # stall threshold = the drain barrier's budget: a lane that
            # would time out a view-change/ST drain is reported by the
            # watchdog with stacks + depths, not discovered by a human
            self.health.register_probe(
                "exec_lane", cfg.execution_drain_timeout_ms / 1e3,
                busy_fn=lambda: not self.exec_lane.idle(),
                detail_fn=lambda: {"depth": self.exec_lane.depth})
        # --- group-commit durability pipeline (tpubft/durability/):
        # the lane seals runs, a dedicated io thread group-commits
        # them across runs (one concatenated apply + one fsync per
        # group) and publishes the durability watermark that gates
        # replies / last_executed / the reply cache. The ledger (when
        # the handler has one with the accumulation bracket) installs
        # the pending-read overlay so sealed-but-unapplied runs stay
        # observable process-wide; reserved pages sharing the ledger
        # DB rebind onto the same view so folded reply pages are too.
        if cfg.execution_lane and cfg.durability_pipeline:
            from tpubft.durability import DurabilityPipeline
            self.durability = DurabilityPipeline(
                self, group_max=cfg.durability_group_max,
                window_us=cfg.durability_window_us)
            _bc = getattr(handler, "blockchain", None)
            if _bc is not None and hasattr(_bc, "attach_durability"):
                view = _bc.attach_durability(
                    self.durability.pending,
                    drain_fn=self.durability.drain)
                if self.res_pages.shares_db(view.base):
                    self.res_pages.rebind(view)
            # watermark-lag stall probe: busy while sealed runs await
            # their group fsync; a disk that stops landing groups is
            # reported with the same budget as the lane's drain barrier
            self.health.register_probe(
                "durability", cfg.execution_drain_timeout_ms / 1e3,
                busy_fn=lambda: self.durability.lag > 0,
                detail_fn=lambda: {"lag": self.durability.lag,
                                   "wm": self.durability.watermark})
            self._diag.register_status(f"replica{self.id}.durability",
                                       self.durability.render)
            self._diag.register_status("durability",
                                       self.durability.render)

        # --- closed-loop autotuner (tpubft/tuning/): drives the perf
        # knobs above (flush windows, batch caps, accumulation depth,
        # admission watermarks, ECDSA crossover) from the telemetry
        # plane, backing everything off to the configured defaults
        # whenever health leaves `healthy` or a breaker opens. The
        # ReplicaConfig fields seed the knob registry; after this point
        # the registry — not the frozen dataclass — owns the values.
        self.tuning = None
        if cfg.autotune_enabled:
            from tpubft.tuning import build_replica_tuning
            self.tuning = build_replica_tuning(self, cfg)
            self._diag.register_status(f"replica{self.id}.tuning",
                                       self.tuning.render)
            self._diag.register_status("tuning", self.tuning.render)

        # assigned BEFORE the restore replay: _restore_window can reach
        # _execute_committed, whose pipeline retrigger reads _running
        self._running = False
        self._restore_window(window_msgs)

    def _page_in_client(self, client: int):
        """Demand pager for the bounded client table: rebuild ONE
        client's record from its reply-ring pages + oversize marker —
        the same rule `_load_client_replies_from_pages` applies to every
        client at boot, including the restore seal, so an evict/reload
        cycle is a single-client restart. Cost is proportional to the
        pages that EXIST for this client (one bounded range scan): a
        never-seen principal pages in for O(log store)."""
        from tpubft.consensus.clients_manager import (
            REPLY_CACHE_PER_CLIENT as _RING, _ClientInfo)
        info = _ClientInfo()
        found = []
        for _slot, raw in self.res_pages.scan(
                "clientreplies", client * _RING, (client + 1) * _RING):
            if not raw or raw[:1] != b"\x00":
                continue
            try:
                reply = m.unpack(raw[1:])
            except m.MsgError:
                continue
            if isinstance(reply, m.ClientReplyMsg):
                # re-personalize the canonical page form
                reply.sender_id = self.id
                reply.current_primary = self.primary
                found.append(reply)
        # oldest-first insertion so later live evictions age correctly
        for reply in sorted(found, key=lambda r: r.req_seq_num):
            info.replies[reply.req_seq_num] = reply
            if reply.req_seq_num > info.last_executed_req:
                info.last_executed_req = reply.req_seq_num
        raw = self.res_pages.load("clients", client)
        if raw and raw[:1] == b"\x01":
            # oversize-reply marker: at-most-once state only
            seq = int.from_bytes(raw[1:9], "big")
            info.replies.setdefault(seq, None)
            if seq > info.last_executed_req:
                info.last_executed_req = seq
        # the restore seal (clients_manager.seal_restore): the persisted
        # ring is bounded, so anything at or below the watermark that
        # did not come back may have executed-and-evicted — refuse it
        if info.last_executed_req > info.evicted_high:
            info.evicted_high = info.last_executed_req
        return info

    def _load_client_replies_from_pages(self) -> None:
        """Seed the at-most-once table + reply cache from reserved pages
        (reference: ClientsManager loadInfoFromReservedPages)."""
        if self.cfg.client_table_max > 0:
            # paged client table: records are demand-built one client at
            # a time by _page_in_client under the same rules, so "reload
            # everything" (boot, ST page install) is just dropping
            # whatever is resident — never an O(clients) eager scan
            self.clients.invalidate_all()
            return
        from tpubft.consensus.clients_manager import \
            REPLY_CACHE_PER_CLIENT as _RING
        from tpubft.consensus.reserved_pages import ReservedPagesClient
        pages = ReservedPagesClient(self.res_pages, "clients")
        ring = ReservedPagesClient(self.res_pages, "clientreplies")

        def seed(client: int, raw: bytes) -> None:
            try:
                reply = m.unpack(raw[1:])
            except m.MsgError:
                return
            if isinstance(reply, m.ClientReplyMsg):
                # re-personalize the canonical page form
                reply.sender_id = self.id
                reply.current_primary = self.primary
                self.clients.on_request_executed(client, reply.req_seq_num,
                                                 reply)

        for c in self.info.all_client_ids():
            # the reply ring first (recent batch elements) ...
            for slot in range(_RING):
                raw = ring.load(index=c * _RING + slot)
                if raw and raw[:1] == b"\x00":
                    seed(c, raw)
            # ... then the newest-reply/at-most-once marker page, which
            # also carries the authoritative last-executed watermark
            raw = pages.load(index=c)
            if not raw:
                continue
            if raw[:1] == b"\x01":
                # oversize-reply marker: at-most-once state only
                self.clients.note_executed(c, int.from_bytes(raw[1:9],
                                                             "big"))
            else:
                seed(c, raw)
        for c in self.info.all_client_ids():
            # the persisted ring is bounded: seqs below the watermark that
            # didn't come back may have executed-and-evicted — refuse them
            self.clients.seal_restore(c)

    # ------------------------------------------------------------------
    # state transfer wiring (ReplicaForStateTransfer equivalent)
    # ------------------------------------------------------------------
    def set_state_transfer(self, st) -> None:
        self.state_transfer = st
        st.bind(
            send_fn=lambda dest, payload: self.comm.send(
                dest, m.StateTransferMsg(sender_id=self.id,
                                         payload=payload).pack()),
            complete_fn=self._on_transfer_complete,
            replica_ids=list(self.info.replica_ids),
            f_val=self.cfg.f_val)
        self.dispatcher.add_timer(0.2, st.tick)
        # fetch-plane progress pulse: busy only while fetching; the
        # last-activity pulse (sends/receives) replaces thread beats —
        # ST runs on the dispatcher, this watches its *progress*
        self.health.register_probe(
            "state_transfer",
            max(self.cfg.health_stall_ms, self.cfg.st_stall_timeout_ms) / 1e3,
            busy_fn=lambda: st.is_fetching,
            last_fn=lambda: st.last_activity,
            detail_fn=lambda: {"state": st.state})
        self._st_stall_mark = (self.last_executed, time.monotonic())
        self.dispatcher.add_timer(
            max(self.cfg.st_stall_timeout_ms / 4000.0, 0.25),
            self._check_st_stall)

    def _check_st_stall(self) -> None:
        """Dead-zone guard: a certified checkpoint is ahead of us but not
        far enough for the immediate window trigger, and ordering has made
        no progress (peers GC'd the needed commits) — fetch state."""
        seq, t = self._st_stall_mark
        now = time.monotonic()
        if self.last_executed != seq:
            self._st_stall_mark = (self.last_executed, now)
            return
        ahead = [s for s in self.certified_checkpoints
                 if s > self.last_executed]
        if not ahead:
            return
        if now - t > self.cfg.st_stall_timeout_ms / 1000.0:
            self._st_stall_mark = (self.last_executed, now)
            self.state_transfer.start_collecting(
                max(ahead), dict(self.certified_checkpoints))

    def _on_transfer_complete(self, seq: int, state_digest: bytes) -> None:
        """onTransferringComplete (IStateTransfer.hpp:113): jump forward to
        the transferred checkpoint and resume normal operation."""
        # apply (not discard) any in-flight execution first: those slots
        # are committed and their effects are part of the state the
        # transferred checkpoint extends — and the page reload below must
        # not race the lane's page writes. A lane that cannot drain means
        # adopting now would race it: skip; the stall checker re-triggers
        # a transfer while the certified checkpoints stay ahead.
        if not self._drain_exec_lane():
            log.error("transfer-complete deferred: execution lane did "
                      "not drain")
            return
        if seq <= self.last_executed:
            return
        self.last_executed = seq
        self.m_last_executed.set(seq)
        self.primary_next_seq = max(self.primary_next_seq, seq + 1)
        with self._tran() as st:
            st.last_executed_seq = seq
        self._on_seq_stable(seq, state_digest)
        # reserved pages were just installed: adopt everything riding them
        self.key_exchange.load_from_pages()
        self.time_service.reload()
        self.cron_table.reload()
        self.control.reload()
        self._load_client_replies_from_pages()
        # the fetched pages may carry a bumped epoch (we missed a
        # reconfiguration): adopt it if the transferred checkpoint is
        # past the era boundary, or every peer message gets dropped by
        # the era gate while we keep stamping a dead epoch
        self.m_epoch.set(self.epoch_mgr.boot_adopt(seq))
        self._last_progress = time.monotonic()
        # adoption done: re-arm execution for any slots committed beyond
        # the transferred checkpoint (the pre-adoption drain deliberately
        # did not re-pump)
        self._execute_committed()

    def set_reconfiguration(self, dispatcher) -> None:
        """Attach the reconfiguration handler chain (kvbc wiring)."""
        self.reconfig = dispatcher

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self.comm.start(self)
        # crash between entering a view as primary and finishing the
        # re-proposals: restrictions were persisted, PrePrepares were not —
        # re-issue any that the restored window is missing
        if self.is_primary and not self.in_view_change and self.restrictions \
                and any(self.window.in_window(s)
                        and (self.window.peek(s) is None
                             or self.window.peek(s).pre_prepare is None)
                        for s in self.restrictions):
            self.incoming.push_internal("repropose", None)
        self.dispatcher.register_internal("repropose",
                                          lambda _: self._repropose())
        # crash between persisting view-change intent (vc.persist seam)
        # and the change completing: resume it — rebuild and retransmit
        # our ViewChangeMsg from the persisted evidence (peers may need
        # it to reach the view-change quorum). Runs on the dispatcher so
        # it serializes with incoming view-change traffic.
        self.dispatcher.register_internal("resume_vc",
                                          self._resume_view_change)
        if self.in_view_change and (self.pending_view or 0) > self.view:
            self.incoming.push_internal("resume_vc", None)
        if self.durability is not None:
            self.durability.start()     # before the lane: seals flow in
        if self.exec_lane is not None:
            self.exec_lane.start()
        if self.admission is not None:
            self.admission.start()
        if self.thin_replica is not None:
            self.thin_replica.start()
        self.health.start()
        if self.tuning is not None:
            self.tuning.start()
        self.dispatcher.start()
        with mdc_scope(r=self.id):       # start() runs on the caller thread
            log.info("replica up: n=%d f=%d c=%d view=%d primary=%d "
                     "backend=%s", self.info.n, self.cfg.f_val,
                     self.cfg.c_val, self.view, self.primary,
                     self.cfg.crypto_backend)
        if self.cfg.key_exchange_on_start:
            # sendInitialKey (BFTEngine start path, ReplicaImp.cpp:4622)
            self.key_exchange.initiate()

    def stop(self) -> None:
        self._running = False
        with mdc_scope(r=self.id):
            log.info("replica stopping: last_executed=%d last_stable=%d",
                     self.last_executed, self.last_stable)
        if self.exec_lane is not None:
            # no drain: pending slots are committed state that recovery
            # replays — stop is crash-equivalent for the lane
            self.exec_lane.stop()
        if self.durability is not None:
            # after the lane (its last seal must be accepted): a clean
            # stop flushes sealed runs to disk — whatever a wedged disk
            # leaves behind is the crash case recovery already replays
            self.durability.stop()
        if self.admission is not None:
            self.admission.stop()
        if self.thin_replica is not None:
            self.thin_replica.stop()
        if self.tuning is not None:
            if self.cfg.autotune_seed_file:
                # clean shutdown: write the converged operating point
                # back to the seed file so the next boot of this host
                # starts warm (ROADMAP 8d); crash paths never get here,
                # so a half-tuned episode cannot poison the seed
                self.tuning.write_seed(self.cfg.autotune_seed_file)
            self.tuning.stop()
        self.health.stop()
        self.dispatcher.stop()
        self.collector_pool.shutdown()
        self.cert_batcher.stop()
        if self.req_batcher is not None:
            self.req_batcher.stop()
        if self.preprocessor:
            self.preprocessor.shutdown()
        self.comm.stop()

    # ------------------------------------------------------------------
    # thin-replica serving plane
    # ------------------------------------------------------------------
    def attach_thin_replica(self, port: int = 0,
                            host: str = "127.0.0.1"):
        """Create (idempotently) the thin-replica server over the
        handler's ledger, wired to the commit stream and this replica's
        quorum-signed checkpoint anchor. Started by start() (or
        immediately when the replica is already running)."""
        if self.thin_replica is not None:
            return self.thin_replica
        bc = getattr(self.handler, "blockchain", None)
        if bc is None:
            log.warning("thin_replica_enabled but the handler has no "
                        "blockchain — read tier inactive")
            return None
        from tpubft.thinreplica import ThinReplicaServer
        self.thin_replica = ThinReplicaServer(
            bc, host=host, port=port,
            sub_buffer=self.cfg.thin_replica_sub_buffer,
            aggregator=self.aggregator,
            anchor_fn=self.thin_replica_anchor)
        # __init__-time attach runs before _running exists; start()
        # brings the server up then
        if getattr(self, "_running", False):
            self.thin_replica.start()
        return self.thin_replica

    def thin_replica_anchor(self) -> Optional[tuple]:
        """(ckpt_seq, block_id, [packed CheckpointMsg...]) snapshot for
        the thin-replica server — called from its handler threads; the
        dispatcher publishes via _publish_trs_anchor."""
        with self._trs_mu:
            return self._trs_anchor

    def _publish_trs_anchor(self, seq: int, block_id: int,
                            certs: tuple) -> None:
        with self._trs_mu:
            cur = self._trs_anchor
            if cur is None or seq > cur[0]:
                self._trs_anchor = (seq, block_id, certs)

    @property
    def is_primary(self) -> bool:
        return self.info.primary_of_view(self.view) == self.id

    @property
    def primary(self) -> int:
        return self.info.primary_of_view(self.view)

    # ------------------------------------------------------------------
    # transport upcall (any thread) → admission plane or queue
    # ------------------------------------------------------------------
    def on_new_message(self, sender: int, data: bytes) -> None:
        if self.admission is not None:
            self.admission.submit(sender, data)
        else:
            self.incoming.push_external(sender, data)

    def on_new_messages(self, msgs) -> None:
        """Burst upcall from batch-receiving transports (udp recvmmsg):
        the whole drain enters the admission queue in one call."""
        if self.admission is not None:
            self.admission.submit_burst(msgs)
        else:
            for sender, data in msgs:
                self.incoming.push_external(sender, data)

    # ------------------------------------------------------------------
    # dispatch (dispatcher thread)
    # ------------------------------------------------------------------
    def _on_external(self, sender: int, raw: bytes) -> None:
        """Legacy/inline path (admission_workers=0, and direct
        push_external callers): parse on the dispatcher, then dispatch."""
        try:
            msg = m.unpack(raw)
        except m.MsgError:
            log.debug("unparseable message from %d (%d bytes)", sender,
                      len(raw))
            return
        # scoped MDC (reference SCOPED_MDC_SEQ_NUM, ReplicaImp.cpp:1067):
        # every line logged while handling this message carries its
        # consensus coordinates
        with mdc_scope(v=self.view,
                       s=getattr(msg, "seq_num", None) or "-"):
            self._dispatch_external(sender, msg)

    def _on_admitted(self, adm) -> None:
        """Admission-plane path: the message arrives parsed with its
        signature verdict attached — the dispatcher only runs the
        stateful gates and mutates protocol state."""
        with mdc_scope(v=self.view,
                       s=getattr(adm.msg, "seq_num", None) or "-"):
            self._dispatch_external(adm.sender, adm.msg)

    @property
    def epoch(self) -> int:
        """The reconfiguration era this replica stamps on (and requires
        of) protocol messages (reference EpochManager selfEpochNumber)."""
        return self.epoch_mgr.self_epoch

    def _share_digest(self, kind: str, view: int, seq_num: int,
                      pp_digest: bytes) -> bytes:
        """share_digest bound to OUR current era — every share signed or
        validated by this replica (including certificate validation
        during view change) authenticates the epoch instead of trusting
        the wire field."""
        return share_digest(kind, self.epoch, view, seq_num, pp_digest)

    def _dispatch_external(self, sender: int, msg) -> None:
        # flight recorder: handler-entry event — the bounded, fixed-size
        # telemetry the hot path is allowed (check_hotpath forbids
        # span/f-string observability here)
        flight.record(flight.EV_DISPATCH,
                      seq=getattr(msg, "seq_num", 0) or 0,
                      view=self.view, arg=int(getattr(msg, "CODE", 0)))
        # era gate (reference: per-message epochNum checks, e.g.
        # PrePrepareMsg.cpp:91, ReplicaImp.cpp:2313): traffic from an
        # older reconfiguration era is dead — drop it before any handler.
        # A HIGHER-epoch checkpoint is the one exception: it is evidence
        # this replica missed a reconfiguration, and checkpoints drive
        # state-transfer catch-up (which also carries the new epoch page).
        msg_epoch = getattr(msg, "epoch", None)
        if msg_epoch is not None and msg_epoch != self.epoch_mgr.self_epoch:
            if not (isinstance(msg, m.CheckpointMsg)
                    and msg_epoch > self.epoch_mgr.self_epoch):
                self.m_epoch_dropped.inc()
                return
        if isinstance(msg, m.ClientRequestMsg):
            # accepted from the client itself OR forwarded by a replica;
            # either way the client's own signature is verified next
            if msg.sender_id != sender and not self.info.is_replica(sender):
                return
            self._on_client_request(msg)
            return
        if isinstance(msg, m.ClientBatchRequestMsg):
            # one wire message, several individually-signed requests
            # (reference ClientBatchRequestMsg::checkElements): every
            # element must decode to a ClientRequestMsg from the SAME
            # client; each then takes the normal admission path, where
            # the async plane verifies them as one device batch
            if msg.sender_id != sender and not self.info.is_replica(sender):
                return
            # unknown principals drop here, BEFORE the relay/suppression
            # path: a byzantine replica streaming fabricated sender_ids
            # must not grow _batch_relayed or mint amplified relays
            if not self.clients.is_valid_client(msg.sender_id):
                return
            # admission attaches the surviving parsed elements (forged
            # elements already dropped, each survivor pre-verified); the
            # legacy path parses them here via the helper
            inners = getattr(msg, "_adm_inners", None)
            if inners is None:
                inners = self._parse_batch_inners(msg)
                if inners is None:
                    return          # malformed element: drop whole batch
            # backup: relay the BATCH as one wire message (exploding it
            # into per-element forwards would defeat the transport
            # amortization); elements below run with relay suppressed
            # and still arm the liveness clock individually post-verify.
            # Retransmissions re-relay at most once per suppression
            # window — _forwarded can't dedup here (entries appear only
            # post-verify and are popped at execution, so a client
            # retrying lost replies would otherwise trigger an
            # (n-1)x-amplified re-relay of the largest message type on
            # every retry).
            # The suppression MAP is keyed on the principal alone so it
            # stays bounded by the client count (keying entries on any
            # element-derived value would let a spoofer mint unbounded
            # keys). The per-client record is (head req_seq, time): a
            # relay fires when the batch head's req_seq ADVANCES past
            # the last relayed one — a client pipelining faster than
            # 1 batch/s still gets backup relay for each new batch —
            # while a re-presented head (client retransmit of the same
            # batch) is still rate-bounded to one relay per second
            # (ADVICE r5). Seq advance happens pre-verify (head_seq is
            # attacker-influencable), but that mints no amplification:
            # each received batch yields at most ONE relay of the same
            # bytes to one destination (the primary), so a flooder gets
            # exactly the 1:1 traffic it could send the primary directly
            # — the old 1/s cap only obscured the origin, it did not
            # reduce attacker power.
            if inners and not self.is_primary and not self.in_view_change:
                now = time.monotonic()
                head_seq = inners[0].req_seq_num
                last = self._batch_relayed.get(msg.sender_id)
                if last is None or head_seq > last[0] \
                        or now - last[1] > 1.0:
                    self._batch_relayed[msg.sender_id] = (head_seq, now)
                    self.comm.send(self.primary, msg.pack())
            for inner in inners:
                self._on_client_request(inner, relay=False)
            return
        # Anti-spoofing: sender_id must match the transport sender —
        # EXCEPT for messages carrying their own end-to-end signature
        # (replica sig or threshold combined sig, verified in their
        # handlers): those are relay-safe, and the gap-resend +
        # ReqMissingData flows forward them on the original's behalf.
        # (m.RELAY_SAFE is shared with the admission plane's pre-drop,
        # so the two gates can never disagree.)
        if not isinstance(msg, m.RELAY_SAFE) \
                and getattr(msg, "sender_id", sender) != sender:
            return                              # sender spoofing: drop
        # view-change & checkpoint msgs flow even mid-view-change; normal
        # ordering msgs are frozen until the new view starts (reference
        # ReplicaImp gates handlers on currentViewIsActive())
        if isinstance(msg, m.ReplicaAsksToLeaveViewMsg):
            self._on_ask_to_leave_view(msg)
            return
        if isinstance(msg, m.ViewChangeMsg):
            self._on_view_change(msg)
            return
        if isinstance(msg, m.NewViewMsg):
            self._on_new_view(msg)
            return
        if isinstance(msg, m.CheckpointMsg):
            self._on_checkpoint(msg)
            return
        if isinstance(msg, m.TimeOpinionMsg):
            self._on_time_opinion(sender, msg)
            return
        if isinstance(msg, m.ReplicaStatusMsg):
            if self.info.is_replica(sender):
                self._on_replica_status(msg)
            return
        if isinstance(msg, m.SimpleAckMsg):
            if self.retrans is not None and self.info.is_replica(sender):
                self.retrans.on_ack(sender, msg.acked_msg_code, msg.seq_num,
                                    time.monotonic())
            return
        if isinstance(msg, m.ReqMissingDataMsg):
            if self.info.is_replica(sender):
                self._on_req_missing_data(sender, msg)
            return
        if isinstance(msg, m.ReqViewPrePrepareMsg):
            if self.info.is_replica(sender):
                self._on_req_view_pp(sender, msg)
            return
        if isinstance(msg, m.ReplicaRestartReadyMsg):
            if self.info.is_replica(msg.sender_id):
                self._on_restart_ready(msg)
            return
        if isinstance(msg, m.StateTransferMsg):
            # ST flows even mid-view-change (reference handles it in
            # ReplicaForStateTransfer below the view gate); read-only
            # replicas are legitimate ST destinations (ReadOnlyReplica)
            if self.state_transfer is not None \
                    and (self.info.is_replica(sender)
                         or self.info.is_ro_replica(sender)):
                self.state_transfer.handle_message(sender, msg.payload)
            return
        if isinstance(msg, m.PreProcessRequestMsg):
            if self.preprocessor and self.info.is_replica(sender):
                self.preprocessor.on_preprocess_request(sender, msg)
            return
        if isinstance(msg, m.PreProcessReplyMsg):
            if self.preprocessor and self.info.is_replica(sender):
                self.preprocessor.on_preprocess_reply(sender, msg)
            return
        if isinstance(msg, m.PreProcessBatchRequestMsg):
            if self.preprocessor and self.info.is_replica(sender):
                self.preprocessor.on_preprocess_batch_request(sender, msg)
            return
        if isinstance(msg, m.PreProcessBatchReplyMsg):
            if self.preprocessor and self.info.is_replica(sender):
                self.preprocessor.on_preprocess_batch_reply(sender, msg)
            return
        if isinstance(msg, m.AskForCheckpointMsg):
            # reference ReplicaImp::onMessage<AskForCheckpointMsg>: resend
            # our latest self checkpoint to the asker (RO replicas poll
            # this so a late joiner doesn't wait a whole window).
            # Rate-bounded per asker: unsigned request, bounded reply.
            if not (self.info.is_replica(sender)
                    or sender in self.info.ro_replica_ids):
                return
            now = time.monotonic()
            if now - self._ck_asked.get(sender, 0.0) < 2.0:
                return
            self._ck_asked[sender] = now
            if self._self_ck_latest is not None:
                self.comm.send(sender, self._self_ck_latest.pack())
            return
        if isinstance(msg, m.PrePrepareMsg) and self._pending_entry \
                and self._try_resolve_body(msg):
            return                  # old-view body answering our fetch
        if self.in_view_change:
            return
        if isinstance(msg, m.PrePrepareMsg):
            self._on_pre_prepare(msg)
        elif isinstance(msg, m.PreparePartialMsg):
            self._on_share(msg, "prepare")
        elif isinstance(msg, m.PrepareFullMsg):
            self._on_prepare_full(msg)
        elif isinstance(msg, m.CommitPartialMsg):
            self._on_share(msg, "commit")
        elif isinstance(msg, m.CommitFullMsg):
            self._on_commit_full(msg)
        elif isinstance(msg, m.PartialCommitProofMsg):
            self._on_share(msg, "fast")
        elif isinstance(msg, m.AggregateShareMsg):
            self._on_agg_share(msg)
        elif isinstance(msg, m.FullCommitProofMsg):
            self._on_full_commit_proof(msg)
        elif isinstance(msg, m.StartSlowCommitMsg):
            self._on_start_slow_commit(msg)

    # ------------------------------------------------------------------
    # client requests (ReplicaImp.cpp:397)
    # ------------------------------------------------------------------
    def _on_client_request(self, req: m.ClientRequestMsg,
                           relay: bool = True) -> None:
        """Recorded entry. The per-request span this used to allocate
        is gone — a span per message is exactly the hot-path telemetry
        the flight recorder replaces (check_hotpath now forbids it);
        the trace still joins end-to-end because _accept_pre_prepare's
        consensus_slot span parents on the first request's cid."""
        flight.record(flight.EV_CLIENT_REQ, seq=req.req_seq_num,
                      arg=req.sender_id)
        self._handle_client_request(req, relay=relay)

    def _handle_client_request(self, req: m.ClientRequestMsg,
                               relay: bool = True) -> None:
        client = req.sender_id
        if not self.clients.is_valid_client(client):
            return
        # flag/topology gates — the ONE predicate shared with the
        # admission plane's pre-verify drop (an admission-side drop is
        # final, so the two must never disagree): INTERNAL/principal
        # correspondence, ordered RECONFIG from the operator only
        # (read-only RECONFIG is open to any valid client — per-command
        # authorization happens at execution), no wire-minted
        # HAS_PRE_PROCESSED
        if not m.client_request_admissible(req, self.info):
            return
        if not req.flags & m.RequestFlag.READ_ONLY:
            if not self.is_primary or self.in_view_change:
                # backup: forward FIRST, unverified — forwarding is cheap
                # and not a commitment (the primary verifies); the verify
                # below is paid ONCE per request, only to arm the
                # dead-primary liveness clock honestly (complaints must
                # never be armed by forged floods)
                if (client, req.req_seq_num) in self._forwarded:
                    return        # already forwarded + liveness armed
                if not self.in_view_change and relay:
                    # relay=False when this element arrived inside a
                    # ClientBatchRequestMsg the dispatcher already
                    # relayed whole
                    self.comm.send(self.primary, req.pack())
            else:
                # primary fast drop BEFORE paying for verification: a
                # pending or already-executed request needs no new
                # signature work (the retransmission path — reference
                # ClientsManager duplicate handling). Resending a cached
                # reply unverified is bounded, client-addressed traffic.
                if not self.clients.can_become_pending(client,
                                                       req.req_seq_num):
                    cached = self.clients.cached_reply(client,
                                                       req.req_seq_num)
                    if cached is not None:
                        self.comm.send(client, cached.pack())
                    return
        if getattr(req, "_adm_verified", None) is True:
            # admission plane already verified the client signature in a
            # coalesced per-drain batch (failed verdicts never reach the
            # dispatcher) — go straight to the stateful tail
            self._post_admission(req)
            return
        if self.req_batcher is not None:
            # async plane: the signature check leaves the dispatcher and
            # verifies in a cross-request batch; the verdict re-enters as
            # the "req_verified" internal message and the post-admission
            # logic (which re-reads mutable state) runs then
            key = (client, req.req_seq_num, int(req.flags))
            if key in self._req_verifying:
                return            # retransmission of an in-flight verify
            self._req_verifying.add(key)
            self.req_batcher.submit_nowait(
                client, req.signed_payload(), req.signature,
                lambda ok, _req=req: self.incoming.push_internal(
                    "req_verified", (_req, ok)))
            return
        if not self._verify_client_sig(req):
            return
        self._post_admission(req)

    def _retrans_tick(self) -> None:
        self.retrans.tick(time.monotonic())
        self.m_retransmitted.set(self.retrans.total_retransmitted)

    def _on_req_verified(self, payload) -> None:
        """Admission-batch verdict (dispatcher thread)."""
        req, ok = payload
        self._req_verifying.discard(
            (req.sender_id, req.req_seq_num, int(req.flags)))
        if not ok:
            return
        self._post_admission(req)

    def _post_admission(self, req: m.ClientRequestMsg) -> None:
        """Everything after the client-signature check. With the async
        plane the world may have moved since the request arrived (view
        change, reply cached) — all state reads happen here, not before
        the verify."""
        client = req.sender_id
        if req.flags & m.RequestFlag.READ_ONLY:
            # replied directly — MUST NOT advance the client's
            # last-executed counter (that would make _execute_committed
            # skip a committed write with a lower req_seq: divergence)
            if req.flags & m.RequestFlag.RECONFIG:
                # non-ordered operator command (reference: the operator's
                # direct/bft=false path — how unwedge reaches a cluster
                # that can no longer order anything)
                if self.reconfig is None:
                    return
                payload = self.reconfig.execute(self, req,
                                                self.last_executed,
                                                direct=True)
            else:
                payload = self.handler.read(client, req.request)
            reply = m.ClientReplyMsg(
                sender_id=self.id, req_seq_num=req.req_seq_num,
                current_primary=self.primary, reply=payload,
                replica_specific_info=b"")
            if self._opt_replies:
                # optimistic plane: reads need the same per-replica
                # vouching as writes — a strict client accepts nothing
                # short of f+1 matching SIGNED replies
                reply.signature = self.sig.sign(reply.signed_payload())
            self.comm.send(client, reply.pack())
            return
        cached = self.clients.cached_reply(client, req.req_seq_num)
        if cached is not None:
            self.comm.send(client, cached.pack())
            return
        if not self.is_primary or self.in_view_change:
            # the forward itself happened at arrival (pre-verify); here —
            # with the signature now checked — arm the dead-primary
            # liveness clock. First-sighting timestamp only:
            # retransmissions must not reset it or the complaint never
            # fires.
            self._forwarded.setdefault((client, req.req_seq_num),
                                       time.monotonic())
            return
        if req.flags & m.RequestFlag.PRE_PROCESS and self.preprocessor:
            # optimistic pre-execution path (PreProcessor, SURVEY §3.5)
            self.preprocessor.on_client_request(req)
            return
        # PRE_PROCESS without a preprocessor: order normally (the flag
        # must stay — it is covered by the client's signature)
        self._admit_request(req)

    def _admit_request(self, req: m.ClientRequestMsg) -> None:
        """Primary: queue a request for batching (tail of
        onMessage<ClientRequestMsg>). Also the entry point for the
        preprocessor's ordered PreProcessResult wrappers."""
        if not self.clients.can_become_pending(req.sender_id,
                                               req.req_seq_num):
            return
        self.clients.add_pending(req.sender_id, req.req_seq_num, req.cid)
        self.pending_requests.append(req)
        self._try_send_pre_prepare()

    # ------------------------------------------------------------------
    # primary: batching + PrePrepare (ReplicaImp.cpp:657,865)
    # ------------------------------------------------------------------
    def _try_send_pre_prepare(self) -> None:
        if not self._running or not self.is_primary or self.in_view_change:
            return
        # wedge fill: an idle cluster must still REACH the agreed stop
        # point, so the primary proposes empty batches up to it
        # (reference: noop fill toward the super-stable checkpoint)
        wedge_fill = (self.control.wedge_point is not None
                      and self.primary_next_seq <= self.control.wedge_point)
        if not self.pending_requests and not wedge_fill:
            return
        # pipeline gate (reference ReplicaImp::tryToSendPrePrepareMsg /
        # concurrencyLevel): cap proposed-but-not-executed slots. Under
        # load this is what creates real batches — requests arriving
        # while the pipeline is full accumulate and ship together when a
        # slot completes (execution re-triggers this), instead of every
        # request paying a full consensus slot of per-replica crypto.
        # At light load nothing is in flight and proposal is immediate.
        in_flight = (self.primary_next_seq - 1) - self.last_executed
        if in_flight >= max(1, self.cfg.concurrency_level):
            return
        seq = self.primary_next_seq
        if seq > self.last_stable + self.cfg.work_window_size:
            return                              # window full: wait for stability
        if self.control.blocks_ordering(seq):
            return                              # wedged (ControlStateManager)
        batch = self.pending_requests[:self.cfg.max_num_of_requests_in_batch]
        self.pending_requests = self.pending_requests[len(batch):]
        raw_reqs = [r.pack() for r in batch]
        pp = m.PrePrepareMsg(
            sender_id=self.id, view=self.view, seq_num=seq,
            epoch=self.epoch,
            first_path=int(self.controller.current_path),
            time=(self.time_service.primary_stamp()
                  if self.cfg.time_service_enabled
                  else int(time.time() * 1e6)),
            requests_digest=m.PrePrepareMsg.compute_requests_digest(raw_reqs),
            requests=raw_reqs, signature=b"")
        pp.signature = self.sig.sign(pp.signed_payload())
        self.primary_next_seq = seq + 1
        self.m_preprepares.inc()
        self._broadcast_tracked(pp)             # backups ack receipt
        self._accept_pre_prepare(pp)            # primary processes its own

    # ------------------------------------------------------------------
    # PrePrepare (ReplicaImp.cpp:1047)
    # ------------------------------------------------------------------
    def _pp_acceptable_now(self, pp: m.PrePrepareMsg) -> bool:
        """Structural acceptance checks that depend on CURRENT protocol
        state — run at arrival AND re-run when the async client-sig
        verdict lands (the view/window may have moved while the batch was
        on a worker). Content checks (parse, per-request validity, time
        bound) run at arrival only: message content cannot change."""
        if pp.view != self.view or pp.sender_id != self.primary \
                or self.in_view_change:
            return False
        if not self.window.in_window(pp.seq_num) \
                or pp.seq_num <= self.last_stable:
            return False
        if self.window.get(pp.seq_num).pre_prepare is not None:
            return False                        # already have it
        if self.control.blocks_ordering(pp.seq_num):
            return False                        # wedged: nothing past stop
        # view-change safety: a seqnum certified as possibly-committed in
        # an earlier view may ONLY be re-proposed with the same batch
        # (ViewChangeSafetyLogic restrictions)
        restr = self.restrictions.get(pp.seq_num)
        return restr is None or pp.requests_digest == restr.requests_digest

    def _on_pre_prepare(self, pp: m.PrePrepareMsg) -> None:
        # slot-stage anchor: adm_wait ends / dispatch begins here
        flight.record(flight.EV_PP_DISPATCH, seq=pp.seq_num,
                      view=pp.view)
        if pp.view == self.view and pp.sender_id == self.primary \
                and self.window.in_window(pp.seq_num):
            # receipt ack, duplicates included (retransmission tracking
            # keys on receipt, not acceptance)
            self._ack(pp.sender_id, int(pp.CODE), pp.seq_num)
        if not self._pp_acceptable_now(pp):
            return
        info = self.window.get(pp.seq_num)
        if info.pp_verifying is not None:
            # a duplicate arriving during the async-verify window must not
            # repay the inline sig check + request validation below
            return
        # admission verdict: True = the replica signature AND every
        # embedded client signature verified in the plane's coalesced
        # batch; False = that batch FAILED (the message was admitted
        # only so _try_resolve_body could consume a digest-authenticated
        # old-view body — as a live proposal it dies here); None =
        # legacy path, verify inline/async below
        adm_ok = getattr(pp, "_adm_verified", None)
        if adm_ok is False:
            log.warning("PrePrepare rejected by admission signature "
                        "batch (sender=%d)", pp.sender_id)
            return
        if adm_ok is None and not self._verify_replica_msg(
                pp, seq=pp.seq_num):
            log.warning("PrePrepare replica-signature check failed "
                        "(sender=%d)", pp.sender_id)
            return
        # Every embedded client request is verified before signing shares
        # over the batch — a byzantine primary must not be able to smuggle
        # forged client operations (reference: per-request verification
        # via RequestThreadPool, ReplicaImp.cpp onMessage<PrePrepareMsg>).
        # Structural checks run here on the dispatcher; the signature
        # batch itself verifies on a background worker (one device
        # dispatch with the TPU backend) and re-enters as "pp_verified".
        try:
            reqs = pp.client_requests()
        except m.MsgError:
            return
        for r in reqs:
            if r.flags & m.RequestFlag.HAS_PRE_PROCESSED:
                from tpubft.preprocessor.preprocessor import (
                    validate_preprocessed_request)
                if not validate_preprocessed_request(self, r):
                    return
            if not self.clients.is_valid_client(r.sender_id):
                return
            # a byzantine primary must not smuggle INTERNAL-flagged ops
            # from external principals (or strip the flag from real ones)
            if bool(r.flags & m.RequestFlag.INTERNAL) \
                    != self.info.is_internal_client(r.sender_id):
                return
            if r.flags & m.RequestFlag.RECONFIG \
                    and r.sender_id != self.info.operator_id:
                return
        # time service: bound the primary's stamp (reference
        # TimeServiceManager::hasTimeRequest). Gap-fill PrePrepares
        # (empty, time=0) and restricted re-proposals (old stamp, content
        # already certified) are exempt or view change could never finish.
        if (self.cfg.time_service_enabled and reqs
                and pp.seq_num not in self.restrictions
                and not self.time_service.validate(pp.time)):
            return
        # pre-executed wrappers carry their own proof set (original client
        # sig + f+1 replica result sigs) instead of a wrapper signature
        if adm_ok is None:
            items = [(r.sender_id, r.signed_payload(), r.signature)
                     for r in reqs
                     if not r.flags & m.RequestFlag.HAS_PRE_PROCESSED]
            if items and self.cfg.async_verification:
                info.pp_verifying = pp          # guarded at entry above
                self.collector_pool.submit(
                    lambda: self._bg_verify_pp(pp, items))
                return
            if items and not self._verify_req_items(items, pp.seq_num):
                return
        self._accept_pre_prepare(pp)

    # ---- inline verification fallbacks (admission-off path) ----
    # Kept OUT of the hot-path handlers on purpose: tools/check_hotpath.py
    # forbids direct unpack/verify call sites inside the dispatcher's
    # admitted-message handlers, so any new inline crypto must route
    # through these seams (and stay skippable when a verdict is attached).
    def _verify_replica_msg(self, msg, seq=None, view_scoped=False) -> bool:
        """One replica-signed message, on the dispatcher (legacy path)."""
        return self.sig.verify(msg.sender_id, msg.signed_payload(),
                               msg.signature, seq=seq,
                               view_scoped=view_scoped)

    def _verify_client_sig(self, req: m.ClientRequestMsg) -> bool:
        return self.sig.verify(req.sender_id, req.signed_payload(),
                               req.signature)

    def _verify_req_items(self, items, seq: int) -> bool:
        """Inline embedded-request batch check (async_verification off)."""
        with TimeRecorder(self._h_verify):
            return all(self.sig.verify_batch(items, seq=seq))

    def _parse_batch_inners(self, msg: m.ClientBatchRequestMsg):
        """Legacy-path ClientBatch element parse (admission attaches
        pre-parsed survivors as `_adm_inners`); None = malformed batch."""
        return m.parse_batch_elements(msg)

    def _bg_verify_pp(self, pp: m.PrePrepareMsg, items) -> None:
        """Worker-thread body: one verify_batch call (one device dispatch
        on the TPU backend), verdict re-enters the dispatcher."""
        from tpubft.diagnostics import TimeRecorder
        try:
            with TimeRecorder(self._h_verify):
                ok = all(self.sig.verify_batch(items, seq=pp.seq_num))
        except Exception:  # noqa: BLE001 — job failure = verify failure
            log.exception("client-sig batch job raised for seq %d",
                          pp.seq_num)
            ok = False
        self.incoming.push_internal("pp_verified", (pp, ok))

    def _on_pp_verified(self, payload) -> None:
        """Async client-sig batch verdict (dispatcher thread). The world
        may have moved while the batch was on the worker: re-run the
        cheap structural checks before accepting."""
        pp, ok = payload
        if not self.window.in_window(pp.seq_num):
            return
        info = self.window.peek(pp.seq_num)
        if info is not None and info.pp_verifying is pp:
            # identity check: a verdict for a message the view change
            # dropped must not clear a NEWER message's in-flight guard
            info.pp_verifying = None
        if not ok:
            log.warning("client-signature batch rejected for seq %d "
                        "(byzantine primary or forged request)", pp.seq_num)
            return
        if info is None:
            return
        if not self._pp_acceptable_now(pp):
            return
        self._accept_pre_prepare(pp)

    def _accept_pre_prepare(self, pp: m.PrePrepareMsg) -> None:
        flight.record(flight.EV_PP_ACCEPT, seq=pp.seq_num, view=pp.view)
        info = self.window.get(pp.seq_num)
        info.pre_prepare = pp
        info.commit_path = pp.first_path
        info.received_at = time.monotonic()
        # consensus-slot span: accept → executed, joined to the first
        # request's trace (reference: per-stage child spans carrying the
        # PrePrepare's span context, ReplicaImp.cpp:1070)
        from tpubft.utils.tracing import SpanContext, get_tracer
        parent = None
        try:
            reqs = pp.client_requests()
            if reqs:
                parent = SpanContext.parse(reqs[0].cid or "")
        except m.MsgError:
            pass
        info.span = get_tracer().start_span("consensus_slot", parent=parent)
        info.span.set_tag("r", self.id).set_tag("seq", pp.seq_num) \
            .set_tag("view", pp.view).set_tag("path", pp.first_path)
        with self._tran() as st:
            st.seq(pp.seq_num).pre_prepare = pp.pack()
        if pp.first_path == int(m.CommitPath.SLOW):
            info.slow_started = True
            self._send_prepare_partial(info)
        else:
            self._send_partial_commit_proof(info)
        self._drain_early_shares(info)
        self._drain_early_certs(info)
        # speculation starts HERE on every path (ISSUE 18a): the
        # combine window opens at acceptance and the overlay covers the
        # whole prepare+commit round. After the early-evidence drains: a
        # slot that just committed from buffered certs takes the normal
        # path instead.
        self._pump_speculation()

    # ------------------------------------------------------------------
    # slow path: shares → collectors (ReplicaImp.cpp:1373,1399)
    # ------------------------------------------------------------------
    def _send_prepare_partial(self, info: SeqNumInfo) -> None:
        pp = info.pre_prepare
        d = self._share_digest("prepare", self.view, pp.seq_num, pp.digest())
        share = self.slow_signer.sign_share(d)
        msg = m.PreparePartialMsg(sender_id=self.id, view=self.view,
                                  seq_num=pp.seq_num, digest=d, sig=share,
                                  epoch=self.epoch)
        self._route_share(msg, "prepare")

    def _send_commit_partial(self, info: SeqNumInfo) -> None:
        pp = info.pre_prepare
        d = self._share_digest("commit", self.view, pp.seq_num, pp.digest())
        share = self.slow_signer.sign_share(d)
        msg = m.CommitPartialMsg(sender_id=self.id, view=self.view,
                                 seq_num=pp.seq_num, digest=d, sig=share,
                                 epoch=self.epoch)
        self._route_share(msg, "commit")

    # ------------------------------------------------------------------
    # share-aggregation overlay (consensus/aggregation.py): slow-path
    # shares climb a view-seeded tree rooted at the collector, each hop
    # folding its subtree into ONE 56-byte partial — the collector's
    # fan-in drops from O(n) datagrams per slot to O(fanout) at every
    # node (arXiv 1911.04698 rebuilt on the collector-centric flow)
    # ------------------------------------------------------------------
    def _overlay(self, view: int, seq_num: int, root: int):
        return overlay_for(self._agg_mode, self.cfg.n_val, self._agg_fanout,
                           root, view, seq_num, self.cfg.agg_rotate_seqs)

    def _route_share(self, msg, kind: str) -> None:
        """Send a slow-path share toward its collector: direct when
        aggregation is off (byte-identical to the historical path), via
        the overlay when on — banked locally if this node is interior,
        else to the overlay parent. Every non-direct route arms the
        parent-timeout fallback."""
        collector_id = self.info.collector_for(self.view, msg.seq_num)
        if collector_id == self.id:
            self._on_share(msg, kind)
            return
        if self._agg_mode != "off":
            ov = self._overlay(self.view, msg.seq_num, collector_id)
            if ov.is_interior(self.id):
                # our own share joins our subtree's next flush
                self._agg_absorb(self.id, self.view, msg.seq_num, kind,
                                 msg.digest, msg.sig)
                up = ov.parent_of(self.id)
                self._agg_arm_fallback(msg, kind, collector_id,
                                       -1 if up is None else up)
                return
            parent = ov.parent_of(self.id)
            if parent is not None and parent != collector_id:
                if not self._agg_parent_sick(parent):
                    self._send_tracked(parent, msg)
                    self._agg_arm_fallback(msg, kind, collector_id, parent)
                    return
                # sick parent: fall through to the direct send — one
                # timeout already proved this edge dead, later slots
                # must not re-pay it
            # depth-1 leaf: the overlay edge IS the direct send
        self._send_tracked(collector_id, msg)

    def _agg_parent_sick(self, parent: int) -> bool:
        """A parent that ate a share until the fallback timeout is
        routed AROUND (direct to the collector) for the rest of the
        view: the overlay reshuffles at the next view change (and per
        rotation window in gossip mode), so sickness is view-scoped —
        without this memory every slot behind a dead interior node
        pays the full parent timeout again."""
        entry = self._agg_sick.get(parent)
        return entry is not None and entry == self.view

    def _agg_arm_fallback(self, msg, kind: str, collector_id: int,
                          parent: int = -1) -> None:
        self._agg_fallback[(self.view, msg.seq_num, kind)] = (
            time.monotonic() + self.cfg.agg_parent_timeout_ms / 1e3,
            msg, collector_id, parent)

    def _agg_absorb(self, sender: int, view: int, seq_num: int, kind: str,
                    digest: bytes, blob: bytes) -> None:
        """Interior node: bank a child's raw share or subtree partial
        for the next flush (dispatcher thread; no crypto here — decode
        and summation happen on the collector-pool worker). The digest
        is part of the buffer key, so shares over a wrong digest
        self-segregate instead of poisoning the honest buffer."""
        key = (view, seq_num, kind, digest)
        buf = self._agg_buffers.get(key)
        if buf is None:
            buf = self._agg_buffers[key] = {}
        cur = buf.get(sender + 1)
        if cur is None or self._agg_weight(blob) > self._agg_weight(cur):
            # a child's cumulative re-flush supersedes its earlier,
            # thinner partial (raw shares always weigh 1, so they never
            # displace anything)
            buf[sender + 1] = blob
            # quiescence debounce: every growth re-arms the age clock,
            # so the age-based flush fires only once the trickle of
            # child arrivals PAUSES (a full subtree still flushes
            # immediately via the weight test) — without this, a slow
            # host flushes one thin partial per arrival window and the
            # overlay's fan-in win evaporates
            self._agg_buffer_born[key] = time.monotonic()

    def _agg_weight(self, blob: bytes) -> int:
        """Contributor count of a banked entry, dispatcher-cheap: the
        bitmap prefix for partials, 1 for raw shares."""
        from tpubft.crypto.systems import AGG_CERT_LEN
        if len(blob) == AGG_CERT_LEN:
            (bm,) = struct.unpack_from("<Q", blob, 0)
            return max(bin(bm).count("1"), 1)
        return 1

    def _agg_flush_tick(self) -> None:
        """Dispatcher timer: flush buffers whose subtree is complete or
        that have been QUIESCENT for agg_flush_ms (the age clock re-arms
        on every arrival, see _agg_absorb). One collector-pool job per tick
        sums EVERY due buffer in one device launch
        (BlsMultisigVerifier.aggregate_partials → msm_batch).

        Flushes are cumulative: the buffer is kept (not popped) and
        re-flushes when membership grew, so a child share that arrives
        AFTER the age-based flush still climbs the overlay — as a
        superset partial that supersedes the earlier one at the parent
        (weight-based replacement) instead of being silently lost to
        the first-flush-wins entry key."""
        if not self._agg_buffers:
            return
        now = time.monotonic()
        age_s = self.cfg.agg_flush_ms / 1e3
        due = []
        for key in list(self._agg_buffers):
            view, seq_num, kind, _digest = key
            if view != self.view or self.in_view_change \
                    or seq_num <= self.last_stable \
                    or not self.window.in_window(seq_num):
                del self._agg_buffers[key]
                self._agg_buffer_born.pop(key, None)
                self._agg_flushed.pop(key, None)
                continue
            members = frozenset(self._agg_buffers[key])
            if members == self._agg_flushed.get(key):
                continue                  # nothing new since last flush
            collector_id = self.info.collector_for(view, seq_num)
            ov = self._overlay(view, seq_num, collector_id)
            expected = len(ov.subtree_ids(self.id))
            weight = sum(self._agg_weight(b)
                         for b in self._agg_buffers[key].values())
            if weight >= expected \
                    or now - self._agg_buffer_born[key] >= age_s:
                due.append(key)
                self._agg_flushed[key] = members
                # re-arm the age window so late stragglers batch up
                # instead of one flush per arrival
                self._agg_buffer_born[key] = now
        if not due:
            return
        snapshot = [(key, dict(self._agg_buffers[key])) for key in due]
        self.collector_pool.submit(lambda: self._agg_combine_job(snapshot))

    def _agg_combine_job(self, snapshot) -> None:
        """Collector-pool worker: decode banked entries (accumulator
        `add` semantics — malformed/overlapping entries dropped
        deterministically) and fold each buffer into one packed partial;
        all sums ride ONE segmented multi-MSM launch. Results re-enter
        the dispatcher as "agg_partial"."""
        try:
            jobs, keys = [], []
            for key, entries in snapshot:
                decoded = self.slow_verifier._decode_job_entries(entries)
                ids: List[int] = []
                pts = []
                for k in sorted(decoded):
                    eids, pt = decoded[k]
                    ids.extend(eids)
                    pts.append(pt)
                if pts:
                    jobs.append((sorted(ids), pts))
                    keys.append(key)
            if not jobs:
                return
            partials = self.slow_verifier.aggregate_partials(jobs)
            self.incoming.push_internal("agg_partial",
                                        list(zip(keys, partials)))
        except Exception:  # noqa: BLE001 — fallback covers a lost flush
            log.exception("agg combine job failed")

    def _on_agg_partials(self, payload) -> None:
        """Flushed partials (dispatcher thread): pack each into an
        AggregateShareMsg and send it one hop up the overlay."""
        for (view, seq_num, kind, digest), partial in payload:
            if view != self.view or self.in_view_change \
                    or seq_num <= self.last_stable:
                continue
            collector_id = self.info.collector_for(view, seq_num)
            if collector_id == self.id:
                continue                    # we became collector mid-flush
            ov = self._overlay(view, seq_num, collector_id)
            parent = ov.parent_of(self.id)
            if parent is None:
                continue
            if parent != collector_id and self._agg_parent_sick(parent):
                parent = collector_id    # route the partial AROUND the
                #                          dead hop; the root absorbs it
            flight.record(flight.EV_AGG_FORWARD, seq=seq_num, view=view,
                          arg=self._agg_weight(partial))
            self.m_agg_forwarded.inc()
            self._send_tracked(parent, m.AggregateShareMsg(
                sender_id=self.id, view=view, seq_num=seq_num,
                kind=0 if kind == "prepare" else 1,
                digest=digest, agg=partial, epoch=self.epoch))

    def _on_agg_share(self, msg: m.AggregateShareMsg) -> None:
        """A partial aggregate climbing the overlay: banked again if this
        node is an interior hop, fed into the slot's ShareCollector at
        the root — keyed by the forwarding child, so a forged partial
        bisects to exactly that child's subtree (contributor bitmap) and
        the bad-share pop in _on_combine_result drops the whole subtree
        in one move."""
        if self._agg_mode == "off":
            return
        if msg.view != self.view or not self.info.is_replica(msg.sender_id):
            return
        if self.in_view_change:
            return
        if not self.window.in_window(msg.seq_num) \
                or msg.seq_num <= self.last_stable:
            return
        self.m_share_msgs_rcvd.inc()
        self._ack(msg.sender_id, int(msg.CODE), msg.seq_num)
        kind = "prepare" if msg.kind == 0 else "commit"
        if self.info.collector_for(self.view, msg.seq_num) != self.id:
            self._agg_absorb(msg.sender_id, msg.view, msg.seq_num, kind,
                             msg.digest, msg.agg)
            return
        info = self.window.get(msg.seq_num)
        if info.pre_prepare is None:
            # PP not accepted yet: park beside early raw shares, drained
            # through _drain_early_shares under the "agg" pseudo-kind
            info.early_shares.setdefault("agg", []).append(msg)
            if not info.first_evidence_at:
                info.first_evidence_at = time.monotonic()
            return
        collector = self._collector(info, kind)
        if collector is None or msg.digest != collector.digest:
            return
        flight.record(flight.EV_AGG_ROOT, seq=msg.seq_num, view=msg.view,
                      arg=self._agg_weight(msg.agg))
        self.m_agg_absorbed.inc()
        if collector.add_share(msg.sender_id, msg.agg):
            self.collector_pool.maybe_launch(collector)

    def _agg_fallback_tick(self) -> None:
        """Dispatcher timer: any share still waiting on the overlay past
        its parent timeout re-sends DIRECT to the collector, and the
        parent that ate it is marked sick for the rest of the view
        (_agg_parent_sick) so later slots route around it immediately.
        The liveness floor is exactly the no-aggregation path — a dead
        or byzantine interior node costs ONE timeout per view, never a
        view change."""
        if not self._agg_fallback:
            return
        now = time.monotonic()
        for key in list(self._agg_fallback):
            view, seq_num, kind = key
            deadline, msg, collector_id, parent = self._agg_fallback[key]
            info = (self.window.peek(seq_num)
                    if self.window.in_window(seq_num) else None)
            done = (view != self.view or self.in_view_change
                    or seq_num <= self.last_stable
                    or (info is not None
                        and (info.committed
                             or (kind == "prepare" and info.prepared))))
            if done:
                del self._agg_fallback[key]
                continue
            if now < deadline:
                continue
            del self._agg_fallback[key]
            if parent >= 0 and view == self.view \
                    and self.retrans is not None \
                    and (self.retrans.is_pending(parent, int(msg.CODE),
                                                 msg.seq_num)
                         or self.retrans.is_pending(
                             parent, int(m.AggregateShareMsg.CODE),
                             msg.seq_num)):
                # unacked after the whole parent window: the EDGE is
                # dead, not just the slot slow — route around it for
                # the rest of the view (leaves track their raw share,
                # interior hops their forwarded partial)
                self._agg_sick[parent] = view
            flight.record(flight.EV_AGG_FALLBACK, seq=seq_num, view=view,
                          arg=0 if kind == "prepare" else 1)
            self.m_agg_fallbacks.inc()
            if collector_id == self.id:
                self._on_share(msg, kind)
            else:
                self._send_tracked(collector_id, msg)

    def _fast_tools(self, path: int):
        """(signer, verifier, domain-tag) for a fast commit path."""
        if path == int(m.CommitPath.OPTIMISTIC_FAST):
            return self.opt_signer, self.opt_verifier, "fast0"
        return self.thr_signer, self.thr_verifier, "fast1"

    def _send_partial_commit_proof(self, info: SeqNumInfo) -> None:
        """Fast path share (reference sendPartialProof ReplicaImp.cpp:1319)."""
        pp = info.pre_prepare
        signer, _, tag = self._fast_tools(pp.first_path)
        d = self._share_digest(tag, self.view, pp.seq_num, pp.digest())
        msg = m.PartialCommitProofMsg(sender_id=self.id, view=self.view,
                                      epoch=self.epoch,
                                      seq_num=pp.seq_num, digest=d,
                                      sig=signer.sign_share(d),
                                      path=pp.first_path)
        collector_id = self.info.collector_for(self.view, pp.seq_num)
        if collector_id == self.id:
            self._on_share(msg, "fast")
        else:
            self._send_tracked(collector_id, msg)

    def _on_share(self, msg: m.PreparePartialMsg, kind: str) -> None:
        """Collector side: accumulate a threshold share
        (CollectorOfThresholdSignatures::addMsgWithPartialSignature)."""
        if msg.view != self.view or not self.info.is_replica(msg.sender_id):
            return
        if self.in_view_change:
            # ordering in this view is frozen and _on_combine_result
            # discards results while the change is in flight: a share
            # accepted here can only launch combines that cannot land.
            # Under a breaker-OPEN + view-change storm those combines run
            # on the scalar fallback — stale-view shares were burning the
            # exact CPU the degraded cluster needs to finish the change.
            return
        if not self.window.in_window(msg.seq_num) \
                or msg.seq_num <= self.last_stable:
            return
        if kind != "fast" and msg.sender_id != self.id:
            # Prepare/Commit share fan-in only (the aggregation overlay's
            # target metric) — fast-path shares are always one direct
            # datagram to the collector and never aggregate
            self.m_share_msgs_rcvd.inc()
        # receipt ack (duplicates too — the sender may have missed the
        # first ack; retransmission keys on receipt, not on usefulness)
        self._ack(msg.sender_id, int(msg.CODE), msg.seq_num)
        if self._agg_mode != "off" and kind != "fast" \
                and msg.sender_id != self.id \
                and self.info.collector_for(self.view, msg.seq_num) != self.id:
            # interior overlay hop: bank the child's raw share for the
            # next flush (no PrePrepare needed — the digest keys the
            # buffer, and only the root resolves digests to collectors)
            self._agg_absorb(msg.sender_id, msg.view, msg.seq_num, kind,
                             msg.digest, msg.sig)
            return
        info = self.window.get(msg.seq_num)
        if info.pre_prepare is None:
            info.early_shares.setdefault(kind, []).append(msg)
            if not info.first_evidence_at:
                info.first_evidence_at = time.monotonic()
            return
        if kind == "fast" and msg.path != info.pre_prepare.first_path:
            return                              # share for the wrong path
        collector = self._collector(info, kind)
        if collector is None or msg.digest != collector.digest:
            return                              # share over a wrong digest
        if collector.add_share(msg.sender_id, msg.sig):
            self.collector_pool.maybe_launch(collector)

    def _collector(self, info: SeqNumInfo, kind: str) -> Optional[ShareCollector]:
        pp = info.pre_prepare
        if pp is None:
            return None
        attr = f"{kind}_collector"
        col = getattr(info, attr)
        if col is None:
            if kind == "fast":
                _, verifier, tag = self._fast_tools(pp.first_path)
            else:
                verifier, tag = self.slow_verifier, kind
            d = self._share_digest(tag, self.view, pp.seq_num, pp.digest())
            col = ShareCollector(self.view, pp.seq_num, kind, d, verifier)
            setattr(info, attr, col)
        return col

    def _drain_early_shares(self, info: SeqNumInfo) -> None:
        for kind, msgs in list(info.early_shares.items()):
            info.early_shares[kind] = []
            for msg in msgs:
                if kind == "agg":
                    self._on_agg_share(msg)
                else:
                    self._on_share(msg, kind)

    # ------------------------------------------------------------------
    # combine results (internal msg; reference onInternalMsg :1517)
    # ------------------------------------------------------------------
    def _on_combine_flush(self, n_slots: int) -> None:
        """Fused combine flush drained (combine-batch thread): batch
        stats only — locked counters/histogram, no protocol state."""
        self.m_combine_batches.inc()
        self.m_combined_slots.inc(n_slots)
        self._h_combine_batch.record(n_slots)

    def _on_combine_result(self, res: CombineResult) -> None:
        # the verdict's state flip happens HERE, dispatcher-side, on the
        # exact collector the job ran for — combine workers/batchers
        # never write collector state (it would race ready_for_job on
        # this thread). Unconditional: even a stale verdict (view
        # changed, window slid) must clear its own collector's
        # job_launched, or an outlived collector could wedge.
        if res.collector is not None:
            res.collector.on_result(res)
        if res.view != self.view or not self.window.in_window(res.seq_num) \
                or self.in_view_change:
            return
        info = self.window.peek(res.seq_num)
        if info is None or info.pre_prepare is None:
            return
        if not res.ok:
            log.warning("combine failed kind=%s seq=%d bad_shares=%s",
                        res.kind, res.seq_num, res.bad_shares)
            # bad shares identified: drop them, then retry if an honest
            # quorum is still present (or when the next share arrives)
            col = getattr(info, f"{res.kind}_collector", None)
            if col is not None:
                for sid in res.bad_shares:
                    col.shares.pop(sid, None)
                    # signer ids are 1-based; origin replica is sid-1
                    self.byz_telemetry.bad_share(sid - 1)
                self.collector_pool.maybe_launch(col)
            return
        pp = info.pre_prepare
        if res.kind == "fast":
            _, _, tag = self._fast_tools(pp.first_path)
            d = self._share_digest(tag, self.view, pp.seq_num, pp.digest())
            full = m.FullCommitProofMsg(sender_id=self.id, view=self.view,
                                        seq_num=res.seq_num, digest=d,
                                        sig=res.combined_sig,
                                        epoch=self.epoch)
            self._broadcast_tracked(full)
            self._accept_full_commit_proof(full)
            return
        d = self._share_digest(res.kind, self.view, pp.seq_num, pp.digest())
        if res.kind == "prepare":
            full = m.PrepareFullMsg(sender_id=self.id, view=self.view,
                                    seq_num=res.seq_num, digest=d,
                                    sig=res.combined_sig,
                                    epoch=self.epoch)
            self._broadcast_tracked(full)
            self._accept_prepare_full(full)
        elif res.kind == "commit":
            full = m.CommitFullMsg(sender_id=self.id, view=self.view,
                                   epoch=self.epoch,
                                   seq_num=res.seq_num, digest=d,
                                   sig=res.combined_sig)
            self._broadcast_tracked(full)
            self._accept_commit_full(full)

    # ------------------------------------------------------------------
    # full certificates
    # ------------------------------------------------------------------
    def _cert_tools(self, msg, kind: str):
        """(verifier, expected digest) for a full-certificate message
        against CURRENT state, "early" when the PrePrepare isn't accepted
        yet, or None when the message can't be valid."""
        if msg.view != self.view or not self.window.in_window(msg.seq_num) \
                or msg.seq_num <= self.last_stable:
            return None
        info = self.window.peek(msg.seq_num)
        if info is None or info.pre_prepare is None:
            return "early"
        if kind == "fast":
            _, verifier, tag = self._fast_tools(info.pre_prepare.first_path)
        else:
            verifier, tag = self.slow_verifier, kind
        d = self._share_digest(tag, self.view, msg.seq_num,
                               info.pre_prepare.digest())
        if msg.digest != d:
            return None
        return verifier, d

    def _handle_full_cert(self, msg, kind: str) -> None:
        """Common path for PrepareFull / CommitFull / FullCommitProof:
        structural checks on the dispatcher, the threshold verification as
        a background job re-entering as "cert_verified" (reference:
        CombinedSigVerificationJob, CollectorOfThresholdSignatures.hpp:409)."""
        tools = self._cert_tools(msg, kind)
        if tools is None:
            return
        self._ack(msg.sender_id, int(msg.CODE), msg.seq_num)
        if tools == "early":
            # PP not here yet (possibly still in async verification):
            # buffer per (kind, sender), drained on PP acceptance — one
            # slot per sender, so a byzantine peer's spam only ever
            # displaces its own buffered certs, never the collector's
            if self.info.is_replica(msg.sender_id):
                info = self.window.get(msg.seq_num)
                info.early_certs[(kind, msg.sender_id)] = msg
                if not info.first_evidence_at:
                    info.first_evidence_at = time.monotonic()
            return
        info = self.window.get(msg.seq_num)
        if info.committed or (kind == "prepare" and info.prepared):
            return
        verifier, d = tools
        # --- optimistic release (ISSUE 18): the structural check above
        # bound this cert to OUR accepted PrePrepare's digest; on the
        # slow path a VERIFIED prepare certificate (2f+c+1) already
        # vouches for the batch. Release the slot to the client-visible
        # path now and let the pairing verify land behind — a later BAD
        # verdict poisons the plane (see _on_cert_verified) but commits
        # still gate last_executed persistence (_apply_exec_runs clamp).
        if self._opt_replies and self.cfg.async_verification \
                and not self._opt_poisoned and not info.opt_committed \
                and kind != "prepare" \
                and (kind == "fast" or info.prepared):
            info.opt_committed = True
            info.opt_committed_ns = time.monotonic_ns()
            flight.record(flight.EV_OPT_REPLY, seq=msg.seq_num,
                          view=msg.view, arg=1 if kind == "fast" else 0)
            self.m_opt_replies.inc()
            self._execute_committed()
        if not self.cfg.async_verification:
            if self._verify_cert_inline(verifier, d, msg.sig):
                self._accept_cert(msg, kind)
            return
        if kind in info.cert_verifying:
            # a same-kind job is in flight (possibly over a forged cert):
            # park this one per sender and retry when that verdict lands,
            # so a forgery can't shadow the genuine certificate
            if self.info.is_replica(msg.sender_id):
                info.cert_pending[(kind, msg.sender_id)] = msg
            return
        info.cert_verifying[kind] = msg
        from tpubft.crypto.interfaces import IThresholdVerifier
        if type(verifier).verify_batch_certs \
                is not IThresholdVerifier.verify_batch_certs:
            # backend has a real aggregated check (BLS RLC pairing):
            # batch across seqnums/kinds
            self.cert_batcher.submit(verifier, d, msg.sig, (msg, kind))
            return
        self.collector_pool.submit(
            lambda: self._bg_verify_cert(verifier, d, msg, kind))

    def _bg_verify_cert(self, verifier, d: bytes, msg, kind: str) -> None:
        """Worker-thread combined-cert check; verdict re-enters the
        dispatcher as "cert_verified"."""
        try:
            ok = verifier.verify(d, msg.sig)
        except Exception:  # noqa: BLE001
            log.exception("cert verify job raised (kind=%s seq=%d)",
                          kind, msg.seq_num)
            ok = False
        self.incoming.push_internal("cert_verified", (msg, kind, ok))

    def _verify_cert_inline(self, verifier, d: bytes, sig: bytes) -> bool:
        """Inline combined-cert check (async_verification=False debug)."""
        return verifier.verify(d, sig)

    def _on_cert_verified(self, payload) -> None:
        """Async combined-cert verdict (dispatcher thread)."""
        msg, kind, ok = payload
        if not self.window.in_window(msg.seq_num):
            return
        info = self.window.peek(msg.seq_num)
        if info is not None and info.cert_verifying.get(kind) is msg:
            del info.cert_verifying[kind]
        if ok:
            # re-validate vs current state: view change may have reset the
            # window entry, or a different PP may sit there now — the
            # digest re-check binds the cert to the PP it actually covers
            tools = self._cert_tools(msg, kind)
            if tools is not None and tools != "early":
                self._accept_cert(msg, kind)
        else:
            # per-origin evidence: a cert that failed the DEFERRED check
            # passed the structural one, so its sender forged or relayed
            # a bad combined signature — attributable, count it
            self.byz_telemetry.deferred_cert_failure(msg.sender_id)
        if not ok and (info is not None and info.opt_committed
                       and not info.committed and kind != "prepare"):
            # the deferred pairing check FAILED on a slot we already
            # released optimistically: an actively-forging peer slipped a
            # structurally-valid cert past us. The reply the client got
            # is still backed by a verified prepare quorum / matching
            # f+1 replies client-side, but stop trusting structure alone
            # until the view changes away from whoever is forging
            self._opt_poisoned = True
            self.m_cert_async_fails.inc()
            log.error("deferred cert verify FAILED on optimistically "
                      "released slot %d (kind=%s) — optimistic plane "
                      "poisoned until next view change", msg.seq_num, kind)
        # certs parked while this job was in flight get their turn now
        # (one may be the genuine one if this verdict was a forgery's);
        # the first re-handled becomes the next in-flight job, the rest
        # re-park into their per-sender slots
        if info is not None:
            parked = [(k, pmsg) for (k, _), pmsg in
                      list(info.cert_pending.items()) if k == kind]
            for key in [key for key in info.cert_pending if key[0] == kind]:
                del info.cert_pending[key]
            for k, pmsg in parked:
                if info.committed or (k == "prepare" and info.prepared):
                    break
                self._handle_full_cert(pmsg, k)

    def _accept_cert(self, msg, kind: str) -> None:
        if kind == "prepare":
            self._accept_prepare_full(msg)
        elif kind == "commit":
            self._accept_commit_full(msg)
        else:
            self._accept_full_commit_proof(msg)

    def _drain_early_certs(self, info: SeqNumInfo) -> None:
        certs, info.early_certs = info.early_certs, {}
        for (kind, _sender), msg in certs.items():
            self._handle_full_cert(msg, kind)

    def _on_prepare_full(self, msg: m.PrepareFullMsg) -> None:
        self._handle_full_cert(msg, "prepare")

    def _accept_prepare_full(self, msg: m.PrepareFullMsg) -> None:
        info = self.window.get(msg.seq_num)
        if info.prepared:
            return
        flight.record(flight.EV_PREPARED, seq=msg.seq_num, view=msg.view)
        info.prepare_full = msg
        info.prepared = True
        with self._tran() as st:
            st.seq(msg.seq_num).prepare_full = msg.pack()
        self._send_commit_partial(info)
        # speculation normally started at PP acceptance (ISSUE 18a);
        # this re-pump catches slots that could not speculate then
        # (e.g. ordered behind a barrier batch that has since drained)
        self._pump_speculation()

    def _on_commit_full(self, msg: m.CommitFullMsg) -> None:
        self._handle_full_cert(msg, "commit")

    def _note_cert_verified(self, info: SeqNumInfo) -> None:
        """Async-certificate bookkeeping (optimistic mode): the slot's
        commit certificate finished its deferred pairing verify. Records
        how long the certificate trailed the optimistic release and
        advances the verified frontier that clamps the persisted
        last_executed watermark (min of two monotone counters)."""
        if not self._opt_replies:
            return
        if info.opt_committed:
            lag_us = max(
                0, (time.monotonic_ns() - info.opt_committed_ns) // 1000)
            flight.record(flight.EV_CERT_ASYNC_DONE, seq=info.seq_num,
                          view=self.view)
            flight.record(flight.EV_CERT_ASYNC_LAG, seq=info.seq_num,
                          view=self.view, arg=lag_us)
        # contiguous walk: committed ⇒ verified (commits only flip via
        # _accept_cert after the verify verdict / stable checkpoint)
        v = max(self._verified_upto, self.last_stable)
        while True:
            nxt = self.window.peek(v + 1)
            if nxt is None or not nxt.committed:
                break
            v += 1
        self._verified_upto = v

    def _accept_commit_full(self, msg: m.CommitFullMsg) -> None:
        info = self.window.get(msg.seq_num)
        if info.committed:
            return
        flight.record(flight.EV_COMMITTED, seq=msg.seq_num,
                      view=msg.view, arg=0)
        info.commit_full = msg
        info.committed = True
        self.m_slow_commits.inc()
        self._note_cert_verified(info)
        if self.is_primary and info.pre_prepare is not None:
            if info.pre_prepare.first_path != int(m.CommitPath.SLOW):
                self.controller.on_slow_fallback(msg.seq_num)
            else:
                self.controller.on_slow_path_commit(msg.seq_num)
        with self._tran() as st:
            st.seq(msg.seq_num).commit_full = msg.pack()
        self._execute_committed()

    # ------------------------------------------------------------------
    # fast path: full proof + demotion (ReplicaImp.cpp:1468,1284)
    # ------------------------------------------------------------------
    def _on_full_commit_proof(self, msg: m.FullCommitProofMsg) -> None:
        self._handle_full_cert(msg, "fast")

    def _accept_full_commit_proof(self, msg: m.FullCommitProofMsg) -> None:
        info = self.window.get(msg.seq_num)
        if info.committed:
            return
        flight.record(flight.EV_COMMITTED, seq=msg.seq_num,
                      view=msg.view, arg=1)
        info.full_commit_proof = msg
        info.committed = True
        self.m_fast_commits.inc()
        self._note_cert_verified(info)
        if self.is_primary:
            self.controller.on_fast_path_commit(msg.seq_num)
        with self._tran() as st:
            st.seq(msg.seq_num).full_commit_proof = msg.pack()
        self._execute_committed()

    def _check_fast_path_timeouts(self) -> None:
        """Primary: demote stuck fast-path seqnums to the slow path
        (reference's controller timeout → StartSlowCommitMsg)."""
        if not self.is_primary:
            return
        now = time.monotonic()
        timeout_s = self.cfg.fast_path_timeout_ms / 1e3
        for seq, info in list(self.window.items()):
            if (info.pre_prepare is not None and not info.committed
                    and not info.slow_started
                    and info.pre_prepare.first_path != int(m.CommitPath.SLOW)
                    and now - info.received_at > timeout_s):
                ssc = m.StartSlowCommitMsg(sender_id=self.id, view=self.view,
                                           seq_num=seq, epoch=self.epoch)
                self._broadcast(ssc)
                self._start_slow_path(info)

    def _on_start_slow_commit(self, msg: m.StartSlowCommitMsg) -> None:
        if msg.view != self.view or msg.sender_id != self.primary:
            return
        if not self.window.in_window(msg.seq_num):
            return
        info = self.window.peek(msg.seq_num)
        if info is None or info.pre_prepare is None:
            return
        self._start_slow_path(info)

    def _start_slow_path(self, info: SeqNumInfo) -> None:
        if info.slow_started or info.committed:
            return
        info.slow_started = True
        self.m_slow_starts.inc()
        with self._tran() as st:
            st.seq(info.seq_num).slow_started = True
        self._send_prepare_partial(info)

    # ------------------------------------------------------------------
    # execution (ReplicaImp.cpp:5720,5364 + the execution lane)
    # ------------------------------------------------------------------
    def _execute_committed(self) -> None:
        """Committed slots became executable. With the execution lane the
        dispatcher only ENQUEUES them (execution + the coalesced commit
        happen on the lane thread); the legacy inline path runs when the
        lane is off — and during __init__'s restore replay, which happens
        before any thread besides the caller exists."""
        if self.exec_lane is not None and self._running:
            self._pump_execution_lane()
        else:
            self._execute_committed_inline()

    def _execute_committed_inline(self) -> None:
        while True:
            nxt = self.last_executed + 1
            if not self.window.in_window(nxt):
                return
            if self.control.blocks_ordering(nxt):
                # wedged: execution halts at the agreed cut; announce
                # readiness for the operator's restart proof
                self._maybe_announce_restart_ready()
                return
            info = self.window.peek(nxt)
            if info is None or not info.committed or info.executed:
                return
            self._execute_one_slot(nxt, info)

    def _execute_one_slot(self, nxt: int, info: SeqNumInfo) -> None:
        """Inline per-slot execution + apply (the pre-lane path, kept for
        execution_lane=off, restore replay, and lane barrier batches —
        INTERNAL/RECONFIG requests mutate dispatcher-owned subsystems)."""
        for req in info.pre_prepare.client_requests():
            # at-most-once: a request already executed for this client
            # must not re-execute (replay inside a later batch). This
            # is a membership test — requests execute out of seq order,
            # so a lower seqnum is not evidence of a replay.
            if self.clients.was_executed(req.sender_id, req.req_seq_num):
                cached = self.clients.cached_reply(req.sender_id,
                                                   req.req_seq_num)
                if cached is not None:
                    self.comm.send(req.sender_id, cached.pack())
                continue
            if self._slowdown.enabled:
                self._slowdown.delay(PHASE_EXECUTE)
            reply = self._execute_request(req, nxt)
            self.m_executed.inc()
            self._send_reply(req.sender_id, req.req_seq_num, reply)
        if self.cfg.time_service_enabled and info.pre_prepare.time:
            self.time_service.on_executed(info.pre_prepare.time)
        info.executed = True
        info.exec_submitted = False
        if getattr(info, "span", None) is not None:
            info.span.set_tag("committed_path", info.commit_path)
            info.span.finish()
            info.span = None
        self.last_executed = nxt
        self._exec_enqueued = max(self._exec_enqueued, nxt)
        self.m_last_executed.set(nxt)
        self._last_progress = time.monotonic()
        with self._tran() as st:
            st.last_executed_seq = nxt
        # inline path: apply and reply complete together on the
        # dispatcher — both slot-stage anchors land here
        flight.record(flight.EV_EXEC_APPLY, seq=nxt, arg=1)
        flight.record(flight.EV_REPLY, seq=nxt)
        if nxt % self.cfg.checkpoint_window_size == 0:
            self._send_checkpoint(nxt)
        # a slot just left the pipeline: the primary proposes the
        # batch that accumulated behind the concurrency gate NOW
        # rather than waiting for the next flush-timer tick
        self._try_send_pre_prepare()

    def _execute_request(self, req: m.ClientRequestMsg, seq: int) -> bytes:
        """One ordered request against the state machine. Runs on the
        dispatcher (inline path, barrier batches) or the execution lane
        (plain + pre-processed requests — the handler is the only state
        those branches touch)."""
        if req.flags & m.RequestFlag.INTERNAL:
            return self._execute_internal_request(req, seq)
        if req.flags & m.RequestFlag.RECONFIG:
            return (self.reconfig.execute(self, req, seq)
                    if self.reconfig is not None else b"")
        if req.flags & m.RequestFlag.HAS_PRE_PROCESSED:
            from tpubft.preprocessor.preprocessor import unpack_preprocessed
            try:
                orig, result = unpack_preprocessed(req.request)
            except Exception:  # noqa: BLE001 — malformed wrapper
                return b""
            # conflict detection at commit (reference verifyWriteCommand
            # at execution): re-validate the pre-executed result's
            # read-set version watermark against CURRENT state — the
            # speculation ran against an older snapshot. On conflict the
            # request falls back to NORMAL ORDERING: the original
            # request executes in this same committed slot (identical
            # total-order position, so ledgers stay byte-identical with
            # a pure-ordering run), and the flight event + counter make
            # the conflict rate observable for tuning.
            try:
                conflicted = self.handler.pre_exec_conflicted(
                    orig.sender_id, orig.req_seq_num, orig.request,
                    result)
            except Exception:  # noqa: BLE001 — advisory check only
                conflicted = False
            if conflicted:
                flight.record(flight.EV_PREEXEC_CONFLICT, seq=seq)
                self.m_preexec_conflicts.inc()
                return self.handler.execute(
                    orig.sender_id, orig.req_seq_num, orig.flags,
                    orig.request)
            self.m_preexec_applied.inc()
            return self.handler.apply_pre_executed(
                orig.sender_id, orig.req_seq_num, orig.flags,
                orig.request, result)
        with TimeRecorder(self._h_execute):
            return self.handler.execute(req.sender_id, req.req_seq_num,
                                        req.flags, req.request)

    # ---- execution lane plumbing (dispatcher side) ----
    @staticmethod
    def _batch_needs_dispatcher(pp: m.PrePrepareMsg) -> bool:
        """Barrier batches: INTERNAL (key exchange, cron) and RECONFIG
        (wedge, prune, epoch) requests mutate dispatcher-owned subsystems
        and must execute inline — the lane drains first so seq order is
        preserved around them."""
        try:
            reqs = pp.client_requests()
        except m.MsgError:           # parsed at acceptance; defensive
            return True
        return any(r.flags & (m.RequestFlag.INTERNAL
                              | m.RequestFlag.RECONFIG) for r in reqs)

    def _pump_execution_lane(self) -> None:
        """Hand every next consecutive committed slot to the lane (or
        execute barrier batches inline after draining it). Speculatively
        submitted slots whose commit just landed are CONFIRMED instead
        of resubmitted — the lane seals their already-executed run."""
        # phase 0: confirm commits for speculative submissions, strictly
        # in seq order (the lane's seal requires the whole run)
        while self._spec_inflight:
            nxt = self._spec_inflight[0]
            info = self.window.peek(nxt)
            if info is None or info.pre_prepare is None:
                # the slot vanished without a view-change abort —
                # defensive: discard the speculation and fall through to
                # the committed path
                self._abort_speculation("window-moved")
                break
            if not info.committed \
                    and not (self._opt_replies and info.opt_committed):
                break
            if self.exec_lane.confirm(nxt, info.pre_prepare.digest()):
                self._spec_inflight.pop(0)
                info.spec_submitted = False
                info.exec_submitted = True    # now normal lane work
            else:
                # speculated digest is not the committed one (or the
                # lane lost the slot): discard everything speculative;
                # the loop below resubmits the committed slots in order
                self._abort_speculation("digest-mismatch")
                break
        while True:
            nxt = max(self._exec_enqueued, self.last_executed) + 1
            if not self.window.in_window(nxt):
                break
            if self.control.blocks_ordering(nxt):
                # wedged: the announcement fires once the lane's applied
                # runs bring last_executed to the stop point (the applier
                # re-checks); calling here covers the already-drained case
                self._maybe_announce_restart_ready()
                break
            info = self.window.peek(nxt)
            if info is None or info.executed \
                    or info.exec_submitted or info.spec_submitted:
                break
            if not info.committed \
                    and not (self._opt_replies and info.opt_committed):
                break
            if self._batch_needs_dispatcher(info.pre_prepare):
                # barrier batches (INTERNAL/RECONFIG) mutate
                # dispatcher-owned subsystems irreversibly: they wait
                # for the VERIFIED commit even under optimistic replies
                if not info.committed:
                    break
                if self._spec_inflight:
                    # speculative slots ahead of the barrier are still
                    # awaiting their commits: the barrier cannot run yet
                    # anyway (last_executed lags) — draining now would
                    # only waste their speculation
                    break
                if not self._drain_exec_lane():
                    break               # lane stuck; retried on next event
                if self.last_executed != nxt - 1:
                    break               # world moved during the drain
                self._execute_one_slot(nxt, info)
                continue
            info.exec_submitted = True
            flight.record(flight.EV_EXEC_ENQ, seq=nxt, view=self.view)
            try:
                self.exec_lane.submit(nxt, info.pre_prepare)
            except BaseException:
                # a failed handoff must not strand the slot as
                # "submitted": clear the guard so the next commit event
                # (or timer) retries it
                info.exec_submitted = False
                raise
            self._exec_enqueued = nxt
        # newly-consecutive prepared/accepted slots may speculate now
        self._pump_speculation()

    def _pump_speculation(self) -> None:
        """Hand every next consecutive NOT-yet-committed slot to the
        lane as SPECULATIVE at PrePrepare ACCEPTANCE — on every path
        (ISSUE 18a; previously the slow path waited for its
        prepare-quorum). The overlay now covers the whole
        prepare+commit window; abort safety is unchanged (the overlay
        is never durable and the seal still requires the committed
        digest to confirm). Replies and last_executed stay strictly
        post-commit — post-release under optimistic replies, where the
        structural cert + verified prepare quorum stand in. Barrier
        batches (INTERNAL/RECONFIG) never speculate."""
        if not self._spec_enabled or self.exec_lane is None \
                or not self._running or self.in_view_change:
            return
        while True:
            nxt = max(self._exec_enqueued, self.last_executed) + 1
            if not self.window.in_window(nxt) \
                    or self.control.blocks_ordering(nxt):
                return
            info = self.window.peek(nxt)
            if info is None or info.pre_prepare is None or info.executed \
                    or info.committed or info.exec_submitted \
                    or info.spec_submitted:
                return
            pp = info.pre_prepare
            if not info.prepared \
                    and pp.first_path == int(m.CommitPath.SLOW):
                return              # slow path: wait for prepare-quorum
            if self._batch_needs_dispatcher(pp):
                return
            info.spec_submitted = True
            flight.record(flight.EV_SPEC_ENQ, seq=nxt, view=self.view)
            try:
                self.exec_lane.submit(nxt, pp, speculative=True)
            except BaseException:
                info.spec_submitted = False
                raise
            self._spec_inflight.append(nxt)
            self._exec_enqueued = nxt

    def _abort_speculation(self, cause: str) -> None:
        """Discard all speculative work (dispatcher thread): the lane
        aborts its open overlay, pending speculative entries (and any
        committed entries queued BEHIND them — order must hold) come
        back, and the submission bookkeeping rolls back so the normal
        committed path re-executes each slot from its committed
        PrePrepare once the certificate is in hand."""
        if self.exec_lane is None:
            return
        if not self._spec_inflight and not self.exec_lane.speculating:
            return
        removed = set(self.exec_lane.abort_speculation())
        removed.update(self._spec_inflight)
        self._spec_inflight = []
        if not removed:
            return
        self.m_exec_spec_aborts.inc()
        log.info("speculation aborted (%s): slots %s re-execute from "
                 "their committed bodies", cause, sorted(removed))
        for seq in sorted(removed):
            flight.record(flight.EV_SPEC_ABORT, seq=seq)
            info = self.window.peek(seq)
            if info is not None and not info.executed:
                info.exec_submitted = False
                info.spec_submitted = False
        self._exec_enqueued = min(self._exec_enqueued, min(removed) - 1)

    def _drain_exec_lane(self, timeout: Optional[float] = None) -> bool:
        """Dispatcher-side barrier: wait until the lane applied every
        submitted slot, then integrate the completed runs NOW (the
        level-triggered wakeup may still be queued behind us). Used
        before view-change send, view entry, state-transfer adoption,
        wedge/barrier execution. The default budget is
        ReplicaConfig.execution_drain_timeout_ms — the same threshold
        the health watchdog holds the lane's progress to, so a drain
        that would time out is independently reported as a stall."""
        if self.exec_lane is None:
            return True
        # speculative work cannot drain (it waits on commit certificates
        # only this thread can confirm, and the barrier callers are
        # about to invalidate it anyway): abort it first — the slots
        # re-execute from their committed bodies through the normal path
        self._abort_speculation("drain")
        if timeout is None:
            timeout = self.cfg.execution_drain_timeout_ms / 1e3
        deadline = time.monotonic() + timeout
        ok = self.exec_lane.drain(timeout)
        if not ok:
            log.warning("execution lane failed to drain in %.0fs "
                        "(depth=%d)", timeout, self.exec_lane.depth)
        if ok and self.durability is not None:
            # the lane drained = every run SEALED; the barrier callers
            # need them DURABLE and integrated (last_executed current,
            # pending overlay empty) before wiping the window / writing
            # the ledger directly — flush-and-wait the group pipeline
            # on the REMAINING budget (one barrier, one deadline)
            remaining = max(0.05, deadline - time.monotonic())
            ok = self.durability.drain(remaining)
            if not ok:
                log.warning("durability pipeline failed to drain in "
                            "%.1fs (lag=%d)", remaining,
                            self.durability.lag)
        # apply WITHOUT the trailing re-pump: refilling the lane here
        # would defeat the barrier (the caller is about to wipe the
        # window / adopt transferred state); newly-unblocked slots are
        # picked up by the next commit/apply event
        self._apply_exec_runs(repump=False)
        return ok and self.exec_lane.idle() \
            and (self.durability is None or self.durability.idle())

    def record_exec_run(self, run_len: int, commit_ms: float) -> None:
        """Lane-thread metrics hook (Counter/Gauge/histograms are
        thread-safe): one completed run of `run_len` slots whose
        coalesced durable apply took `commit_ms`."""
        self.m_exec_runs.inc()
        self.m_exec_run_slots.inc(run_len)
        self._h_exec_run_len.record(run_len)
        self._h_exec_commit_ms.record(commit_ms)

    def record_spec_seal(self, run_len: int, overlap_ms: float) -> None:
        """Lane-thread metrics hook: one SPECULATIVE run of `run_len`
        slots sealed at commit after overlapping `overlap_ms` of its
        threshold-combine window with execution."""
        self.m_exec_spec_runs.inc()
        self.m_exec_spec_overlap.set(int(overlap_ms))
        self._h_spec_overlap.record(overlap_ms)

    def _apply_exec_runs(self, _payload=None, repump: bool = True) -> None:
        """Integrate durably-applied runs (dispatcher thread): advance
        last_executed (only now — after the durable apply), persist the
        watermark, send the run's replies (riding the transport batcher
        via the dispatcher post-hook), finish spans, fire checkpoints
        computed at the run boundary, and re-arm the proposal pipeline."""
        if self.exec_lane is None:
            return
        runs = self.exec_lane.pop_completed()
        if not runs:
            return
        for run in runs:
            for seq in range(run.first, run.last + 1):
                info = self.window.peek(seq)
                if info is None:
                    continue
                info.executed = True
                info.exec_submitted = False
                info.spec_submitted = False
                if getattr(info, "span", None) is not None:
                    info.span.set_tag("committed_path", info.commit_path)
                    info.span.finish()
                    info.span = None
            for key in run.reply_keys:
                self._forwarded.pop(key, None)
            # replies already on the wire when the durability pipeline
            # released them as the group-boundary burst (ISSUE 16) —
            # sending again here would duplicate every reply datagram
            if not getattr(run, "replies_sent", False):
                for client, raw in run.replies:
                    self.comm.send(client, raw)
            self.m_executed.inc(run.n_requests)
            if run.last > self.last_executed:
                self.last_executed = run.last
                self.m_last_executed.set(run.last)
            self._last_progress = time.monotonic()
            # slot integrated + replies on the wire: the `reply` stage
            # ends here (the lane recorded EV_EXEC_APPLY at its apply/
            # seal; with the durability pipeline the group-fsync wait
            # shows up in this stage), finalizing each slot's record
            for seq in range(run.first, run.last + 1):
                flight.record(flight.EV_REPLY, seq=seq)
            if run.checkpoint is not None:
                seq, state_digest, pages_digest, height = run.checkpoint
                self._send_checkpoint(seq, state_digest=state_digest,
                                      pages_digest=pages_digest,
                                      block_id=height)
        # ONE metadata watermark persist per integration event — the
        # synchronous consensus-metadata fsync (the carve-out) now
        # covers every run the event delivered instead of paying the
        # disk once per run; with the durability pipeline the runs
        # integrate in group-sized batches, so the dispatcher's fsync
        # rate drops by the group factor too
        with self._tran() as st:
            if self._opt_replies:
                # optimistic mode: never persist past the verified-commit
                # frontier — a restart must not resume from a watermark
                # supported only by structurally-accepted (unverified)
                # certificates. Re-executing the durable-but-unpersisted
                # tail is replay-safe: the reply ring's at-most-once
                # dedup skips it (min of two monotones stays monotone)
                self._verified_upto = max(self._verified_upto,
                                          self.last_stable)
                st.last_executed_seq = min(self.last_executed,
                                           self._verified_upto)
            else:
                st.last_executed_seq = self.last_executed
        crashpoint("meta.watermark", rid=self.id)
        self._maybe_announce_restart_ready()
        self._try_send_pre_prepare()
        if repump:
            # a barrier batch may have been waiting behind these runs
            self._pump_execution_lane()

    def _execute_internal_request(self, req: m.ClientRequestMsg,
                                  seq: int = 0) -> bytes:
        """Ordered consensus-internal operation (key exchange, cron tick)
        — executed identically on every replica."""
        from tpubft.consensus import internal as iops
        try:
            op = iops.unpack_op(req.request)
        except Exception:
            return b""
        if isinstance(op, iops.KeyExchangeOp):
            # only the replica owning the internal client may rotate its key
            if self.info.internal_client_of(op.replica_id) == req.sender_id:
                self.key_exchange.on_executed(op, seq)
                return b"ok"
            return b""
        if isinstance(op, iops.TickOp):
            self.cron_table.on_tick(op)
            return b"ok"
        return b""

    def _build_reply(self, client: int, req_seq: int, payload: bytes,
                     pages_batch=None, defer_sign: bool = False):
        """Build an executed request's reply + stage its persisted
        canonical form. Returns (reply_msg, wire_bytes_or_None) — the
        caller records it in the ClientsManager (immediately on the
        inline path; AFTER the durable commit on the execution lane, so
        an aborted run can retry without the at-most-once state claiming
        its requests already executed). `pages_batch` stages the page
        write into a caller-owned WriteBatch (the lane's
        one-batch-per-run path) instead of a direct put.

        The reply RING is the single canonical persisted location — one
        slot per req_seq mod window, so every element of a recently
        executed batch stays regenerable across crash/ST, and the
        restore watermark is the ring's newest seq. (An earlier layout
        ALSO wrote each reply to the per-client "clients" page; that
        write was fully shadowed by the ring — same canonical bytes,
        newest-seq watermark derivable from the ring — so it is gone:
        one page write per request instead of two, digest-deterministic
        across replicas because every replica runs the same rule.) The
        "clients" page now carries only the oversize-reply marker, the
        one record the bounded ring cannot hold."""
        reply = m.ClientReplyMsg(sender_id=self.id, req_seq_num=req_seq,
                                 current_primary=self.primary, reply=payload,
                                 replica_specific_info=b"")
        if self._opt_replies and not defer_sign:
            # optimistic replies: the client can no longer lean on the
            # certificate, so each replica vouches individually — f+1
            # MATCHING SIGNED replies is the client's acceptance rule.
            # sign() is thread-safe (pure signer + counter), so the
            # execution lane may call this off the dispatcher. With
            # `defer_sign` (execution lane + durability pipeline) the
            # signature is batched per sealed GROUP on the io thread
            # instead — the reply cannot leave before the group fsync,
            # so deferring to that boundary is free; external replies
            # then return wire=None and ride CompletedRun.unsigned
            reply.signature = self.sig.sign(reply.signed_payload())
        # at-most-once state rides reserved pages so it survives crashes
        # AND state transfer (reference keeps client replies in res pages).
        # Persist a CANONICAL form — per-replica fields (sender, primary)
        # zeroed — or the pages digest would differ across replicas and no
        # checkpoint certificate could ever form.
        canonical = b"\x00" + m.ClientReplyMsg(
            sender_id=0, req_seq_num=req_seq, current_primary=0,
            reply=payload, replica_specific_info=b"").pack()
        from tpubft.consensus.reserved_pages import PAGE_SIZE

        def save(category: str, index: int, data: bytes) -> None:
            if pages_batch is not None:
                self.res_pages.stage_save(pages_batch, category, index,
                                          data)
            else:
                self.res_pages.save(category, index, data)

        if len(canonical) > PAGE_SIZE:
            # reply too big for its page: keep the at-most-once marker so
            # a crash/ST never re-executes, even though the cached reply
            # is lost (the client re-reads; reference paginates large
            # replies)
            save("clients", client, b"\x01" + req_seq.to_bytes(8, "big"))
        else:
            from tpubft.consensus.clients_manager import \
                REPLY_CACHE_PER_CLIENT as _RING
            save("clientreplies", client * _RING + req_seq % _RING,
                 canonical)
        if self.info.is_internal_client(client) \
                or (defer_sign and self._opt_replies):
            # internal replies are consumed in-process (never packed);
            # deferred external replies pack AFTER the group sign
            return reply, None
        return reply, reply.pack()

    def _send_reply(self, client: int, req_seq: int, payload: bytes) -> None:
        """Inline-path reply (dispatcher thread): record + send now."""
        reply, wire = self._build_reply(client, req_seq, payload)
        self.clients.on_request_executed(client, req_seq, reply)
        self._forwarded.pop((client, req_seq), None)
        if wire is not None:
            self.comm.send(client, wire)

    # ------------------------------------------------------------------
    # status beacons + gap retransmission (reference ReplicaStatusMsg +
    # RetransmissionsManager / ReqMissingData duties)
    # ------------------------------------------------------------------
    def _send_status(self) -> None:
        if not self._running:
            return
        self.m_dropped_external.set(self.incoming.dropped_external)
        if self.admission is not None:
            self.admission.adm_queue_depth.set(self.admission.depth)
        status = m.ReplicaStatusMsg(
            sender_id=self.id, view=self.view,
            last_stable_seq=self.last_stable,
            last_executed_seq=self.last_executed,
            in_view_change=self.in_view_change,
            capabilities=self._my_capabilities())
        self._broadcast(status)
        # restart votes are liveness-critical for the n/n proof: keep
        # re-announcing until the proof forms (peers may have been
        # lagging or lossy when the first broadcast went out)
        if self._my_restart_vote is not None \
                and not self.control.restart_proof \
                and self.control.wedge_point is not None:
            self._broadcast(self._my_restart_vote)

    MAX_GAP_RESEND = 8

    def _my_capabilities(self) -> int:
        """CAP_* bitmap this replica advertises on status beacons.
        Clients can already infer CAP_OPT_REPLIES from the wire (an
        optimistic reply carries a signature before the combine check
        lands); this makes the same fact peer-visible and auditable."""
        caps = 0
        if self._opt_replies:
            caps |= m.CAP_OPT_REPLIES
        if self.cfg.offload_enabled:
            caps |= m.CAP_OFFLOAD
        return caps

    def _on_replica_status(self, msg: m.ReplicaStatusMsg) -> None:
        """A peer is behind: push it what it's missing. Status is
        advisory/unsigned — worst case a spoofed one costs a bounded
        retransmission, never state."""
        peer = msg.sender_id
        if peer == self.id:
            return
        # record the peer's advertised capability bitmap (advisory,
        # like the rest of the beacon — mixed-cluster detection only)
        self.peer_capabilities[peer] = msg.capabilities
        # (a) peer in an older view: resend the proof of ours so it can
        # enter (NewViewMsg + the ViewChangeMsgs it references)
        if msg.view < self.view and self._entered_view_proof is not None:
            nv, vcs = self._entered_view_proof
            for vc in vcs:
                self.comm.send(peer, vc.pack())
            self.comm.send(peer, nv.pack())
            return
        if msg.view != self.view:
            return
        # (b) same view, peer's execution lags inside our window: resend
        # PrePrepare + commit certificate from persisted state
        if msg.last_executed_seq >= self.last_executed:
            return
        st = self.storage.load()
        first = msg.last_executed_seq + 1
        for seq in range(first, min(self.last_executed,
                                    first + self.MAX_GAP_RESEND - 1) + 1):
            entry = st.seq_states.get(seq)
            if entry is None or entry.pre_prepare is None:
                continue
            self.comm.send(peer, entry.pre_prepare)
            if entry.full_commit_proof is not None:
                self.comm.send(peer, entry.full_commit_proof)
            elif entry.commit_full is not None:
                if entry.prepare_full is not None:
                    self.comm.send(peer, entry.prepare_full)
                self.comm.send(peer, entry.commit_full)

    # ------------------------------------------------------------------
    # missing-data flow (reference ReqMissingDataMsg + tryToSendReqMissing)
    # ------------------------------------------------------------------
    def _check_missing_data(self) -> None:
        """Evidence without a PrePrepare (buffered shares/certs) that has
        aged past the retransmission horizon: explicitly ask for the PP —
        first the primary, then everyone (the primary may be the one
        withholding it)."""
        if not self._running or self.in_view_change:
            return
        now = time.monotonic()
        grace = self.cfg.retransmission_timer_ms * 8 / 1000.0
        for seq, info in list(self.window.items()):
            if info.pre_prepare is not None or info.pp_verifying is not None:
                self._missing_since.pop(seq, None)
                continue
            if not info.early_shares and not info.early_certs:
                continue
            if not info.first_evidence_at \
                    or now - info.first_evidence_at < grace:
                continue
            entry = self._missing_since.setdefault(seq, [0.0, 0])
            if entry[1] and now - entry[0] < grace:
                continue                      # asked recently: wait
            entry[0] = now
            entry[1] += 1
            req = m.ReqMissingDataMsg(sender_id=self.id, view=self.view,
                                      seq_num=seq, missing=1)
            log.info("requesting missing PrePrepare for seq %d "
                     "(attempt %d)", seq, entry[1])
            if entry[1] == 1:
                self.comm.send(self.primary, req.pack())
            else:
                self._broadcast(req)

    def _on_req_missing_data(self, sender: int,
                             msg: m.ReqMissingDataMsg) -> None:
        """Serve a peer's explicit gap request from live window state or
        persisted metadata (reference handleReqMissingDataMsg). Unsigned
        like status — a spoofed request costs a bounded resend."""
        if msg.view != self.view or sender == self.id:
            return
        info = self.window.peek(msg.seq_num)
        pieces = []
        if info is not None and info.pre_prepare is not None:
            if msg.missing & 1:
                pieces.append(info.pre_prepare.pack())
            if msg.missing & 2 and info.prepare_full is not None:
                pieces.append(info.prepare_full.pack())
            if msg.missing & 4 and info.commit_full is not None:
                pieces.append(info.commit_full.pack())
            if msg.missing & 8 and info.full_commit_proof is not None:
                pieces.append(info.full_commit_proof.pack())
        else:
            entry = self.storage.load().seq_states.get(msg.seq_num)
            if entry is not None:
                for want, raw in ((1, entry.pre_prepare),
                                  (2, entry.prepare_full),
                                  (4, entry.commit_full),
                                  (8, entry.full_commit_proof)):
                    if msg.missing & want and raw is not None:
                        pieces.append(raw)
        for raw in pieces:
            self.comm.send(sender, raw)

    # ------------------------------------------------------------------
    # restart-readiness at the wedge point (ReplicaRestartReadyMsg)
    # ------------------------------------------------------------------
    def _maybe_announce_restart_ready(self) -> None:
        """Wedged at the agreed stop point: broadcast a signed readiness
        vote; a 2f+c+1 certificate of these is the restart proof the
        operator's wrapper waits for (reference ReplicaRestartReadyMsg →
        ReplicasRestartReadyProofMsg flow)."""
        point = self.control.wedge_point
        if point is None or self._restart_announced == point \
                or self.last_executed < point:
            return
        self._restart_announced = point
        msg = m.ReplicaRestartReadyMsg(
            sender_id=self.id, seq_num=point,
            reason=0, signature=b"", epoch=self.epoch)
        msg.signature = self.sig.sign(msg.signed_payload())
        self._my_restart_vote = msg
        log.info("wedged at %d: announcing restart readiness", point)
        self._broadcast(msg)
        self._on_restart_ready(msg)

    def _on_restart_ready(self, msg: m.ReplicaRestartReadyMsg) -> None:
        """Collect signed readiness votes. Votes arriving BEFORE this
        replica reaches (or even learns of) the wedge point are buffered —
        a lagging replica must still be able to complete its proof later.
        Bounded: at most 4 candidate points, highest kept."""
        votes = self._restart_votes.get(msg.seq_num)
        if votes is None:
            if len(self._restart_votes) >= 4:
                lowest = min(self._restart_votes)
                if msg.seq_num <= lowest:
                    return
                del self._restart_votes[lowest]
            votes = self._restart_votes[msg.seq_num] = set()
        if msg.sender_id in votes:
            return
        if getattr(msg, "_adm_verified", None) is None \
                and not self._verify_replica_msg(msg, seq=msg.seq_num):
            return
        votes.add(msg.sender_id)
        # super-stable n/n proof (the reference's AddRemoveWithWedge
        # semantics): EVERY replica finished executing to the stop point,
        # so a restart loses no execution anywhere
        if (self.control.wedge_point == msg.seq_num
                and len(votes) >= self.info.n
                and not self.control.restart_proof):
            log.info("restart proof complete at wedge point %d "
                     "(%d/%d votes)", msg.seq_num, len(votes), self.info.n)
            self.control.restart_proof = True

    def unwedge(self) -> None:
        """Operator unwedge: clear control state AND the restart election
        (a later re-wedge — even at the same point — starts fresh)."""
        self.control.unwedge()
        self._restart_announced = None
        self._my_restart_vote = None
        self._restart_votes.clear()

    # ------------------------------------------------------------------
    # checkpointing (ReplicaImp.cpp:2280,3274,3439)
    # ------------------------------------------------------------------
    def _send_checkpoint(self, seq: int,
                         state_digest: Optional[bytes] = None,
                         pages_digest: Optional[bytes] = None,
                         block_id: Optional[int] = None) -> None:
        """Broadcast our checkpoint for `seq`. The digests may be passed
        in by the execution lane, which snapshots them AT the run
        boundary (before the next run mutates state) — computing them
        here would race the executor. The inline path computes them now
        (nothing executes concurrently there). `block_id` is the ledger
        height the state digest binds — remembered so the thin-replica
        anchor can resolve a certified digest to a servable block."""
        if state_digest is None:
            state_digest = self.handler.state_digest()
            bc = getattr(self.handler, "blockchain", None)
            if bc is not None and block_id is None:
                block_id = bc.last_block_id   # inline path: same thread
            if self.state_transfer is not None:
                # snapshot NOW — this is the state the cert will bind
                self.state_transfer.on_checkpoint_created(seq, state_digest)
        if pages_digest is None:
            pages_digest = self.res_pages.digest()
        if block_id is not None:
            self._ckpt_blocks[state_digest] = block_id
            while len(self._ckpt_blocks) > 8:
                del self._ckpt_blocks[next(iter(self._ckpt_blocks))]
        ck = m.CheckpointMsg(sender_id=self.id, seq_num=seq,
                             state_digest=state_digest,
                             is_stable=False, epoch=self.epoch,
                             res_pages_digest=pages_digest,
                             signature=b"")
        ck.signature = self.sig.sign(ck.signed_payload())
        self._broadcast(ck)
        # read-only replicas feed on checkpoint certificates too (their
        # state-transfer trust anchors — reference: RO replicas receive
        # the same CheckpointMsg traffic)
        raw = ck.pack()
        for ro in self.info.ro_replica_ids:
            self.comm.send(ro, raw)
        self._store_checkpoint(ck)

    def _broadcast_time_opinion(self) -> None:
        if not self._running:
            return
        op = m.TimeOpinionMsg(sender_id=self.id,
                              t_ms=int(time.time() * 1000),
                              signature=b"", epoch=self.epoch)
        op.signature = self.sig.sign(op.signed_payload())
        self._broadcast(op)

    def _on_time_opinion(self, sender: int, msg: m.TimeOpinionMsg) -> None:
        # transport binding: opinions are live clock readings, never
        # relayed on another's behalf — a peer re-broadcasting someone
        # else's (validly signed, old) opinion is exactly the replay
        # vector the monotonicity check in add_opinion also closes
        if not self.cfg.time_service_enabled \
                or msg.sender_id != sender \
                or not self.info.is_replica(msg.sender_id) \
                or msg.sender_id == self.id:
            return
        if getattr(msg, "_adm_verified", None) is None \
                and not self._verify_replica_msg(msg):
            return
        self.time_service.add_opinion(msg.sender_id, msg.t_ms)

    def _on_checkpoint(self, ck: m.CheckpointMsg) -> None:
        if not self.info.is_replica(ck.sender_id):
            return
        if ck.seq_num <= self.last_stable:
            return
        # only checkpoint-window multiples are real checkpoints (honest
        # replicas checkpoint exactly there); arbitrary seq_nums would let
        # one key mint unbounded distinct slots
        if ck.seq_num % self.cfg.checkpoint_window_size != 0:
            return
        # monotone per sender: we keep each replica's HIGHEST checkpoint
        # only, so total storage is bounded at n messages — no horizon
        # needed, and a replica arbitrarily far behind still learns about
        # far-future checkpoints (its state-transfer trigger)
        if ck.seq_num < self._ck_latest_seq.get(ck.sender_id, 0):
            return
        if getattr(ck, "_adm_verified", None) is None \
                and not self._verify_replica_msg(ck, seq=ck.seq_num):
            return
        self._store_checkpoint(ck)

    def _store_checkpoint(self, ck: m.CheckpointMsg) -> None:
        # evict the sender's previous (lower) checkpoint: one live slot
        # per sender bounds memory; honest replicas only move forward
        prev = self._ck_latest_seq.get(ck.sender_id)
        if prev is not None and prev != ck.seq_num:
            old_slot = self.checkpoints.get(prev)
            if old_slot is not None:
                old_slot.pop(ck.sender_id, None)
                if not old_slot:
                    self.checkpoints.pop(prev, None)
        self._ck_latest_seq[ck.sender_id] = ck.seq_num
        slot = self.checkpoints.setdefault(ck.seq_num, {})
        slot[ck.sender_id] = ck
        if ck.sender_id == self.id:
            # retained past stability GC: AskForCheckpoint answers with
            # this (reference checkpointsLog keeps the last stable's
            # selfCheckpointMsg)
            self._self_ck_latest = ck
        matching = sum(1 for other in slot.values()
                       if other.state_digest == ck.state_digest
                       and other.res_pages_digest == ck.res_pages_digest)
        # thin-replica anchor: f+1 matching SIGNED digests — at least
        # one honest replica vouches — and we know which ledger height
        # the digest binds (our own checkpoint at that state). Publish
        # the cert set for untrusted thin-replica clients to verify.
        if matching >= self.info.st_anchor_quorum \
                and self.thin_replica is not None:
            height = self._ckpt_blocks.get(ck.state_digest)
            if height is not None:
                certs = tuple(
                    other.pack() for other in slot.values()
                    if other.state_digest == ck.state_digest
                    and other.res_pages_digest == ck.res_pages_digest)
                self._publish_trs_anchor(ck.seq_num, height, certs)
        if matching >= self.info.st_anchor_quorum \
                and ck.seq_num > self.last_executed:
            # f+1 matching signed digests = at least one honest vouches:
            # a valid trust anchor state transfer may fetch toward (ST
            # sub-messages are unauthenticated; safety comes from the
            # digest chain ending at a certificate-backed digest)
            self.certified_checkpoints[ck.seq_num] = (ck.state_digest,
                                                      ck.res_pages_digest)
            if len(self.certified_checkpoints) > 32:
                del self.certified_checkpoints[
                    min(self.certified_checkpoints)]
            if (self.state_transfer is not None
                    and ck.seq_num >= self.last_executed
                    + self.cfg.work_window_size):
                # hopelessly behind: fetch state now (BCStateTran trigger,
                # reference startCollectingState on checkpoint beyond
                # window)
                log.info("lagging by >window (ckpt %d vs executed %d): "
                         "starting state transfer", ck.seq_num,
                         self.last_executed)
                self.state_transfer.start_collecting(
                    ck.seq_num, dict(self.certified_checkpoints))
        # stability needs the full 2f+c+1 certificate (reference
        # CheckpointInfo.hpp): guarantees f+1 honest replicas hold this
        # checkpoint before we GC the window behind it
        if matching < self.info.checkpoint_quorum:
            return
        if ck.seq_num <= self.last_executed:
            self._on_seq_stable(ck.seq_num, ck.state_digest)

    def _on_seq_stable(self, seq: int,
                       state_digest: Optional[bytes] = None) -> None:
        """onSeqNumIsStable: slide the work window, GC old state."""
        if seq <= self.last_stable:
            return
        crashpoint("ckpt.stable", rid=self.id)
        log.debug("checkpoint stable at seq %d", seq)
        # checkpoint-era key expiry (reference CryptoManager per-era keys)
        self.sig.on_stable(seq)
        if self.retrans is not None:
            self.retrans.gc_stable(seq)
        for s in [s for s in self._missing_since if s <= seq]:
            del self._missing_since[s]
        if self.state_transfer is not None:
            self.state_transfer.on_checkpoint_stable(
                seq, state_digest if state_digest is not None
                else self.handler.state_digest())
        self.last_stable = seq
        self.m_last_stable.set(seq)
        self.window.advance(seq)
        for s in [s for s in self.checkpoints if s <= seq]:
            del self.checkpoints[s]
        for r in [r for r, s in self._ck_latest_seq.items() if s <= seq]:
            del self._ck_latest_seq[r]
        for s in [s for s in self.certified_checkpoints if s <= seq]:
            del self.certified_checkpoints[s]
        for key in [k for k in self.carried_certs if k[0] <= seq]:
            del self.carried_certs[key]
        for s in [s for s in self.restrictions if s <= seq]:
            del self.restrictions[s]
        # bodies are only needed while a cert references them
        live = {c.pp_digest for c in self.carried_certs.values()}
        for d in [d for d in self.vc_bodies if d not in live]:
            del self.vc_bodies[d]
        # a view entry parked on bodies for now-stable seqnums must not
        # wedge: those batches already executed cluster-wide (and peers
        # have pruned the bodies), so they need no re-proposal — drop them
        # and enter if nothing else is missing
        if self._pending_entry is not None:
            new_view, restrictions, missing = self._pending_entry
            stale = [s for s, r in restrictions.items()
                     if s <= seq and not r.resolved]
            for s in stale:
                missing.discard(restrictions[s].pp_digest)
                del restrictions[s]
            if stale and not missing:
                self._pending_entry = None
                self._enter_view(new_view, restrictions)
        with self._tran() as st:
            st.last_stable_seq = seq
            for s in [s for s in st.seq_states if s <= seq]:
                del st.seq_states[s]
            st.restrictions = [pack_restriction(r)
                               for r in self.restrictions.values()]
            st.carried_certs = [pack_cert(c)
                                for c in self.carried_certs.values()]
            st.carried_bodies = list(self.vc_bodies.values())

    # ------------------------------------------------------------------
    # view change (ReplicaImp.cpp:3771,544,2900,2978,3094 + ViewsManager)
    # ------------------------------------------------------------------
    def _verifier_for_cert_kind(self, kind: int):
        if kind in (CERT_PREPARE, CERT_COMMIT):
            return self.slow_verifier
        if kind == CERT_FAST_OPT:
            return self.opt_verifier
        if kind == CERT_FAST_THR:
            return self.thr_verifier
        return None

    def _check_view_change_timer(self) -> None:
        """Liveness watchdog: no progress while work is in flight, or a
        view change that never completes, triggers a complaint about the
        stuck view (reference viewChangeTimerMillisec → askToLeaveView)."""
        if not self._running:
            return
        now = time.monotonic()
        timeout = self.cfg.view_change_timer_ms / 1e3
        if self.in_view_change:
            if self._pending_entry is not None \
                    and now - self._vc_started_at > timeout / 4:
                # entry parked on missing bodies: re-fetch aggressively
                # (the escalation below still fires if nothing arrives)
                self._fetch_missing_bodies()
            if now - self._vc_started_at > timeout:
                self._vc_started_at = now
                # escalate AND retransmit: UDP may have dropped our
                # complaint or ViewChangeMsg; a one-shot broadcast could
                # wedge the cluster forever
                self._complain(self.pending_view or self.view, force=True)
                if self._my_vc_msg is not None \
                        and self._my_vc_msg.new_view == self.pending_view:
                    self._broadcast(self._my_vc_msg)
            return
        in_flight = any(info.pre_prepare is not None and not info.committed
                        for _, info in self.window.items())
        # forwarded-but-unexecuted client requests are work the primary owes
        # us; executed or abandoned entries are GC'd
        for key in [k for k, t in self._forwarded.items()
                    if self.clients.was_executed(k[0], k[1])
                    or now - t > 4 * timeout]:
            del self._forwarded[key]
        if in_flight or self.pending_requests or self._forwarded:
            if now - self._last_progress > timeout:
                self._complain(self.view)
        else:
            self._last_progress = now           # idle: reset the clock

    def _complain(self, view: int, reason: int = 0,
                  force: bool = False) -> None:
        """Broadcast a signed view-change complaint for `view` (complaints
        about the pending view escalate a failed view change). `force`
        retransmits an already-issued complaint."""
        first = view not in self._complained_views
        if not first and not force:
            return
        if first:
            log.warning("no progress: complaining about view %d "
                        "(primary=%d)", view, self.info.primary_of_view(view))
        self._complained_views.add(view)
        msg = m.ReplicaAsksToLeaveViewMsg(sender_id=self.id, view=view,
                                          reason=reason, signature=b"",
                                          epoch=self.epoch)
        msg.signature = self.sig.sign(msg.signed_payload())
        if first:
            self.vc.add_complaint(msg)
        self._broadcast(msg)
        if first:
            self._maybe_start_view_change()

    def _on_ask_to_leave_view(self, msg: m.ReplicaAsksToLeaveViewMsg) -> None:
        if not self.info.is_replica(msg.sender_id) or msg.view < self.view:
            return
        if getattr(msg, "_adm_verified", None) is None \
                and not self._verify_replica_msg(msg, view_scoped=True):
            return
        self.vc.add_complaint(msg)
        # adopt: quorum-minus-me complaints for a view I'm stuck in too
        self._maybe_start_view_change()

    def _maybe_start_view_change(self) -> None:
        for v in sorted(self.vc.complaints):
            if v >= self.view and self.vc.has_complaint_quorum(v):
                self._start_view_change(v + 1)

    def _start_view_change(self, target: int) -> None:
        if target <= self.view:
            return
        if self.in_view_change and self.pending_view is not None \
                and target <= self.pending_view:
            return
        # the execution lane drains BEFORE the view-change message is
        # built: last_executed must reflect every applied run, and the
        # window must not be harvested/wiped under a run in flight. A
        # stuck lane defers our participation — the view-change timer's
        # escalation path re-attempts (peers can proceed without us)
        if not self._drain_exec_lane():
            log.error("view change to %d deferred: execution lane did "
                      "not drain", target)
            return
        self.in_view_change = True
        self.pending_view = target
        self._pending_entry = None      # a parked entry for a lower view
                                        # is superseded by this change
        self._vc_started_at = time.monotonic()
        # harvest evidence: current window + evidence carried from earlier
        # views (a cert or signed report must survive cascading view
        # changes or a committed request could be lost)
        self._harvest_evidence()
        certs = sorted(self.carried_certs.values(),
                       key=lambda c: (c.seq_num, c.kind))
        vc = m.ViewChangeMsg(sender_id=self.id, new_view=target,
                             last_stable_seq=self.last_stable,
                             prepared=certs, signature=b"",
                             epoch=self.epoch)
        vc.signature = self.sig.sign(vc.signed_payload())
        self._my_vc_msg = vc
        self.vc.add_view_change(vc)
        with self._tran() as st:
            st.in_view_change = True
            st.pending_view = target
            st.carried_certs = [pack_cert(c) for c in certs]
            st.carried_bodies = list(self.vc_bodies.values())
        crashpoint("vc.persist", rid=self.id)
        self._broadcast(vc)
        self._try_complete_view_change(target)

    def _resume_view_change(self, _payload=None) -> None:
        """Crash recovery mid-view-change: in_view_change/pending_view
        were persisted (the vc.persist seam) but the change never
        completed. Rebuild the ViewChangeMsg from the persisted evidence
        and retransmit. The rebuild is deterministic over persisted state
        (carried_certs, last_stable), so peers that already hold our
        pre-crash message see an identical digest — a NewViewMsg formed
        from either copy resolves."""
        target = self.pending_view or 0
        if not self.in_view_change or target <= self.view:
            return
        if self._my_vc_msg is not None:
            return                        # already rebuilt/resumed
        self._vc_started_at = time.monotonic()
        certs = sorted(self.carried_certs.values(),
                       key=lambda c: (c.seq_num, c.kind))
        vc = m.ViewChangeMsg(sender_id=self.id, new_view=target,
                             last_stable_seq=self.last_stable,
                             prepared=certs, signature=b"",
                             epoch=self.epoch)
        vc.signature = self.sig.sign(vc.signed_payload())
        self._my_vc_msg = vc
        self.vc.add_view_change(vc)
        log.info("resuming view change to %d after restart "
                 "(%d carried certs)", target, len(certs))
        self._broadcast(vc)
        self._try_complete_view_change(target)

    def _harvest_evidence(self) -> None:
        """Merge the window's current certs/reports into carried_certs
        (keyed by (seq, is_signed_element); higher view wins); retain the
        batch bodies locally (certs are digest-only on the wire)."""
        certs, bodies = build_certificates(self.window.items(),
                                           self.last_stable,
                                           lambda pp: pp.first_path)
        self.vc_bodies.update(bodies)
        for c in certs:
            key = (c.seq_num, c.kind == CERT_SIGNED)
            cur = self.carried_certs.get(key)
            if cur is None or c.view > cur.view:
                self.carried_certs[key] = c

    def _on_view_change(self, msg: m.ViewChangeMsg) -> None:
        if not self.info.is_replica(msg.sender_id) \
                or msg.new_view <= self.view:
            return
        if getattr(msg, "_adm_verified", None) is None \
                and not self._verify_replica_msg(msg, view_scoped=True):
            return
        self.vc.add_view_change(msg)
        # f+1 replicas already moving to a higher view ⇒ join them
        # (reference computeCorrectRelevantViewNumbers)
        if self.vc.view_change_count(msg.new_view) \
                >= self.info.complaint_quorum:
            self._start_view_change(msg.new_view)
        self._try_complete_view_change(msg.new_view)

    def _try_complete_view_change(self, new_view: int) -> None:
        """New primary: form NewViewMsg once the quorum is in. Backup:
        enter once a pending NewViewMsg resolves."""
        if new_view <= self.view:
            return
        if self._pending_entry is not None \
                and self._pending_entry[0] == new_view:
            # entry already parked on body fetches: the restriction set is
            # FIXED (the primary must not re-form a different NewViewMsg
            # from late ViewChangeMsgs — backups matched the first one and
            # would diverge on the re-proposal set)
            return
        if self.info.primary_of_view(new_view) == self.id:
            if not self.vc.has_view_change_quorum(new_view):
                return
            quorum = self.vc.quorum_for_new_view(new_view)
            nv = m.NewViewMsg(
                sender_id=self.id, new_view=new_view, epoch=self.epoch,
                view_change_digests=[
                    m.ReplicaDigest(replica=vc.sender_id, digest=vc.digest())
                    for vc in quorum],
                signature=b"")
            nv.signature = self.sig.sign(nv.signed_payload())
            # rebroadcast the quorum's ViewChangeMsgs first so every backup
            # can resolve the NewView digests without a fetch round
            for vc in quorum:
                if vc.sender_id != self.id:
                    self._broadcast(vc)
            self._broadcast(nv)
            restrictions = compute_restrictions(
                quorum, self._share_digest, self._verifier_for_cert_kind,
                self.info.f + self.info.c + 1)
            self._entered_view_proof = (nv, list(quorum))
            self._resolve_and_enter(new_view, restrictions)
        else:
            nv = self.vc.pending_new_view
            if nv is None or nv.new_view != new_view:
                return
            matched = self.vc.match_new_view(nv)
            if matched is None:
                return                          # still missing VC msgs
            restrictions = compute_restrictions(
                matched, self._share_digest, self._verifier_for_cert_kind,
                self.info.f + self.info.c + 1)
            self._entered_view_proof = (nv, list(matched))
            self._resolve_and_enter(new_view, restrictions)

    # ------------------------------------------------------------------
    # restricted-batch body resolution (reference addPotentiallyMissingPP,
    # ReplicaImp.cpp:1078 — ViewChangeMsgs carry digests; bodies are
    # fetched before the view activates)
    # ------------------------------------------------------------------
    def _resolve_and_enter(self, new_view: int,
                           restrictions: Dict[int, Restriction]) -> None:
        """Fill each restriction's batch body from local evidence; if any
        is missing, park the entry and fetch (the view is entered when the
        last body arrives — reference ViewsManager obtainMissingInfo)."""
        # harvest first so our own window's PrePrepares can resolve
        self._harvest_evidence()
        missing = set()
        for r in restrictions.values():
            if r.resolved:
                continue
            body = self.vc_bodies.get(r.pp_digest)
            if body is None or not r.resolve(body):
                missing.add(r.pp_digest)
        if not missing:
            self._pending_entry = None
            self._enter_view(new_view, restrictions)
            return
        self._pending_entry = (new_view, restrictions, missing)
        log.info("view %d entry blocked on %d missing batch bodies — "
                 "fetching", new_view, len(missing))
        self._fetch_missing_bodies()

    def _fetch_missing_bodies(self) -> None:
        if self._pending_entry is None:
            return
        new_view, restrictions, missing = self._pending_entry
        by_digest = {r.pp_digest: r for r in restrictions.values()}
        for d in missing:
            r = by_digest[d]
            req = m.ReqViewPrePrepareMsg(sender_id=self.id,
                                         new_view=new_view,
                                         seq_num=r.seq_num, pp_digest=d)
            self._broadcast(req)

    def _on_req_view_pp(self, sender: int,
                        msg: m.ReqViewPrePrepareMsg) -> None:
        """Serve a peer's restricted-body fetch from harvested evidence or
        the live window. The response is the raw packed original
        PrePrepare — authenticated at the requester by digest."""
        body = self.vc_bodies.get(msg.pp_digest)
        if body is None:
            info = self.window.peek(msg.seq_num)
            if info is not None and info.pre_prepare is not None \
                    and info.pre_prepare.digest() == msg.pp_digest:
                body = info.pre_prepare.pack()
        if body is not None:
            self.comm.send(sender, body)

    def _try_resolve_body(self, pp: m.PrePrepareMsg) -> bool:
        """A PrePrepare arriving while entry is parked: if it is a body we
        are fetching, adopt it (digest check inside resolve) and enter the
        view once complete. Returns True iff consumed."""
        if self._pending_entry is None:
            return False
        new_view, restrictions, missing = self._pending_entry
        d = pp.digest()
        if d not in missing:
            return False
        r = next(x for x in restrictions.values() if x.pp_digest == d)
        if not r.resolve(pp.pack()):
            return False
        self.vc_bodies[d] = r.pre_prepare
        missing.discard(d)
        log.info("resolved restricted batch body for seq %d "
                 "(%d still missing)", r.seq_num, len(missing))
        if not missing:
            self._pending_entry = None
            self._enter_view(new_view, restrictions)
        return True

    def _on_new_view(self, msg: m.NewViewMsg) -> None:
        if msg.new_view <= self.view:
            return
        if msg.sender_id != self.info.primary_of_view(msg.new_view):
            return
        if getattr(msg, "_adm_verified", None) is None \
                and not self._verify_replica_msg(msg, view_scoped=True):
            return
        self.vc.pending_new_view = msg
        self._try_complete_view_change(msg.new_view)

    def _enter_view(self, new_view: int,
                    restrictions: Dict[int, Restriction]) -> None:
        """tryToEnterView: adopt the new view, wipe in-flight state, apply
        re-proposal restrictions; the new primary re-proposes."""
        if new_view <= self.view:
            return
        # a backup can enter a view it never complained about (NewViewMsg
        # arriving with the quorum's ViewChangeMsgs): the lane must be
        # empty before the window wipe below drops slots it references.
        # A stuck lane defers entry — peers' NewView/status retransmits
        # re-trigger it
        if not self._drain_exec_lane():
            log.error("entry into view %d deferred: execution lane did "
                      "not drain", new_view)
            return
        # evidence was harvested by _resolve_and_enter in this same view
        # change (ordering msgs are frozen, so the window cannot have
        # gained certs since) — carried_certs already holds the strongest
        # local certs before the window wipe below
        self.view = new_view
        self.in_view_change = False
        self.pending_view = None
        self._pending_entry = None
        # the forger (if any) that poisoned the optimistic plane is the
        # old view's problem; the new view starts trusting again
        self._opt_poisoned = False
        self.restrictions = restrictions
        self.m_view.set(new_view)
        log.info("entered view %d (primary=%d, %d restricted seqnums)",
                 new_view, self.primary, len(restrictions))
        if self.retrans is not None:
            # ordering messages of older views are dead letters
            self.retrans.clear_view(new_view)
        self._missing_since.clear()
        # purge complaints ABOUT the view we just entered too: complaint
        # quorums accumulated while the view change was forming must not
        # depose the fresh primary; if it really is unhealthy, complaints
        # re-accumulate via the escalation retransmit
        self.vc.gc_below(new_view + 1)
        # wipe all in-flight entries; consensus for uncommitted seqnums
        # restarts in the new view under the restrictions
        for seq, _ in list(self.window.items()):
            self.window.drop(seq)
        self.clients.clear_pending()
        self.pending_requests = []
        # reset liveness clocks: the new primary gets a full timeout before
        # anyone complains about the view we just entered
        now = time.monotonic()
        self._last_progress = now
        self._forwarded = {k: now for k in self._forwarded}
        with self._tran() as st:
            st.last_view = new_view
            st.in_view_change = False
            st.pending_view = 0
            st.seq_states.clear()
            st.restrictions = [pack_restriction(r)
                               for r in restrictions.values()]
            st.carried_certs = [pack_cert(c)
                                for c in self.carried_certs.values()]
            st.carried_bodies = list(self.vc_bodies.values())
        crashpoint("vc.enter", rid=self.id)
        if self.is_primary:
            self._repropose()

    def _repropose(self) -> None:
        """New primary: re-issue PrePrepares for every restricted seqnum
        (same batch, slow path — safest after a view change) and fill gaps
        below the highest certified seqnum with empty batches."""
        base = self.last_stable
        max_cert = max(self.restrictions, default=base)
        self.primary_next_seq = max(max_cert, self.last_executed, base) + 1
        for seq in range(base + 1, max_cert + 1):
            existing = self.window.peek(seq)
            if existing is not None and existing.pre_prepare is not None:
                # already (re)proposed before a crash — rebroadcast the
                # SAME message; a fresh timestamp would change the digest
                # and strand backups' shares on the old one
                self._broadcast(existing.pre_prepare)
                continue
            restr = self.restrictions.get(seq)
            if restr is not None:
                old = m.unpack(restr.pre_prepare)
                requests, pp_time = old.requests, old.time
            else:
                requests, pp_time = [], 0
            pp = m.PrePrepareMsg(
                sender_id=self.id, view=self.view, seq_num=seq,
                epoch=self.epoch,
                first_path=int(m.CommitPath.SLOW), time=pp_time,
                requests_digest=m.PrePrepareMsg.compute_requests_digest(
                    requests),
                requests=requests, signature=b"")
            pp.signature = self.sig.sign(pp.signed_payload())
            self._broadcast(pp)
            self._accept_pre_prepare(pp)

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------
    def _broadcast(self, msg) -> None:
        raw = msg.pack()
        for r in self.info.other_replicas(self.id):
            self.comm.send(r, raw)

    # ---- retransmission plumbing (RetransmissionsManager consumers) ----

    def _send_tracked(self, dest: int, msg) -> None:
        """Send + register for ack-tracked retransmission."""
        raw = msg.pack()
        self.comm.send(dest, raw)
        if self.retrans is not None and dest != self.id:
            self.retrans.track(dest, int(msg.CODE), msg.seq_num, self.view,
                               raw, time.monotonic())

    def _broadcast_tracked(self, msg) -> None:
        raw = msg.pack()
        now = time.monotonic()
        for r in self.info.other_replicas(self.id):
            self.comm.send(r, raw)
            if self.retrans is not None:
                self.retrans.track(r, int(msg.CODE), msg.seq_num, self.view,
                                   raw, now)

    def _ack(self, dest: int, code: int, seq: int) -> None:
        """Ack receipt of a retransmittable message (SimpleAckMsg)."""
        if self.retrans is None or dest == self.id:
            return
        self.comm.send(dest, m.SimpleAckMsg(
            sender_id=self.id, seq_num=seq, view=self.view,
            acked_msg_code=code, epoch=self.epoch).pack())

    def _tran(self):
        storage = self.storage

        class _Ctx:
            def __enter__(self_inner):
                return storage.begin_write_tran()

            def __exit__(self_inner, *exc):
                storage.end_write_tran()
                return False
        return _Ctx()

    def _restore_window(self, window_msgs: Dict[int, dict]) -> None:
        """Seed in-flight state from persisted metadata (ReplicaLoader)."""
        for seq, row in sorted(window_msgs.items()):
            if not self.window.in_window(seq):
                continue
            info = self.window.get(seq)
            pp = row.get("pre_prepare")
            if pp is not None and pp.view == self.view:
                info.pre_prepare = pp
                info.commit_path = pp.first_path
                info.received_at = time.monotonic()  # fresh fast-path clock
            pf = row.get("prepare_full")
            if pf is not None and info.pre_prepare is not None:
                info.prepare_full = pf
                info.prepared = True
            cf = row.get("commit_full")
            if cf is not None and info.pre_prepare is not None:
                info.commit_full = cf
                info.committed = True
            fcp = row.get("full_commit_proof")
            if fcp is not None and info.pre_prepare is not None:
                info.full_commit_proof = fcp
                info.committed = True
            info.slow_started = row.get("slow_started", False)
        # re-execute anything committed-but-unexecuted (recoverRequests)
        self._execute_committed()
