"""The replica: SBFT protocol state machine (slow path first).

Rebuild of the reference's ReplicaImp
(/root/reference/bftengine/src/bftengine/ReplicaImp.{hpp,cpp}): message
handlers per MsgCode (onMessage<ClientRequestMsg> :397,
onMessage<PrePrepareMsg> :1047, tryToSendPrePrepareMsg :657,
sendPreparePartial :1373, sendCommitPartial :1399,
executeNextCommittedRequests :5720), driven by the single dispatcher
thread; threshold combine/verify jobs run on the collector pool and
re-enter as internal msgs, exactly the reference's
CollectorOfThresholdSignatures round trip.

Commit flow implemented here (slow path, the PBFT-like 2-round core):
  ClientRequest → [primary] batch → PrePrepare
  → every replica sends PreparePartial (threshold share) to the collector
  → collector combines 2f+c+1 shares → PrepareFull broadcast → prepared
  → every replica sends CommitPartial → collector → CommitFull → committed
  → execute in seqnum order → ClientReply
Fast-path (PartialCommitProof/FullCommitProof) arrives in the fast-path
module; this replica already persists + window-manages for it.
"""
from __future__ import annotations

import abc
import struct
import threading
import time
from typing import Dict, List, Optional

from tpubft.comm.interfaces import ICommunication, IReceiver
from tpubft.consensus import messages as m
from tpubft.consensus.clients_manager import ClientsManager
from tpubft.consensus.collectors import (CollectorPool, CombineResult,
                                         ShareCollector)
from tpubft.consensus.controller import CommitPathController
from tpubft.consensus.incoming import Dispatcher, IncomingMsgsStorage
from tpubft.consensus.keys import ClusterKeys
from tpubft.consensus.persistent import (InMemoryPersistentStorage,
                                         PersistentStorage,
                                         restore_replica_state)
from tpubft.consensus.replicas_info import ReplicasInfo
from tpubft.consensus.seq_num_info import ActiveWindow, SeqNumInfo
from tpubft.consensus.sig_manager import SigManager
from tpubft.crypto.digest import digest as sha256
from tpubft.utils.config import ReplicaConfig
from tpubft.utils.metrics import Aggregator, Component


def share_digest(kind: str, view: int, seq_num: int, pp_digest: bytes) -> bytes:
    """Domain-separated digest each threshold share signs: 'prepare' and
    'commit' rounds must not be cross-replayable (the reference separates
    them by message type inside the signed blob)."""
    return sha256(kind.encode() + b"|" + struct.pack("<QQ", view, seq_num)
                  + pp_digest)


class IRequestsHandler(abc.ABC):
    """Execution upcall (reference IRequestsHandler.hpp / RequestHandler)."""

    @abc.abstractmethod
    def execute(self, client_id: int, req_seq: int, flags: int,
                request: bytes) -> bytes: ...

    def read(self, client_id: int, request: bytes) -> bytes:
        """Read-only query — must not mutate state."""
        return b""

    def state_digest(self) -> bytes:
        """Digest of app state for checkpoint agreement."""
        return b"\x00" * 32


class Replica(IReceiver):
    def __init__(self, cfg: ReplicaConfig, keys: ClusterKeys,
                 comm: ICommunication, handler: IRequestsHandler,
                 storage: Optional[PersistentStorage] = None,
                 aggregator: Optional[Aggregator] = None):
        cfg.validate()
        self.cfg = cfg
        self.id = cfg.replica_id
        self.info = ReplicasInfo.from_config(cfg)
        self.keys = keys
        self.comm = comm
        self.handler = handler
        self.storage = storage or InMemoryPersistentStorage()
        self.aggregator = aggregator or Aggregator()

        self.sig = SigManager(keys, self.aggregator)
        # threshold machinery per commit path (CryptoManager.hpp:109-111):
        # slow = 2f+c+1, fast-with-threshold = 3f+c+1, optimistic = n
        self.slow_signer = keys.threshold_signer(keys.slow_path_system,
                                                 self.id)
        self.slow_verifier = keys.threshold_verifier(keys.slow_path_system)
        self.thr_signer = keys.threshold_signer(keys.commit_path_system,
                                                self.id)
        self.thr_verifier = keys.threshold_verifier(keys.commit_path_system)
        self.opt_signer = keys.threshold_signer(keys.optimistic_system,
                                                self.id)
        self.opt_verifier = keys.threshold_verifier(keys.optimistic_system)
        self.controller = CommitPathController(cfg.f_val, cfg.c_val)

        # --- protocol state (dispatcher-thread only) ---
        st, window_msgs = restore_replica_state(self.storage)
        self.view = st.last_view
        self.last_executed = st.last_executed_seq
        self.last_stable = st.last_stable_seq
        self.primary_next_seq = max(st.last_executed_seq,
                                    st.last_stable_seq) + 1
        self.window: ActiveWindow[SeqNumInfo] = ActiveWindow(
            cfg.work_window_size, SeqNumInfo)
        self.window.advance(st.last_stable_seq)
        self.clients = ClientsManager(
            range(self.info.first_client_id,
                  self.info.first_client_id + self.info.num_clients))
        self.pending_requests: List[m.ClientRequestMsg] = []
        self.checkpoints: Dict[int, Dict[int, m.CheckpointMsg]] = {}

        # --- pipeline ---
        self.incoming = IncomingMsgsStorage()
        self.dispatcher = Dispatcher(self.incoming, name=f"replica-{self.id}")
        self.dispatcher.set_external_handler(self._on_external)
        self.dispatcher.register_internal("combine", self._on_combine_result)
        self.dispatcher.add_timer(cfg.batch_flush_period_ms / 1000.0,
                                  self._try_send_pre_prepare)
        self.dispatcher.add_timer(cfg.fast_path_timeout_ms / 1000.0 / 4,
                                  self._check_fast_path_timeouts)
        self.collector_pool = CollectorPool(
            lambda res: self.incoming.push_internal("combine", res))

        # --- metrics (names mirror the reference's replica component) ---
        self.metrics = Component("replica", self.aggregator)
        self.m_executed = self.metrics.register_counter("executed_requests")
        self.m_preprepares = self.metrics.register_counter("sent_preprepares")
        self.m_fast_commits = self.metrics.register_counter("fast_path_commits")
        self.m_slow_commits = self.metrics.register_counter("slow_path_commits")
        self.m_slow_starts = self.metrics.register_counter("slow_path_starts")
        self.m_view = self.metrics.register_gauge("view")
        self.m_last_executed = self.metrics.register_gauge("last_executed_seq")
        self.m_last_stable = self.metrics.register_gauge("last_stable_seq")

        self._restore_window(window_msgs)
        self._running = False

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self.comm.start(self)
        self.dispatcher.start()

    def stop(self) -> None:
        self._running = False
        self.dispatcher.stop()
        self.collector_pool.shutdown()
        self.comm.stop()

    @property
    def is_primary(self) -> bool:
        return self.info.primary_of_view(self.view) == self.id

    @property
    def primary(self) -> int:
        return self.info.primary_of_view(self.view)

    # ------------------------------------------------------------------
    # transport upcall (any thread) → queue
    # ------------------------------------------------------------------
    def on_new_message(self, sender: int, data: bytes) -> None:
        self.incoming.push_external(sender, data)

    # ------------------------------------------------------------------
    # dispatch (dispatcher thread)
    # ------------------------------------------------------------------
    def _on_external(self, sender: int, raw: bytes) -> None:
        try:
            msg = m.unpack(raw)
        except m.MsgError:
            return
        if isinstance(msg, m.ClientRequestMsg):
            # accepted from the client itself OR forwarded by a replica;
            # either way the client's own signature is verified next
            if msg.sender_id != sender and not self.info.is_replica(sender):
                return
            self._on_client_request(msg)
            return
        if getattr(msg, "sender_id", sender) != sender:
            return                              # sender spoofing: drop
        if isinstance(msg, m.PrePrepareMsg):
            self._on_pre_prepare(msg)
        elif isinstance(msg, m.PreparePartialMsg):
            self._on_share(msg, "prepare")
        elif isinstance(msg, m.PrepareFullMsg):
            self._on_prepare_full(msg)
        elif isinstance(msg, m.CommitPartialMsg):
            self._on_share(msg, "commit")
        elif isinstance(msg, m.CommitFullMsg):
            self._on_commit_full(msg)
        elif isinstance(msg, m.PartialCommitProofMsg):
            self._on_share(msg, "fast")
        elif isinstance(msg, m.FullCommitProofMsg):
            self._on_full_commit_proof(msg)
        elif isinstance(msg, m.StartSlowCommitMsg):
            self._on_start_slow_commit(msg)
        elif isinstance(msg, m.CheckpointMsg):
            self._on_checkpoint(msg)

    # ------------------------------------------------------------------
    # client requests (ReplicaImp.cpp:397)
    # ------------------------------------------------------------------
    def _on_client_request(self, req: m.ClientRequestMsg) -> None:
        client = req.sender_id
        if not self.clients.is_valid_client(client):
            return
        if not self.sig.verify(client, req.signed_payload(), req.signature):
            return
        if req.flags & m.RequestFlag.READ_ONLY:
            reply = self.handler.read(client, req.request)
            self._send_reply(client, req.req_seq_num, reply)
            return
        cached = self.clients.cached_reply(client, req.req_seq_num)
        if cached is not None:
            self.comm.send(client, cached.pack())
            return
        if not self.is_primary:
            # forward to the current primary (reference forwards or the
            # client retransmits; forwarding is cheap and speeds recovery)
            self.comm.send(self.primary, req.pack())
            return
        if not self.clients.can_become_pending(client, req.req_seq_num):
            return
        self.clients.add_pending(client, req.req_seq_num, req.cid)
        self.pending_requests.append(req)
        self._try_send_pre_prepare()

    # ------------------------------------------------------------------
    # primary: batching + PrePrepare (ReplicaImp.cpp:657,865)
    # ------------------------------------------------------------------
    def _try_send_pre_prepare(self) -> None:
        if not (self._running and self.is_primary and self.pending_requests):
            return
        seq = self.primary_next_seq
        if seq > self.last_stable + self.cfg.work_window_size:
            return                              # window full: wait for stability
        batch = self.pending_requests[:self.cfg.max_num_of_requests_in_batch]
        self.pending_requests = self.pending_requests[len(batch):]
        raw_reqs = [r.pack() for r in batch]
        pp = m.PrePrepareMsg(
            sender_id=self.id, view=self.view, seq_num=seq,
            first_path=int(self.controller.current_path),
            time=int(time.time() * 1e6),
            requests_digest=m.PrePrepareMsg.compute_requests_digest(raw_reqs),
            requests=raw_reqs, signature=b"")
        pp.signature = self.sig.sign(pp.signed_payload())
        self.primary_next_seq = seq + 1
        self.m_preprepares.inc()
        self._broadcast(pp)
        self._accept_pre_prepare(pp)            # primary processes its own

    # ------------------------------------------------------------------
    # PrePrepare (ReplicaImp.cpp:1047)
    # ------------------------------------------------------------------
    def _on_pre_prepare(self, pp: m.PrePrepareMsg) -> None:
        if pp.view != self.view or pp.sender_id != self.primary:
            return
        if not self.window.in_window(pp.seq_num) or pp.seq_num <= self.last_stable:
            return
        info = self.window.get(pp.seq_num)
        if info.pre_prepare is not None:
            return                              # already have it
        if not self.sig.verify(pp.sender_id, pp.signed_payload(), pp.signature):
            return
        # Verify every embedded client request before signing shares over
        # the batch — a byzantine primary must not be able to smuggle
        # forged client operations (reference: per-request verification
        # via RequestThreadPool, ReplicaImp.cpp onMessage<PrePrepareMsg>).
        try:
            reqs = pp.client_requests()
        except m.MsgError:
            return
        items = [(r.sender_id, r.signed_payload(), r.signature)
                 for r in reqs]
        if items and not all(self.sig.verify_batch(items)):
            return
        for r in reqs:
            if not self.clients.is_valid_client(r.sender_id):
                return
        self._accept_pre_prepare(pp)

    def _accept_pre_prepare(self, pp: m.PrePrepareMsg) -> None:
        info = self.window.get(pp.seq_num)
        info.pre_prepare = pp
        info.commit_path = pp.first_path
        info.received_at = time.monotonic()
        with self._tran() as st:
            st.seq(pp.seq_num).pre_prepare = pp.pack()
        if pp.first_path == int(m.CommitPath.SLOW):
            info.slow_started = True
            self._send_prepare_partial(info)
        else:
            self._send_partial_commit_proof(info)
        self._drain_early_shares(info)

    # ------------------------------------------------------------------
    # slow path: shares → collectors (ReplicaImp.cpp:1373,1399)
    # ------------------------------------------------------------------
    def _send_prepare_partial(self, info: SeqNumInfo) -> None:
        pp = info.pre_prepare
        d = share_digest("prepare", self.view, pp.seq_num, pp.digest())
        share = self.slow_signer.sign_share(d)
        msg = m.PreparePartialMsg(sender_id=self.id, view=self.view,
                                  seq_num=pp.seq_num, digest=d, sig=share)
        collector_id = self.info.collector_for(self.view, pp.seq_num)
        if collector_id == self.id:
            self._on_share(msg, "prepare")
        else:
            self.comm.send(collector_id, msg.pack())

    def _send_commit_partial(self, info: SeqNumInfo) -> None:
        pp = info.pre_prepare
        d = share_digest("commit", self.view, pp.seq_num, pp.digest())
        share = self.slow_signer.sign_share(d)
        msg = m.CommitPartialMsg(sender_id=self.id, view=self.view,
                                 seq_num=pp.seq_num, digest=d, sig=share)
        collector_id = self.info.collector_for(self.view, pp.seq_num)
        if collector_id == self.id:
            self._on_share(msg, "commit")
        else:
            self.comm.send(collector_id, msg.pack())

    def _fast_tools(self, path: int):
        """(signer, verifier, domain-tag) for a fast commit path."""
        if path == int(m.CommitPath.OPTIMISTIC_FAST):
            return self.opt_signer, self.opt_verifier, "fast0"
        return self.thr_signer, self.thr_verifier, "fast1"

    def _send_partial_commit_proof(self, info: SeqNumInfo) -> None:
        """Fast path share (reference sendPartialProof ReplicaImp.cpp:1319)."""
        pp = info.pre_prepare
        signer, _, tag = self._fast_tools(pp.first_path)
        d = share_digest(tag, self.view, pp.seq_num, pp.digest())
        msg = m.PartialCommitProofMsg(sender_id=self.id, view=self.view,
                                      seq_num=pp.seq_num, digest=d,
                                      sig=signer.sign_share(d),
                                      path=pp.first_path)
        collector_id = self.info.collector_for(self.view, pp.seq_num)
        if collector_id == self.id:
            self._on_share(msg, "fast")
        else:
            self.comm.send(collector_id, msg.pack())

    def _on_share(self, msg: m.PreparePartialMsg, kind: str) -> None:
        """Collector side: accumulate a threshold share
        (CollectorOfThresholdSignatures::addMsgWithPartialSignature)."""
        if msg.view != self.view or not self.info.is_replica(msg.sender_id):
            return
        if not self.window.in_window(msg.seq_num) \
                or msg.seq_num <= self.last_stable:
            return
        info = self.window.get(msg.seq_num)
        if info.pre_prepare is None:
            info.early_shares.setdefault(kind, []).append(msg)
            return
        if kind == "fast" and msg.path != info.pre_prepare.first_path:
            return                              # share for the wrong path
        collector = self._collector(info, kind)
        if collector is None or msg.digest != collector.digest:
            return                              # share over a wrong digest
        if collector.add_share(msg.sender_id, msg.sig):
            self.collector_pool.maybe_launch(collector)

    def _collector(self, info: SeqNumInfo, kind: str) -> Optional[ShareCollector]:
        pp = info.pre_prepare
        if pp is None:
            return None
        attr = f"{kind}_collector"
        col = getattr(info, attr)
        if col is None:
            if kind == "fast":
                _, verifier, tag = self._fast_tools(pp.first_path)
            else:
                verifier, tag = self.slow_verifier, kind
            d = share_digest(tag, self.view, pp.seq_num, pp.digest())
            col = ShareCollector(self.view, pp.seq_num, kind, d, verifier)
            setattr(info, attr, col)
        return col

    def _drain_early_shares(self, info: SeqNumInfo) -> None:
        for kind, msgs in list(info.early_shares.items()):
            info.early_shares[kind] = []
            for msg in msgs:
                self._on_share(msg, kind)

    # ------------------------------------------------------------------
    # combine results (internal msg; reference onInternalMsg :1517)
    # ------------------------------------------------------------------
    def _on_combine_result(self, res: CombineResult) -> None:
        if res.view != self.view or not self.window.in_window(res.seq_num):
            return
        info = self.window.peek(res.seq_num)
        if info is None or info.pre_prepare is None:
            return
        if not res.ok:
            # bad shares identified: drop them, then retry if an honest
            # quorum is still present (or when the next share arrives)
            col = getattr(info, f"{res.kind}_collector", None)
            if col is not None:
                for sid in res.bad_shares:
                    col.shares.pop(sid, None)
                self.collector_pool.maybe_launch(col)
            return
        pp = info.pre_prepare
        if res.kind == "fast":
            _, _, tag = self._fast_tools(pp.first_path)
            d = share_digest(tag, self.view, pp.seq_num, pp.digest())
            full = m.FullCommitProofMsg(sender_id=self.id, view=self.view,
                                        seq_num=res.seq_num, digest=d,
                                        sig=res.combined_sig)
            self._broadcast(full)
            self._accept_full_commit_proof(full)
            return
        d = share_digest(res.kind, self.view, pp.seq_num, pp.digest())
        if res.kind == "prepare":
            full = m.PrepareFullMsg(sender_id=self.id, view=self.view,
                                    seq_num=res.seq_num, digest=d,
                                    sig=res.combined_sig)
            self._broadcast(full)
            self._accept_prepare_full(full)
        elif res.kind == "commit":
            full = m.CommitFullMsg(sender_id=self.id, view=self.view,
                                   seq_num=res.seq_num, digest=d,
                                   sig=res.combined_sig)
            self._broadcast(full)
            self._accept_commit_full(full)

    # ------------------------------------------------------------------
    # full certificates
    # ------------------------------------------------------------------
    def _verify_full(self, msg, kind: str) -> bool:
        if msg.view != self.view or not self.window.in_window(msg.seq_num):
            return False
        info = self.window.peek(msg.seq_num)
        if info is None or info.pre_prepare is None:
            return False                        # need PP first (ReqMissing later)
        d = share_digest(kind, self.view, msg.seq_num,
                         info.pre_prepare.digest())
        if msg.digest != d:
            return False
        return self.slow_verifier.verify(d, msg.sig)

    def _on_prepare_full(self, msg: m.PrepareFullMsg) -> None:
        if self._verify_full(msg, "prepare"):
            self._accept_prepare_full(msg)

    def _accept_prepare_full(self, msg: m.PrepareFullMsg) -> None:
        info = self.window.get(msg.seq_num)
        if info.prepared:
            return
        info.prepare_full = msg
        info.prepared = True
        with self._tran() as st:
            st.seq(msg.seq_num).prepare_full = msg.pack()
        self._send_commit_partial(info)

    def _on_commit_full(self, msg: m.CommitFullMsg) -> None:
        if self._verify_full(msg, "commit"):
            self._accept_commit_full(msg)

    def _accept_commit_full(self, msg: m.CommitFullMsg) -> None:
        info = self.window.get(msg.seq_num)
        if info.committed:
            return
        info.commit_full = msg
        info.committed = True
        self.m_slow_commits.inc()
        if self.is_primary and info.pre_prepare is not None:
            if info.pre_prepare.first_path != int(m.CommitPath.SLOW):
                self.controller.on_slow_fallback(msg.seq_num)
            else:
                self.controller.on_slow_path_commit(msg.seq_num)
        with self._tran() as st:
            st.seq(msg.seq_num).commit_full = msg.pack()
        self._execute_committed()

    # ------------------------------------------------------------------
    # fast path: full proof + demotion (ReplicaImp.cpp:1468,1284)
    # ------------------------------------------------------------------
    def _on_full_commit_proof(self, msg: m.FullCommitProofMsg) -> None:
        if msg.view != self.view or not self.window.in_window(msg.seq_num):
            return
        info = self.window.peek(msg.seq_num)
        if info is None or info.pre_prepare is None:
            return
        _, verifier, tag = self._fast_tools(info.pre_prepare.first_path)
        d = share_digest(tag, self.view, msg.seq_num,
                         info.pre_prepare.digest())
        if msg.digest != d or not verifier.verify(d, msg.sig):
            return
        self._accept_full_commit_proof(msg)

    def _accept_full_commit_proof(self, msg: m.FullCommitProofMsg) -> None:
        info = self.window.get(msg.seq_num)
        if info.committed:
            return
        info.full_commit_proof = msg
        info.committed = True
        self.m_fast_commits.inc()
        if self.is_primary:
            self.controller.on_fast_path_commit(msg.seq_num)
        with self._tran() as st:
            st.seq(msg.seq_num).full_commit_proof = msg.pack()
        self._execute_committed()

    def _check_fast_path_timeouts(self) -> None:
        """Primary: demote stuck fast-path seqnums to the slow path
        (reference's controller timeout → StartSlowCommitMsg)."""
        if not self.is_primary:
            return
        now = time.monotonic()
        timeout_s = self.cfg.fast_path_timeout_ms / 1e3
        for seq, info in list(self.window.items()):
            if (info.pre_prepare is not None and not info.committed
                    and not info.slow_started
                    and info.pre_prepare.first_path != int(m.CommitPath.SLOW)
                    and now - info.received_at > timeout_s):
                ssc = m.StartSlowCommitMsg(sender_id=self.id, view=self.view,
                                           seq_num=seq)
                self._broadcast(ssc)
                self._start_slow_path(info)

    def _on_start_slow_commit(self, msg: m.StartSlowCommitMsg) -> None:
        if msg.view != self.view or msg.sender_id != self.primary:
            return
        if not self.window.in_window(msg.seq_num):
            return
        info = self.window.peek(msg.seq_num)
        if info is None or info.pre_prepare is None:
            return
        self._start_slow_path(info)

    def _start_slow_path(self, info: SeqNumInfo) -> None:
        if info.slow_started or info.committed:
            return
        info.slow_started = True
        self.m_slow_starts.inc()
        with self._tran() as st:
            st.seq(info.seq_num).slow_started = True
        self._send_prepare_partial(info)

    # ------------------------------------------------------------------
    # execution (ReplicaImp.cpp:5720,5364)
    # ------------------------------------------------------------------
    def _execute_committed(self) -> None:
        while True:
            nxt = self.last_executed + 1
            if not self.window.in_window(nxt):
                return
            info = self.window.peek(nxt)
            if info is None or not info.committed or info.executed:
                return
            for req in info.pre_prepare.client_requests():
                # at-most-once: a request seqnum already executed for this
                # client must not re-execute (replay inside a later batch)
                if req.req_seq_num <= self.clients.last_executed(req.sender_id):
                    cached = self.clients.cached_reply(req.sender_id,
                                                       req.req_seq_num)
                    if cached is not None:
                        self.comm.send(req.sender_id, cached.pack())
                    continue
                reply = self.handler.execute(req.sender_id, req.req_seq_num,
                                             req.flags, req.request)
                self.m_executed.inc()
                self._send_reply(req.sender_id, req.req_seq_num, reply)
            info.executed = True
            self.last_executed = nxt
            self.m_last_executed.set(nxt)
            with self._tran() as st:
                st.last_executed_seq = nxt
            if nxt % self.cfg.checkpoint_window_size == 0:
                self._send_checkpoint(nxt)

    def _send_reply(self, client: int, req_seq: int, payload: bytes) -> None:
        reply = m.ClientReplyMsg(sender_id=self.id, req_seq_num=req_seq,
                                 current_primary=self.primary, reply=payload,
                                 replica_specific_info=b"")
        self.clients.on_request_executed(client, req_seq, reply)
        self.comm.send(client, reply.pack())

    # ------------------------------------------------------------------
    # checkpointing (ReplicaImp.cpp:2280,3274,3439)
    # ------------------------------------------------------------------
    def _send_checkpoint(self, seq: int) -> None:
        ck = m.CheckpointMsg(sender_id=self.id, seq_num=seq,
                             state_digest=self.handler.state_digest(),
                             is_stable=False, signature=b"")
        ck.signature = self.sig.sign(ck.signed_payload())
        self._broadcast(ck)
        self._store_checkpoint(ck)

    def _on_checkpoint(self, ck: m.CheckpointMsg) -> None:
        if not self.info.is_replica(ck.sender_id):
            return
        if ck.seq_num <= self.last_stable:
            return
        if not self.sig.verify(ck.sender_id, ck.signed_payload(),
                               ck.signature):
            return
        self._store_checkpoint(ck)

    def _store_checkpoint(self, ck: m.CheckpointMsg) -> None:
        slot = self.checkpoints.setdefault(ck.seq_num, {})
        slot[ck.sender_id] = ck
        matching = sum(1 for other in slot.values()
                       if other.state_digest == ck.state_digest)
        if matching >= self.info.checkpoint_quorum \
                and ck.seq_num <= self.last_executed:
            self._on_seq_stable(ck.seq_num)

    def _on_seq_stable(self, seq: int) -> None:
        """onSeqNumIsStable: slide the work window, GC old state."""
        if seq <= self.last_stable:
            return
        self.last_stable = seq
        self.m_last_stable.set(seq)
        self.window.advance(seq)
        for s in [s for s in self.checkpoints if s <= seq]:
            del self.checkpoints[s]
        with self._tran() as st:
            st.last_stable_seq = seq
            for s in [s for s in st.seq_states if s <= seq]:
                del st.seq_states[s]

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------
    def _broadcast(self, msg) -> None:
        raw = msg.pack()
        for r in self.info.other_replicas(self.id):
            self.comm.send(r, raw)

    def _tran(self):
        storage = self.storage

        class _Ctx:
            def __enter__(self_inner):
                return storage.begin_write_tran()

            def __exit__(self_inner, *exc):
                storage.end_write_tran()
                return False
        return _Ctx()

    def _restore_window(self, window_msgs: Dict[int, dict]) -> None:
        """Seed in-flight state from persisted metadata (ReplicaLoader)."""
        for seq, row in sorted(window_msgs.items()):
            if not self.window.in_window(seq):
                continue
            info = self.window.get(seq)
            pp = row.get("pre_prepare")
            if pp is not None and pp.view == self.view:
                info.pre_prepare = pp
                info.commit_path = pp.first_path
                info.received_at = time.monotonic()  # fresh fast-path clock
            pf = row.get("prepare_full")
            if pf is not None and info.pre_prepare is not None:
                info.prepare_full = pf
                info.prepared = True
            cf = row.get("commit_full")
            if cf is not None and info.pre_prepare is not None:
                info.commit_full = cf
                info.committed = True
            fcp = row.get("full_commit_proof")
            if fcp is not None and info.pre_prepare is not None:
                info.full_commit_proof = fcp
                info.committed = True
            info.slow_started = row.get("slow_started", False)
        # re-execute anything committed-but-unexecuted (recoverRequests)
        self._execute_committed()
