"""Admission plane: off-dispatcher parse + verify between the
transports and the consensus dispatcher.

The single dispatcher thread used to pay `m.unpack()` plus per-message
signature checks for every datagram (the reference keeps this loop lean
in C++ — IncomingMsgsStorageImp.hpp:32 pops pre-allocated message
objects; verification rides RequestThreadPool). Here a small pool of
admission workers does all *stateless* per-message work:

  1. header peek — msg code / view / seq from the fixed wire prefix,
     dropping garbage, dead-view/stale-seq traffic and within-drain
     duplicates before paying a full unpack;
  2. full parse (`m.unpack`), plus stateless gates the dispatcher would
     apply anyway (dead-era epoch, sender spoofing vs the transport
     sender, client-principal topology checks);
  3. signature verification for every SigManager-signed message type
     (ClientRequest / ClientBatch elements / PrePrepare incl. its
     embedded client requests / Checkpoint / TimeOpinion / the
     view-change family / RestartReady), coalesced into ONE
     `SigManager.verify_batch` call per drain cycle — one device
     dispatch behind `ops.dispatch.device_dispatch` on the TPU backend.
     Threshold SHARES carry no SigManager signature (they are verified
     at combine time by the collector plane), so they pass through
     parse-only.

Survivors enter the dispatcher's external queue as `AdmittedMsg`
objects with the verdict attached (`msg._adm_verified`); handlers
consult the verdict instead of re-verifying and re-check only the
cheap *stateful* gates (current epoch/view/window, spoofing, client
state) that admission cannot freeze. A forged signature poisons only
the guilty message, never its drain batch. One deliberate asymmetry:
a verify-failed PrePrepare is admitted WITH its failed verdict
(`_adm_verified = False`) instead of dropped — a view-change entry
parked on missing restriction bodies consumes fetched old-view
PrePrepares authenticated by digest alone (replica._try_resolve_body),
including bodies signed under since-rotated keys; the handler rejects
the failed verdict for live proposals.

Gated by `ReplicaConfig.admission_workers` (0 = legacy inline path:
raw bytes to the dispatcher, parse/verify in the handlers).

Overload backpressure: ingest classifies each datagram by its 2-byte
code peek. Protocol-critical traffic (view-change family, checkpoints,
state transfer, restart votes — `_CRITICAL_CODES`) rides a dedicated
priority queue with its own headroom that workers drain FIRST and that
watermark shedding never touches. Everything else shares the main
buffer: when its depth crosses `admission_high_watermark` the plane
enters shed mode and drops fresh client datagrams at ingest (counted
in `adm_shed_overload`, one counter per shed) until depth falls to
`admission_low_watermark`. Blind tail-drop at the hard bound still
exists (`adm_dropped_ingress`) but watermark shedding fires first, so
an overloaded replica degrades by shedding client goodput — never its
liveness machinery.
"""
from __future__ import annotations

import struct
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from tpubft.consensus import messages as m
from tpubft.consensus.incoming import MAX_EXTERNAL_PENDING
from tpubft.utils import flight
from tpubft.utils.logging import get_logger
from tpubft.utils.metrics import Aggregator, Component

log = get_logger("admission")


@dataclass
class AdmittedMsg:
    """A pre-parsed, pre-verified external message, ready for the
    dispatcher. `msg` carries `_adm_verified = True` when the admission
    plane checked a SigManager signature for its type (absent when the
    type has none to check; False for the PrePrepare digest-fetch
    passage), and `_adm_inners` with the surviving parsed elements for
    ClientBatchRequestMsg. The raw datagram is deliberately NOT carried:
    under backpressure the external queue holds up to 20k entries, and
    pinning every admitted datagram's bytes next to its parsed form
    would retain ~max_message_size per entry for nothing (no dispatcher
    consumer reads it; the batch relay re-packs)."""
    sender: int
    msg: object


# fixed wire prefix offsets (messages.py SPECs; serialize.py packs
# fixed-width ints little-endian back-to-back):
#   u16 code | u32 sender_id | ...
_CODE = struct.Struct("<H")
# codes whose prefix continues | u64 view @6 | u64 seq @14 | and whose
# handlers only ever accept current-view, in-window traffic.
# PrePrepare is deliberately NOT here despite sharing the layout: an
# old-view (or just-stabilized) PrePrepare body is exactly what a
# view-change entry parked on missing restriction bodies is fetching
# (replica._try_resolve_body / _on_req_view_pp) — peek-dropping it
# would stall view entry forever. Old-view PrePrepares pay full
# parse+verify off-dispatcher and are then judged by the dispatcher's
# stateful gates, like any relay-safe message.
_VIEW_SEQ_CODES = frozenset(int(c) for c in (
    m.MsgCode.StartSlowCommit,
    m.MsgCode.PreparePartial, m.MsgCode.PrepareFull,
    m.MsgCode.CommitPartial, m.MsgCode.CommitFull,
    m.MsgCode.PartialCommitProof, m.MsgCode.FullCommitProof,
    m.MsgCode.AggregateShare))
_VIEW_SEQ = struct.Struct("<QQ")        # at offset 6
# Checkpoint: | u64 seq @6 |
_CKPT_CODE = int(m.MsgCode.Checkpoint)
_SEQ = struct.Struct("<Q")              # at offset 6
# view-change family: | u64 view-or-new_view @6 |. Handlers drop
# view < current (complaints) / new_view <= current (VC, NewView)
# pre-verify; fronting the same monotone gates here keeps dead-view
# floods from buying signature work in the drain batch.
_COMPLAINT_CODE = int(m.MsgCode.ReplicaAsksToLeaveView)
_VC_CODES = frozenset((int(m.MsgCode.ViewChange), int(m.MsgCode.NewView)))

# ---- overload backpressure classes (ingest-time, code peek only) ----
# protocol-critical traffic rides a dedicated priority queue that
# watermark shedding never touches and workers drain first: view-change
# family (liveness), checkpoints (stability/GC), state transfer
# (recovery), restart votes/proofs (operator control). An overloaded
# replica sheds client goodput, never its ability to stay in the
# protocol.
_CRITICAL_CODES = frozenset(int(c) for c in (
    m.MsgCode.ReplicaAsksToLeaveView, m.MsgCode.ViewChange,
    m.MsgCode.NewView, m.MsgCode.Checkpoint, m.MsgCode.AskForCheckpoint,
    m.MsgCode.StateTransfer, m.MsgCode.ReplicaRestartReady,
    m.MsgCode.RestartProof))
# fresh client load — the sheddable class under overload
_CLIENT_CODES = frozenset((int(m.MsgCode.ClientRequest),
                           int(m.MsgCode.ClientBatchRequest)))
# client principal for shard routing: u32 sender_id at wire offset 2
# (the same fixed prefix every peek uses)
_SENDER = struct.Struct("<I")


def shard_of(sender_id: int, shards: int) -> int:
    """Stable shard for a client principal: Knuth multiplicative hash of
    the wire sender_id. Deterministic across drains/restarts (the whole
    point — each worker's SigManager verify batches, memo and comb
    caches see a disjoint, STABLE slice of the key population, so
    per-principal key material stays hot per shard instead of being
    diluted across every worker), and mixing keeps adjacent principal
    ids from landing in lockstep with any client-side id striping."""
    return ((sender_id * 2654435761) & 0xFFFFFFFF) % shards


class AdmissionPipeline:
    """Bounded ingest queue + worker pool. Thread-safe producers
    (transport receive threads) call `submit`/`submit_burst`; workers
    drain bursts and hand `AdmittedMsg`s to `sink` (the dispatcher's
    external queue) in drain order."""

    def __init__(self, sig, info, sink: Callable[[AdmittedMsg], bool],
                 epoch_fn: Callable[[], int],
                 view_fn: Callable[[], int],
                 stable_fn: Callable[[], int],
                 workers: int = 1, drain_max: int = 256,
                 max_pending: int = MAX_EXTERNAL_PENDING,
                 aggregator: Optional[Aggregator] = None,
                 name: str = "admission", ckpt_window: int = 0,
                 high_watermark: int = 0, low_watermark: int = 0,
                 beat_fn: Optional[Callable[[], None]] = None,
                 rid: int = -1, shard_by_key: bool = True):
        self._sig = sig
        self._info = info
        self._sink = sink
        # replica id for flight-recorder attribution (multi-replica
        # processes: the in-process test cluster)
        self._rid = rid
        self._epoch_fn = epoch_fn
        self._view_fn = view_fn
        self._stable_fn = stable_fn
        self._drain_max = max(1, drain_max)
        self._n_workers = max(1, workers)
        self._name = name
        # checkpoint-window size for the peek-stage multiple check
        # (0 = disabled; the dispatcher gate still applies)
        self._ckpt_window = ckpt_window
        # ingest buffer: deque + Condition instead of queue.Queue so a
        # whole transport burst (the recvmmsg drain) enters under ONE
        # lock round (extend + one wake), not a lock cycle per datagram
        self._buf: "deque[Tuple[int, bytes]]" = deque()
        # key-sharded client routing (million-principal client plane):
        # with >1 workers, CLIENT datagrams route to a per-worker shard
        # buffer by a stable hash of the wire principal, so each
        # worker's verify batches / memo / comb caches see a disjoint,
        # stable key population. Critical + other traffic stays on the
        # shared queues (any worker drains it — liveness machinery must
        # never wait behind one shard's backlog). Empty list = routing
        # off (single worker, or shard_by_key=False for the A/B).
        self._shards: List["deque[Tuple[int, bytes]]"] = (
            [deque() for _ in range(self._n_workers)]
            if shard_by_key and self._n_workers > 1 else [])
        # protocol-critical priority queue (see _CRITICAL_CODES): its
        # own headroom up to max_pending — a client flood filling _buf
        # can never push a view-change or checkpoint out
        self._crit: "deque[Tuple[int, bytes]]" = deque()
        self._max_pending = max_pending
        # overload watermarks (0 = shedding disabled): depth >= high
        # enters shed mode (fresh client datagrams dropped at ingest,
        # each counted in adm_shed_overload), depth <= low leaves it.
        # Both clamp under max_pending so a small hard bound degrades
        # the hysteresis gap instead of inverting it (low above high
        # would flap shed mode on every other datagram).
        self._high = min(high_watermark, max_pending) if high_watermark \
            else 0
        self._low = min(low_watermark, self._high - 1) if self._high \
            else low_watermark
        self._shedding = False
        self._beat = beat_fn          # health-plane liveness hook
        # per-worker liveness stamps (re-seeded in start()); the probe
        # beat tracks the OLDEST stamp so one wedged worker is visible
        self._worker_beats: List[float] = [time.monotonic()] \
            * self._n_workers
        # ingest handoff Condition: CheckedLock-backed under
        # TPUBFT_THREADCHECK (racecheck.make_condition) so the
        # transport->worker handoff feeds the runtime lock-order
        # graph like every make_lock site
        from tpubft.utils.racecheck import make_condition, make_lock
        self._cv = make_condition(f"{name}.cv")
        self._threads: List[threading.Thread] = []
        self._running = False
        self._processed = 0
        # client-principal topology is static: capture it once so the
        # worker-side gates never touch replica state. Production
        # topologies hand us a contiguous `range` (O(1) membership, O(1)
        # memory at 1M principals); anything else is frozen to a set.
        ids = info.all_client_ids()
        self._clients = ids if isinstance(ids, range) else frozenset(ids)
        # instrumented under TPUBFT_THREADCHECK: admission worker ⇄
        # dispatcher lock ordering rides the global order graph
        self._stats_mu = make_lock(f"{name}.stats")

        self.metrics = Component("admission", aggregator)
        # ingest backpressure drops (queue full at the transport edge)
        self.adm_dropped_ingress = self.metrics.register_counter(
            "adm_dropped_ingress")
        # header-peek / parse-stage drops: garbage, unknown code,
        # dead-view / stale-seq prefix, within-drain duplicates,
        # unparseable bytes
        self.adm_drops_pre_parse = self.metrics.register_counter(
            "adm_drops_pre_parse")
        # post-parse stateless-gate drops: dead-era epoch, sender
        # spoofing, client-topology violations
        self.adm_drops_stateless = self.metrics.register_counter(
            "adm_drops_stateless")
        # signatures verified through the per-drain coalesced batch
        self.adm_batched_verifies = self.metrics.register_counter(
            "adm_batched_verifies")
        # messages dropped for a failed signature (the guilty message
        # only — the rest of its drain batch is unaffected)
        self.adm_verify_fail = self.metrics.register_counter(
            "adm_verify_fail")
        self.adm_queue_depth = self.metrics.register_gauge(
            "adm_queue_depth")
        # client datagrams shed at ingest while in overload shed mode —
        # with adm_dropped_ingress (hard bound) these are the only two
        # ingest-time dispositions besides admission to the buffer, so
        # submitted == buffered + shed + dropped_ingress always holds
        self.adm_shed_overload = self.metrics.register_counter(
            "adm_shed_overload")
        self.adm_shedding = self.metrics.register_gauge("adm_shedding")
        self.adm_drains = self.metrics.register_counter("adm_drains")
        # messages handed to the dispatcher queue; admitted + the four
        # drop counters above account for every ingested message, which
        # benches/tests use as a drain marker
        self.adm_admitted = self.metrics.register_counter("adm_admitted")

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> None:
        if self._running:
            return
        self._running = True
        now = time.monotonic()
        self._worker_beats = [now] * self._n_workers
        for i in range(self._n_workers):
            t = threading.Thread(target=self._run, args=(i,), daemon=True,
                                 name=f"{self._name}-{i}")
            self._threads.append(t)
            t.start()

    def stop(self) -> None:
        self._running = False
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []

    # ------------------------------------------------------------------
    # ingest (transport threads)
    # ------------------------------------------------------------------
    def _class_of(self, raw: bytes) -> Tuple[str, int]:
        """Ingest class from the 2-byte code peek: 'crit' (protected
        priority queue), 'client' (sheddable under overload), 'other'
        (consensus shares etc. — bounded but never watermark-shed).
        Second element is the client's shard route (worker index) when
        key-sharded routing is on, else -1 (shared buffer)."""
        if len(raw) >= 2:
            (code,) = _CODE.unpack_from(raw)
            if code in _CRITICAL_CODES:
                return "crit", -1
            if code in _CLIENT_CODES:
                if self._shards and len(raw) >= 6:
                    (principal,) = _SENDER.unpack_from(raw, 2)
                    return "client", shard_of(principal, self._n_workers)
                return "client", -1
        return "other", -1

    def _client_depth(self) -> int:
        """Queued client+other datagrams (caller holds self._cv)."""
        return len(self._buf) + sum(map(len, self._shards))

    def _ingest_locked(self, sender: int, raw: bytes,
                       cls: Tuple[str, int]) -> str:
        """One datagram's ingest disposition under self._cv (`cls`
        precomputed by the caller OUTSIDE the lock — classification is
        stateless and must not extend the critical section):
        'ok' (buffered), 'shed' (overload watermark), 'full' (hard
        bound). Exactly one counter fires per disposition — the
        accounting invariant tests and benches rely on. Watermarks and
        the hard bound are computed over the TOTAL queued depth, so the
        sharded router keeps byte-identical shed/drop accounting with
        the shared-buffer path."""
        kind, route = cls
        if kind == "crit":
            if len(self._crit) >= self._max_pending:
                return "full"
            self._crit.append((sender, raw))
            return "ok"
        depth = self._client_depth() + len(self._crit)
        if self._high:
            if not self._shedding and depth >= self._high:
                self._shedding = True
                self.adm_shedding.set(1)
            elif self._shedding and depth <= self._low:
                self._shedding = False
                self.adm_shedding.set(0)
        if self._shedding and kind == "client":
            return "shed"
        if self._client_depth() >= self._max_pending:
            return "full"
        if route >= 0:
            self._shards[route].append((sender, raw))
        else:
            self._buf.append((sender, raw))
        return "ok"

    def set_watermarks(self, high_watermark: int,
                       low_watermark: int) -> None:
        """Autotuner actuator: retune the overload watermarks live.
        Same clamping as construction (both bounded by max_pending, low
        strictly under high so the hysteresis gap never inverts); a
        shed mode now outside the new band clears on the next ingest's
        watermark pass."""
        with self._cv:
            self._high = min(high_watermark, self._max_pending) \
                if high_watermark else 0
            self._low = min(low_watermark, self._high - 1) if self._high \
                else low_watermark
            if not self._high and self._shedding:
                # shedding disabled mid-episode: nothing will ever
                # cross the (gone) low watermark to clear the flag
                self._shedding = False
                self.adm_shedding.set(0)

    @property
    def high_watermark(self) -> int:
        return self._high

    def submit(self, sender: int, raw: bytes) -> bool:
        flight.record(flight.EV_ADM_INGEST, arg=1)
        cls = self._class_of(raw)
        with self._cv:
            d = self._ingest_locked(sender, raw, cls)
            if d == "ok":
                if self._shards:
                    # one shared Condition across sharded workers: a
                    # single notify could land on a worker whose shard
                    # stayed empty while the routed worker sleeps out
                    # its 0.1s wait — wake everyone, the non-owners
                    # re-sleep immediately
                    self._cv.notify_all()
                else:
                    self._cv.notify()
        if d == "full":
            self.adm_dropped_ingress.inc()
        elif d == "shed":
            self.adm_shed_overload.inc()
        return d == "ok"

    def submit_burst(self, msgs: Iterable[Tuple[int, bytes]]) -> None:
        """Whole-burst ingest: one Condition acquire for the burst, one
        wake (all workers when the burst spans several drains) — the
        handoff half of the recvmmsg amortization."""
        # classify OUTSIDE the lock: the whole burst's unpack_from peeks
        # happen before workers are blocked on _cv, preserving the
        # one-lock-round handoff recvmmsg bought
        classed = [(sender, raw, self._class_of(raw))
                   for sender, raw in msgs]
        flight.record(flight.EV_ADM_INGEST, arg=len(classed))
        taken = shed = full = 0
        with self._cv:
            for sender, raw, cls in classed:
                d = self._ingest_locked(sender, raw, cls)
                if d == "ok":
                    taken += 1
                elif d == "shed":
                    shed += 1
                else:
                    full += 1
            if taken:
                if self._shards or taken > self._drain_max:
                    self._cv.notify_all()
                else:
                    self._cv.notify()
        if full:
            self.adm_dropped_ingress.inc(full)
        if shed:
            self.adm_shed_overload.inc(shed)

    @property
    def depth(self) -> int:
        # racy read is fine for a gauge
        return (len(self._buf) + len(self._crit)
                + sum(map(len, self._shards)))

    @property
    def shedding(self) -> bool:
        """Overload shed mode (degraded-state input to the health
        plane)."""
        return self._shedding

    @property
    def processed(self) -> int:
        """Messages fully through the plane (admitted or dropped) —
        `processed == submitted-minus-ingress-drops` is the benches' and
        tests' drain marker."""
        return self._processed

    # ------------------------------------------------------------------
    # worker loop
    # ------------------------------------------------------------------
    def _next_batch(self, idx: int = 0) -> List[Tuple[int, bytes]]:
        with self._cv:
            mine = self._shards[idx] if self._shards else None
            if not self._buf and not self._crit \
                    and not (mine and len(mine)):
                self._cv.wait(0.1)
            out: List[Tuple[int, bytes]] = []
            # protocol-critical first: under overload the liveness
            # machinery is parsed/verified ahead of queued client load
            while self._crit and len(out) < self._drain_max:
                out.append(self._crit.popleft())
            # own shard next (key-sharded routing: this worker's stable
            # slice of the client principal population), then the shared
            # buffer — so non-client traffic and unrouted clients never
            # starve behind one shard's backlog
            if mine is not None:
                while mine and len(out) < self._drain_max:
                    out.append(mine.popleft())
            while self._buf and len(out) < self._drain_max:
                out.append(self._buf.popleft())
            if self._shedding \
                    and self._client_depth() + len(self._crit) \
                    <= self._low:
                self._shedding = False
                self.adm_shedding.set(0)
            return out

    def _stamp_beat(self, idx: int) -> None:
        """Per-worker liveness stamp; the external health beat fires
        only when the STALEST worker's stamp advances. One wedged
        worker (and the drained batch it holds) therefore freezes the
        probe age even while sibling workers keep looping — with a
        shared beat, any surviving worker would mask the stall."""
        if self._beat is None:
            return
        now = time.monotonic()
        with self._cv:
            beats = self._worker_beats
            was_oldest = beats[idx] <= min(beats)
            beats[idx] = now
        if was_oldest:
            try:
                self._beat()
            except Exception:  # noqa: BLE001 — the health hook must not
                pass           # kill a worker

    def _run(self, idx: int = 0) -> None:
        flight.set_thread_rid(self._rid)
        while self._running:
            self._stamp_beat(idx)     # health probe: a worker wedged
            # inside _drain stops stamping; once it is the stalest, the
            # probe age grows while depth does — that IS the stall
            batch = self._next_batch(idx)
            if not batch:
                continue
            try:
                self._drain(batch)
            except Exception:  # noqa: BLE001 — a bad drain must not kill
                log.exception("admission drain raised (%d msgs dropped)",
                              len(batch))
                with self._stats_mu:
                    self._processed += len(batch)

    # ------------------------------------------------------------------
    # one drain cycle
    # ------------------------------------------------------------------
    def _peek_ok(self, raw: bytes, view: int, stable: int) -> bool:
        """Fixed-prefix drop decisions that need no parse. Conservative
        by construction: `view`/`stable` only ever advance, so a stale
        read under-drops and the dispatcher's stateful gates still
        apply; nothing a current-state dispatcher would accept is
        dropped here."""
        if len(raw) < 2:
            return False
        (code,) = _CODE.unpack_from(raw)
        if not m.known_code(code):
            return False
        if code in _VIEW_SEQ_CODES:
            if len(raw) < 22:
                return False                    # shorter than its prefix
            mview, mseq = _VIEW_SEQ.unpack_from(raw, 6)
            if mview < view or mseq <= stable:
                return False                    # dead view / GC'd seqnum
        elif code == _CKPT_CODE:
            if len(raw) < 14:
                return False
            (mseq,) = _SEQ.unpack_from(raw, 6)
            if mseq <= stable:
                return False
            # only checkpoint-window multiples are real checkpoints
            # (config-static; the handler applies the same rule
            # pre-verify) — a garbage-seq flood must not buy verifies
            if self._ckpt_window and mseq % self._ckpt_window:
                return False
        elif code == _COMPLAINT_CODE:
            if len(raw) < 14:
                return False
            (mview,) = _SEQ.unpack_from(raw, 6)
            if mview < view:
                return False                    # complaint about a dead view
        elif code in _VC_CODES:
            if len(raw) < 14:
                return False
            (mview,) = _SEQ.unpack_from(raw, 6)
            if mview <= view:
                return False                    # new_view already entered
        return True

    def _stateless_ok(self, sender: int, msg, epoch: int) -> bool:
        """Post-parse gates that depend only on the message, the
        transport sender, and monotone replica state. The dispatcher
        re-checks the stateful versions (current epoch/view, client
        state) — admission cannot freeze those."""
        # dead-era drop: strictly-lower epochs only (epoch is monotone,
        # so a stale read under-drops; higher-epoch traffic passes —
        # the dispatcher keeps the higher-epoch Checkpoint exception)
        msg_epoch = getattr(msg, "epoch", None)
        if msg_epoch is not None and msg_epoch < epoch:
            return False
        if isinstance(msg, (m.ClientRequestMsg, m.ClientBatchRequestMsg)):
            # accepted from the client itself OR forwarded by a replica
            if msg.sender_id != sender and not self._info.is_replica(sender):
                return False
            if msg.sender_id not in self._clients:
                return False
            if isinstance(msg, m.ClientRequestMsg):
                return self._client_req_ok(msg)
            return True
        if not isinstance(msg, m.RELAY_SAFE) \
                and getattr(msg, "sender_id", sender) != sender:
            return False                        # sender spoofing
        return True

    def _client_req_ok(self, req: m.ClientRequestMsg) -> bool:
        """Topology-static request gates, THE SAME predicate the
        dispatcher applies (messages.client_request_admissible) — forged
        floods never reach the verify batch, and the two paths can never
        disagree about what is admissible."""
        return m.client_request_admissible(req, self._info)

    def _collect_jobs(self, msg, jobs: List[tuple]) -> Optional[List[int]]:
        """Append this message's signature-verification items to `jobs`
        as (principal, data, sig, seq, view_scoped); returns the list of
        job indices backing the message's verdict, or None when the type
        carries nothing for SigManager (shares, status, acks, ST, …)."""
        idxs: List[int] = []

        def add(principal, data, sig, seq=None, view_scoped=False):
            idxs.append(len(jobs))
            jobs.append((principal, data, sig, seq, view_scoped))

        REPLICA_SIGNED = (m.PrePrepareMsg, m.CheckpointMsg,
                          m.TimeOpinionMsg, m.ReplicaAsksToLeaveViewMsg,
                          m.ViewChangeMsg, m.NewViewMsg,
                          m.ReplicaRestartReadyMsg)
        if isinstance(msg, REPLICA_SIGNED) \
                and not self._info.is_replica(msg.sender_id):
            # junk principals must not buy signature work (the handlers'
            # is_replica gates, fronted); NOT applied to pass-through
            # types — StateTransfer/AskForCheckpoint legitimately come
            # from read-only replicas
            return []
        if isinstance(msg, m.ClientRequestMsg):
            add(msg.sender_id, msg.signed_payload(), msg.signature)
        elif isinstance(msg, m.PrePrepareMsg):
            add(msg.sender_id, msg.signed_payload(), msg.signature,
                seq=msg.seq_num)
            # embedded client requests: parsed once here (memoized on the
            # message), verified in the same coalesced batch — a
            # byzantine primary's forged element fails the whole proposal
            # exactly as the dispatcher's batch check would
            for r in msg.client_requests():
                if not r.flags & m.RequestFlag.HAS_PRE_PROCESSED:
                    add(r.sender_id, r.signed_payload(), r.signature,
                        seq=msg.seq_num)
        elif isinstance(msg, m.CheckpointMsg):
            add(msg.sender_id, msg.signed_payload(), msg.signature,
                seq=msg.seq_num)
        elif isinstance(msg, m.TimeOpinionMsg):
            add(msg.sender_id, msg.signed_payload(), msg.signature)
        elif isinstance(msg, (m.ReplicaAsksToLeaveViewMsg, m.ViewChangeMsg,
                              m.NewViewMsg)):
            add(msg.sender_id, msg.signed_payload(), msg.signature,
                view_scoped=True)
        elif isinstance(msg, m.ReplicaRestartReadyMsg):
            add(msg.sender_id, msg.signed_payload(), msg.signature,
                seq=msg.seq_num)
        else:
            return None
        return idxs

    def _verify_jobs(self, jobs: List[tuple]) -> List[bool]:
        """ONE coalesced SigManager.verify_batch for the whole drain —
        at most one device dispatch per scheme on the TPU backend, taken
        behind the process-wide `ops.dispatch.device_dispatch` gate
        INSIDE the kernel (ops/ed25519.py, ops/ecdsa.py), so the gate is
        held exactly for the device call and never across the memo pass
        or a scalar-fallback residue. Items that fail under the current
        key and carry protocol context retry in small per-context groups
        so the post-rotation grace path stays correct."""
        if not jobs:
            return []
        flat = [(p, d, s) for p, d, s, _, _ in jobs]
        verdicts = self._sig.verify_batch(flat)
        self.adm_batched_verifies.inc(len(flat))
        retries: Dict[Tuple, List[int]] = {}
        for i, ok in enumerate(verdicts):
            _, _, _, seq, vs = jobs[i]
            if not ok and (seq is not None or vs):
                retries.setdefault((seq, vs), []).append(i)
        for (seq, vs), idxs in retries.items():
            sub = self._sig.verify_batch([flat[i] for i in idxs],
                                         seq=seq, view_scoped=vs)
            for i, ok in zip(idxs, sub):
                verdicts[i] = ok
        return verdicts

    def _drain(self, batch: List[Tuple[int, bytes]]) -> None:
        from tpubft.utils.tracing import get_tracer
        flight.record(flight.EV_ADM_DRAIN, arg=len(batch))
        view, stable, epoch = (self._view_fn(), self._stable_fn(),
                               self._epoch_fn())
        with get_tracer().start_span("adm_drain") as span:
            pre_drops = stateless_drops = verify_fails = 0
            seen: set = set()
            parsed: List[Tuple[int, bytes, object]] = []
            for sender, raw in batch:
                # per-message isolation: ANY failure (not just the
                # anticipated MsgError) poisons only this message, never
                # its drain batch — the documented guarantee holds for
                # exception-class poisoning too
                try:
                    if not self._peek_ok(raw, view, stable):
                        pre_drops += 1
                        continue
                    key = (sender, raw)
                    if key in seen:
                        # within-drain duplicate (flood retransmit
                        # burst): collapse — a real retransmission
                        # arrives in a later drain and still earns its
                        # receipt ack
                        pre_drops += 1
                        continue
                    seen.add(key)
                    msg = m.unpack(raw)
                    if not self._stateless_ok(sender, msg, epoch):
                        stateless_drops += 1
                        continue
                except m.MsgError:
                    pre_drops += 1
                    continue
                except Exception:  # noqa: BLE001 — hostile bytes must
                    log.debug("admission parse stage raised",  # not kill
                              exc_info=True)
                    pre_drops += 1
                    continue
                parsed.append((sender, raw, msg))

            # per-message verification jobs, coalesced across the drain
            jobs: List[tuple] = []
            backing: List[Optional[List[int]]] = []
            inner_sets: List[Optional[List]] = []
            for sender, raw, msg in parsed:
                n_jobs_before = len(jobs)
                try:
                    if isinstance(msg, m.ClientBatchRequestMsg):
                        inners = m.parse_batch_elements(msg)
                        if inners is None:
                            backing.append([])  # malformed: drop batch
                            inner_sets.append(None)  # (counted below)
                            continue
                        # topology-static element gates BEFORE the
                        # verify batch (like wire ClientRequestMsgs):
                        # flag-violating elements must not buy signature
                        # work, and they are stateless drops, not forged
                        # signatures
                        kept = [r for r in inners
                                if self._client_req_ok(r)]
                        stateless_drops += len(inners) - len(kept)
                        per_inner = []
                        for inner in kept:
                            idx = len(jobs)
                            jobs.append((inner.sender_id,
                                         inner.signed_payload(),
                                         inner.signature, None, False))
                            per_inner.append(idx)
                        backing.append(per_inner)
                        inner_sets.append(kept)
                    else:
                        backing.append(self._collect_jobs(msg, jobs))
                        inner_sets.append(None)
                except Exception:  # noqa: BLE001 — per-message isolation
                    del jobs[n_jobs_before:]    # its half-added jobs too
                    backing.append([])          # (counted below)
                    inner_sets.append(None)

            try:
                verdicts = self._verify_jobs(jobs)
            except Exception:  # noqa: BLE001 — an engine failure must
                # not discard the drain's no-signature traffic; items
                # that needed a verdict fail closed
                log.exception("coalesced verify raised (%d items)",
                              len(jobs))
                verdicts = [False] * len(jobs)

            admitted = 0
            for (sender, raw, msg), idxs, inners in zip(parsed, backing,
                                                        inner_sets):
                if inners is not None:
                    # per-element verdicts: only guilty elements drop
                    survivors = []
                    for inner, i in zip(inners, idxs):
                        if verdicts[i]:
                            inner._adm_verified = True
                            survivors.append(inner)
                        else:
                            verify_fails += 1
                    if not survivors:
                        continue
                    msg._adm_inners = survivors
                elif idxs is not None:
                    if not idxs:
                        # structurally rejected (junk principal on a
                        # replica-signed type, malformed batch/embedded
                        # content, or a per-message exception above) —
                        # the ONE counting site for []-backed drops, so
                        # the drop counters account for every message
                        stateless_drops += 1
                        continue
                    if not all(verdicts[i] for i in idxs):
                        verify_fails += sum(1 for i in idxs
                                            if not verdicts[i])
                        if not isinstance(msg, m.PrePrepareMsg):
                            continue            # guilty message dropped
                        # a verify-FAILED PrePrepare is still admitted,
                        # carrying an explicit failed verdict: a parked
                        # view-change entry consumes fetched old-view
                        # bodies authenticated by DIGEST only
                        # (_try_resolve_body) — a body signed under a
                        # since-rotated key must not be shed here or
                        # view entry stalls. _on_pre_prepare rejects the
                        # failed verdict for live proposals.
                        msg._adm_verified = False
                    else:
                        msg._adm_verified = True
                        if isinstance(msg, m.PrePrepareMsg):
                            # the embedded requests passed the same
                            # batch: mark them so the PP handler (and
                            # any future per-request consumer) can
                            # trust the verdict
                            for r in msg.client_requests():
                                if not r.flags \
                                        & m.RequestFlag.HAS_PRE_PROCESSED:
                                    r._adm_verified = True
                if isinstance(msg, m.PrePrepareMsg):
                    # slot-lifecycle anchor: the adm_wait stage runs
                    # from here to the dispatcher's PP handler entry
                    flight.record(flight.EV_ADM_ADMIT, seq=msg.seq_num,
                                  view=msg.view)
                self._sink(AdmittedMsg(sender, msg))
                admitted += 1

            # stats under the (racecheck-instrumented) admission lock:
            # held briefly, never across verification or the sink
            with self._stats_mu:
                self._processed += len(batch)
                self.adm_drains.inc()
                if admitted:
                    self.adm_admitted.inc(admitted)
                if pre_drops:
                    self.adm_drops_pre_parse.inc(pre_drops)
                if stateless_drops:
                    self.adm_drops_stateless.inc(stateless_drops)
                if verify_fails:
                    self.adm_verify_fail.inc(verify_fails)
                self.adm_queue_depth.set(self.depth)
            span.set_tag("msgs", len(batch)).set_tag("admitted", admitted) \
                .set_tag("verifies", len(jobs)) \
                .set_tag("pre_drops", pre_drops) \
                .set_tag("verify_fails", verify_fails)
