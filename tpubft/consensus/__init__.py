"""Consensus engine — SBFT state machine replication.

Rebuild of /root/reference/bftengine/: wire messages, replica state
machine (3 commit paths), threshold-signature collectors, view change,
checkpointing, persistent metadata. The signature hot paths are batched
behind the crypto plugin seams so the TPU backend can vectorize them.
"""
