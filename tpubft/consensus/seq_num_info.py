"""Per-seqnum consensus state + the sliding work window.

Rebuild of the reference's SeqNumInfo
(/root/reference/bftengine/src/bftengine/SeqNumInfo.hpp:34) and
SequenceWithActiveWindow (SequenceWithActiveWindow.hpp): each in-flight
seqnum holds the PrePrepare, the prepare/commit share collectors (slow
path), the fast-path collector, and the full (combined) certificates;
the window slides on stable checkpoints.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Generic, Iterator, Optional, TypeVar

from tpubft.consensus.collectors import ShareCollector
from tpubft.consensus.messages import (CommitFullMsg, FullCommitProofMsg,
                                       PrePrepareMsg, PrepareFullMsg)


@dataclass
class SeqNumInfo:
    seq_num: int
    pre_prepare: Optional[PrePrepareMsg] = None
    commit_path: Optional[int] = None          # CommitPath actually taken
    slow_started: bool = False
    # slow path
    prepare_collector: Optional[ShareCollector] = None
    prepare_full: Optional[PrepareFullMsg] = None
    commit_collector: Optional[ShareCollector] = None
    commit_full: Optional[CommitFullMsg] = None
    # fast path
    fast_collector: Optional[ShareCollector] = None
    full_commit_proof: Optional[FullCommitProofMsg] = None
    # flags
    prepared: bool = False
    committed: bool = False
    executed: bool = False
    # optimistic reply plane: the slot was released to the client-visible
    # path on a STRUCTURALLY-valid commit cert (pairing verify still in
    # flight) — reply visibility only, `committed` still gates persistence
    opt_committed: bool = False
    opt_committed_ns: int = 0                  # monotonic_ns at release
    # slot handed to the execution lane (run in flight or queued): the
    # dispatcher's guard against double-submitting a slot whose
    # committed certificate is re-accepted while the lane still owns it
    exec_submitted: bool = False
    # slot handed to the lane SPECULATIVELY (prepare-quorum / fast-path
    # acceptance, commit certificate still combining): cleared when the
    # commit confirms (→ exec_submitted) or the speculation aborts
    spec_submitted: bool = False
    received_at: float = 0.0                   # monotonic, for path timeout
    # shares that arrived before our PrePrepare did (reference keeps them
    # in the collectors keyed by digest; we buffer until digest is known)
    early_shares: Dict[str, list] = field(default_factory=dict)
    # async verification state: the exact messages whose verify jobs are
    # in flight (identity-checked when the verdict re-enters, so a stale
    # verdict for a dropped/replaced message can't clear a newer job's
    # guard): the PrePrepare being batch-verified / per-kind full certs
    pp_verifying: Optional[PrePrepareMsg] = None
    cert_verifying: Dict[str, object] = field(default_factory=dict)
    # full certs that arrived before the PrePrepare was accepted (window
    # widened by async PP verification), keyed (kind, sender): one slot
    # PER SENDER, so a byzantine peer's forgeries can only ever displace
    # that peer's own buffered certs, never the honest collector's
    # (bounded at n_kinds x n_replicas entries)
    early_certs: Dict[tuple, object] = field(default_factory=dict)
    # certs that arrived while a same-kind verify job was in flight,
    # keyed (kind, sender) for the same anti-shadowing reason; retried
    # when the in-flight verdict lands
    cert_pending: Dict[tuple, object] = field(default_factory=dict)
    # when evidence (shares/certs) first arrived WITHOUT a PrePrepare —
    # the ReqMissingDataMsg trigger clock
    first_evidence_at: float = 0.0
    # open consensus-slot tracing span (accept -> executed)
    span: Optional[object] = None


T = TypeVar("T")


class ActiveWindow(Generic[T]):
    """Sliding window keyed by seqnum: (stable, stable + size]. The
    reference's SequenceWithActiveWindow with kWorkWindowSize=300."""

    def __init__(self, size: int, factory):
        self._size = size
        self._factory = factory
        self._base = 0                         # last stable seq
        self._items: Dict[int, T] = {}

    @property
    def base(self) -> int:
        return self._base

    def in_window(self, seq: int) -> bool:
        return self._base < seq <= self._base + self._size

    def get(self, seq: int) -> T:
        if not self.in_window(seq):
            raise KeyError(f"seq {seq} outside window "
                           f"({self._base}, {self._base + self._size}]")
        item = self._items.get(seq)
        if item is None:
            item = self._items[seq] = self._factory(seq)
        return item

    def peek(self, seq: int) -> Optional[T]:
        return self._items.get(seq)

    def advance(self, new_base: int) -> None:
        """Slide forward on stable checkpoint; drops state <= new_base."""
        if new_base <= self._base:
            return
        self._base = new_base
        for s in [s for s in self._items if s <= new_base]:
            del self._items[s]

    def drop(self, seq: int) -> None:
        """Discard one entry (view change wipes in-flight state)."""
        self._items.pop(seq, None)

    def items(self) -> Iterator:
        return iter(sorted(self._items.items()))
