"""View change: complaints, view-change/new-view certificates, safety.

Rebuild of the reference's ViewsManager
(/root/reference/bftengine/src/bftengine/ViewsManager.hpp:41 —
`tryToEnterView` :131, `computeCorrectRelevantViewNumbers` :100) and
ViewChangeSafetyLogic (ViewChangeSafetyLogic.cpp): when the primary of
view v stops making progress, replicas broadcast signed complaints
(ReplicaAsksToLeaveViewMsg, ReplicaImp.cpp:3771); f+1 complaints move
everyone to a view change; each replica broadcasts a ViewChangeMsg
carrying its prepared certificates (threshold-signed evidence that a
seqnum may have committed); the new primary assembles >= 2f+2c+1 of them
into a NewViewMsg and re-proposes every certified seqnum so no committed
request can be lost (the PBFT quorum-intersection argument: any slow-path
commit quorum of 2f+c+1 intersects any view-change quorum of 2f+2c+1 in
at least f+1 replicas, hence in one honest replica carrying the cert).

Fast-path safety needs a second mechanism (the reference's ViewChangeMsg
elements carry the PrePrepare digest even without a prepared proof): a
fast-path commit leaves no threshold certificate at the SIGNERS, only at
the collector. So every replica also reports a SIGNED element — "I signed
shares for this PrePrepare" — for each in-flight seqnum. If a seqnum
committed on the fast path, all n (or 3f+c+1) replicas signed it, so any
view-change quorum contains >= f+c+1 honest reporters; conversely <=f
byzantine replicas cannot fabricate f+c+1 reports. Hence the report rule:
f+c+1 matching SIGNED elements restrict the new view like a certificate.

The safety computation (`compute_restrictions`) is deterministic over the
set of ViewChangeMsgs fixed by the NewViewMsg digests, so every honest
replica derives the identical restriction map.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from tpubft.consensus import messages as m
from tpubft.crypto.digest import digest as sha256
from tpubft.utils import serialize as ser

# PreparedCertificate.kind values: which threshold system signed the cert.
CERT_PREPARE = 0        # slow-path PrepareFull (2f+c+1)
CERT_COMMIT = 1         # slow-path CommitFull (2f+c+1)
CERT_FAST_OPT = 2       # optimistic fast path FullCommitProof (n)
CERT_FAST_THR = 3       # fast-with-threshold FullCommitProof (3f+c+1)
CERT_SIGNED = 4         # no combined proof — "I signed shares for this PP"

_CERT_TAG = {CERT_PREPARE: "prepare", CERT_COMMIT: "commit",
             CERT_FAST_OPT: "fast0", CERT_FAST_THR: "fast1"}


@dataclass
class Restriction:
    """What the new primary MUST re-propose for one seqnum.

    Born digest-only from the view-change evidence (certificates carry no
    batch bodies); `resolve` fills the body once the original PrePrepare
    is found locally or fetched (ReqViewPrePrepareMsg). Only the certified
    pp_digest is trusted: requests_digest/pre_prepare are derived from a
    body that hashes to it, never from a peer's claim."""
    seq_num: int
    view: int                     # view of the strongest certificate
    pp_digest: bytes              # certified digest of the original PP
    requests_digest: bytes        # filled by resolve(); b"" = unresolved
    pre_prepare: bytes            # packed original PP; b"" = unresolved
    SPEC = [("seq_num", "u64"), ("view", "u64"), ("pp_digest", "bytes"),
            ("requests_digest", "bytes"), ("pre_prepare", "bytes")]

    @property
    def resolved(self) -> bool:
        return bool(self.pre_prepare)

    def resolve(self, packed_pp: bytes) -> bool:
        """Adopt a candidate body iff it is structurally a PrePrepare for
        this (seq, view) hashing to the certified digest."""
        pp = _parse_pp(packed_pp, self.seq_num, self.view, self.pp_digest)
        if pp is None:
            return False
        self.requests_digest = pp.requests_digest
        self.pre_prepare = packed_pp
        return True


def pack_restriction(r: Restriction) -> bytes:
    return ser.encode_msg(r)


def unpack_restriction(data: bytes) -> Restriction:
    return ser.decode_msg(data, Restriction)


def pack_cert(c: m.PreparedCertificate) -> bytes:
    return ser.encode_msg(c)


def unpack_cert(data: bytes) -> m.PreparedCertificate:
    return ser.decode_msg(data, m.PreparedCertificate)


def build_certificates(window_items, last_stable: int, fast_path_of
                       ) -> Tuple[List[m.PreparedCertificate], Dict[bytes, bytes]]:
    """Collect evidence from the in-flight window (what the reference's
    ViewsManager harvests from SeqNumInfo before emitting a
    ViewChangeMsg): a threshold certificate where one exists, plus a
    SIGNED element for every PrePrepare we signed shares over.

    Returns (certs, bodies): certs are digest-only (the wire form);
    bodies maps pp_digest -> packed PrePrepare, retained LOCALLY so this
    replica can resolve its own restrictions and serve peers' fetches."""
    certs: List[m.PreparedCertificate] = []
    bodies: Dict[bytes, bytes] = {}
    for seq, info in window_items:
        if seq <= last_stable or info.pre_prepare is None:
            continue
        pp = info.pre_prepare
        bodies[pp.digest()] = pp.pack()
        if info.full_commit_proof is not None:
            path = fast_path_of(pp)
            kind = CERT_FAST_OPT if path == int(m.CommitPath.OPTIMISTIC_FAST) \
                else CERT_FAST_THR
            certs.append(m.PreparedCertificate(
                seq_num=seq, view=pp.view, kind=kind, pp_digest=pp.digest(),
                combined_sig=info.full_commit_proof.sig))
        elif info.commit_full is not None:
            certs.append(m.PreparedCertificate(
                seq_num=seq, view=pp.view, kind=CERT_COMMIT,
                pp_digest=pp.digest(), combined_sig=info.commit_full.sig))
        elif info.prepare_full is not None:
            certs.append(m.PreparedCertificate(
                seq_num=seq, view=pp.view, kind=CERT_PREPARE,
                pp_digest=pp.digest(), combined_sig=info.prepare_full.sig))
        # always also report that we signed this PrePrepare — fast-path
        # commits are only provable by counting these reports
        certs.append(m.PreparedCertificate(
            seq_num=seq, view=pp.view, kind=CERT_SIGNED,
            pp_digest=pp.digest(), combined_sig=b""))
    return certs, bodies


def _parse_pp(packed: bytes, seq_num: int, view: int,
              pp_digest: bytes) -> Optional[m.PrePrepareMsg]:
    """Structural consistency of a candidate PrePrepare body against the
    certified (seq, view, digest) triple."""
    try:
        pp = m.unpack(packed)
    except m.MsgError:
        return None
    if not isinstance(pp, m.PrePrepareMsg):
        return None
    if pp.seq_num != seq_num or pp.view != view:
        return None
    if pp.digest() != pp_digest:
        return None
    return pp


def validate_certificate(cert: m.PreparedCertificate, share_digest_fn,
                         verifier_for_kind) -> Optional[Restriction]:
    """Check a threshold-backed PreparedCertificate; returns the
    (unresolved, digest-only) Restriction it proves, or None if bogus.
    SIGNED elements carry no proof and are handled by the report rule in
    compute_restrictions.

    `share_digest_fn(tag, view, seq, pp_digest)` must be the replica's
    share-digest derivation — in production Replica._share_digest, which
    additionally binds the replica's current reconfiguration epoch, so a
    certificate assembled from dead-era shares cannot validate here;
    `verifier_for_kind(kind)` returns the IThresholdVerifier whose
    combined signature the cert carries.
    """
    tag = _CERT_TAG.get(cert.kind)
    if tag is None:
        return None
    verifier = verifier_for_kind(cert.kind)
    if verifier is None:
        return None
    d = share_digest_fn(tag, cert.view, cert.seq_num, cert.pp_digest)
    if not verifier.verify(d, cert.combined_sig):
        return None
    return Restriction(seq_num=cert.seq_num, view=cert.view,
                       pp_digest=cert.pp_digest,
                       requests_digest=b"", pre_prepare=b"")


def compute_restrictions(vc_msgs: List[m.ViewChangeMsg], share_digest_fn,
                         verifier_for_kind,
                         report_quorum: int) -> Dict[int, Restriction]:
    """ViewChangeSafetyLogic equivalent. Two sources of restrictions:

    1. threshold certificates — self-certifying, highest view wins;
    2. SIGNED reports — `report_quorum` (= f+c+1) matching reports of the
       same (view, pp_digest) prove at least one honest replica accepted
       that PrePrepare, and a fast-path commit guarantees that many
       reporters exist in any view-change quorum.

    Per seqnum the higher-view evidence wins (certificate on ties).
    Deterministic for a fixed vc_msgs set.
    """
    certs: Dict[int, Restriction] = {}
    # reports[seq][(view, pp_digest)] = (set of reporters, restriction)
    reports: Dict[int, Dict[Tuple[int, bytes], Tuple[set, Restriction]]] = {}
    for vc in vc_msgs:
        for cert in vc.prepared:
            if cert.kind == CERT_SIGNED:
                slot = reports.setdefault(cert.seq_num, {})
                key = (cert.view, cert.pp_digest)
                if key not in slot:
                    slot[key] = (set(), Restriction(
                        seq_num=cert.seq_num, view=cert.view,
                        pp_digest=cert.pp_digest,
                        requests_digest=b"", pre_prepare=b""))
                slot[key][0].add(vc.sender_id)
                continue
            r = validate_certificate(cert, share_digest_fn, verifier_for_kind)
            if r is None:
                continue
            cur = certs.get(r.seq_num)
            if cur is None or r.view > cur.view:
                certs[r.seq_num] = r
    out: Dict[int, Restriction] = {}
    for seq in set(certs) | set(reports):
        cert_r = certs.get(seq)
        report_r = None
        for (view, ppd), (who, r) in sorted(
                reports.get(seq, {}).items(),
                key=lambda kv: (-kv[0][0], kv[0][1])):
            if len(who) >= report_quorum:
                report_r = r        # highest view; lowest digest on ties
                break
        if cert_r is not None and (report_r is None
                                   or cert_r.view >= report_r.view):
            out[seq] = cert_r
        elif report_r is not None:
            out[seq] = report_r
    return out


class ViewChangeState:
    """Bookkeeping shared by all replicas during a view change: complaint
    sets per view, ViewChangeMsg sets per target view, and the pending
    NewViewMsg awaiting its referenced ViewChangeMsgs. Memory is bounded
    to one complaint and one ViewChangeMsg per sender (the latest-view
    one wins), so a byzantine replica cannot grow state without bound."""

    def __init__(self, complaint_quorum: int, view_change_quorum: int):
        self.complaint_quorum = complaint_quorum
        self.view_change_quorum = view_change_quorum
        self.complaints: Dict[int, Dict[int, m.ReplicaAsksToLeaveViewMsg]] = {}
        self.vc_msgs: Dict[int, Dict[int, m.ViewChangeMsg]] = {}
        self.pending_new_view: Optional[m.NewViewMsg] = None

    @staticmethod
    def _put_latest(store: Dict[int, Dict[int, object]], view: int,
                    sender: int, msg) -> None:
        for v in list(store):
            if sender in store[v]:
                if v > view:
                    return                      # stale: sender moved on
                if v < view:
                    del store[v][sender]
                    if not store[v]:
                        del store[v]
        store.setdefault(view, {})[sender] = msg

    # ---- complaints ----
    def add_complaint(self, msg: m.ReplicaAsksToLeaveViewMsg) -> None:
        self._put_latest(self.complaints, msg.view, msg.sender_id, msg)

    def complaint_count(self, view: int) -> int:
        return len(self.complaints.get(view, {}))

    def has_complaint_quorum(self, view: int) -> bool:
        return self.complaint_count(view) >= self.complaint_quorum

    # ---- view change msgs ----
    def add_view_change(self, msg: m.ViewChangeMsg) -> None:
        self._put_latest(self.vc_msgs, msg.new_view, msg.sender_id, msg)

    def view_change_count(self, new_view: int) -> int:
        return len(self.vc_msgs.get(new_view, {}))

    def has_view_change_quorum(self, new_view: int) -> bool:
        return self.view_change_count(new_view) >= self.view_change_quorum

    def quorum_for_new_view(self, new_view: int) -> List[m.ViewChangeMsg]:
        """ALL ViewChangeMsgs held for new_view (>= the quorum) — using
        every available message maximizes the certificate evidence the
        restriction computation sees."""
        msgs = self.vc_msgs.get(new_view, {})
        return [msgs[r] for r in sorted(msgs)]

    def match_new_view(self, nv: m.NewViewMsg) -> Optional[List[m.ViewChangeMsg]]:
        """Resolve a NewViewMsg's digests against stored ViewChangeMsgs;
        None if any referenced msg is missing or digest-mismatched."""
        have = self.vc_msgs.get(nv.new_view, {})
        out = {}
        for ref in nv.view_change_digests:
            vc = have.get(ref.replica)
            if vc is None or vc.digest() != ref.digest:
                return None
            out[ref.replica] = vc
        # DISTINCT senders must reach the quorum — a byzantine primary
        # repeating one digest to hide fast-path evidence must fail here
        if len(out) < self.view_change_quorum:
            return None
        return [out[r] for r in sorted(out)]

    def gc_below(self, view: int) -> None:
        """Drop state for views below the one just entered."""
        for d in (self.complaints, self.vc_msgs):
            for v in [v for v in d if v < view]:
                del d[v]
        self.pending_new_view = None
