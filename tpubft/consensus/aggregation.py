"""Share-aggregation overlay: the deterministic tree shares climb.

Rebuilds the aggregation-gossip topology from "Scalable BFT Consensus
Through Aggregated Signature Gossip" (arXiv 1911.04698) on top of this
codebase's collector-centric share flow: the root of the overlay is the
slot's collector (the view's primary — replicas_info.collector_for), so
the finished aggregate lands exactly where the ShareCollector verdict
path already lives; leaves send their Prepare/Commit shares only to
their overlay parent; interior nodes forward 56-byte partial aggregates
(crypto/systems.pack_agg_cert). Per-replica share traffic drops from the
collector's O(n) fan-in to O(fanout) at every node.

Determinism contract: every replica derives the SAME overlay from
(n, fanout, root, view[, gossip salt]) with no wire negotiation — the
permutation is seeded by a hash of those values. The permutation is
rotated per view ("tree" mode) so a slow interior node is never
permanent, and additionally every `agg_rotate_seqs` sequence numbers in
"gossip" mode. `agg_fanout` is therefore a PINNED wire-visible knob
(tuning/wiring.py): per-replica drift would fragment the overlay.
"""
from __future__ import annotations

import hashlib
from functools import lru_cache
from typing import List, Optional, Tuple


class Overlay:
    """One materialized aggregation tree: a heap layout over a seeded
    permutation of the replica ids, root pinned to the collector."""

    def __init__(self, order: Tuple[int, ...], fanout: int):
        self.order = order                  # position -> replica id
        self.fanout = fanout
        self._pos = {r: i for i, r in enumerate(order)}

    @property
    def root(self) -> int:
        return self.order[0]

    def parent_of(self, r: int) -> Optional[int]:
        """Overlay parent of replica r (None for the root)."""
        i = self._pos[r]
        if i == 0:
            return None
        return self.order[(i - 1) // self.fanout]

    def children_of(self, r: int) -> List[int]:
        i = self._pos[r]
        lo = i * self.fanout + 1
        return list(self.order[lo:lo + self.fanout])

    def is_interior(self, r: int) -> bool:
        """Has at least one child (the root counts)."""
        return self._pos[r] * self.fanout + 1 < len(self.order)

    def subtree_ids(self, r: int) -> List[int]:
        """Every replica in r's subtree, r included — the contributor
        set an interior node waits for before flushing early."""
        out, stack = [], [r]
        while stack:
            x = stack.pop()
            out.append(x)
            stack.extend(self.children_of(x))
        return out

    def depth(self) -> int:
        d, i = 0, len(self.order) - 1
        while i > 0:
            i = (i - 1) // self.fanout
            d += 1
        return d


@lru_cache(maxsize=128)
def _build(n: int, fanout: int, root: int, view: int, salt: int) -> Overlay:
    seed = hashlib.sha256(
        b"tpubft-agg-overlay|%d|%d|%d|%d|%d"
        % (n, fanout, root, view, salt)).digest()
    others = sorted(
        (r for r in range(n) if r != root),
        key=lambda r: hashlib.sha256(seed + r.to_bytes(4, "big")).digest())
    return Overlay((root,) + tuple(others), fanout)


def overlay_for(mode: str, n: int, fanout: int, root: int,
                view: int, seq_num: int, rotate_seqs: int) -> Overlay:
    """The overlay governing one (view, seq) slot. "tree": one shape per
    view. "gossip": additionally re-seeded every `rotate_seqs` seqnums,
    so a slow interior node can only delay a bounded run of slots."""
    salt = (seq_num // max(rotate_seqs, 1)) if mode == "gossip" else 0
    return _build(n, min(fanout, n), root, view, salt)
