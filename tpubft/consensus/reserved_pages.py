"""Reserved pages — small mutable consensus-replicated page store.

Rebuild of the reference's IReservedPages / ReservedPagesClient
(/root/reference/bftengine/include/bftengine/IReservedPages.hpp,
ReservedPagesClient.hpp): a fixed-size page store that travels with state
transfer alongside the ledger, used by the clients reply cache, key
exchange, time service, cron, and reconfiguration. Pages are namespaced
per subsystem (the reference statically carves page-id ranges per
registered client type; we key by (category, index) which gives the same
isolation without a global allocation table).
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Tuple

from tpubft.storage.interfaces import IDBClient, WriteBatch

PAGE_SIZE = 4096
_FAMILY = b"respages"


class ReservedPages:
    def __init__(self, db: IDBClient) -> None:
        self._db = db

    @property
    def db(self) -> IDBClient:
        """The backing store (read-only exposure: the execution lane
        needs it as a group-fsync target on the unfolded path)."""
        return self._db

    @staticmethod
    def _key(category: str, index: int) -> bytes:
        cb = category.encode()
        return len(cb).to_bytes(2, "big") + cb + index.to_bytes(4, "big")

    def load(self, category: str, index: int = 0) -> Optional[bytes]:
        return self._db.get(self._key(category, index), _FAMILY)

    def save(self, category: str, index: int, data: bytes) -> None:
        if len(data) > PAGE_SIZE:
            raise ValueError(f"page exceeds {PAGE_SIZE} bytes")
        self._db.put(self._key(category, index), data, _FAMILY)

    def delete(self, category: str, index: int) -> None:
        self._db.delete(self._key(category, index), _FAMILY)

    # ---- batched staging (execution-lane run coalescing) ----
    def stage_save(self, wb: WriteBatch, category: str, index: int,
                   data: bytes) -> None:
        """Stage a save into a caller-owned WriteBatch: the execution
        lane coalesces a whole run's reply/marker pages into ONE batch
        (committed via write_batch, or riding the ledger's run batch
        when pages share its DB) instead of one put per page."""
        if len(data) > PAGE_SIZE:
            raise ValueError(f"page exceeds {PAGE_SIZE} bytes")
        wb.put(self._key(category, index), data, _FAMILY)

    def write_batch(self, wb: WriteBatch) -> None:
        if wb.ops:
            self._db.write(wb)

    def shares_db(self, other_db) -> bool:
        """True when this page store writes to `other_db` — the lane uses
        this to fold the pages batch into the ledger commit atomically."""
        return self._db is other_db

    def rebind(self, db: IDBClient) -> None:
        """Swap the backing handle — used when the ledger installs its
        durability pending view over a SHARED db, so page reads/digests
        observe folded-but-not-yet-applied reply pages exactly like
        ledger readers observe sealed blocks."""
        self._db = db

    def scan(self, category: str, lo_index: int,
             hi_index: int) -> List[Tuple[int, bytes]]:
        """EXISTING pages of `category` with lo_index <= index < hi_index,
        as (index, data). One bounded range_iter — cost proportional to
        the pages that exist in the range (zero for a cold client), never
        to the range width: the demand pager's primitive, so paging in a
        never-seen principal is O(log store), not O(ring slots)."""
        out: List[Tuple[int, bytes]] = []
        for k, v in self._db.range_iter(_FAMILY,
                                        start=self._key(category, lo_index),
                                        end=self._key(category, hi_index)):
            out.append((int.from_bytes(k[-4:], "big"), v))
        return out

    def all_pages(self) -> List[Tuple[bytes, bytes]]:
        return list(self._db.range_iter(_FAMILY))

    @staticmethod
    def digest_of(pages: List[Tuple[bytes, bytes]]) -> bytes:
        h = hashlib.sha256()
        for k, v in sorted(pages):
            h.update(len(k).to_bytes(4, "big") + k)
            h.update(len(v).to_bytes(4, "big") + v)
        return h.digest()

    def digest(self) -> bytes:
        """Digest over all pages — part of the checkpoint certificate
        (reference: digestOfResPagesDescriptor)."""
        return self.digest_of(list(self._db.range_iter(_FAMILY)))

    def replace_all(self, pages: List[Tuple[bytes, bytes]]) -> None:
        """State transfer install: swap the whole page set atomically."""
        wb = WriteBatch()
        for k, _ in self._db.range_iter(_FAMILY):
            wb.delete(k, _FAMILY)
        for k, v in pages:
            wb.put(k, v, _FAMILY)
        self._db.write(wb)


class ReservedPagesClient:
    """Subsystem-scoped view (reference ReservedPagesClient<T>)."""

    def __init__(self, pages: ReservedPages, category: str) -> None:
        self._pages = pages
        self._category = category

    def load(self, index: int = 0) -> Optional[bytes]:
        return self._pages.load(self._category, index)

    def scan(self, lo_index: int, hi_index: int):
        return self._pages.scan(self._category, lo_index, hi_index)

    def save(self, data: bytes, index: int = 0) -> None:
        self._pages.save(self._category, index, data)

    def delete(self, index: int = 0) -> None:
        self._pages.delete(self._category, index)
