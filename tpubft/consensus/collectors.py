"""Threshold-signature share collection — the consensus hot path.

Rebuild of the reference's CollectorOfThresholdSignatures
(/root/reference/bftengine/src/bftengine/CollectorOfThresholdSignatures.hpp:38):
shares for one (view, seq, kind) accumulate until the quorum is reached;
combine + verify runs as a background job (SignaturesProcessingJob :291-407)
on a worker pool; the verdict re-enters the dispatcher as an internal msg.
On combined-verification failure the job re-verifies share-by-share to
identify bad shares (:363-401 strategy: optimistic accumulate first).

TPU-first delta: the worker drains *all* due collectors in one go, so share
verification across collectors lands in one `verify_batch` call — with the
BLS backend that is one Lagrange+MSM kernel dispatch per combine and one
vmapped pairing batch per identification pass.
"""
from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from tpubft.crypto.interfaces import IThresholdVerifier


@dataclass
class CombineResult:
    view: int
    seq_num: int
    kind: str                      # "prepare" | "commit" | "fast"
    ok: bool
    combined_sig: bytes = b""
    bad_shares: List[int] = field(default_factory=list)


class ShareCollector:
    """Accumulates shares for one (view, seq, kind, digest) instance."""

    def __init__(self, view: int, seq_num: int, kind: str, digest: bytes,
                 verifier: IThresholdVerifier):
        self.view = view
        self.seq_num = seq_num
        self.kind = kind
        self.digest = digest
        self.verifier = verifier
        self.shares: Dict[int, bytes] = {}     # signer id (1-based) -> share
        self.combined: Optional[bytes] = None
        self.job_launched = False
        self.last_attempt: Optional[frozenset] = None

    def add_share(self, signer_id: int, share: bytes) -> bool:
        """Store a share (0-based replica id). Returns True if new."""
        sid = signer_id + 1                    # threshold signers are 1-based
        if sid in self.shares or self.combined is not None:
            return False
        self.shares[sid] = share
        return True

    def has_quorum(self) -> bool:
        return len(self.shares) >= self.verifier.threshold

    def ready_for_job(self) -> bool:
        """Quorum reached, no job in flight, not combined yet, and the
        share set changed since the last (failed) attempt — identical
        inputs would fail identically."""
        return (self.has_quorum() and not self.job_launched
                and self.combined is None
                and frozenset(self.shares) != self.last_attempt)

    def combine_and_verify(self, shares: Dict[int, bytes]) -> CombineResult:
        """The background job body (reference SignaturesProcessingJob
        ::execute) over a SNAPSHOT of the shares (the dispatcher thread
        keeps mutating self.shares): accumulate WITHOUT share
        verification, combine, verify the combined signature; on failure
        verify shares individually."""
        acc = self.verifier.new_accumulator(with_share_verification=False)
        acc.set_expected_digest(self.digest)
        for sid, share in shares.items():
            acc.add(sid, share)
        combined = acc.get_full_signed_data()
        if self.verifier.verify(self.digest, combined):
            return CombineResult(self.view, self.seq_num, self.kind, True,
                                 combined)
        bad = acc.identify_bad_shares()
        return CombineResult(self.view, self.seq_num, self.kind, False,
                             bad_shares=bad)


class CertBatchVerifier:
    """Cross-seqnum combined-certificate verification batcher.

    The reference verifies each received full certificate in its own
    CombinedSigVerificationJob (CollectorOfThresholdSignatures.hpp:409) —
    one ~2-pairing check per cert. Here certs arriving within the flush
    window are verified TOGETHER per verifier through
    IThresholdVerifier.verify_batch_certs (BLS: one random-linear-
    combination pairing check + two MSMs for the whole batch), so a busy
    replica pays O(1) pairing checks per flush instead of O(certs)."""

    def __init__(self, post: Callable[[object, bool], None],
                 flush_us: int = 500, max_batch: int = 64):
        from tpubft.utils.batcher import FlushBatcher
        self._post = post              # (cookie, ok) -> None
        self._batcher = FlushBatcher(
            self._drain, batch_size=max_batch, flush_us=flush_us,
            on_drop=lambda item: self._post(item[3], False),
            name="cert-batch-verify")

    def submit(self, verifier, digest: bytes, sig: bytes,
               cookie) -> None:
        self._batcher.submit((verifier, digest, sig, cookie))

    def _drain(self, batch) -> None:
        by_verifier: Dict[int, List[int]] = {}
        for i, (v, _, _, _) in enumerate(batch):
            by_verifier.setdefault(id(v), []).append(i)
        for idxs in by_verifier.values():
            verifier = batch[idxs[0]][0]
            items = [(batch[i][1], batch[i][2]) for i in idxs]
            try:
                verdicts = verifier.verify_batch_certs(items)
            except Exception:  # noqa: BLE001 — failure = reject batch
                from tpubft.utils.logging import get_logger
                get_logger("collectors").exception(
                    "cert batch verify raised")
                verdicts = [False] * len(items)
            for i, ok in zip(idxs, verdicts):
                try:
                    self._post(batch[i][3], bool(ok))
                except Exception:  # noqa: BLE001 — one failed post (e.g.
                    # shutdown) must not make the batcher re-resolve the
                    # rest as failures; but a consumer bug must be visible
                    from tpubft.utils.logging import get_logger
                    get_logger("collectors").exception(
                        "cert verdict post failed")

    def stop(self) -> None:
        self._batcher.stop()


class CollectorPool:
    """Owns the worker pool; launches combine jobs and posts results back
    via `post_result` (the replica wires this to push_internal). The
    reference's SimpleThreadPool + internal-msg round trip."""

    def __init__(self, post_result: Callable[[CombineResult], None],
                 workers: int = 2):
        self._post = post_result
        self._pool = ThreadPoolExecutor(max_workers=workers,
                                        thread_name_prefix="sig-combine")
        self._closed = False

    def submit(self, fn: Callable[[], None]) -> bool:
        """Run an arbitrary background verification job on the pool (the
        reference's RequestThreadPool / CombinedSigVerificationJob role —
        the job itself posts its verdict back as an internal msg)."""
        if self._closed:
            return False
        self._pool.submit(fn)
        return True

    def maybe_launch(self, collector: ShareCollector) -> bool:
        """Called on the dispatcher thread only; snapshots the share set
        so the job never races dispatcher-side mutations."""
        if self._closed or not collector.ready_for_job():
            return False
        collector.job_launched = True
        snapshot = dict(collector.shares)
        collector.last_attempt = frozenset(snapshot)
        self._pool.submit(self._run, collector, snapshot)
        return True

    def _run(self, collector: ShareCollector, shares) -> None:
        try:
            result = collector.combine_and_verify(shares)
        except Exception:  # noqa: BLE001 — job failure = combine failure
            from tpubft.utils.logging import get_logger
            get_logger("collectors").exception(
                "combine job raised (kind=%s seq=%d)", collector.kind,
                collector.seq_num)
            result = CombineResult(collector.view, collector.seq_num,
                                   collector.kind, False)
        if result.ok:
            collector.combined = result.combined_sig
        collector.job_launched = False
        self._post(result)

    def shutdown(self) -> None:
        self._closed = True
        self._pool.shutdown(wait=False)
