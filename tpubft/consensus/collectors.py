"""Threshold-signature share collection — the consensus hot path.

Rebuild of the reference's CollectorOfThresholdSignatures
(/root/reference/bftengine/src/bftengine/CollectorOfThresholdSignatures.hpp:38):
shares for one (view, seq, kind) accumulate until the quorum is reached;
combine + verify runs as a background job (SignaturesProcessingJob :291-407)
on a worker pool; the verdict re-enters the dispatcher as an internal msg.
On combined-verification failure the job re-verifies share-by-share to
identify bad shares (:363-401 strategy: optimistic accumulate first).

TPU-first delta — the fused combine plane: the reference launches one
combine job per slot, so a pipelined replica pays one Lagrange+MSM
device dispatch per seqnum ("The Latency Price of Threshold
Cryptosystems", arXiv 2407.12172, is exactly this tax). Here due
collectors drain through a `FlushBatcher` (the same discipline as
CertBatchVerifier) into ONE `IThresholdVerifier.combine_batch` call per
verifier per flush — with the BLS backend that is one segmented
multi-MSM kernel launch for every slot's combine plus one RLC'd pairing
check for every combined signature of the flush; with the Ed25519
multisig vector it is one batched verify kernel call. One slot's bad
share fails only its own CombineResult; sibling slots in the same flush
still land.

Thread discipline (tpulint static-race pass, sig_combine/batcher roles):
ShareCollector state is SINGLE-WRITER from the dispatcher. `maybe_launch`
snapshots the share set dispatcher-side; combine workers and the flush
batcher only read their snapshot and post a CombineResult carrying the
collector; the dispatcher applies the verdict's state flip
(`ShareCollector.on_result`) when the internal msg re-enters.
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from tpubft.crypto.interfaces import IThresholdVerifier
from tpubft.utils import flight


@dataclass
class CombineResult:
    view: int
    seq_num: int
    kind: str                      # "prepare" | "commit" | "fast"
    ok: bool
    combined_sig: bytes = b""
    bad_shares: List[int] = field(default_factory=list)
    # the collector this verdict belongs to: the dispatcher flips its
    # job_launched/combined state on re-entry (workers must not — the
    # dispatcher reads those fields in ready_for_job)
    collector: Optional["ShareCollector"] = field(default=None,
                                                 compare=False, repr=False)


class ShareCollector:
    """Accumulates shares for one (view, seq, kind, digest) instance."""

    def __init__(self, view: int, seq_num: int, kind: str, digest: bytes,
                 verifier: IThresholdVerifier):
        self.view = view
        self.seq_num = seq_num
        self.kind = kind
        self.digest = digest
        self.verifier = verifier
        self.shares: Dict[int, bytes] = {}     # signer id (1-based) -> share
        self.combined: Optional[bytes] = None
        self.job_launched = False
        self.last_attempt: Optional[frozenset] = None

    def add_share(self, signer_id: int, share: bytes) -> bool:
        """Store a share (0-based replica id). Returns True if new.

        Under share aggregation the root feeds subtree PARTIALS through
        this same path, keyed by the forwarding child (the entry is
        self-describing — crypto/systems.AGG_CERT_LEN blobs carry their
        contributor bitmap), so the whole verdict machinery downstream
        (snapshot, fused combine, bad-share pop) is unchanged. A
        strictly HEAVIER blob under an existing key replaces it: interior
        flushes are cumulative, so a child's later superset partial must
        supersede its earlier thin one or those contributors are lost
        until the parent-timeout fallback."""
        sid = signer_id + 1                    # threshold signers are 1-based
        if self.combined is not None:
            return False
        cur = self.shares.get(sid)
        if cur is not None and (cur == share or
                                self.verifier.share_weight(share)
                                <= self.verifier.share_weight(cur)):
            return False
        self.shares[sid] = share
        return True

    def has_quorum(self) -> bool:
        # every entry weighs >= 1, so the cheap len check short-circuits
        # the common all-raw case; with partial aggregates in the dict
        # quorum counts CONTRIBUTORS (bitmap popcount), not datagrams
        if len(self.shares) >= self.verifier.threshold:
            return True
        return sum(self.verifier.share_weight(s)
                   for s in self.shares.values()) >= self.verifier.threshold

    def ready_for_job(self) -> bool:
        """Quorum reached, no job in flight, not combined yet, and the
        share set changed since the last (failed) attempt — identical
        inputs would fail identically."""
        return (self.has_quorum() and not self.job_launched
                and self.combined is None
                # items, not keys: a superseded partial under an
                # unchanged key must still retrigger the combine
                and frozenset(self.shares.items()) != self.last_attempt)

    def on_result(self, res: CombineResult) -> None:
        """Dispatcher-side verdict application: the ONLY place collector
        state flips after launch (the combine ran on a worker/batcher
        thread over a snapshot; writing here keeps every field
        single-writer from the dispatcher)."""
        self.job_launched = False
        if res.ok:
            self.combined = res.combined_sig

    def combine_and_verify(self, shares: Dict[int, bytes]) -> CombineResult:
        """The background job body (reference SignaturesProcessingJob
        ::execute) over a SNAPSHOT of the shares (the dispatcher thread
        keeps mutating self.shares): accumulate WITHOUT share
        verification, combine, verify the combined signature; on failure
        verify shares individually. Delegates to the verifier's
        combine_batch so the per-slot and fused paths share one
        verdict-producing code path."""
        ((ok, combined, bad),) = self.verifier.combine_batch(
            [(self.digest, shares)])
        if ok:
            return CombineResult(self.view, self.seq_num, self.kind, True,
                                 combined, collector=self)
        return CombineResult(self.view, self.seq_num, self.kind, False,
                             bad_shares=bad, collector=self)


class CertBatchVerifier:
    """Cross-seqnum combined-certificate verification batcher.

    The reference verifies each received full certificate in its own
    CombinedSigVerificationJob (CollectorOfThresholdSignatures.hpp:409) —
    one ~2-pairing check per cert. Here certs arriving within the flush
    window are verified TOGETHER per verifier through
    IThresholdVerifier.verify_batch_certs (BLS: one random-linear-
    combination pairing check + two MSMs for the whole batch), so a busy
    replica pays O(1) pairing checks per flush instead of O(certs)."""

    def __init__(self, post: Callable[[object, bool], None],
                 flush_us: int = 500, max_batch: int = 64):
        from tpubft.utils.batcher import FlushBatcher
        self._post = post              # (cookie, ok) -> None
        self._batcher = FlushBatcher(
            self._drain, batch_size=max_batch, flush_us=flush_us,
            on_drop=lambda item: self._post(item[3], False),
            name="cert-batch-verify")

    def submit(self, verifier, digest: bytes, sig: bytes,
               cookie) -> None:
        self._batcher.submit((verifier, digest, sig, cookie))

    def reconfigure(self, max_batch: int = None,
                    flush_us: int = None) -> None:
        """Autotuner actuator: retune the cert-batch flush live."""
        self._batcher.reconfigure(batch_size=max_batch,
                                  flush_us=flush_us)

    def _drain(self, batch) -> None:
        # keyed by the verifier OBJECT, not id(): the dict key holds the
        # verifier alive for the drain, so a GC'd-and-recycled id can
        # never co-mingle two verifiers' certs in one aggregated check
        by_verifier: Dict[object, List[int]] = {}
        for i, (v, _, _, _) in enumerate(batch):
            by_verifier.setdefault(v, []).append(i)
        for verifier, idxs in by_verifier.items():
            items = [(batch[i][1], batch[i][2]) for i in idxs]
            try:
                verdicts = verifier.verify_batch_certs(items)
            except Exception:  # noqa: BLE001 — failure = reject batch
                from tpubft.utils.logging import get_logger
                get_logger("collectors").exception(
                    "cert batch verify raised")
                verdicts = [False] * len(items)
            for i, ok in zip(idxs, verdicts):
                try:
                    self._post(batch[i][3], bool(ok))
                except Exception:  # noqa: BLE001 — one failed post (e.g.
                    # shutdown) must not make the batcher re-resolve the
                    # rest as failures; but a consumer bug must be visible
                    from tpubft.utils.logging import get_logger
                    get_logger("collectors").exception(
                        "cert verdict post failed")

    def stop(self) -> None:
        self._batcher.stop()


class CombineBatcher:
    """Cross-slot fused combine plane: due collectors from ALL seqnums
    and kinds flush together, one `combine_batch` call per verifier per
    flush (BLS: one segmented multi-MSM launch + one RLC pairing check
    for the whole batch). Same FlushBatcher wake discipline as
    CertBatchVerifier, so pipelined slots arriving within the flush
    window amortize the device dispatch instead of paying it per slot."""

    def __init__(self, post: Callable[[CombineResult], None],
                 flush_us: int = 300, max_batch: int = 64,
                 on_flush: Optional[Callable[[int], None]] = None,
                 rid: int = -1):
        from tpubft.utils.batcher import FlushBatcher
        self._post = post              # CombineResult -> None
        self._on_flush = on_flush      # batch-size metrics sink
        self._rid = rid                # flight attribution (multi-replica
        self._rid_seeded = False       # processes share one recorder)
        self._batcher = FlushBatcher(
            self._drain, batch_size=max_batch, flush_us=flush_us,
            on_drop=self._drop, name="combine-batch")

    def submit(self, collector: ShareCollector,
               snapshot: Dict[int, bytes]) -> None:
        """Dispatcher-side: `snapshot` was taken under the dispatcher's
        ownership of collector.shares; the drain only reads it."""
        self._batcher.submit((collector, snapshot))

    def reconfigure(self, max_batch: int = None,
                    flush_us: int = None) -> None:
        """Autotuner actuator: retune the fused-combine flush live
        (combine_flush_us / combine_batch_max move through the knob
        registry after startup, not the frozen ReplicaConfig field)."""
        self._batcher.reconfigure(batch_size=max_batch,
                                  flush_us=flush_us)

    def _drop(self, item: Tuple[ShareCollector, Dict[int, bytes]]) -> None:
        # stopped batcher: resolve as a combine failure so the
        # dispatcher-side state flip still happens and no collector is
        # wedged with job_launched forever
        c, _ = item
        self._post(CombineResult(c.view, c.seq_num, c.kind, False,
                                 collector=c))

    def _drain(self, batch) -> None:
        if not self._rid_seeded:
            # the drain owns its FlushBatcher thread: seed the replica id
            # once so combine_flush events attribute correctly (same
            # convention as the dispatcher/exec/admission loop entries)
            flight.set_thread_rid(self._rid)
            self._rid_seeded = True
        flight.record(flight.EV_COMBINE_FLUSH, arg=len(batch))
        # group by verifier object (stable identity — see
        # CertBatchVerifier._drain): slow-path prepare/commit share one
        # verifier, fast paths their own, so one flush usually makes
        # 1-2 combine_batch calls
        by_verifier: Dict[object, List[int]] = {}
        for i, (c, _snap) in enumerate(batch):
            by_verifier.setdefault(c.verifier, []).append(i)
        for verifier, idxs in by_verifier.items():
            jobs = [(batch[i][0].digest, batch[i][1]) for i in idxs]
            try:
                results = verifier.combine_batch(jobs)
                if len(results) != len(jobs):
                    # contract violation must fail LOUD into the per-job
                    # failure path — a silently zip-truncated tail would
                    # leave collectors with job_launched wedged True
                    raise ValueError(
                        f"combine_batch returned {len(results)} results "
                        f"for {len(jobs)} jobs")
            except Exception:  # noqa: BLE001 — whole-group failure =
                # per-job combine failure (no bad-share knowledge)
                from tpubft.utils.logging import get_logger
                get_logger("collectors").exception(
                    "fused combine raised (%d jobs)", len(jobs))
                results = [(False, b"", [])] * len(jobs)
            for i, (ok, sig, bad) in zip(idxs, results):
                c = batch[i][0]
                self._post(CombineResult(c.view, c.seq_num, c.kind,
                                         bool(ok), sig if ok else b"",
                                         list(bad), collector=c))
        if self._on_flush is not None:
            try:
                self._on_flush(len(batch))
            except Exception:  # noqa: BLE001 — metrics must not kill
                pass           # the combine plane

    def stop(self) -> None:
        self._batcher.stop()


class CollectorPool:
    """Owns the combine plane; launches combine work and posts results
    back via `post_result` (the replica wires this to push_internal).
    The reference's SimpleThreadPool + internal-msg round trip, with the
    per-slot jobs replaced by the fused CombineBatcher (fused=False
    keeps the one-job-per-collector control path for A/B runs)."""

    def __init__(self, post_result: Callable[[CombineResult], None],
                 workers: int = 2, fused: bool = True,
                 flush_us: int = 300, max_batch: int = 64,
                 on_flush: Optional[Callable[[int], None]] = None,
                 rid: int = -1):
        self._post = post_result
        self._pool = ThreadPoolExecutor(max_workers=workers,
                                        thread_name_prefix="sig-combine")
        self._closed = False
        self._combiner = (CombineBatcher(post_result, flush_us=flush_us,
                                         max_batch=max_batch,
                                         on_flush=on_flush, rid=rid)
                          if fused else None)

    def submit(self, fn: Callable[[], None]) -> bool:
        """Run an arbitrary background verification job on the pool (the
        reference's RequestThreadPool / CombinedSigVerificationJob role —
        the job itself posts its verdict back as an internal msg)."""
        if self._closed:
            return False
        self._pool.submit(fn)
        return True

    def reconfigure(self, max_batch: int = None,
                    flush_us: int = None) -> None:
        """Autotuner actuator (no-op on the per-collector control
        path, which has no flush to tune)."""
        if self._combiner is not None:
            self._combiner.reconfigure(max_batch=max_batch,
                                       flush_us=flush_us)

    def maybe_launch(self, collector: ShareCollector) -> bool:
        """Called on the dispatcher thread only; snapshots the share set
        so the job never races dispatcher-side mutations. The result's
        state flip happens dispatcher-side in ShareCollector.on_result
        when the verdict re-enters as an internal msg."""
        if self._closed or not collector.ready_for_job():
            return False
        collector.job_launched = True
        snapshot = dict(collector.shares)
        collector.last_attempt = frozenset(snapshot.items())
        if self._combiner is not None:
            self._combiner.submit(collector, snapshot)
        else:
            self._pool.submit(self._run, collector, snapshot)
        return True

    def _run(self, collector: ShareCollector, shares) -> None:
        try:
            result = collector.combine_and_verify(shares)
        except Exception:  # noqa: BLE001 — job failure = combine failure
            from tpubft.utils.logging import get_logger
            get_logger("collectors").exception(
                "combine job raised (kind=%s seq=%d)", collector.kind,
                collector.seq_num)
            result = CombineResult(collector.view, collector.seq_num,
                                   collector.kind, False,
                                   collector=collector)
        self._post(result)

    def shutdown(self) -> None:
        self._closed = True
        if self._combiner is not None:
            self._combiner.stop()
        self._pool.shutdown(wait=False)


class ByzTelemetry:
    """Per-origin Byzantine-evidence counters (ISSUE 20 satellite).

    The combine plane already IDENTIFIES misbehaving share origins
    (`CombineResult.bad_shares`, the deferred-cert poison path) but the
    evidence was consumed anonymously — one aggregate counter, no way
    to tell "replica 3 keeps sending garbage" from background noise.
    This rolls it up per ORIGIN replica id so `status get health` and
    flight dumps answer *who*:

      * bad_shares[origin]             — threshold shares that failed
        share-level identification after a combine-verify miss
        (replica._on_combine_result pops them; origin = signer_id - 1)
      * deferred_cert_failures[origin] — async cert verifications that
        failed AFTER structural acceptance, keyed by the cert's sender
        (the optimistic plane's poison trigger)

    Counters only — classification/eviction stays with the callers.
    Thread-safe: the dispatcher and verify workers both report."""

    def __init__(self) -> None:
        import threading
        self._mu = threading.Lock()
        self.bad_shares: Dict[int, int] = {}
        self.deferred_cert_failures: Dict[int, int] = {}

    def bad_share(self, origin: int) -> None:
        with self._mu:
            self.bad_shares[origin] = self.bad_shares.get(origin, 0) + 1

    def deferred_cert_failure(self, origin: int) -> None:
        with self._mu:
            self.deferred_cert_failures[origin] = \
                self.deferred_cert_failures.get(origin, 0) + 1

    def snapshot(self) -> Dict[str, Dict[int, int]]:
        with self._mu:
            return {"bad_shares": dict(self.bad_shares),
                    "deferred_cert_failures":
                        dict(self.deferred_cert_failures)}
