"""Incoming message pipeline: bounded queues + THE dispatcher thread.

Rebuild of the reference's IncomingMsgsStorageImp
(/root/reference/bftengine/src/bftengine/IncomingMsgsStorageImp.hpp:32,
maxNumberOfPendingExternalMsgs_=20000 :64) + MsgHandlersRegistrator
(MsgHandlersRegistrator.hpp:48) + MsgsCommunicator (MsgsCommunicator.cpp:41).

All protocol state is mutated only on the single dispatcher thread;
transports and crypto workers communicate with it exclusively through
these queues. Internal messages (collector results, timer ticks) bypass
the external bound and have priority, as in the reference.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from tpubft.utils.logging import get_logger, set_mdc
from tpubft.utils.racecheck import make_condition

log = get_logger("dispatch")

MAX_EXTERNAL_PENDING = 20000


@dataclass
class ExternalMsg:
    sender: int
    raw: bytes


@dataclass
class InternalMsg:
    """Result of background work re-entering the main loop (reference
    CombinedSigSucceeded/Failed internal msgs)."""
    kind: str
    payload: Any


class IncomingMsgsStorage:
    """Bounded external + unbounded internal deques under ONE
    racecheck-registered Condition (`incoming.cv`): every producer —
    transport receive threads, admission workers, the execution lane's
    completed-run wakeups — and the dispatcher's pop ride the same lock,
    so under TPUBFT_THREADCHECK the queue's ordering edges and hold
    times are visible to the runtime lock-order graph (queue.Queue's
    internal Conditions never were)."""

    def __init__(self, max_external: int = MAX_EXTERNAL_PENDING):
        self._cv = make_condition("incoming.cv")
        self._external: "deque[ExternalMsg]" = deque()
        self._internal: "deque[InternalMsg]" = deque()
        self._max_external = max_external
        self._dropped_external = 0
        # level-triggered wakeup kinds currently enqueued (see
        # push_internal_once)
        self._once_pending: set = set()

    def push_external(self, sender: int, raw: bytes) -> bool:
        return self.push_external_obj(ExternalMsg(sender, raw))

    def push_external_obj(self, obj) -> bool:
        """Bounded external-queue entry shared by the raw path and the
        admission plane (already-parsed, already-verified AdmittedMsgs
        ride the same queue and the same drop accounting)."""
        with self._cv:
            if len(self._external) >= self._max_external:
                self._dropped_external += 1
                return False
            self._external.append(obj)
            self._cv.notify()
        return True

    def push_internal(self, kind: str, payload: Any = None) -> None:
        with self._cv:
            self._internal.append(InternalMsg(kind, payload))
            self._cv.notify()

    def push_internal_once(self, kind: str) -> None:
        """Level-triggered wakeup: enqueue `kind` (payload None) unless an
        identical wakeup is already pending. Background producers whose
        results live in their own handoff structure (e.g. the execution
        lane's completed-run queue) signal with this so a fast producer
        can't flood the internal queue with redundant wakeups."""
        with self._cv:
            if kind in self._once_pending:
                return
            self._once_pending.add(kind)
            self._internal.append(InternalMsg(kind, None))
            self._cv.notify()

    def pop(self, timeout: float):
        """Internal msgs first (they unblock consensus progress), then
        external; returns ExternalMsg | InternalMsg | None on timeout.
        Single consumer (the dispatcher); a spurious wakeup reads as a
        timeout, which the dispatch loop already tolerates."""
        with self._cv:
            if not self._internal and not self._external:
                self._cv.wait(timeout)
            if self._internal:
                item = self._internal.popleft()
                self._once_pending.discard(item.kind)
                return item
            if self._external:
                return self._external.popleft()
            return None

    @property
    def external_depth(self) -> int:
        return len(self._external)        # racy read is fine for a gauge

    @property
    def internal_depth(self) -> int:
        return len(self._internal)

    @property
    def dropped_external(self) -> int:
        return self._dropped_external


class Dispatcher:
    """The single consensus thread: pops queues, dispatches to registered
    handlers, fires periodic timers between messages."""

    def __init__(self, storage: IncomingMsgsStorage, name: str = "dispatch",
                 thread_mdc: Optional[Dict[str, Any]] = None):
        self._storage = storage
        self._external_handler: Optional[Callable[[int, bytes], None]] = None
        self._admitted_handler: Optional[Callable[[Any], None]] = None
        self._internal_handlers: Dict[str, Callable[[Any], None]] = {}
        self._timers = []  # (period_s, callback, next_due)
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._name = name
        # sticky MDC pinned on the dispatcher thread (e.g. replica id) so
        # every log line from protocol handlers is attributable
        self._thread_mdc = thread_mdc or {}
        # runs at the end of every loop iteration (message + due timers):
        # the transport's batched-send flush point
        self._post_hook: Optional[Callable[[], None]] = None
        # external-path items handled (raw + admitted), read by benches
        # and tests as a drain marker — dispatcher-thread writes only
        self.handled_external = 0

    def set_post_hook(self, fn: Callable[[], None]) -> None:
        self._post_hook = fn

    def set_external_handler(self, fn: Callable[[int, bytes], None]) -> None:
        self._external_handler = fn

    def set_admitted_handler(self, fn: Callable[[Any], None]) -> None:
        """Handler for AdmittedMsg objects (pre-parsed, pre-verified by
        the admission plane); anything on the external queue that is not
        a raw ExternalMsg routes here."""
        self._admitted_handler = fn

    def register_internal(self, kind: str, fn: Callable[[Any], None]) -> None:
        self._internal_handlers[kind] = fn

    def add_timer(self, period_s: float, fn: Callable[[], None]) -> None:
        self._timers.append([period_s, fn, time.monotonic() + period_s])

    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=self._name)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        from tpubft.utils.racecheck import get_watchdog
        get_watchdog().unregister(self._name)

    def _loop(self) -> None:
        import os
        prof_dir = os.environ.get("TPUBFT_PROFILE_DIR")
        if prof_dir:
            # saturation profiling of THE consensus thread (where all
            # protocol state mutates): dump pstats when the loop exits —
            # pair with the SIGTERM handler in apps that enables a clean
            # stop (see skvbc_replica.main)
            import cProfile
            prof = cProfile.Profile()
            try:
                prof.runcall(self._loop_body)
            finally:
                prof.dump_stats(os.path.join(
                    prof_dir, f"{self._name}-{os.getpid()}.pstats"))
        else:
            self._loop_body()

    def _loop_body(self) -> None:
        set_mdc(**self._thread_mdc)
        # flight-recorder attribution: this thread's events carry the
        # replica id (from the same MDC that labels its log lines)
        from tpubft.utils import flight
        try:
            flight.set_thread_rid(int(self._thread_mdc.get("r", -1)))
        except (TypeError, ValueError):
            pass
        # liveness heartbeat: a wedged dispatcher (deadlock, hung handler)
        # gets a full-process stack dump from the watchdog (§5.2 role)
        from tpubft.utils.racecheck import get_watchdog
        watchdog = get_watchdog()
        while self._running:
            watchdog.beat(self._name)
            now = time.monotonic()
            next_due = min((t[2] for t in self._timers), default=now + 0.05)
            timeout = max(0.0, min(next_due - now, 0.05))
            item = self._storage.pop(timeout)
            if item is not None:
                try:
                    if isinstance(item, ExternalMsg):
                        self.handled_external += 1
                        if self._external_handler is not None:
                            self._external_handler(item.sender, item.raw)
                    elif isinstance(item, InternalMsg):
                        fn = self._internal_handlers.get(item.kind)
                        if fn is not None:
                            fn(item.payload)
                    else:
                        # AdmittedMsg from the admission plane: already
                        # parsed + verified, the handler only mutates
                        # protocol state
                        self.handled_external += 1
                        if self._admitted_handler is not None:
                            self._admitted_handler(item)
                except Exception:  # noqa: BLE001 — a bad msg must not kill
                    log.exception("handler raised (msg dropped)")
            now = time.monotonic()
            for t in self._timers:
                if now >= t[2]:
                    t[2] = now + t[0]
                    try:
                        t[1]()
                    except Exception:  # noqa: BLE001
                        log.exception("timer callback raised")
            if self._post_hook is not None:
                try:
                    self._post_hook()
                except Exception:  # noqa: BLE001
                    log.exception("post hook raised")
