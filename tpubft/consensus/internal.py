"""Internal BFT client + the consensus-internal operations riding it.

Rebuild of the reference's InternalBFTClient
(/root/reference/bftengine/src/bftengine/InternalBFTClient.cpp) and the
subsystems that submit requests through it: KeyExchangeManager
(KeyExchangeManager.cpp — rotates a replica's signing key via an ordered,
self-signed request) and the TimeServiceManager
(TimeServiceManager.hpp — primary-stamped, replica-validated, consensus-
agreed monotonic clock persisted in a reserved page).

Every replica owns one internal client principal (id =
first_client_id + num_clients + replica_id); its requests are signed with
the replica's key and executed by the replica engine itself rather than
the application handler.
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from tpubft.consensus import messages as m
from tpubft.consensus.reserved_pages import ReservedPagesClient
from tpubft.crypto.cpu import Ed25519Signer
from tpubft.utils import serialize as ser


# ---------------- internal operation envelope ----------------

@dataclass
class KeyExchangeOp:
    """Replica `replica_id` announces a new signing public key."""
    ID = 1
    replica_id: int = 0
    pubkey: bytes = b""
    generation: int = 0
    SPEC = [("replica_id", "u32"), ("pubkey", "bytes"),
            ("generation", "u64")]


@dataclass
class TickOp:
    """Deterministic cron tick for one component (ccron TickInternalMsg)."""
    ID = 2
    component: str = ""
    tick_seq: int = 0
    SPEC = [("component", "str"), ("tick_seq", "u64")]


_OPS = {cls.ID: cls for cls in (KeyExchangeOp, TickOp)}


def pack_op(op) -> bytes:
    return bytes([op.ID]) + ser.encode_msg(op)


def unpack_op(data: bytes):
    if not data or data[0] not in _OPS:
        raise ser.SerializeError(f"unknown internal op {data[:1]!r}")
    return ser.decode_msg(data[1:], _OPS[data[0]])


# ---------------- internal client ----------------

class InternalBFTClient:
    """Lets the replica submit requests into its own consensus
    (key exchange, cron ticks, reconfiguration)."""

    RETRANSMIT_PERIOD_S = 1.0
    MAX_RETRANSMITS = 30

    def __init__(self, replica) -> None:
        self._replica = replica
        self.client_id = replica.info.internal_client_of(replica.id)
        # req seqnums must survive restarts (at-most-once filtering);
        # wall-clock ms + in-process counter is monotonic enough
        self._req_seq = int(time.time() * 1000)
        self._pending: Dict[int, tuple] = {}  # req_seq -> (raw, sent, tries)
        replica.dispatcher.add_timer(self.RETRANSMIT_PERIOD_S,
                                     self._retransmit_pending)

    def submit(self, payload: bytes,
               flags: int = int(m.RequestFlag.INTERNAL)) -> int:
        self._req_seq += 1
        req = m.ClientRequestMsg(
            sender_id=self.client_id, req_seq_num=self._req_seq,
            flags=flags | int(m.RequestFlag.INTERNAL), request=payload,
            cid=f"int-{self._replica.id}-{self._req_seq}", signature=b"")
        req.signature = self._replica.sig.sign(req.signed_payload())
        raw = req.pack()
        self._pending[self._req_seq] = (raw, time.monotonic(), 0)
        self._broadcast(raw)
        return self._req_seq

    def _broadcast(self, raw: bytes) -> None:
        for r in self._replica.info.other_replicas(self._replica.id):
            self._replica.comm.send(r, raw)
        # self-delivery through the normal external queue
        self._replica.incoming.push_external(self.client_id, raw)

    def _retransmit_pending(self) -> None:
        """Internal requests are not fire-and-forget: keep resending until
        ordered+executed (a one-shot key exchange lost at startup would
        otherwise never happen)."""
        now = time.monotonic()
        clients = self._replica.clients
        for seq in sorted(self._pending):
            raw, sent, tries = self._pending[seq]
            if (clients.was_executed(self.client_id, seq)
                    or tries >= self.MAX_RETRANSMITS):
                del self._pending[seq]
                continue
            if now - sent >= self.RETRANSMIT_PERIOD_S:
                self._pending[seq] = (raw, now, tries + 1)
                self._broadcast(raw)


# ---------------- key exchange ----------------

class KeyExchangeManager:
    """Orders a replica's new signing key through consensus and swaps it
    on execution; exchanged keys persist in reserved pages so state-
    transferred replicas adopt them (reference KeyExchangeManager +
    ClientsPubKeysStore roles)."""

    CATEGORY = "keyex"

    def __init__(self, replica, pages: ReservedPagesClient) -> None:
        self._replica = replica
        self._pages = pages
        self._candidates: Dict[int, Ed25519Signer] = {}  # generation -> key
        self._generation = 0

    def initiate(self) -> int:
        """Generate a candidate key and submit the exchange op
        (sendInitialKey / sendKeyExchange). The rotated-in key keeps the
        cluster's replica signature scheme — verifiers derive theirs from
        it per principal."""
        from tpubft.crypto.cpu import make_signer
        signer = make_signer(self._replica.keys.replica_sig_scheme,
                             seed=os.urandom(32))
        self._generation += 1
        self._candidates[self._generation] = signer
        op = KeyExchangeOp(replica_id=self._replica.id,
                           pubkey=signer.public_bytes(),
                           generation=self._generation)
        self._replica.internal_client.submit(
            pack_op(op), flags=int(m.RequestFlag.KEY_EXCHANGE))
        return self._generation

    def on_executed(self, op: KeyExchangeOp, seq: int = 0) -> None:
        """Ordered on every replica: swap the principal's public key; the
        owner additionally activates its private candidate. `seq` is the
        consensus seqnum the exchange executed at — it scopes the old
        key's grace window (SigManager seq-scoped grace)."""
        from tpubft.utils.logging import get_logger
        get_logger("keyexchange").info(
            "key rotation executed for replica %d at seq %d",
            op.replica_id, seq)
        self._replica.sig.set_replica_key(op.replica_id, op.pubkey,
                                          rotation_seq=seq)
        self._pages.save(op.pubkey, index=op.replica_id)
        if op.replica_id == self._replica.id:
            cand = self._candidates.pop(op.generation, None)
            if cand is not None and cand.public_bytes() == op.pubkey:
                self._replica.sig.set_my_signer(cand)

    def load_from_pages(self) -> None:
        """Startup / post-state-transfer: adopt previously exchanged keys."""
        for r in self._replica.info.replica_ids:
            pk = self._pages.load(index=r)
            if pk:
                self._replica.sig.set_replica_key(r, pk)


# ---------------- time service ----------------

class TimeServiceManager:
    """Consensus-agreed monotonic clock (reference TimeServiceManager +
    TimeServiceResPageClient): the primary stamps each PrePrepare; backups
    bound it against their clock; execution advances the agreed time."""

    CATEGORY = "time"

    # opinions older than this are stale (their holder may be dead; the
    # estimate would drift with the receipt-age extrapolation)
    OPINION_TTL_S = 10.0

    def __init__(self, pages: ReservedPagesClient,
                 max_skew_ms: int = 1000,
                 clock: Callable[[], float] = time.time,
                 mono: Callable[[], float] = time.monotonic) -> None:
        self._pages = pages
        self._clock = clock
        self._mono = mono
        self.max_skew_ms = max_skew_ms
        raw = pages.load()
        self.last_agreed_ms = int.from_bytes(raw, "big") if raw else 0
        self._last_stamp = 0
        # replica time voting: peer id -> (claimed t_ms, receipt mono).
        # quorum = 2f+1 clocks incl. our own: the median of >= 2f+1
        # samples with at most f faulty is BRACKETED by honest clocks —
        # f+1 would let f fresh faulty opinions plus our own clock put
        # the median entirely under attacker control.
        self.opinions: dict = {}
        self.opinion_quorum = 0         # set by the replica (2f+1); 0=off

    def add_opinion(self, replica_id: int, t_ms: int) -> bool:
        """Record a peer's clock reading. Rejects non-monotone values —
        a replayed old (still validly signed) opinion must not replace a
        newer one, or a single faulty replica could re-send the cluster's
        hour-old opinions and drag the median arbitrarily into the past —
        and implausible ones (farther from our clock than any envelope
        could tolerate; such a clock can never contribute a useful vote,
        but unbounded it could steer the median)."""
        prev = self.opinions.get(replica_id)
        if prev is not None and t_ms <= prev[0]:
            return False
        plaus = 10 * self.max_skew_ms + int(self.OPINION_TTL_S * 1000)
        if abs(t_ms - int(self._clock() * 1000)) > plaus:
            return False
        self.opinions[replica_id] = (t_ms, self._mono())
        return True

    def envelope_median_ms(self) -> Optional[int]:
        """The cluster's agreed 'now': median of fresh peer opinions
        (each extrapolated by its receipt age) plus our own clock. None
        until opinion_quorum distinct clocks are represented."""
        if self.opinion_quorum <= 0:
            return None
        now_mono = self._mono()
        estimates = [int(self._clock() * 1000)]
        for t_ms, at in self.opinions.values():
            age = now_mono - at
            if age <= self.OPINION_TTL_S:
                estimates.append(t_ms + int(age * 1000))
        if len(estimates) < self.opinion_quorum:
            return None
        estimates.sort()
        return estimates[len(estimates) // 2]

    def primary_stamp(self) -> int:
        """Strictly increasing across PIPELINED proposals too — two
        PrePrepares stamped in the same millisecond would make backups
        that executed the first reject the second forever."""
        self._last_stamp = max(int(self._clock() * 1000),
                               self.last_agreed_ms + 1,
                               self._last_stamp + 1)
        return self._last_stamp

    def validate(self, t_ms: int) -> bool:
        if t_ms <= self.last_agreed_ms:
            return False
        if t_ms > int(self._clock() * 1000) + self.max_skew_ms:
            return False
        # voting envelope: with f+1 clocks represented, the primary's
        # stamp must also sit within the median's skew bound — a primary
        # whose clock races ahead of the cluster is rejected even by a
        # backup whose own clock races with it
        median = self.envelope_median_ms()
        if median is not None and abs(t_ms - median) > self.max_skew_ms:
            return False
        return True

    def on_executed(self, t_ms: int) -> None:
        if t_ms > self.last_agreed_ms:
            self.last_agreed_ms = t_ms
            self._pages.save(t_ms.to_bytes(8, "big"))

    def reload(self) -> None:
        raw = self._pages.load()
        if raw:
            self.last_agreed_ms = max(self.last_agreed_ms,
                                      int.from_bytes(raw, "big"))
