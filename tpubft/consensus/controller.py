"""Commit-path controller: adaptive fast/slow path selection.

Rebuild of the reference's ControllerWithSimpleHistory
(/root/reference/bftengine/src/bftengine/ControllerWithSimpleHistory.cpp):
the primary evaluates, per window of sequence numbers, whether the fast
path is completing; repeated fast-path failures demote new PrePrepares to
a slower path, sustained success upgrades back. Also owns the
fast-path-timeout decision that triggers StartSlowCommit for an in-flight
seqnum (reference ReplicaImp's commit-path timer).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

from tpubft.consensus.messages import CommitPath

EVALUATION_WINDOW = 16          # reference EvaluationPeriod
DOWNGRADE_FAILURE_RATIO = 0.3   # >30% slow fallbacks in a window: demote
UPGRADE_SUCCESS_RATIO = 0.9     # >=90% fast success while demoted: promote


@dataclass
class PathStats:
    fast_completions: int = 0
    slow_fallbacks: int = 0

    @property
    def total(self) -> int:
        return self.fast_completions + self.slow_fallbacks


class CommitPathController:
    def __init__(self, f: int, c: int, start_path: CommitPath = None):
        self._f = f
        self._c = c
        # reference default: OPTIMISTIC_FAST when c == 0 (all n replicas
        # expected), FAST_WITH_THRESHOLD when c > 0
        if start_path is None:
            start_path = (CommitPath.OPTIMISTIC_FAST if c == 0
                          else CommitPath.FAST_WITH_THRESHOLD)
        self._current = start_path
        self._stats = PathStats()
        self._slow_probe = 0

    @property
    def current_path(self) -> CommitPath:
        return self._current

    def on_fast_path_commit(self, seq_num: int) -> None:
        """A seqnum proposed on a fast path committed via its fast path."""
        self._stats.fast_completions += 1
        self._maybe_adapt()

    def on_slow_fallback(self, seq_num: int) -> None:
        """A seqnum proposed on a fast path had to commit via slow."""
        self._stats.slow_fallbacks += 1
        self._maybe_adapt()

    def on_slow_path_commit(self, seq_num: int) -> None:
        """A seqnum proposed as SLOW committed. After a full window of
        stability, probe one step faster (the reference periodically
        retries the faster path rather than staying demoted forever)."""
        if self._current is not CommitPath.SLOW:
            return
        self._slow_probe += 1
        if self._slow_probe >= EVALUATION_WINDOW:
            self._slow_probe = 0
            self._current = self._next_faster(self._current)
            self._stats = PathStats()

    def _maybe_adapt(self) -> None:
        if self._stats.total < EVALUATION_WINDOW:
            return
        failure_ratio = self._stats.slow_fallbacks / self._stats.total
        if self._current != CommitPath.SLOW \
                and failure_ratio > DOWNGRADE_FAILURE_RATIO:
            self._current = self._next_slower(self._current)
        elif self._current != self._fastest() \
                and (1 - failure_ratio) >= UPGRADE_SUCCESS_RATIO:
            self._current = self._next_faster(self._current)
        self._stats = PathStats()

    def _fastest(self) -> CommitPath:
        return (CommitPath.OPTIMISTIC_FAST if self._c == 0
                else CommitPath.FAST_WITH_THRESHOLD)

    @staticmethod
    def _next_slower(p: CommitPath) -> CommitPath:
        return CommitPath(min(int(p) + 1, int(CommitPath.SLOW)))

    @staticmethod
    def _next_faster(p: CommitPath) -> CommitPath:
        return CommitPath(max(int(p) - 1, int(CommitPath.OPTIMISTIC_FAST)))
