"""ControlStateManager — wedge/stop coordination for upgrades & reconfig.

Rebuild of the reference's ControlStateManager / EpochManager
(/root/reference/bftengine/include/bftengine/EpochManager.hpp,
IControlHandler Replica.hpp:68): an ordered wedge command sets a stop
sequence; once execution reaches it the replica refuses to order beyond,
holding the whole cluster at an agreed point so operators can upgrade or
re-scale. The wedge point rides a reserved page, surviving crashes and
state transfer.
"""
from __future__ import annotations

from typing import Optional

from tpubft.consensus.reserved_pages import ReservedPagesClient


class ControlStateManager:
    CATEGORY = "control"

    def __init__(self, pages: ReservedPagesClient) -> None:
        self._pages = pages
        self.wedge_point: Optional[int] = None
        self.restart_ready = False
        # 2f+c+1 replicas announced ReplicaRestartReadyMsg at the wedge
        # point — the operator's wrapper may safely restart the cluster
        self.restart_proof = False
        self.reload()

    def reload(self) -> None:
        raw = self._pages.load()
        self.wedge_point = (int.from_bytes(raw, "big")
                            if raw else None)

    def set_wedge_point(self, seq: int) -> None:
        self.wedge_point = seq
        self._pages.save(seq.to_bytes(8, "big"))

    def unwedge(self) -> None:
        self.wedge_point = None
        self.restart_ready = False
        self.restart_proof = False
        self._pages.delete()

    def blocks_ordering(self, seq: int) -> bool:
        """True if ordering `seq` would cross the wedge point."""
        return self.wedge_point is not None and seq > self.wedge_point

    def is_wedged(self, last_executed: int) -> bool:
        return self.wedge_point is not None \
            and last_executed >= self.wedge_point

    def mark_restart_ready(self) -> None:
        self.restart_ready = True

    def status(self) -> str:
        return (f"wedge_point={self.wedge_point} "
                f"restart_ready={self.restart_ready} "
                f"restart_proof={self.restart_proof}")
