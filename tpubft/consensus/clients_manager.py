"""Per-client bookkeeping: pending requests + reply cache.

Rebuild of the reference's ClientsManager
(/root/reference/bftengine/src/bftengine/ClientsManager.cpp): tracks the
highest executed request seqnum per client (for at-most-once execution),
the pending (not yet committed) request, and caches the last reply so a
retransmitted request gets the cached answer instead of re-execution.
"""
from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, Optional

from tpubft.consensus.messages import ClientBatchRequestMsg, ClientReplyMsg
from tpubft.utils.racecheck import make_lock

# replies kept per client for retransmission recovery. Must cover a full
# client batch PLUS interleaved single writes: every element of an
# executed batch has to stay regenerable until the client stops
# retransmitting it (reference keeps per-request reply slots in reserved
# pages, bounded by the client batching limit). The client enforces one
# outstanding batch per principal (bftclient._batch_lock), so 2× the
# batch bound covers a retransmitting batch alongside a full batch's
# worth of other traffic from the same principal.
REPLY_CACHE_PER_CLIENT = 2 * ClientBatchRequestMsg.MAX_BATCH


# in-flight (admitted, not yet executed) requests tracked per client.
# Multiple pending seqs are first-class (reference ClientsManager
# requestsInfo map, bounded by maxNumOfRequestsInBatch): a batch's 64
# elements plus interleaved singles may all be in flight, and they can
# ARRIVE out of seq order (a later-allocated single can beat a batch to
# the primary), so membership — not ordering — is the dedup test.
MAX_PENDING_PER_CLIENT = 2 * ClientBatchRequestMsg.MAX_BATCH


@dataclass
class _ClientInfo:
    # newest executed seq. NOT a dedup watermark (see replies below) — it
    # exists only to seal the post-restore floor: reserved pages persist a
    # bounded reply ring, so after a restart/state transfer anything at or
    # below this that is absent from the ring may have executed and been
    # forgotten, and must be refused (seal_restore).
    last_executed_req: int = -1
    # req_seq -> reply (None = executed with oversize/absent reply). This
    # map IS the at-most-once record: requests execute out of seq order
    # (multi-pending + pre-exec sessions complete independently), so dedup
    # is membership here, never a seqnum watermark (reference
    # ClientsManager.cpp:455 canBecomePending checks requestsInfo/
    # repliesInfo membership for the same reason).
    replies: "OrderedDict[int, Optional[ClientReplyMsg]]" = field(
        default_factory=OrderedDict)
    # highest req_seq ever evicted from the bounded replies map: a seq at
    # or below this may have executed and been forgotten, so it must be
    # refused (can't prove it isn't a replay). Only eviction — never
    # execution — advances this.
    evicted_high: int = -1
    pending: "OrderedDict[int, str]" = field(
        default_factory=OrderedDict)      # req_seq -> cid


class ClientsManager:
    """Admission runs on the dispatcher thread; execution results arrive
    from the execution lane's thread — the compound read-modify-write
    paths (admission check vs. reply-cache eviction) are guarded by one
    small lock (instrumented under TPUBFT_THREADCHECK)."""

    def __init__(self, client_ids) -> None:
        self._clients: Dict[int, _ClientInfo] = {c: _ClientInfo()
                                                 for c in client_ids}
        self._mu = make_lock("clients_manager")

    def is_valid_client(self, client_id: int) -> bool:
        return client_id in self._clients

    # ---- request admission (primary + all replicas) ----
    def can_become_pending(self, client_id: int, req_seq: int) -> bool:
        info = self._clients.get(client_id)
        if info is None:
            return False
        with self._mu:
            if self._executed(info, req_seq):
                return False                   # already executed (dup)
            if req_seq in info.pending:
                return False                   # already in flight
            if len(info.pending) >= MAX_PENDING_PER_CLIENT:
                return False                   # per-client flood bound
            return True

    @staticmethod
    def _executed(info: _ClientInfo, req_seq: int) -> bool:
        return req_seq in info.replies or req_seq <= info.evicted_high

    def was_executed(self, client_id: int, req_seq: int) -> bool:
        """At-most-once membership test: True if this request executed (or
        its record aged out of the bounded cache, which must be treated as
        executed). A lower seq than the newest execution is NOT evidence
        of a dup — requests complete out of order."""
        info = self._clients.get(client_id)
        if info is None:
            return False
        with self._mu:
            return self._executed(info, req_seq)

    def add_pending(self, client_id: int, req_seq: int, cid: str = "") -> None:
        with self._mu:
            self._clients[client_id].pending[req_seq] = cid

    def has_pending(self, client_id: int) -> bool:
        return bool(self._clients[client_id].pending)

    # ---- execution results ----
    def on_request_executed(self, client_id: int, req_seq: int,
                            reply: Optional[ClientReplyMsg]) -> None:
        info = self._clients.get(client_id)
        if info is None:
            return
        with self._mu:
            if req_seq > info.last_executed_req:
                info.last_executed_req = req_seq
            info.replies[req_seq] = reply
            while len(info.replies) > REPLY_CACHE_PER_CLIENT:
                seq, _ = info.replies.popitem(last=False)  # evict oldest
                if seq > info.evicted_high:
                    info.evicted_high = seq
            info.pending.pop(req_seq, None)

    def note_executed(self, client_id: int, req_seq: int) -> None:
        """Record execution without a cached reply (oversize reply marker
        loaded from reserved pages). Keeps a None entry in the replies map
        so the at-most-once membership test still covers the request."""
        self.on_request_executed(client_id, req_seq, None)

    def cached_reply(self, client_id: int,
                     req_seq: int) -> Optional[ClientReplyMsg]:
        """Reply for a retransmitted already-executed request (reference
        stores per-request reply slots in reserved pages; we keep a
        bounded per-client map so every element of an executed batch
        stays regenerable, not just the newest request). None for both
        never-executed and oversize-reply entries."""
        info = self._clients.get(client_id)
        if info is None:
            return None
        with self._mu:
            return info.replies.get(req_seq)

    def seal_restore(self, client_id: int) -> None:
        """Call after seeding this client from reserved pages (restart or
        completed state transfer): the persisted reply ring is bounded, so
        any seq at or below the persisted newest-executed watermark that
        did not make it back into the ring may have executed and been
        evicted — refuse it. Without this seal, a restart would reopen the
        at-most-once window for old validly-signed requests."""
        info = self._clients.get(client_id)
        if info is not None and info.last_executed_req > info.evicted_high:
            info.evicted_high = info.last_executed_req

    def clear_pending(self) -> None:
        """View change: in-flight requests are abandoned; clients will
        retransmit and the new primary re-admits them."""
        with self._mu:
            for info in self._clients.values():
                info.pending.clear()
