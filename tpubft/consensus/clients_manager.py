"""Per-client bookkeeping: pending requests + reply cache.

Rebuild of the reference's ClientsManager
(/root/reference/bftengine/src/bftengine/ClientsManager.cpp): tracks the
highest executed request seqnum per client (for at-most-once execution),
the pending (not yet committed) request, and caches the last reply so a
retransmitted request gets the cached answer instead of re-execution.

Million-principal shape: resident state is a bounded LRU over the
reserved-pages machinery. `max_resident` caps how many `_ClientInfo`
records stay in memory; a cold client's record is demand-paged back from
its reply-ring pages through the `pager` callback (the replica wires
`Replica._page_in_client`, which replays the same restore rule as a
restart: ring membership + the oversize marker, sealed with the
evict/reload floor). Eviction never loses at-most-once state because the
reply ring IS the canonical record — execution persists every reply page
before the in-memory table learns about it — so evict→reload is
indistinguishable from a crash→restart for that one client, the
semantics every restore test already pins down. Clients with in-flight
(pending) requests are pinned resident: pending is memory-only state,
and an active client is by definition hot.
"""
from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Optional

from tpubft.consensus.messages import ClientBatchRequestMsg, ClientReplyMsg
from tpubft.utils.racecheck import make_lock

# replies kept per client for retransmission recovery. Must cover a full
# client batch PLUS interleaved single writes: every element of an
# executed batch has to stay regenerable until the client stops
# retransmitting it (reference keeps per-request reply slots in reserved
# pages, bounded by the client batching limit). The client enforces one
# outstanding batch per principal (bftclient._batch_lock), so 2× the
# batch bound covers a retransmitting batch alongside a full batch's
# worth of other traffic from the same principal.
REPLY_CACHE_PER_CLIENT = 2 * ClientBatchRequestMsg.MAX_BATCH


# in-flight (admitted, not yet executed) requests tracked per client.
# Multiple pending seqs are first-class (reference ClientsManager
# requestsInfo map, bounded by maxNumOfRequestsInBatch): a batch's 64
# elements plus interleaved singles may all be in flight, and they can
# ARRIVE out of seq order (a later-allocated single can beat a batch to
# the primary), so membership — not ordering — is the dedup test.
MAX_PENDING_PER_CLIENT = 2 * ClientBatchRequestMsg.MAX_BATCH

# how many LRU candidates one insert will pass over looking for an
# evictable (pending-free) record before letting the table temporarily
# exceed its bound — an O(1) cap so a burst of active clients degrades
# to a slightly-over-budget table, never an O(resident) scan per insert
_EVICT_SCAN_MAX = 8


@dataclass
class _ClientInfo:
    # newest executed seq. NOT a dedup watermark (see replies below) — it
    # exists only to seal the post-restore floor: reserved pages persist a
    # bounded reply ring, so after a restart/state transfer anything at or
    # below this that is absent from the ring may have executed and been
    # forgotten, and must be refused (seal_restore).
    last_executed_req: int = -1
    # req_seq -> reply (None = executed with oversize/absent reply). This
    # map IS the at-most-once record: requests execute out of seq order
    # (multi-pending + pre-exec sessions complete independently), so dedup
    # is membership here, never a seqnum watermark (reference
    # ClientsManager.cpp:455 canBecomePending checks requestsInfo/
    # repliesInfo membership for the same reason).
    replies: "OrderedDict[int, Optional[ClientReplyMsg]]" = field(
        default_factory=OrderedDict)
    # highest req_seq ever evicted from the bounded replies map: a seq at
    # or below this may have executed and been forgotten, so it must be
    # refused (can't prove it isn't a replay). Only eviction — never
    # execution — advances this.
    evicted_high: int = -1
    pending: "OrderedDict[int, str]" = field(
        default_factory=OrderedDict)      # req_seq -> cid


class ClientsManager:
    """Admission runs on the dispatcher thread; execution results arrive
    from the execution lane's thread — the compound read-modify-write
    paths (admission check vs. reply-cache eviction) are guarded by one
    small lock (instrumented under TPUBFT_THREADCHECK)."""

    def __init__(self, client_ids, max_resident: int = 0,
                 pager: Optional[Callable[[int], _ClientInfo]] = None
                 ) -> None:
        # the id universe: a `range` for production topologies (contiguous
        # by construction — ReplicasInfo.all_client_ids — so membership is
        # O(1) with O(1) memory even at 1M principals), any container with
        # `in` otherwise (unit tests pass small lists)
        self._universe = client_ids if isinstance(client_ids, range) \
            else frozenset(client_ids)
        # 0 = unbounded: every touched client stays resident (the legacy
        # test-cluster shape, and the right answer when no pager exists)
        self._max_resident = max_resident if pager is not None else 0
        self._pager = pager
        self._clients: "OrderedDict[int, _ClientInfo]" = OrderedDict()
        if self._pager is None:
            # eager population keeps the legacy O(clients)-resident shape
            # for pager-less tables (unit tests, tiny topologies); a
            # paged table starts empty and demand-pages
            for c in self._universe:
                self._clients[c] = _ClientInfo()
        self._mu = make_lock("clients_manager")
        # table telemetry (racy reads fine — monotone counters)
        self.table_hits = 0
        self.table_misses = 0
        self.table_evictions = 0

    # ---- resident-table mechanics ----
    @property
    def resident_count(self) -> int:
        return len(self._clients)

    @property
    def max_resident(self) -> int:
        return self._max_resident

    def set_max_resident(self, n: int) -> None:
        """Autotuner actuator (client_table_max knob): retune the resident
        bound live; shrinking evicts down on the next inserts rather than
        synchronously (bounded work per operation)."""
        if self._pager is not None:
            self._max_resident = max(0, n)

    def invalidate_all(self) -> None:
        """Drop every pageable resident record (state transfer installed a
        new page set under us — resident state may describe dead pages).
        Pending is memory-only and the caller (view/ST machinery) clears
        it separately; unbounded tables keep their records because no
        pager could rebuild them."""
        if self._pager is None:
            return
        with self._mu:
            self._clients.clear()

    def _resident(self, client_id: int) -> Optional[_ClientInfo]:
        """Resident record for `client_id`, demand-paging it in (and LRU-
        evicting past the bound) as needed. Caller holds self._mu. None
        for ids outside the universe."""
        info = self._clients.get(client_id)
        if info is not None:
            self._clients.move_to_end(client_id)
            self.table_hits += 1
            return info
        if client_id not in self._universe:
            return None
        self.table_misses += 1
        info = self._pager(client_id) if self._pager is not None \
            else _ClientInfo()
        self._clients[client_id] = info
        if self._max_resident:
            scanned = 0
            while len(self._clients) > self._max_resident \
                    and scanned < _EVICT_SCAN_MAX:
                victim, vinfo = next(iter(self._clients.items()))
                scanned += 1
                if vinfo.pending:
                    # pinned: in-flight requests are memory-only state —
                    # rotate it to the MRU end and try the next candidate
                    self._clients.move_to_end(victim)
                    continue
                # safe to drop: every executed reply was persisted to its
                # ring page BEFORE this table learned of it, so the pager
                # rebuilds an equivalent (restart-sealed) record
                del self._clients[victim]
                self.table_evictions += 1
        return info

    def is_valid_client(self, client_id: int) -> bool:
        return client_id in self._universe

    # ---- request admission (primary + all replicas) ----
    def can_become_pending(self, client_id: int, req_seq: int) -> bool:
        with self._mu:
            info = self._resident(client_id)
            if info is None:
                return False
            if self._executed(info, req_seq):
                return False                   # already executed (dup)
            if req_seq in info.pending:
                return False                   # already in flight
            if len(info.pending) >= MAX_PENDING_PER_CLIENT:
                return False                   # per-client flood bound
            return True

    @staticmethod
    def _executed(info: _ClientInfo, req_seq: int) -> bool:
        return req_seq in info.replies or req_seq <= info.evicted_high

    def was_executed(self, client_id: int, req_seq: int) -> bool:
        """At-most-once membership test: True if this request executed (or
        its record aged out of the bounded cache, which must be treated as
        executed). A lower seq than the newest execution is NOT evidence
        of a dup — requests complete out of order."""
        with self._mu:
            info = self._resident(client_id)
            if info is None:
                return False
            return self._executed(info, req_seq)

    def add_pending(self, client_id: int, req_seq: int, cid: str = "") -> None:
        with self._mu:
            info = self._resident(client_id)
            if info is not None:
                info.pending[req_seq] = cid

    def has_pending(self, client_id: int) -> bool:
        # resident-only read: a non-resident client cannot have pending
        # requests (records with pending are pinned against eviction)
        info = self._clients.get(client_id)
        return bool(info is not None and info.pending)

    # ---- execution results ----
    def on_request_executed(self, client_id: int, req_seq: int,
                            reply: Optional[ClientReplyMsg]) -> None:
        with self._mu:
            info = self._resident(client_id)
            if info is None:
                return
            if req_seq > info.last_executed_req:
                info.last_executed_req = req_seq
            info.replies[req_seq] = reply
            while len(info.replies) > REPLY_CACHE_PER_CLIENT:
                seq, _ = info.replies.popitem(last=False)  # evict oldest
                if seq > info.evicted_high:
                    info.evicted_high = seq
            info.pending.pop(req_seq, None)

    def note_executed(self, client_id: int, req_seq: int) -> None:
        """Record execution without a cached reply (oversize reply marker
        loaded from reserved pages). Keeps a None entry in the replies map
        so the at-most-once membership test still covers the request."""
        self.on_request_executed(client_id, req_seq, None)

    def cached_reply(self, client_id: int,
                     req_seq: int) -> Optional[ClientReplyMsg]:
        """Reply for a retransmitted already-executed request (reference
        stores per-request reply slots in reserved pages; we keep a
        bounded per-client map so every element of an executed batch
        stays regenerable, not just the newest request). None for both
        never-executed and oversize-reply entries."""
        with self._mu:
            info = self._resident(client_id)
            if info is None:
                return None
            return info.replies.get(req_seq)

    def seal_restore(self, client_id: int) -> None:
        """Call after seeding this client from reserved pages (restart or
        completed state transfer): the persisted reply ring is bounded, so
        any seq at or below the persisted newest-executed watermark that
        did not make it back into the ring may have executed and been
        evicted — refuse it. Without this seal, a restart would reopen the
        at-most-once window for old validly-signed requests. The demand
        pager applies the same seal to every record it rebuilds (an
        evict/reload cycle is a single-client restart)."""
        info = self._clients.get(client_id)
        if info is not None and info.last_executed_req > info.evicted_high:
            info.evicted_high = info.last_executed_req

    def clear_pending(self) -> None:
        """View change: in-flight requests are abandoned; clients will
        retransmit and the new primary re-admits them."""
        with self._mu:
            for info in self._clients.values():
                info.pending.clear()
