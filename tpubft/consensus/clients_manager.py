"""Per-client bookkeeping: pending requests + reply cache.

Rebuild of the reference's ClientsManager
(/root/reference/bftengine/src/bftengine/ClientsManager.cpp): tracks the
highest executed request seqnum per client (for at-most-once execution),
the pending (not yet committed) request, and caches the last reply so a
retransmitted request gets the cached answer instead of re-execution.
"""
from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, Optional

from tpubft.consensus.messages import ClientBatchRequestMsg, ClientReplyMsg

# replies kept per client for retransmission recovery. Must cover a full
# client batch PLUS interleaved single writes: every element of an
# executed batch has to stay regenerable until the client stops
# retransmitting it (reference keeps per-request reply slots in reserved
# pages, bounded by the client batching limit). The client enforces one
# outstanding batch per principal (bftclient._batch_lock), so 2× the
# batch bound covers a retransmitting batch alongside a full batch's
# worth of other traffic from the same principal.
REPLY_CACHE_PER_CLIENT = 2 * ClientBatchRequestMsg.MAX_BATCH


# in-flight (admitted, not yet executed) requests tracked per client.
# Multiple pending seqs are first-class (reference ClientsManager
# requestsInfo map, bounded by maxNumOfRequestsInBatch): a batch's 64
# elements plus interleaved singles may all be in flight, and they can
# ARRIVE out of seq order (a later-allocated single can beat a batch to
# the primary), so membership — not ordering — is the dedup test.
MAX_PENDING_PER_CLIENT = 2 * ClientBatchRequestMsg.MAX_BATCH


@dataclass
class _ClientInfo:
    last_executed_req: int = -1
    replies: "OrderedDict[int, ClientReplyMsg]" = field(
        default_factory=OrderedDict)
    pending: "OrderedDict[int, str]" = field(
        default_factory=OrderedDict)      # req_seq -> cid


class ClientsManager:
    def __init__(self, client_ids) -> None:
        self._clients: Dict[int, _ClientInfo] = {c: _ClientInfo()
                                                 for c in client_ids}

    def is_valid_client(self, client_id: int) -> bool:
        return client_id in self._clients

    # ---- request admission (primary + all replicas) ----
    def can_become_pending(self, client_id: int, req_seq: int) -> bool:
        info = self._clients.get(client_id)
        if info is None:
            return False
        if req_seq <= info.last_executed_req:
            return False                       # already executed (dup)
        if req_seq in info.pending:
            return False                       # already in flight
        if len(info.pending) >= MAX_PENDING_PER_CLIENT:
            return False                       # per-client flood bound
        return True

    def add_pending(self, client_id: int, req_seq: int, cid: str = "") -> None:
        self._clients[client_id].pending[req_seq] = cid

    def has_pending(self, client_id: int) -> bool:
        return bool(self._clients[client_id].pending)

    # ---- execution results ----
    def on_request_executed(self, client_id: int, req_seq: int,
                            reply: ClientReplyMsg) -> None:
        info = self._clients.get(client_id)
        if info is None:
            return
        if req_seq > info.last_executed_req:
            info.last_executed_req = req_seq
        info.replies[req_seq] = reply
        while len(info.replies) > REPLY_CACHE_PER_CLIENT:
            info.replies.popitem(last=False)     # evict oldest
        info.pending.pop(req_seq, None)

    def note_executed(self, client_id: int, req_seq: int) -> None:
        """Advance at-most-once state without a cached reply (oversize
        reply marker loaded from reserved pages)."""
        info = self._clients.get(client_id)
        if info is None:
            return
        if req_seq > info.last_executed_req:
            info.last_executed_req = req_seq
        info.pending.pop(req_seq, None)

    def cached_reply(self, client_id: int,
                     req_seq: int) -> Optional[ClientReplyMsg]:
        """Reply for a retransmitted already-executed request (reference
        stores per-request reply slots in reserved pages; we keep a
        bounded per-client map so every element of an executed batch
        stays regenerable, not just the newest request)."""
        info = self._clients.get(client_id)
        return info.replies.get(req_seq) if info else None

    def last_executed(self, client_id: int) -> int:
        info = self._clients.get(client_id)
        return info.last_executed_req if info else -1

    def clear_pending(self) -> None:
        """View change: in-flight requests are abandoned; clients will
        retransmit and the new primary re-admits them."""
        for info in self._clients.values():
            info.pending.clear()
