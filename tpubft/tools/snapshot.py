"""tpubft-snapshot — operator CLI for state snapshots.

Rebuild of the reference's snapshot/object-store operator tooling
(kvbc state_snapshot_interface.hpp consumers + object_store_utility):
create a self-verifying snapshot file from a replica DB, inspect its
manifest, verify its integrity, and provision a fresh replica DB from
it — without any cluster running.

Usage:
  python -m tpubft.tools.snapshot create  <db-path> <snapshot-file>
  python -m tpubft.tools.snapshot inspect <snapshot-file>
  python -m tpubft.tools.snapshot verify  <snapshot-file>
  python -m tpubft.tools.snapshot restore <snapshot-file> <new-db-path>
"""
from __future__ import annotations

import argparse
import json
import sys


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("command",
                    choices=("create", "inspect", "verify", "restore"))
    ap.add_argument("source")
    ap.add_argument("target", nargs="?")
    ap.add_argument("--kvbc-version", default="categorized",
                    choices=("categorized", "v4", "v1"))
    args = ap.parse_args()

    from tpubft.kvbc import create_blockchain
    from tpubft.kvbc.replica import open_db
    from tpubft.kvbc.snapshots import (SnapshotError, create_snapshot,
                                       read_manifest, restore_snapshot)

    import os
    try:
        if args.command == "create":
            if not args.target:
                raise SystemExit("create needs <db-path> <snapshot-file>")
            if not os.path.exists(args.source):
                # open_db would CREATE an empty store at a typo'd path
                # and the tool would happily snapshot nothing
                raise SystemExit(f"no such DB: {args.source}")
            db = open_db(args.source)
            bc = create_blockchain(db, version=args.kvbc_version,
                                   use_device_hashing=False)
            man = create_snapshot(db, args.target,
                                  head_block=bc.last_block_id,
                                  state_digest=bc.state_digest())
            print(json.dumps({"created": args.target, **man}))
        elif args.command == "inspect":
            print(json.dumps(read_manifest(args.source)))
        elif args.command == "verify":
            # restore into a throwaway in-memory store: runs the full
            # pass-1 integrity + framing + count validation
            from tpubft.storage.memorydb import MemoryDB
            man = restore_snapshot(args.source, MemoryDB())
            print(json.dumps({"ok": True, **man}))
        elif args.command == "restore":
            if not args.target:
                raise SystemExit(
                    "restore needs <snapshot-file> <new-db-path>")
            if os.path.exists(args.target):
                # restore_snapshot requires an EMPTY target; merging over
                # an existing DB would leave mixed state behind a failed
                # digest check
                raise SystemExit(
                    f"target already exists: {args.target} "
                    "(restore provisions a NEW db)")
            # offline restore: fsync per batch — the provisioned DB must
            # survive a power cut the moment the tool reports success
            db = open_db(args.target, sync_writes=True)
            man = restore_snapshot(args.source, db)
            bc = create_blockchain(db, version=args.kvbc_version,
                                   use_device_hashing=False)
            ok = (man["state_digest"] == bc.state_digest().hex())
            print(json.dumps({"restored": args.target, "digest_ok": ok,
                              **man}))
            if not ok:
                return 1
    except SnapshotError as e:
        print(json.dumps({"ok": False, "error": str(e)}))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
