"""Operator/developer tools (reference /root/reference/tools/:
GenerateConcordKeys, TestGeneratedKeys, DBEditor; diagnostics/concord-ctl).
"""
