"""DB editor — inspect/patch kvlog database files offline.

Rebuild of /root/reference/kvbc/tools/db_editor/: operators poke at a
replica's storage without the replica running.

Usage:
  python -m tpubft.tools.db_editor <db.kvlog> families
  python -m tpubft.tools.db_editor <db.kvlog> scan <family> [limit]
  python -m tpubft.tools.db_editor <db.kvlog> get <family> <key-hex>
  python -m tpubft.tools.db_editor <db.kvlog> put <family> <key-hex> <val-hex>
  python -m tpubft.tools.db_editor <db.kvlog> delete <family> <key-hex>
  python -m tpubft.tools.db_editor <db.kvlog> stats
"""
from __future__ import annotations

import sys
from collections import Counter

from tpubft.storage.interfaces import split_fkey
from tpubft.storage.native import NativeDB


def _families(db: NativeDB):
    counts: Counter = Counter()
    for fam in _all_physical(db):
        counts[fam] += 1
    return counts


def _all_physical(db: NativeDB):
    # scan the whole physical keyspace by iterating family prefixes we see
    out = db._lib  # intentional low-level: whole-space scan
    import ctypes
    from tpubft.storage.native import _U8P, _decode_scan
    buf = _U8P()
    n = ctypes.c_uint32()
    rc = out.kvlog_scan(db._handle(), b"", 0, b"", 0xFFFFFFFF,
                        ctypes.byref(buf), ctypes.byref(n))
    if rc != 0:
        raise SystemExit(f"scan failed rc={rc}")
    try:
        raw = ctypes.string_at(buf, n.value)
    finally:
        out.kvlog_free(buf)
    for k, _v in _decode_scan(raw):
        yield split_fkey(k)[0]


def main() -> int:
    if len(sys.argv) < 3:
        print(__doc__)
        return 2
    path, cmd = sys.argv[1], sys.argv[2]
    db = NativeDB(path)
    try:
        if cmd == "families":
            for fam, count in sorted(_families(db).items()):
                print(f"{fam.decode(errors='replace'):30s} {count}")
        elif cmd == "stats":
            print(f"entries: {db.count()}")
            print(f"families: {len(_families(db))}")
        elif cmd == "scan":
            fam = sys.argv[3].encode()
            limit = int(sys.argv[4]) if len(sys.argv) > 4 else 50
            for i, (k, v) in enumerate(db.range_iter(fam)):
                if i >= limit:
                    print("...")
                    break
                print(f"{k.hex()} = {v.hex()[:96]}"
                      + ("..." if len(v) > 48 else ""))
        elif cmd == "get":
            v = db.get(bytes.fromhex(sys.argv[4]), sys.argv[3].encode())
            print(v.hex() if v is not None else "(not found)")
        elif cmd == "put":
            db.put(bytes.fromhex(sys.argv[4]), bytes.fromhex(sys.argv[5]),
                   sys.argv[3].encode())
            print("ok")
        elif cmd == "delete":
            db.delete(bytes.fromhex(sys.argv[4]), sys.argv[3].encode())
            print("ok")
        else:
            print(__doc__)
            return 2
    finally:
        db.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
