"""Ledger engine migration: v1 / categorized / v4, any direction.

Rebuild of the reference's v4 migration CLI
(/root/reference/kvbc/tools/migrations/v4migration_tool/): replays every
block of a source DB into a destination DB running the other engine,
verifying block-update round-trips as it goes. The chain digests differ
across engines by design (category digests are computed differently), so
the tool re-derives them and reports both heads.

Usage:
  python -m tpubft.tools.migrate_v4 --src DB --dst DB \
      --from categorized --to v4 [--no-verify]
"""
from __future__ import annotations

import argparse
import sys

from tpubft.kvbc import create_blockchain
from tpubft.kvbc import categories as cat


def migrate(src_db, dst_db, src_version: str, dst_version: str,
            verify: bool = True, log=print) -> int:
    src = create_blockchain(src_db, version=src_version,
                            use_device_hashing=False)
    dst = create_blockchain(dst_db, version=dst_version,
                            use_device_hashing=False)
    if dst.last_block_id != 0:
        raise SystemExit("destination DB is not empty")
    first = src.genesis_block_id or 1
    if first > 1:
        raise SystemExit(
            "source chain is pruned below genesis block 1; a migrated "
            "chain must replay from block 1 to reproduce state")
    # bulk replay through add_blocks: one atomic WriteBatch (and, with
    # sync_writes, one fsync) per chunk instead of per block, and the
    # categorized engine hashes each chunk's merkle updates level-wise
    # across all its blocks in one batched call per tree level
    # (SparseMerkleTree.update_batches) instead of per-block host walks
    CHUNK = 64
    migrated = 0
    buf = []
    for bid in range(1, src.last_block_id + 1):
        blk = src.get_block(bid)
        if blk is None:
            raise SystemExit(f"missing source block {bid}")
        buf.append(cat.decode_block_updates(blk.updates_blob))
        if len(buf) == CHUNK:
            head = dst.add_blocks(buf)
            if head != bid:
                raise SystemExit(f"migration desync: dst head {head} "
                                 f"after source block {bid}")
            migrated += len(buf)
            buf = []
            if migrated % 1024 == 0:
                log(f"migrated {migrated} blocks...")
    if buf:
        head = dst.add_blocks(buf)
        if head != src.last_block_id:
            raise SystemExit(f"migration desync: dst head {head} != "
                             f"source head {src.last_block_id}")
        migrated += len(buf)
    if verify:
        for bid in range(1, dst.last_block_id + 1):
            sb, db_ = src.get_block(bid), dst.get_block(bid)
            if sb.updates_blob != db_.updates_blob:
                raise SystemExit(f"updates mismatch at block {bid}")
    log(f"migrated {migrated} blocks "
        f"({src_version} head {src.state_digest().hex()[:16]} -> "
        f"{dst_version} head {dst.state_digest().hex()[:16]})")
    return migrated


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--src", required=True)
    ap.add_argument("--dst", required=True)
    ap.add_argument("--from", dest="src_version", default="categorized")
    ap.add_argument("--to", dest="dst_version", default="v4")
    ap.add_argument("--no-verify", dest="verify", action="store_false",
                    default=True,
                    help="skip the full second read-and-compare pass")
    args = ap.parse_args()
    from tpubft.kvbc.replica import open_db
    # offline tool: full per-batch durability on the destination (the
    # replica's unsynced default is a latency tradeoff this tool
    # doesn't need)
    migrate(open_db(args.src), open_db(args.dst, sync_writes=True),
            args.src_version, args.dst_version, verify=args.verify)
    return 0


if __name__ == "__main__":
    sys.exit(main())
