"""Key generation + validation tool.

Rebuild of /root/reference/tools/GenerateConcordKeys.cpp +
TestGeneratedKeys.cpp + KeyfileIOUtils.cpp: writes one keyfile per
principal (replicas, clients, operator) containing the cluster's public
material plus that principal's private seed — optionally encrypted at
rest with the secrets manager.

Usage:
  python -m tpubft.tools.keygen generate -f 1 --clients 4 -o keys/ \
      [--seed S] [--password PW]
  python -m tpubft.tools.keygen verify keys/replica-0.keys [--password PW]
"""
from __future__ import annotations

import argparse
import base64
import json
import os
import sys
from typing import Optional

from tpubft.consensus.keys import ClusterKeys
from tpubft.utils.config import ReplicaConfig


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def keyfile_dict(keys: ClusterKeys) -> dict:
    """Serialized per-node view (private seed included — encrypt!)."""
    return {
        "n": keys.n, "f": keys.f, "c": keys.c,
        "threshold_scheme": keys.threshold_scheme,
        "replica_sig_scheme": keys.replica_sig_scheme,
        "client_sig_scheme": keys.client_sig_scheme,
        "my_id": keys.my_id,
        "my_sign_seed": _b64(keys.my_sign_seed),
        "operator_id": keys.operator_id,
        "replica_pubkeys": {str(k): _b64(v)
                            for k, v in keys.replica_pubkeys.items()},
        "client_pubkeys": {str(k): _b64(v)
                           for k, v in keys.client_pubkeys.items()},
    }


def _manager(password: Optional[str]):
    if password:
        from tpubft.secrets import SecretsManagerEnc
        return SecretsManagerEnc(password.encode())
    from tpubft.secrets import SecretsManagerPlain
    return SecretsManagerPlain()


def generate(args) -> int:
    cfg = ReplicaConfig(f_val=args.f, c_val=args.c,
                        num_ro_replicas=args.ro,
                        num_of_client_proxies=args.clients)
    cluster = ClusterKeys.generate(cfg, args.clients,
                                   seed=args.seed.encode())
    os.makedirs(args.out, exist_ok=True)
    sm = _manager(args.password)
    names = {}
    for r in range(cfg.n_val):
        names[cluster.for_node(r).my_id] = f"replica-{r}.keys"
    for ro in range(cfg.n_val, cfg.n_val + args.ro):
        names[ro] = f"ro-replica-{ro}.keys"
    first_client = cfg.n_val + cfg.num_ro_replicas
    for cl in range(first_client, first_client + args.clients):
        names[cl] = f"client-{cl}.keys"
    names[cluster.operator_id] = "operator.keys"
    for node_id, fname in names.items():
        view = cluster.for_node(node_id)
        raw = json.dumps(keyfile_dict(view), indent=1).encode()
        sm.encrypt_file(os.path.join(args.out, fname), raw)
    if args.tls_certs:
        # per-node TLS material for the pinned-cert transport (reference
        # GenerateConcordKeys' cert emission for TlsTCPCommunication).
        # ALWAYS random keys: a TLS certificate is public (any handshake
        # reveals it), so a seed-derivable private key would let anyone
        # knowing the seed impersonate every node
        from tpubft.comm.tls import generate_tls_material
        generate_tls_material(args.out, sorted(names), seed=None,
                              password=args.password)
        print(f"wrote TLS certs for {len(names)} nodes to {args.out}")
    print(f"wrote {len(names)} keyfiles to {args.out}")
    return 0


def load_keyfile(path: str, password: Optional[str] = None) -> ClusterKeys:
    sm = _manager(password)
    d = json.loads(sm.decrypt_file(path).decode())
    keys = ClusterKeys(
        n=d["n"], f=d["f"], c=d["c"],
        threshold_scheme=d["threshold_scheme"], my_id=d["my_id"],
        replica_sig_scheme=d.get("replica_sig_scheme", "ed25519"),
        client_sig_scheme=d.get("client_sig_scheme", "ed25519"),
        my_sign_seed=base64.b64decode(d["my_sign_seed"]),
        operator_id=d.get("operator_id"),
        replica_pubkeys={int(k): base64.b64decode(v)
                         for k, v in d["replica_pubkeys"].items()},
        client_pubkeys={int(k): base64.b64decode(v)
                        for k, v in d["client_pubkeys"].items()})
    # NOTE: threshold systems are seed-derived at runtime by the replica
    # from its configured cluster seed; keyfiles carry the signing layer.
    return keys


def verify(args) -> int:
    """TestGeneratedKeys role: the private seed must produce the public
    key the file claims for this principal."""
    keys = load_keyfile(args.keyfile, args.password)
    signer = keys.my_signer()
    expect = (keys.replica_pubkeys.get(keys.my_id)
              or keys.client_pubkeys.get(keys.my_id))
    if signer.public_bytes() != expect:
        print("MISMATCH: private seed does not produce the claimed pubkey")
        return 1
    payload = b"keygen-selftest"
    from tpubft.crypto.cpu import make_verifier
    if not make_verifier(keys.scheme_of(keys.my_id),
                         expect).verify(payload, signer.sign(payload)):
        print("MISMATCH: sign/verify roundtrip failed")
        return 1
    print(f"keyfile OK (principal {keys.my_id}, n={keys.n}, f={keys.f})")
    return 0


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    g = sub.add_parser("generate")
    g.add_argument("-f", type=int, default=1)
    g.add_argument("-c", type=int, default=0)
    g.add_argument("--ro", type=int, default=0,
                   help="read-only replicas in the topology")
    g.add_argument("--clients", type=int, default=4)
    g.add_argument("-o", "--out", required=True)
    g.add_argument("--seed", default="tpubft-cluster")
    g.add_argument("--password", default=None)
    g.add_argument("--tls-certs", action="store_true",
                   help="also emit per-node TLS keys/certs")
    g.set_defaults(fn=generate)
    v = sub.add_parser("verify")
    v.add_argument("keyfile")
    v.add_argument("--password", default=None)
    v.set_defaults(fn=verify)
    args = p.parse_args()
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
