"""tpubft-ctl — CLI for the diagnostics admin server (reference
diagnostics/concord-ctl).

Usage: python -m tpubft.tools.ctl <port> <command...>
  e.g. python -m tpubft.tools.ctl 6888 status list
       python -m tpubft.tools.ctl 6888 perf show execute
"""
from __future__ import annotations

import socket
import sys


def query(port: int, command: str, host: str = "127.0.0.1",
          timeout: float = 3.0) -> str:
    with socket.create_connection((host, port), timeout=timeout) as s:
        fh = s.makefile("rw", encoding="utf-8", newline="\n")
        fh.write(command + "\n")
        fh.flush()
        lines = []
        for line in fh:
            if line.rstrip("\n") == ".":
                break
            lines.append(line.rstrip("\n"))
        return "\n".join(lines)


def main() -> int:
    if len(sys.argv) < 3:
        print(__doc__)
        return 2
    port = int(sys.argv[1])
    print(query(port, " ".join(sys.argv[2:])))
    return 0


if __name__ == "__main__":
    sys.exit(main())
