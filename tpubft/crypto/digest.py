"""Digests (reference: util/include/Digest.hpp, DigestType.hpp — SHA-256, 32B).

Also provides the digest combinations the protocol uses:
`calc_combination(digest, view, seq)` mirrors Digest::calcCombination
(/root/reference/util/include/Digest.hpp) used when signing fast-path commit
proofs (ReplicaImp.cpp:1344).
"""
from __future__ import annotations

import hashlib
import struct

DIGEST_SIZE = 32
EMPTY_DIGEST = b"\x00" * DIGEST_SIZE


def digest(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def digest_of_parts(*parts: bytes) -> bytes:
    h = hashlib.sha256()
    for p in parts:
        h.update(struct.pack("<Q", len(p)))
        h.update(p)
    return h.digest()


def calc_combination(d: bytes, view: int, seq: int) -> bytes:
    """Bind a content digest to its consensus slot (view, seqnum)."""
    return hashlib.sha256(struct.pack("<QQ", view, seq) + d).digest()
