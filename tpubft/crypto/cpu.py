"""Host signer/verifier backends — self-hosted engine, OpenSSL optional.

Rebuild of the reference's crypto_utils (Crypto++ RSA/ECDSA signers —
/root/reference/util/include/crypto_utils.hpp:41-100) plus the EdDSA
path, with one crucial delta: the implementation underneath is OURS.
The pure-python scalar engine (tpubft/crypto/scalar.py) provides
Ed25519 + ECDSA sign/verify/keygen from the stdlib alone; the
third-party `cryptography` package (OpenSSL) is a soft OPTIONAL
accelerator, probed at runtime and used only when importable. No module
under tpubft/ may hard-import it (tools/check_imports.py enforces
this) — the repo must work fully offline, because the batched device
kernels in tpubft/ops are the primary verification plane and the host
engine exists for signing, keygen, and small/cold verifies.

Backend order for a verify (see docs/OPERATIONS.md):
  1. batched device kernels — SigManager.verify_batch / BatchVerifier;
  2. OpenSSL via `cryptography`, when present (`have_openssl()`);
  3. the scalar engine — always available.

All signatures use fixed-length raw encodings so wire messages have
static layouts (TPU batches need fixed shapes).
"""
from __future__ import annotations

import functools
import os
from typing import Optional

from tpubft.crypto import scalar
from tpubft.crypto.interfaces import ISigner, IVerifier

ED25519_SIG_LEN = 64
ED25519_PK_LEN = 32
ECDSA_SIG_LEN = 64  # raw r||s, 32B each


@functools.lru_cache(maxsize=1)
def _openssl():
    """Feature probe for the optional OpenSSL stack: the needed
    `cryptography` submodules as a namespace, or None. Never raises.
    TPUBFT_NO_OPENSSL=1 forces the scalar engine (tests use it to pin
    down the pure path even where `cryptography` is installed)."""
    if os.environ.get("TPUBFT_NO_OPENSSL"):
        return None
    try:
        from cryptography.exceptions import InvalidSignature
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import ec, ed25519
        from cryptography.hazmat.primitives.asymmetric.utils import (
            decode_dss_signature, encode_dss_signature)
    except Exception:  # noqa: BLE001 — any import failure = not available
        return None
    import types
    return types.SimpleNamespace(
        InvalidSignature=InvalidSignature, hashes=hashes,
        serialization=serialization, ec=ec, ed25519=ed25519,
        decode_dss=decode_dss_signature, encode_dss=encode_dss_signature)


def have_openssl() -> bool:
    """True when the optional OpenSSL accelerator is importable."""
    return _openssl() is not None


# ---------------- Ed25519 ----------------

class Ed25519Signer(ISigner):
    def __init__(self, private_key_bytes: bytes):
        if len(private_key_bytes) != 32:
            raise ValueError("ed25519 private key must be 32 bytes")
        self.private_bytes = private_key_bytes
        ossl = _openssl()
        self._sk = (ossl.ed25519.Ed25519PrivateKey.from_private_bytes(
            private_key_bytes) if ossl is not None else None)
        self._pub: Optional[bytes] = None

    @classmethod
    def generate(cls, seed: Optional[bytes] = None) -> "Ed25519Signer":
        if seed is not None:
            return cls(scalar.ed25519_seed_to_private(seed))
        return cls(os.urandom(32))

    def sign(self, data: bytes) -> bytes:
        if self._sk is not None:
            return self._sk.sign(data)
        return scalar.ed25519_sign(self.private_bytes, data,
                                   pk=self.public_bytes())

    def sign_batch(self, datas) -> list:
        """Batch signing seam (SigManager.sign_batch): OpenSSL stays a
        per-item loop (its one-shot sign has no batch API), the
        self-hosted engine amortizes the per-signature field inversion
        across the batch (scalar.ed25519_sign_batch)."""
        if self._sk is not None:
            return [self._sk.sign(d) for d in datas]
        return scalar.ed25519_sign_batch(self.private_bytes, datas,
                                         pk=self.public_bytes())

    @property
    def signature_length(self) -> int:
        return ED25519_SIG_LEN

    def public_bytes(self) -> bytes:
        if self._pub is None:
            if self._sk is not None:
                ossl = _openssl()
                self._pub = self._sk.public_key().public_bytes(
                    ossl.serialization.Encoding.Raw,
                    ossl.serialization.PublicFormat.Raw)
            else:
                self._pub = scalar.ed25519_public_key(self.private_bytes)
        return self._pub


class Ed25519Verifier(IVerifier):
    def __init__(self, public_key_bytes: bytes):
        if len(public_key_bytes) != ED25519_PK_LEN:
            raise ValueError("ed25519 public key must be 32 bytes")
        self.public_key_bytes = public_key_bytes
        ossl = _openssl()
        self._pk = (ossl.ed25519.Ed25519PublicKey.from_public_bytes(
            public_key_bytes) if ossl is not None else None)

    def verify(self, data: bytes, sig: bytes) -> bool:
        if len(sig) != ED25519_SIG_LEN:
            return False
        if self._pk is not None:
            try:
                self._pk.verify(sig, data)
                return True
            except _openssl().InvalidSignature:
                return False
        return scalar.ed25519_verify(self.public_key_bytes, data, sig)

    @property
    def signature_length(self) -> int:
        return ED25519_SIG_LEN


# ---------------- ECDSA (secp256k1 / P-256), raw r||s signatures ----------------

def _ossl_curve(ossl, curve: str):
    return {"secp256k1": ossl.ec.SECP256K1,
            "secp256r1": ossl.ec.SECP256R1}[curve]()


class EcdsaSigner(ISigner):
    def __init__(self, private_value: int, curve: str = "secp256k1"):
        if curve not in scalar.CURVES:
            raise ValueError(f"unknown curve {curve}")
        if not 1 <= private_value < scalar.CURVES[curve]["n"]:
            # same construction-time validation as the OpenSSL path
            # (ec.derive_private_key) — invalid keys must not fail late
            # with backend-dependent errors
            raise ValueError("ECDSA private value out of range [1, n-1]")
        self.curve_name = curve
        self.private_value = private_value
        ossl = _openssl()
        self._sk = (ossl.ec.derive_private_key(
            private_value, _ossl_curve(ossl, curve))
            if ossl is not None else None)
        self._pub: Optional[bytes] = None

    @classmethod
    def generate(cls, curve: str = "secp256k1",
                 seed: Optional[bytes] = None) -> "EcdsaSigner":
        if seed is not None:
            return cls(scalar.ecdsa_seed_to_private(seed, curve), curve)
        return cls(scalar.ecdsa_random_private(curve), curve)

    def sign(self, data: bytes) -> bytes:
        if self._sk is not None:
            ossl = _openssl()
            der = self._sk.sign(data, ossl.ec.ECDSA(ossl.hashes.SHA256()))
            r, s = ossl.decode_dss(der)
            return r.to_bytes(32, "big") + s.to_bytes(32, "big")
        return scalar.ecdsa_sign(self.private_value, data, self.curve_name)

    @property
    def signature_length(self) -> int:
        return ECDSA_SIG_LEN

    def public_bytes(self) -> bytes:
        """Uncompressed SEC1 point (0x04 || x || y), 65 bytes."""
        if self._pub is None:
            if self._sk is not None:
                ossl = _openssl()
                self._pub = self._sk.public_key().public_bytes(
                    ossl.serialization.Encoding.X962,
                    ossl.serialization.PublicFormat.UncompressedPoint)
            else:
                self._pub = scalar.ecdsa_public_key(self.private_value,
                                                    self.curve_name)
        return self._pub


class EcdsaVerifier(IVerifier):
    def __init__(self, public_key_bytes: bytes, curve: str = "secp256k1"):
        if curve not in scalar.CURVES:
            raise ValueError(f"unknown curve {curve}")
        self.curve_name = curve
        self.public_key_bytes = public_key_bytes
        ossl = _openssl()
        if ossl is not None:
            # raises ValueError on a malformed/off-curve point, matching
            # the scalar-path checks below
            self._pk = ossl.ec.EllipticCurvePublicKey.from_encoded_point(
                _ossl_curve(ossl, curve), public_key_bytes)
        else:
            self._pk = None
            if (len(public_key_bytes) != 65 or public_key_bytes[0] != 0x04
                    or not scalar.ecdsa_on_curve(
                        int.from_bytes(public_key_bytes[1:33], "big"),
                        int.from_bytes(public_key_bytes[33:], "big"),
                        curve)):
                raise ValueError("invalid SEC1 uncompressed public key")

    def verify(self, data: bytes, sig: bytes) -> bool:
        if len(sig) != ECDSA_SIG_LEN:
            return False
        if self._pk is not None:
            ossl = _openssl()
            r = int.from_bytes(sig[:32], "big")
            s = int.from_bytes(sig[32:], "big")
            try:
                self._pk.verify(ossl.encode_dss(r, s), data,
                                ossl.ec.ECDSA(ossl.hashes.SHA256()))
                return True
            except (ossl.InvalidSignature, ValueError):
                return False
        return scalar.ecdsa_verify(self.public_key_bytes, data, sig,
                                   self.curve_name)

    @property
    def uses_scalar_engine(self) -> bool:
        """True when verifies run on the in-repo scalar engine (no
        OpenSSL) — the shape whose batches ride ecdsa_verify_batch."""
        return self._pk is None

    def verify_batch(self, items) -> list:
        """Batch verification through the Montgomery/comb engine
        (scalar.ecdsa_verify_batch) when the scalar path would carry the
        items anyway: this is what keeps degraded mode (breaker OPEN, no
        device, no OpenSSL) at thousands of verifies/sec instead of the
        per-item ladder's tens. With OpenSSL present the per-item
        C-backed verify is already faster than the batched python walk."""
        if self.uses_scalar_engine and len(items) > 1:
            return scalar.ecdsa_verify_batch(
                [(self.public_key_bytes, d, s) for d, s in items],
                self.curve_name)
        return [self.verify(d, s) for d, s in items]

    @property
    def signature_length(self) -> int:
        return ECDSA_SIG_LEN


def make_signer(scheme: str, seed: Optional[bytes] = None) -> ISigner:
    if scheme == "ed25519":
        return Ed25519Signer.generate(seed=seed)
    if scheme in ("ecdsa-secp256k1", "secp256k1"):
        return EcdsaSigner.generate("secp256k1", seed=seed)
    if scheme in ("ecdsa-secp256r1", "secp256r1", "ecdsa-p256"):
        return EcdsaSigner.generate("secp256r1", seed=seed)
    raise ValueError(f"unknown signature scheme {scheme}")


def make_verifier(scheme: str, public_key_bytes: bytes) -> IVerifier:
    if scheme == "ed25519":
        return Ed25519Verifier(public_key_bytes)
    if scheme in ("ecdsa-secp256k1", "secp256k1"):
        return EcdsaVerifier(public_key_bytes, "secp256k1")
    if scheme in ("ecdsa-secp256r1", "secp256r1", "ecdsa-p256"):
        return EcdsaVerifier(public_key_bytes, "secp256r1")
    raise ValueError(f"unknown signature scheme {scheme}")
