"""CPU signer/verifier backends (OpenSSL via the `cryptography` package).

Rebuild of the reference's crypto_utils (Crypto++ RSA/ECDSA signers —
/root/reference/util/include/crypto_utils.hpp:41-100) plus the EdDSA path.
These are the "cpu" crypto backend and the golden reference the TPU kernels
are tested against. All signatures use fixed-length raw encodings so wire
messages have static layouts (TPU batches need fixed shapes).
"""
from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence, Tuple

from cryptography.exceptions import InvalidSignature
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec, ed25519
from cryptography.hazmat.primitives.asymmetric.utils import (
    Prehashed, decode_dss_signature, encode_dss_signature)

from tpubft.crypto.interfaces import ISigner, IVerifier

ED25519_SIG_LEN = 64
ED25519_PK_LEN = 32
ECDSA_SIG_LEN = 64  # raw r||s, 32B each


# ---------------- Ed25519 ----------------

class Ed25519Signer(ISigner):
    def __init__(self, private_key_bytes: bytes):
        self._sk = ed25519.Ed25519PrivateKey.from_private_bytes(private_key_bytes)
        self.private_bytes = private_key_bytes

    @classmethod
    def generate(cls, seed: Optional[bytes] = None) -> "Ed25519Signer":
        if seed is not None:
            return cls(hashlib.sha256(b"ed25519-keygen" + seed).digest())
        sk = ed25519.Ed25519PrivateKey.generate()
        raw = sk.private_bytes(serialization.Encoding.Raw,
                               serialization.PrivateFormat.Raw,
                               serialization.NoEncryption())
        return cls(raw)

    def sign(self, data: bytes) -> bytes:
        return self._sk.sign(data)

    @property
    def signature_length(self) -> int:
        return ED25519_SIG_LEN

    def public_bytes(self) -> bytes:
        return self._sk.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw)


class Ed25519Verifier(IVerifier):
    def __init__(self, public_key_bytes: bytes):
        self.public_key_bytes = public_key_bytes
        self._pk = ed25519.Ed25519PublicKey.from_public_bytes(public_key_bytes)

    def verify(self, data: bytes, sig: bytes) -> bool:
        if len(sig) != ED25519_SIG_LEN:
            return False
        try:
            self._pk.verify(sig, data)
            return True
        except InvalidSignature:
            return False

    @property
    def signature_length(self) -> int:
        return ED25519_SIG_LEN


# ---------------- ECDSA (secp256k1 / P-256), raw r||s signatures ----------------

_CURVES = {
    "secp256k1": ec.SECP256K1(),
    "secp256r1": ec.SECP256R1(),
}


class EcdsaSigner(ISigner):
    def __init__(self, private_value: int, curve: str = "secp256k1"):
        self.curve_name = curve
        self._sk = ec.derive_private_key(private_value, _CURVES[curve])
        self.private_value = private_value

    @classmethod
    def generate(cls, curve: str = "secp256k1",
                 seed: Optional[bytes] = None) -> "EcdsaSigner":
        if seed is not None:
            order = {"secp256k1":
                     0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141,
                     "secp256r1":
                     0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551}[curve]
            v = int.from_bytes(hashlib.sha512(b"ecdsa-keygen" + seed).digest(), "big")
            return cls(v % (order - 1) + 1, curve)
        sk = ec.generate_private_key(_CURVES[curve])
        return cls(sk.private_numbers().private_value, curve)

    def sign(self, data: bytes) -> bytes:
        der = self._sk.sign(data, ec.ECDSA(hashes.SHA256()))
        r, s = decode_dss_signature(der)
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")

    @property
    def signature_length(self) -> int:
        return ECDSA_SIG_LEN

    def public_bytes(self) -> bytes:
        """Uncompressed SEC1 point (0x04 || x || y), 65 bytes."""
        return self._sk.public_key().public_bytes(
            serialization.Encoding.X962, serialization.PublicFormat.UncompressedPoint)


class EcdsaVerifier(IVerifier):
    def __init__(self, public_key_bytes: bytes, curve: str = "secp256k1"):
        self.curve_name = curve
        self.public_key_bytes = public_key_bytes
        self._pk = ec.EllipticCurvePublicKey.from_encoded_point(
            _CURVES[curve], public_key_bytes)

    def verify(self, data: bytes, sig: bytes) -> bool:
        if len(sig) != ECDSA_SIG_LEN:
            return False
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        try:
            self._pk.verify(encode_dss_signature(r, s), data,
                            ec.ECDSA(hashes.SHA256()))
            return True
        except InvalidSignature:
            return False

    @property
    def signature_length(self) -> int:
        return ECDSA_SIG_LEN


def make_signer(scheme: str, seed: Optional[bytes] = None) -> ISigner:
    if scheme == "ed25519":
        return Ed25519Signer.generate(seed=seed)
    if scheme in ("ecdsa-secp256k1", "secp256k1"):
        return EcdsaSigner.generate("secp256k1", seed=seed)
    if scheme in ("ecdsa-secp256r1", "secp256r1", "ecdsa-p256"):
        return EcdsaSigner.generate("secp256r1", seed=seed)
    raise ValueError(f"unknown signature scheme {scheme}")


def make_verifier(scheme: str, public_key_bytes: bytes) -> IVerifier:
    if scheme == "ed25519":
        return Ed25519Verifier(public_key_bytes)
    if scheme in ("ecdsa-secp256k1", "secp256k1"):
        return EcdsaVerifier(public_key_bytes, "secp256k1")
    if scheme in ("ecdsa-secp256r1", "secp256r1", "ecdsa-p256"):
        return EcdsaVerifier(public_key_bytes, "secp256r1")
    raise ValueError(f"unknown signature scheme {scheme}")
