"""Cryptography layer (reference: threshsign/ + util crypto — SURVEY.md §2.2/2.3).

Interfaces mirror the reference plugin boundary (IThresholdSigner/Verifier/
Accumulator, ISigner/IVerifier, Cryptosystem) so consensus code is backend-
agnostic; backends are "cpu" (OpenSSL via `cryptography` + pure-python BLS
reference math) and "tpu" (batched JAX kernels in tpubft.ops).
"""
from tpubft.crypto.interfaces import (  # noqa: F401
    ISigner, IVerifier, IThresholdSigner, IThresholdVerifier,
    IThresholdAccumulator, Cryptosystem,
)
