"""Cryptography layer (reference: threshsign/ + util crypto — SURVEY.md §2.2/2.3).

Interfaces mirror the reference plugin boundary (IThresholdSigner/Verifier/
Accumulator, ISigner/IVerifier, Cryptosystem) so consensus code is backend-
agnostic. The stack is self-hosted: "cpu" is the pure-stdlib scalar engine
(crypto/scalar.py — RFC 8032 Ed25519 + RFC 6979 ECDSA) with OpenSSL via
`cryptography` as a soft optional accelerator (runtime feature probe, never
a module-level import), plus the pure-python BLS reference math; "tpu" is
the batched JAX kernels in tpubft.ops — the primary verification plane.
"""
from tpubft.crypto.interfaces import (  # noqa: F401
    ISigner, IVerifier, IThresholdSigner, IThresholdVerifier,
    IThresholdAccumulator, Cryptosystem,
)
