"""Threshold cryptosystem backends.

Rebuild of the reference's scheme registry + BLS backend
(threshsign/src/ThresholdSignaturesTypes.cpp:183-200 createThresholdVerifier/
Signer; threshsign/src/bls/relic/ BlsThresholdSigner/Verifier/Accumulator):

  "multisig-ed25519" — k-of-n multisig: the combined signature is the sorted
      list of (signer_id, ed25519_sig) pairs. Constant-time verify per share,
      batch-friendly. Mirrors the reference's "multisig-bls" role for the
      n-signer fast path, using the cheapest scheme on CPU.
  "threshold-bls"    — BLS12-381 k-of-n Shamir threshold: shares are G1
      points; accumulate = Lagrange + MSM; verify = pairing check. Mirrors
      "threshold-bls" (BlsThresholdFactory.cpp:39).

Both accumulators defer share verification (accumulate first, verify the
combined result, and only on failure identify bad shares) — exactly the
reference's SignaturesProcessingJob strategy
(CollectorOfThresholdSignatures.hpp:291-407).
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple

from tpubft.crypto import bls12381 as bls
from tpubft.crypto.cpu import Ed25519Signer, Ed25519Verifier
from tpubft.crypto.interfaces import (Cryptosystem, IThresholdAccumulator,
                                      IThresholdFactory, IThresholdSigner,
                                      IThresholdVerifier)


# ---------------- multisig-ed25519 ----------------

def pack_multisig_vector(ids: Sequence[int],
                         shares: Dict[int, bytes]) -> bytes:
    """THE multisig-vector certificate encoding: <H count, then per
    signer <H id + 64-byte ed25519 sig, ids in the given order. The one
    serializer for both the accumulator and the fused combine paths —
    their byte-identity is a pinned correctness invariant."""
    out = bytearray(struct.pack("<H", len(ids)))
    for i in ids:
        out += struct.pack("<H", i)
        out += shares[i]
    return bytes(out)


class MultisigEd25519Signer(IThresholdSigner):
    def __init__(self, signer_id: int, seed_or_sk: bytes):
        self._signer = Ed25519Signer(seed_or_sk)
        self._id = signer_id

    def sign_share(self, data: bytes) -> bytes:
        return self._signer.sign(data)

    @property
    def signer_id(self) -> int:
        return self._id


class MultisigEd25519Accumulator(IThresholdAccumulator):
    def __init__(self, verifier: "MultisigEd25519Verifier", share_verification: bool):
        self._verifier = verifier
        self._share_verification = share_verification
        self._digest: Optional[bytes] = None
        self._shares: Dict[int, bytes] = {}

    def set_expected_digest(self, digest: bytes) -> None:
        self._digest = digest

    def add(self, share_id: int, share: bytes) -> int:
        if self._share_verification and self._digest is not None:
            if not self._verifier.verify_share(share_id, self._digest, share):
                return len(self._shares)
        self._shares[share_id] = share
        return len(self._shares)

    def has_threshold(self) -> bool:
        return len(self._shares) >= self._verifier.threshold

    def get_full_signed_data(self) -> bytes:
        ids = sorted(self._shares)[: self._verifier.threshold]
        return pack_multisig_vector(ids, self._shares)

    def identify_bad_shares(self) -> List[int]:
        assert self._digest is not None
        return [i for i, s in self._shares.items()
                if not self._verifier.verify_share(i, self._digest, s)]


class MultisigEd25519Verifier(IThresholdVerifier):
    def __init__(self, threshold: int, total: int, share_public_keys: Sequence[bytes]):
        self._threshold = threshold
        self._total = total
        self._share_verifiers = [Ed25519Verifier(pk) for pk in share_public_keys]

    def new_accumulator(self, with_share_verification: bool) -> MultisigEd25519Accumulator:
        return MultisigEd25519Accumulator(self, with_share_verification)

    def verify_share(self, share_id: int, data: bytes, share: bytes) -> bool:
        if not 1 <= share_id <= self._total:
            return False
        return self._share_verifiers[share_id - 1].verify(data, share)

    def verify(self, data: bytes, sig: bytes) -> bool:
        try:
            (k,) = struct.unpack_from("<H", sig, 0)
            if k < self._threshold:
                return False
            off = 2
            seen = set()
            for _ in range(k):
                (i,) = struct.unpack_from("<H", sig, off)
                off += 2
                share = sig[off:off + 64]
                off += 64
                if i in seen or not self.verify_share(i, data, share):
                    return False
                seen.add(i)
            return off == len(sig)
        except (struct.error, IndexError):
            return False

    @property
    def threshold(self) -> int:
        return self._threshold

    @property
    def total_signers(self) -> int:
        return self._total


class MultisigEd25519Factory(IThresholdFactory):
    def new_signer(self, signer_id: int, secret_share: bytes) -> MultisigEd25519Signer:
        return MultisigEd25519Signer(signer_id, secret_share)

    def new_verifier(self, threshold, total, public_key, share_public_keys):
        return MultisigEd25519Verifier(threshold, total, share_public_keys)

    def keygen(self, threshold: int, total: int, seed: Optional[bytes] = None):
        import hashlib
        sks, pks = [], []
        for i in range(total):
            s = (hashlib.sha256(b"ms-ed" + seed + i.to_bytes(4, "big")).digest()
                 if seed is not None else None)
            signer = Ed25519Signer.generate(seed=s)
            sks.append(signer.private_bytes)
            pks.append(signer.public_bytes())
        # no single master public key for multisig; use the pk list
        return pks, pks, sks


# ---------------- threshold-bls (BLS12-381) ----------------

class BlsThresholdSigner(IThresholdSigner):
    def __init__(self, signer_id: int, secret_share: int):
        self._id = signer_id
        self._sk = secret_share

    def sign_share(self, data: bytes) -> bytes:
        return bls.g1_compress(bls.sign(self._sk, data))

    @property
    def signer_id(self) -> int:
        return self._id


class BlsThresholdAccumulator(IThresholdAccumulator):
    """Accumulate G1 shares; combine = Lagrange + MSM (the TPU-sharded op)."""

    def __init__(self, verifier: "BlsThresholdVerifier", share_verification: bool):
        self._verifier = verifier
        self._share_verification = share_verification
        self._digest: Optional[bytes] = None
        self._shares: Dict[int, object] = {}

    def set_expected_digest(self, digest: bytes) -> None:
        self._digest = digest

    def add(self, share_id: int, share: bytes) -> int:
        if not 1 <= share_id <= self._verifier.total_signers:
            return len(self._shares)
        try:
            pt = bls.g1_decompress(share)
        except ValueError:
            return len(self._shares)
        if pt is None:
            return len(self._shares)
        if self._share_verification and self._digest is not None:
            if not self._verifier.verify_share(share_id, self._digest, share):
                return len(self._shares)
        self._shares[share_id] = pt
        return len(self._shares)

    def has_threshold(self) -> bool:
        return len(self._shares) >= self._verifier.threshold

    def get_full_signed_data(self) -> bytes:
        ids = sorted(self._shares)[: self._verifier.threshold]
        combined = bls.combine_shares(ids, [self._shares[i] for i in ids])
        return bls.g1_compress(combined)

    def identify_bad_shares(self) -> List[int]:
        """Aggregation-tree isolation: O(b·log n) pairing checks for b bad
        shares (reference BlsBatchVerifier.cpp:44,84) instead of the naive
        O(n) one-pairing-per-share sweep. One implementation shared with
        the fused path (verifier._identify_bad) so per-slot and fused
        bad-share verdicts can never diverge."""
        assert self._digest is not None
        return self._verifier._identify_bad(self._digest, self._shares)


class BlsThresholdVerifier(IThresholdVerifier):
    def __init__(self, threshold: int, total: int, master_pk, share_pks):
        self._threshold = threshold
        self._total = total
        self._master_pk = master_pk
        self._share_pks = share_pks

    def new_accumulator(self, with_share_verification: bool) -> BlsThresholdAccumulator:
        return BlsThresholdAccumulator(self, with_share_verification)

    def share_pk(self, share_id: int):
        if not 1 <= share_id <= self._total:
            raise ValueError(f"share id {share_id} out of range 1..{self._total}")
        return self._share_pks[share_id - 1]

    def verify_share(self, share_id: int, data: bytes, share: bytes) -> bool:
        if not 1 <= share_id <= self._total:
            return False
        try:
            pt = bls.g1_decompress(share)
        except ValueError:
            return False
        return bls.verify(self.share_pk(share_id), data, pt)

    def verify(self, data: bytes, sig: bytes) -> bool:
        try:
            pt = bls.g1_decompress(sig)
        except ValueError:
            return False
        return bls.verify(self._master_pk, data, pt)

    def verify_batch_certs(self, items) -> List[bool]:
        """Aggregated combined-cert verification: ONE pairing check for
        the whole batch via random linear combination —
        e(Σ z_i·sig_i, -g2) · e(Σ z_i·H(d_i), pk) == 1. The same
        soundness argument as batch_verify_shares (forged certs survive
        with probability 2^-128); on aggregate failure the rare path
        verifies per cert. Replaces k sequential ~2-pairing verifies with
        2 pairings + two k-point G1 MSMs."""
        out = [False] * len(items)
        pts, hs, idxs = [], [], []
        for i, (d, s) in enumerate(items):
            try:
                pt = bls.g1_decompress(s)
            except ValueError:
                continue
            if pt is None:
                continue
            pts.append(pt)
            hs.append(bls.hash_to_g1(d))
            idxs.append(i)
        if not pts:
            return out
        if len(pts) == 1:
            ok = bls.pairing_check([(pts[0], bls.g2_neg(bls.G2_GEN)),
                                    (hs[0], self._master_pk)])
            out[idxs[0]] = ok
            return out
        # the RLC transcript binds the FULL statement (master pk, each
        # digest, each signature) so coefficients are fixed only after
        # the adversary committed to every input, not just the sigs
        ctx = (b"certs" + bls.g2_compress(self._master_pk)
               + b"".join(items[i][0] + bls.g1_compress(p)
                          for i, p in zip(idxs, pts)))
        zs = bls._rlc_scalars(len(pts), ctx)
        agg_sig = bls.g1_msm(pts, zs)
        agg_h = bls.g1_msm(hs, zs)
        if bls.pairing_check([(agg_sig, bls.g2_neg(bls.G2_GEN)),
                              (agg_h, self._master_pk)]):
            for i in idxs:
                out[i] = True
            return out
        # aggregate failed (byzantine input in the batch): isolate
        for pt, h, i in zip(pts, hs, idxs):
            out[i] = bls.pairing_check([(pt, bls.g2_neg(bls.G2_GEN)),
                                        (h, self._master_pk)])
        return out

    # ---- fused cross-slot combine (the per-slot combine tax killer) ----

    def _decode_job_shares(self, shares: Dict[int, bytes]) -> Dict[int, object]:
        """Accumulator `add` semantics over a raw share dict: out-of-range
        ids and undecodable/infinity points are silently dropped — the
        job combines over what remains, exactly as the per-slot path."""
        pts: Dict[int, object] = {}
        for sid, share in shares.items():
            if not 1 <= sid <= self._total:
                continue
            try:
                pt = bls.g1_decompress(share)
            except ValueError:
                continue
            if pt is None:
                continue
            pts[sid] = pt
        return pts

    def _combine_segments(self, segments) -> List:
        """[(ids, [share points])] -> one combined G1 point per segment.
        Host path: per-segment Lagrange + MSM; the TPU subclass folds
        every segment into ONE segmented multi-MSM device launch."""
        return [bls.combine_shares(ids, pts) if ids else None
                for ids, pts in segments]

    def combine_batch(self, jobs) -> List[Tuple[bool, bytes, List[int]]]:
        """Fused combine across slots: all jobs' Lagrange+MSM combines in
        one pass (one device launch on the TPU subclass), then ONE
        RLC-aggregated pairing check for every combined signature of the
        flush (`verify_batch_certs`). On aggregate failure the batcher
        isolates per job, and only failing jobs pay bad-share
        identification — one slot's byzantine share fails only its own
        job, sibling slots in the same flush still land. Verdicts are
        identical to the per-job default (interfaces.combine_batch)."""
        decoded = [(digest, self._decode_job_shares(shares))
                   for digest, shares in jobs]
        segments = []
        for _digest, pts in decoded:
            ids = sorted(pts)[: self._threshold]
            segments.append((ids, [pts[i] for i in ids]))
        combined = self._combine_segments(segments)
        sigs = [bls.g1_compress(pt) for pt in combined]
        verdicts = self.verify_batch_certs(
            [(digest, sig) for (digest, _), sig in zip(decoded, sigs)])
        out: List[Tuple[bool, bytes, List[int]]] = []
        for (digest, pts), sig, ok in zip(decoded, sigs, verdicts):
            if ok:
                out.append((True, sig, []))
                continue
            out.append((False, b"", self._identify_bad(digest, pts)))
        return out

    def _identify_bad(self, digest: bytes, pts: Dict[int, object]
                      ) -> List[int]:
        """Aggregation-tree isolation over one failing job's decoded
        shares — the same BlsBatchVerifier walk the accumulator path
        runs (O(b·log n) pairing checks for b bad shares)."""
        h = bls.hash_to_g1(digest)
        ids = sorted(pts)
        tree = bls.BlsBatchVerifier([self.share_pk(i) for i in ids], h)
        verdicts = tree.batch_verify([pts[i] for i in ids])
        return [i for i, good in zip(ids, verdicts) if not good]

    @property
    def threshold(self) -> int:
        return self._threshold

    @property
    def total_signers(self) -> int:
        return self._total


class BlsThresholdFactory(IThresholdFactory):
    def new_signer(self, signer_id: int, secret_share: int) -> BlsThresholdSigner:
        return BlsThresholdSigner(signer_id, secret_share)

    def new_verifier(self, threshold, total, public_key, share_public_keys):
        return BlsThresholdVerifier(threshold, total, public_key, share_public_keys)

    def keygen(self, threshold: int, total: int, seed: Optional[bytes] = None):
        master_pk, share_pks, shares = bls.threshold_keygen(threshold, total, seed=seed)
        return master_pk, share_pks, shares


def register_builtin(type_name: str) -> None:
    if type_name == "multisig-ed25519":
        Cryptosystem.register_type(type_name, MultisigEd25519Factory())
    elif type_name in ("threshold-bls", "multisig-bls"):
        Cryptosystem.register_type(type_name, BlsThresholdFactory())
    else:
        raise ValueError(f"unknown cryptosystem type {type_name}"
                         + (" ('adaptive' must be resolved by "
                            "resolve_threshold_scheme before key "
                            "generation)" if type_name == "adaptive"
                            else ""))


# Default n-crossover for the "adaptive" certificate scheme. Below it a
# cluster certifies with the Ed25519 multisig vector (k constant-time
# EdDSA verifies, batch-friendly, zero G1 ladder math); at or above it
# with compact BLS threshold certificates (48 bytes on the wire and in
# every carried proof, vs 66·k for the vector). The EdDSA-vs-BLS
# committee measurements (arXiv 2302.00418) put per-share threshold math
# far above EdDSA cost at committee sizes this small; the default is
# picked by `python -m benchmarks.bench_combine --crossover`
# (benchmarks/RESULTS.md) and overridable per cluster via
# ReplicaConfig.threshold_scheme_crossover_n.
ADAPTIVE_SCHEME_CROSSOVER_N = 16


def resolve_threshold_scheme(scheme: str, n: int,
                             crossover_n: int = 0) -> str:
    """Configure-time resolution of the certificate scheme: "adaptive"
    becomes a concrete cryptosystem type from the cluster size, anything
    else passes through. Every replica must resolve identically (same n,
    same crossover) — the scheme is part of the cluster's key material,
    so it is resolved once at keygen, never re-negotiated on the wire."""
    if scheme != "adaptive":
        return scheme
    cx = crossover_n or ADAPTIVE_SCHEME_CROSSOVER_N
    return "multisig-ed25519" if n < cx else "threshold-bls"
