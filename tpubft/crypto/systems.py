"""Threshold cryptosystem backends.

Rebuild of the reference's scheme registry + BLS backend
(threshsign/src/ThresholdSignaturesTypes.cpp:183-200 createThresholdVerifier/
Signer; threshsign/src/bls/relic/ BlsThresholdSigner/Verifier/Accumulator):

  "multisig-ed25519" — k-of-n multisig: the combined signature is the sorted
      list of (signer_id, ed25519_sig) pairs. Constant-time verify per share,
      batch-friendly. Mirrors the reference's "multisig-bls" role for the
      n-signer fast path, using the cheapest scheme on CPU.
  "threshold-bls"    — BLS12-381 k-of-n Shamir threshold: shares are G1
      points; accumulate = Lagrange + MSM; verify = pairing check. Mirrors
      "threshold-bls" (BlsThresholdFactory.cpp:39).

Both accumulators defer share verification (accumulate first, verify the
combined result, and only on failure identify bad shares) — exactly the
reference's SignaturesProcessingJob strategy
(CollectorOfThresholdSignatures.hpp:291-407).
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple

from tpubft.crypto import bls12381 as bls
from tpubft.crypto.cpu import Ed25519Signer, Ed25519Verifier
from tpubft.crypto.interfaces import (Cryptosystem, IThresholdAccumulator,
                                      IThresholdFactory, IThresholdSigner,
                                      IThresholdVerifier)


# ---------------- multisig-ed25519 ----------------

def pack_multisig_vector(ids: Sequence[int],
                         shares: Dict[int, bytes]) -> bytes:
    """THE multisig-vector certificate encoding: <H count, then per
    signer <H id + 64-byte ed25519 sig, ids in the given order. The one
    serializer for both the accumulator and the fused combine paths —
    their byte-identity is a pinned correctness invariant."""
    out = bytearray(struct.pack("<H", len(ids)))
    for i in ids:
        out += struct.pack("<H", i)
        out += shares[i]
    return bytes(out)


class MultisigEd25519Signer(IThresholdSigner):
    def __init__(self, signer_id: int, seed_or_sk: bytes):
        self._signer = Ed25519Signer(seed_or_sk)
        self._id = signer_id

    def sign_share(self, data: bytes) -> bytes:
        return self._signer.sign(data)

    @property
    def signer_id(self) -> int:
        return self._id


class MultisigEd25519Accumulator(IThresholdAccumulator):
    def __init__(self, verifier: "MultisigEd25519Verifier", share_verification: bool):
        self._verifier = verifier
        self._share_verification = share_verification
        self._digest: Optional[bytes] = None
        self._shares: Dict[int, bytes] = {}

    def set_expected_digest(self, digest: bytes) -> None:
        self._digest = digest

    def add(self, share_id: int, share: bytes) -> int:
        if self._share_verification and self._digest is not None:
            if not self._verifier.verify_share(share_id, self._digest, share):
                return len(self._shares)
        self._shares[share_id] = share
        return len(self._shares)

    def has_threshold(self) -> bool:
        return len(self._shares) >= self._verifier.threshold

    def get_full_signed_data(self) -> bytes:
        ids = sorted(self._shares)[: self._verifier.threshold]
        return pack_multisig_vector(ids, self._shares)

    def identify_bad_shares(self) -> List[int]:
        assert self._digest is not None
        return [i for i, s in self._shares.items()
                if not self._verifier.verify_share(i, self._digest, s)]


class MultisigEd25519Verifier(IThresholdVerifier):
    def __init__(self, threshold: int, total: int, share_public_keys: Sequence[bytes]):
        self._threshold = threshold
        self._total = total
        self._share_verifiers = [Ed25519Verifier(pk) for pk in share_public_keys]

    def new_accumulator(self, with_share_verification: bool) -> MultisigEd25519Accumulator:
        return MultisigEd25519Accumulator(self, with_share_verification)

    def verify_share(self, share_id: int, data: bytes, share: bytes) -> bool:
        if not 1 <= share_id <= self._total:
            return False
        return self._share_verifiers[share_id - 1].verify(data, share)

    def verify(self, data: bytes, sig: bytes) -> bool:
        try:
            (k,) = struct.unpack_from("<H", sig, 0)
            if k < self._threshold:
                return False
            off = 2
            seen = set()
            for _ in range(k):
                (i,) = struct.unpack_from("<H", sig, off)
                off += 2
                share = sig[off:off + 64]
                off += 64
                if i in seen or not self.verify_share(i, data, share):
                    return False
                seen.add(i)
            return off == len(sig)
        except (struct.error, IndexError):
            return False

    @property
    def threshold(self) -> int:
        return self._threshold

    @property
    def total_signers(self) -> int:
        return self._total


class MultisigEd25519Factory(IThresholdFactory):
    def new_signer(self, signer_id: int, secret_share: bytes) -> MultisigEd25519Signer:
        return MultisigEd25519Signer(signer_id, secret_share)

    def new_verifier(self, threshold, total, public_key, share_public_keys):
        return MultisigEd25519Verifier(threshold, total, share_public_keys)

    def keygen(self, threshold: int, total: int, seed: Optional[bytes] = None):
        import hashlib
        sks, pks = [], []
        for i in range(total):
            s = (hashlib.sha256(b"ms-ed" + seed + i.to_bytes(4, "big")).digest()
                 if seed is not None else None)
            signer = Ed25519Signer.generate(seed=s)
            sks.append(signer.private_bytes)
            pks.append(signer.public_bytes())
        # no single master public key for multisig; use the pk list
        return pks, pks, sks


# ---------------- threshold-bls (BLS12-381) ----------------

class BlsThresholdSigner(IThresholdSigner):
    def __init__(self, signer_id: int, secret_share: int):
        self._id = signer_id
        self._sk = secret_share

    def sign_share(self, data: bytes) -> bytes:
        return bls.g1_compress(bls.sign(self._sk, data))

    @property
    def signer_id(self) -> int:
        return self._id


class BlsThresholdAccumulator(IThresholdAccumulator):
    """Accumulate G1 shares; combine = Lagrange + MSM (the TPU-sharded op)."""

    def __init__(self, verifier: "BlsThresholdVerifier", share_verification: bool):
        self._verifier = verifier
        self._share_verification = share_verification
        self._digest: Optional[bytes] = None
        self._shares: Dict[int, object] = {}

    def set_expected_digest(self, digest: bytes) -> None:
        self._digest = digest

    def add(self, share_id: int, share: bytes) -> int:
        if not 1 <= share_id <= self._verifier.total_signers:
            return len(self._shares)
        try:
            pt = bls.g1_decompress(share)
        except ValueError:
            return len(self._shares)
        if pt is None:
            return len(self._shares)
        if self._share_verification and self._digest is not None:
            if not self._verifier.verify_share(share_id, self._digest, share):
                return len(self._shares)
        self._shares[share_id] = pt
        return len(self._shares)

    def has_threshold(self) -> bool:
        return len(self._shares) >= self._verifier.threshold

    def get_full_signed_data(self) -> bytes:
        ids = sorted(self._shares)[: self._verifier.threshold]
        combined = bls.combine_shares(ids, [self._shares[i] for i in ids])
        return bls.g1_compress(combined)

    def identify_bad_shares(self) -> List[int]:
        """Aggregation-tree isolation: O(b·log n) pairing checks for b bad
        shares (reference BlsBatchVerifier.cpp:44,84) instead of the naive
        O(n) one-pairing-per-share sweep. One implementation shared with
        the fused path (verifier._identify_bad) so per-slot and fused
        bad-share verdicts can never diverge."""
        assert self._digest is not None
        return self._verifier._identify_bad(self._digest, self._shares)


class BlsThresholdVerifier(IThresholdVerifier):
    def __init__(self, threshold: int, total: int, master_pk, share_pks):
        self._threshold = threshold
        self._total = total
        self._master_pk = master_pk
        self._share_pks = share_pks

    def new_accumulator(self, with_share_verification: bool) -> BlsThresholdAccumulator:
        return BlsThresholdAccumulator(self, with_share_verification)

    def share_pk(self, share_id: int):
        if not 1 <= share_id <= self._total:
            raise ValueError(f"share id {share_id} out of range 1..{self._total}")
        return self._share_pks[share_id - 1]

    def verify_share(self, share_id: int, data: bytes, share: bytes) -> bool:
        if not 1 <= share_id <= self._total:
            return False
        try:
            pt = bls.g1_decompress(share)
        except ValueError:
            return False
        return bls.verify(self.share_pk(share_id), data, pt)

    def verify(self, data: bytes, sig: bytes) -> bool:
        try:
            pt = bls.g1_decompress(sig)
        except ValueError:
            return False
        return bls.verify(self._master_pk, data, pt)

    def verify_batch_certs(self, items) -> List[bool]:
        """Aggregated combined-cert verification: ONE pairing check for
        the whole batch via random linear combination —
        e(Σ z_i·sig_i, -g2) · e(Σ z_i·H(d_i), pk) == 1. The same
        soundness argument as batch_verify_shares (forged certs survive
        with probability 2^-128); on aggregate failure the rare path
        verifies per cert. Replaces k sequential ~2-pairing verifies with
        2 pairings + two k-point G1 MSMs."""
        out = [False] * len(items)
        pts, hs, idxs = [], [], []
        for i, (d, s) in enumerate(items):
            try:
                pt = bls.g1_decompress(s)
            except ValueError:
                continue
            if pt is None:
                continue
            pts.append(pt)
            hs.append(bls.hash_to_g1(d))
            idxs.append(i)
        if not pts:
            return out
        if len(pts) == 1:
            ok = bls.pairing_check([(pts[0], bls.g2_neg(bls.G2_GEN)),
                                    (hs[0], self._master_pk)])
            out[idxs[0]] = ok
            return out
        # the RLC transcript binds the FULL statement (master pk, each
        # digest, each signature) so coefficients are fixed only after
        # the adversary committed to every input, not just the sigs
        ctx = (b"certs" + bls.g2_compress(self._master_pk)
               + b"".join(items[i][0] + bls.g1_compress(p)
                          for i, p in zip(idxs, pts)))
        zs = bls._rlc_scalars(len(pts), ctx)
        agg_sig = bls.g1_msm(pts, zs)
        agg_h = bls.g1_msm(hs, zs)
        if bls.pairing_check([(agg_sig, bls.g2_neg(bls.G2_GEN)),
                              (agg_h, self._master_pk)]):
            for i in idxs:
                out[i] = True
            return out
        # aggregate failed (byzantine input in the batch): isolate
        for pt, h, i in zip(pts, hs, idxs):
            out[i] = bls.pairing_check([(pt, bls.g2_neg(bls.G2_GEN)),
                                        (h, self._master_pk)])
        return out

    # ---- fused cross-slot combine (the per-slot combine tax killer) ----

    def _decode_job_shares(self, shares: Dict[int, bytes]) -> Dict[int, object]:
        """Accumulator `add` semantics over a raw share dict: out-of-range
        ids and undecodable/infinity points are silently dropped — the
        job combines over what remains, exactly as the per-slot path."""
        pts: Dict[int, object] = {}
        for sid, share in shares.items():
            if not 1 <= sid <= self._total:
                continue
            try:
                pt = bls.g1_decompress(share)
            except ValueError:
                continue
            if pt is None:
                continue
            pts[sid] = pt
        return pts

    def _combine_segments(self, segments, digests=None) -> List:
        """[(ids, [share points])] -> one combined G1 point per segment.
        Host path: per-segment Lagrange + MSM; the TPU subclass folds
        every segment into ONE segmented multi-MSM device launch (and,
        with the offload tier active, leases the launch to a verified
        helper first). `digests` carries the per-segment slot digests —
        unused here, but the offload soundness check needs them to bind
        each returned point to its statement."""
        return [bls.combine_shares(ids, pts) if ids else None
                for ids, pts in segments]

    def combine_batch(self, jobs) -> List[Tuple[bool, bytes, List[int]]]:
        """Fused combine across slots: all jobs' Lagrange+MSM combines in
        one pass (one device launch on the TPU subclass), then ONE
        RLC-aggregated pairing check for every combined signature of the
        flush (`verify_batch_certs`). On aggregate failure the batcher
        isolates per job, and only failing jobs pay bad-share
        identification — one slot's byzantine share fails only its own
        job, sibling slots in the same flush still land. Verdicts are
        identical to the per-job default (interfaces.combine_batch)."""
        decoded = [(digest, self._decode_job_shares(shares))
                   for digest, shares in jobs]
        segments = []
        for _digest, pts in decoded:
            ids = sorted(pts)[: self._threshold]
            segments.append((ids, [pts[i] for i in ids]))
        combined = self._combine_segments(
            segments, digests=[digest for digest, _ in decoded])
        sigs = [bls.g1_compress(pt) for pt in combined]
        verdicts = self.verify_batch_certs(
            [(digest, sig) for (digest, _), sig in zip(decoded, sigs)])
        out: List[Tuple[bool, bytes, List[int]]] = []
        for (digest, pts), sig, ok in zip(decoded, sigs, verdicts):
            if ok:
                out.append((True, sig, []))
                continue
            out.append((False, b"", self._identify_bad(digest, pts)))
        return out

    def _identify_bad(self, digest: bytes, pts: Dict[int, object]
                      ) -> List[int]:
        """Aggregation-tree isolation over one failing job's decoded
        shares — the same BlsBatchVerifier walk the accumulator path
        runs (O(b·log n) pairing checks for b bad shares)."""
        h = bls.hash_to_g1(digest)
        ids = sorted(pts)
        tree = bls.BlsBatchVerifier([self.share_pk(i) for i in ids], h)
        verdicts = tree.batch_verify([pts[i] for i in ids])
        return [i for i, good in zip(ids, verdicts) if not good]

    @property
    def threshold(self) -> int:
        return self._threshold

    @property
    def total_signers(self) -> int:
        return self._total


class BlsThresholdFactory(IThresholdFactory):
    def new_signer(self, signer_id: int, secret_share: int) -> BlsThresholdSigner:
        return BlsThresholdSigner(signer_id, secret_share)

    def new_verifier(self, threshold, total, public_key, share_public_keys):
        return BlsThresholdVerifier(threshold, total, public_key, share_public_keys)

    def keygen(self, threshold: int, total: int, seed: Optional[bytes] = None):
        master_pk, share_pks, shares = bls.threshold_keygen(threshold, total, seed=seed)
        return master_pk, share_pks, shares


# ---------------- multisig-bls (BLS12-381, aggregation-friendly) ----------------
#
# n INDEPENDENT BLS keys (like multisig-ed25519, not Shamir): the combined
# certificate is an unweighted sum of identified G1 shares plus a
# contributor bitmap, verified against the sum of the contributors' G2
# public keys. Unlike Shamir threshold shares, any SUBSET of these shares
# sums to a meaningful partial aggregate — which is exactly what the
# share-aggregation overlay needs interior nodes to produce — so this is
# the scheme `share_aggregation` mode requires (the reference's
# "multisig-bls" role, threshsign BlsMultisigKeygen).

AGG_BITMAP_LEN = 8          # u64 LE contributor bitmap: 1-based id i -> bit i-1
AGG_CERT_LEN = AGG_BITMAP_LEN + 48   # bitmap + compressed G1 point


def pack_contributors(ids: Sequence[int]) -> int:
    bm = 0
    for i in ids:
        bm |= 1 << (i - 1)
    return bm


def unpack_contributors(bm: int) -> List[int]:
    return [i + 1 for i in range(bm.bit_length()) if bm >> i & 1]


def pack_agg_cert(ids: Sequence[int], pt) -> bytes:
    """THE multisig-bls certificate/partial encoding: u64 LE contributor
    bitmap + 48-byte compressed aggregate. One serializer for the
    accumulator, fused-combine, and interior-partial paths — byte-identity
    between a raw-share feed and a partial-aggregate feed of the same
    contributor set is a pinned invariant."""
    return struct.pack("<Q", pack_contributors(ids)) + bls.g1_compress(pt)


def unpack_agg_cert(blob: bytes) -> Optional[Tuple[List[int], object]]:
    """-> (sorted contributor ids, G1 point) or None if malformed."""
    if len(blob) != AGG_CERT_LEN:
        return None
    (bm,) = struct.unpack_from("<Q", blob, 0)
    if bm == 0:
        return None
    try:
        pt = bls.g1_decompress(blob[AGG_BITMAP_LEN:])
    except ValueError:
        return None
    if pt is None:
        return None
    return unpack_contributors(bm), pt


class BlsMultisigSigner(BlsThresholdSigner):
    """Same share shape as the threshold signer (H(m)^sk, compressed);
    only the key material differs (independent sk, not a Shamir share)."""


class BlsMultisigAccumulator(IThresholdAccumulator):
    """Accumulates raw shares AND interior-node partial aggregates.

    `add` accepts either form (48-byte raw share keyed by signer id, or a
    self-describing 56-byte bitmap+point partial) so the fused
    combine_batch default loop and the ShareCollector snapshot path feed
    it without caring which kind each entry is. Contributor sets must be
    disjoint — an overlapping add is rejected (first-come wins), which
    keeps the final sum a plain union and the cert deterministic."""

    def __init__(self, verifier: "BlsMultisigVerifier", share_verification: bool):
        self._verifier = verifier
        self._share_verification = share_verification
        self._digest: Optional[bytes] = None
        # entry key (signer id for raw, arbitrary for partial) ->
        # (contributor-id tuple, G1 point)
        self._entries: Dict[int, Tuple[Tuple[int, ...], object]] = {}
        self._contrib: set = set()

    def set_expected_digest(self, digest: bytes) -> None:
        self._digest = digest

    def _count(self) -> int:
        return len(self._contrib)

    def add(self, share_id: int, share: bytes) -> int:
        if len(share) == AGG_CERT_LEN:
            return self._add_partial_entry(share_id, share)
        if not 1 <= share_id <= self._verifier.total_signers:
            return self._count()
        if share_id in self._contrib:
            return self._count()
        try:
            pt = bls.g1_decompress(share)
        except ValueError:
            return self._count()
        if pt is None:
            return self._count()
        if self._share_verification and self._digest is not None:
            if not self._verifier.verify_share(share_id, self._digest, share):
                return self._count()
        self._entries[share_id] = ((share_id,), pt)
        self._contrib.add(share_id)
        return self._count()

    def add_partial(self, partial: bytes) -> int:
        """Absorb an interior node's partial aggregate; entry key is the
        smallest contributor id (stable + collision-free given the
        disjointness rule)."""
        dec = unpack_agg_cert(partial)
        if dec is None:
            return self._count()
        return self._add_partial_entry(dec[0][0], partial)

    def _add_partial_entry(self, key: int, partial: bytes) -> int:
        dec = unpack_agg_cert(partial)
        if dec is None:
            return self._count()
        ids, pt = dec
        if any(i > self._verifier.total_signers for i in ids):
            return self._count()
        if self._contrib.intersection(ids):
            return self._count()          # overlap: first-come wins
        if self._share_verification and self._digest is not None:
            if not bls.verify(self._verifier.agg_pk(ids), self._digest, pt):
                return self._count()
        self._entries[key] = (tuple(ids), pt)
        self._contrib.update(ids)
        return self._count()

    def has_threshold(self) -> bool:
        return self._count() >= self._verifier.threshold

    def contributor_ids(self) -> List[int]:
        return sorted(self._contrib)

    def points(self) -> List[object]:
        """Entry points in sorted-entry-key order (summation input)."""
        return [self._entries[k][1] for k in sorted(self._entries)]

    def get_full_signed_data(self) -> bytes:
        """ALL accumulated contributors, never threshold-truncated: the
        cert bytes depend only on the contributor SET, so a raw-share
        feed and a partial-aggregate feed of the same signers produce
        identical certificates."""
        acc = None
        for pt in self.points():
            acc = bls.g1_add(acc, pt)
        return pack_agg_cert(self.contributor_ids(), acc)

    def partial_signed_data(self) -> bytes:
        """Current partial aggregate (what an interior node flushes up).
        Same encoding as the certificate — a partial IS a cert over a
        sub-threshold contributor set."""
        return self.get_full_signed_data()

    def identify_bad_shares(self) -> List[int]:
        assert self._digest is not None
        return self._verifier._identify_bad_entries(self._digest, self._entries)


class BlsMultisigVerifier(IThresholdVerifier):
    def __init__(self, threshold: int, total: int, share_pks):
        self._threshold = threshold
        self._total = total
        self._share_pks = share_pks
        self._apk_cache: Dict[int, object] = {}

    def new_accumulator(self, with_share_verification: bool) -> BlsMultisigAccumulator:
        return BlsMultisigAccumulator(self, with_share_verification)

    @property
    def supports_partial_aggregation(self) -> bool:
        return True

    def share_weight(self, share: bytes) -> int:
        if len(share) == AGG_CERT_LEN:
            (bm,) = struct.unpack_from("<Q", share, 0)
            return max(bin(bm).count("1"), 1)
        return 1

    def share_pk(self, share_id: int):
        if not 1 <= share_id <= self._total:
            raise ValueError(f"share id {share_id} out of range 1..{self._total}")
        return self._share_pks[share_id - 1]

    def agg_pk(self, ids: Sequence[int]):
        """Sum of the contributors' G2 public keys (cached by bitmap —
        overlay subtrees recur across slots, so hit rates are high)."""
        bm = pack_contributors(ids)
        apk = self._apk_cache.get(bm)
        if apk is None:
            apk = None
            for i in ids:
                apk = bls.g2_add(apk, self.share_pk(i)) if apk is not None \
                    else self.share_pk(i)
            if len(self._apk_cache) > 4096:
                self._apk_cache.clear()
            self._apk_cache[bm] = apk
        return apk

    def verify_share(self, share_id: int, data: bytes, share: bytes) -> bool:
        if not 1 <= share_id <= self._total:
            return False
        try:
            pt = bls.g1_decompress(share)
        except ValueError:
            return False
        return bls.verify(self.share_pk(share_id), data, pt)

    def verify(self, data: bytes, sig: bytes) -> bool:
        dec = unpack_agg_cert(sig)
        if dec is None:
            return False
        ids, pt = dec
        if len(ids) < self._threshold or ids[-1] > self._total:
            return False
        return bls.verify(self.agg_pk(ids), data, pt)

    def verify_batch_certs(self, items) -> List[bool]:
        """Aggregated verification with PER-CERT aggregate public keys:
        e(Σ z_i·sig_i, -g2) · Π e(z_i·H(d_i), apk_i) == 1 — one Miller
        batch of m+1 pairings instead of 2m (each apk differs, so the
        H-side cannot fold to a single pairing the way the master-pk
        threshold scheme's can). Per-cert loop on aggregate failure."""
        out = [False] * len(items)
        decoded = []
        for i, (d, s) in enumerate(items):
            dec = unpack_agg_cert(s)
            if dec is None:
                continue
            ids, pt = dec
            if len(ids) < self._threshold or ids[-1] > self._total:
                continue
            decoded.append((i, d, ids, pt))
        if not decoded:
            return out
        if len(decoded) == 1:
            i, d, ids, pt = decoded[0]
            out[i] = bls.verify(self.agg_pk(ids), d, pt)
            return out
        ctx = b"agg-certs" + b"".join(
            d + struct.pack("<Q", pack_contributors(ids)) + bls.g1_compress(pt)
            for _, d, ids, pt in decoded)
        zs = bls._rlc_scalars(len(decoded), ctx)
        agg_sig = bls.g1_msm([pt for _, _, _, pt in decoded], zs)
        pairs = [(agg_sig, bls.g2_neg(bls.G2_GEN))]
        for z, (_, d, ids, _) in zip(zs, decoded):
            pairs.append((bls.g1_mul(bls.hash_to_g1(d), z), self.agg_pk(ids)))
        if bls.pairing_check(pairs):
            for i, _, _, _ in decoded:
                out[i] = True
            return out
        for i, d, ids, pt in decoded:
            out[i] = bls.verify(self.agg_pk(ids), d, pt)
        return out

    # ---- fused cross-slot combine (CombineBatcher protocol) ----

    def _decode_job_entries(self, shares: Dict[int, bytes]
                            ) -> Dict[int, Tuple[Tuple[int, ...], object]]:
        """Snapshot-dict decode with accumulator `add` semantics: raw
        48-byte shares keyed by signer id, 56-byte partials keyed by the
        forwarding child; malformed/out-of-range/overlapping entries
        silently dropped. Entries are visited heaviest-first (contributor
        popcount, key as the deterministic tie-break) so a duplicate —
        e.g. a parent-timeout fallback raw whose signer already rides a
        subtree partial — is the entry dropped, never the partial: the
        surviving contributor union stays maximal, keeping the combined
        cert at or above threshold."""
        entries: Dict[int, Tuple[Tuple[int, ...], object]] = {}
        taken: set = set()
        for key in sorted(shares,
                          key=lambda k: (-self.share_weight(shares[k]), k)):
            blob = shares[key]
            if len(blob) == AGG_CERT_LEN:
                dec = unpack_agg_cert(blob)
                if dec is None:
                    continue
                ids, pt = dec
                if ids[-1] > self._total or taken.intersection(ids):
                    continue
                entries[key] = (tuple(ids), pt)
                taken.update(ids)
            else:
                if not 1 <= key <= self._total or key in taken:
                    continue
                try:
                    pt = bls.g1_decompress(blob)
                except ValueError:
                    continue
                if pt is None:
                    continue
                entries[key] = ((key,), pt)
                taken.add(key)
        return entries

    def _sum_segments(self, segments: List[List[object]],
                      meta=None) -> List[object]:
        """[[points]] -> one unweighted G1 sum per segment. Host path:
        sequential adds; the TPU subclass folds every segment into ONE
        all-ones-scalar segmented multi-MSM launch (the PR 11 kernel,
        new call shape). `meta` = per-segment (digest, contributor ids)
        or None — only the offload tier consumes it (the soundness
        check verifies each leased sum against its contributors'
        aggregate pk); `aggregate_partials` passes none, so interior
        overlay sums never offload (no digest to bind them to)."""
        out = []
        for pts in segments:
            acc = None
            for pt in pts:
                acc = bls.g1_add(acc, pt)
            out.append(acc)
        return out

    def aggregate_partials(self, jobs: List[Tuple[List[int], List[object]]]
                           ) -> List[bytes]:
        """Interior-node flush: [(contributor ids, entry points)] -> one
        packed partial per job, all sums in one `_sum_segments` pass (one
        device launch on the TPU subclass)."""
        sums = self._sum_segments([pts for _, pts in jobs])
        return [pack_agg_cert(ids, pt) for (ids, _), pt in zip(jobs, sums)]

    def combine_batch(self, jobs) -> List[Tuple[bool, bytes, List[int]]]:
        decoded = [(digest, self._decode_job_entries(shares))
                   for digest, shares in jobs]
        # contributor ids are known BEFORE the sums (they come from the
        # entry bitmaps, not the arithmetic) — computing them first
        # hands the offload tier the metadata its soundness check binds
        # each leased sum to
        ids_list = [tuple(sorted(i for ids, _ in entries.values()
                                 for i in ids))
                    for _, entries in decoded]
        sums = self._sum_segments(
            [[pt for _, pt in entries.values()] for _, entries in decoded],
            meta=[(digest, ids) if ids else None
                  for (digest, _), ids in zip(decoded, ids_list)])
        certs = [pack_agg_cert(list(ids), pt) if ids else b""
                 for ids, pt in zip(ids_list, sums)]
        verdicts = self.verify_batch_certs(
            [(digest, cert) for (digest, _), cert in zip(decoded, certs)])
        out: List[Tuple[bool, bytes, List[int]]] = []
        for (digest, entries), cert, ok in zip(decoded, certs, verdicts):
            if ok:
                out.append((True, cert, []))
            else:
                out.append((False, b"",
                            self._identify_bad_entries(digest, entries)))
        return out

    def _identify_bad_entries(self, digest: bytes,
                              entries: Dict[int, Tuple[Tuple[int, ...], object]]
                              ) -> List[int]:
        """Contributor-bitmap bisection: each entry (raw share OR subtree
        partial) verifies against its bitmap's aggregate pk, walked with
        the O(b·log n) aggregation tree — a forged partial indicts
        exactly its subtree's entry key, so the collector drops that
        subtree and the direct-send fallback refills it."""
        keys = sorted(entries)
        if not keys:
            return []
        h = bls.hash_to_g1(digest)
        tree = bls.BlsBatchVerifier(
            [self.agg_pk(entries[k][0]) for k in keys], h)
        verdicts = tree.batch_verify([entries[k][1] for k in keys])
        return [k for k, good in zip(keys, verdicts) if not good]

    @property
    def threshold(self) -> int:
        return self._threshold

    @property
    def total_signers(self) -> int:
        return self._total


class BlsMultisigFactory(IThresholdFactory):
    def new_signer(self, signer_id: int, secret_share: int) -> BlsMultisigSigner:
        return BlsMultisigSigner(signer_id, secret_share)

    def new_verifier(self, threshold, total, public_key, share_public_keys):
        return BlsMultisigVerifier(threshold, total, share_public_keys)

    def keygen(self, threshold: int, total: int, seed: Optional[bytes] = None):
        import hashlib
        sks, pks = [], []
        for i in range(total):
            s = (hashlib.sha256(b"ms-bls" + seed + i.to_bytes(4, "big")).digest()
                 if seed is not None else None)
            sk, pk = bls.keygen(seed=s)
            sks.append(sk)
            pks.append(pk)
        # no single master public key for multisig; use the pk list
        return pks, pks, sks


def register_builtin(type_name: str) -> None:
    if type_name == "multisig-ed25519":
        Cryptosystem.register_type(type_name, MultisigEd25519Factory())
    elif type_name == "multisig-bls":
        Cryptosystem.register_type(type_name, BlsMultisigFactory())
    elif type_name == "threshold-bls":
        Cryptosystem.register_type(type_name, BlsThresholdFactory())
    else:
        raise ValueError(f"unknown cryptosystem type {type_name}"
                         + (" ('adaptive' must be resolved by "
                            "resolve_threshold_scheme before key "
                            "generation)" if type_name == "adaptive"
                            else ""))


# Default n-crossover for the "adaptive" certificate scheme. Below it a
# cluster certifies with the Ed25519 multisig vector (k constant-time
# EdDSA verifies, batch-friendly, zero G1 ladder math); at or above it
# with compact BLS threshold certificates (48 bytes on the wire and in
# every carried proof, vs 66·k for the vector). The EdDSA-vs-BLS
# committee measurements (arXiv 2302.00418) put per-share threshold math
# far above EdDSA cost at committee sizes this small; the default is
# picked by `python -m benchmarks.bench_combine --crossover`
# (benchmarks/RESULTS.md) and overridable per cluster via
# ReplicaConfig.threshold_scheme_crossover_n.
ADAPTIVE_SCHEME_CROSSOVER_N = 16


def resolve_threshold_scheme(scheme: str, n: int,
                             crossover_n: int = 0,
                             aggregation: str = "off") -> str:
    """Configure-time resolution of the certificate scheme: "adaptive"
    becomes a concrete cryptosystem type from the cluster size, anything
    else passes through. Every replica must resolve identically (same n,
    same crossover, same aggregation mode) — the scheme is part of the
    cluster's key material, so it is resolved once at keygen, never
    re-negotiated on the wire.

    When share aggregation is on, "adaptive" resolves to "multisig-bls"
    regardless of n: interior overlay nodes must produce partial
    aggregates, which Shamir threshold shares cannot (the Lagrange
    weights depend on the final contributor set) and the Ed25519 vector
    only can by concatenation (no bandwidth win). BLS multisig partials
    are a constant 56 bytes at every tree level, which is the whole
    point of aggregating (arXiv 1911.04698)."""
    if scheme != "adaptive":
        return scheme
    if aggregation and aggregation != "off":
        return "multisig-bls"
    cx = crossover_n or ADAPTIVE_SCHEME_CROSSOVER_N
    return "multisig-ed25519" if n < cx else "threshold-bls"
