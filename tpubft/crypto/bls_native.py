"""ctypes bridge to the native BLS12-381 engine (native/bls12381.cpp).

The RELIC role in the reference (threshsign/src/bls/relic/): pairing
checks and G1/G2 multi-scalar multiplications in C++ instead of pure
Python — the ~100x that takes a combined-certificate verification from
~1 s to low milliseconds. Falls back transparently: callers go through
tpubft.crypto.bls12381, which routes here only when the library builds
(set TPUBFT_NO_NATIVE=1 to force the pure-Python paths)."""
from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Sequence, Tuple

_lib = None
_tried = False


def available() -> bool:
    global _lib, _tried
    if _tried:
        return _lib is not None
    _tried = True
    if os.environ.get("TPUBFT_NO_NATIVE"):
        return False
    try:
        from tpubft.native.build import load
        lib = load("bls12381")
        lib.bls381_pairing_check.restype = ctypes.c_int
        lib.bls381_g1_msm.restype = ctypes.c_int
        lib.bls381_g2_msm.restype = ctypes.c_int
        lib.bls381_g1_decompress.restype = ctypes.c_int
        lib.bls381_fp_sqrt.restype = ctypes.c_int
        _lib = lib
    except Exception:  # noqa: BLE001 — no toolchain: pure-Python fallback
        _lib = None
    return _lib is not None


def _fp48(x: int) -> bytes:
    return x.to_bytes(48, "big")


def pairing_check(pairs: Sequence[Tuple[object, object]]) -> bool:
    """prod e(P_i, Q_i) == 1 with P affine G1 int tuples, Q affine G2
    Fp2-tuple points (None = infinity) — same contract as the Python
    pairing_check."""
    n = len(pairs)
    g1 = bytearray(96 * n)
    g2 = bytearray(192 * n)
    infs = bytearray(n)
    for i, (p, q) in enumerate(pairs):
        if p is None:
            infs[i] |= 1
        else:
            g1[96 * i:96 * i + 48] = _fp48(p[0])
            g1[96 * i + 48:96 * i + 96] = _fp48(p[1])
        if q is None:
            infs[i] |= 2
        else:
            (x0, x1), (y0, y1) = q
            off = 192 * i
            g2[off:off + 48] = _fp48(x0)
            g2[off + 48:off + 96] = _fp48(x1)
            g2[off + 96:off + 144] = _fp48(y0)
            g2[off + 144:off + 192] = _fp48(y1)
    ok = _lib.bls381_pairing_check(
        bytes(g1), bytes(g2), bytes(infs), n)
    return ok == 1


def g1_msm(points: Sequence, scalars: Sequence[int]):
    """sum_i [k_i] P_i over affine G1 int-tuple points -> point/None."""
    n = len(points)
    pts = bytearray(96 * n)
    infs = bytearray(n)
    ks = bytearray(32 * n)
    for i, (p, k) in enumerate(zip(points, scalars)):
        if p is None:
            infs[i] = 1
        else:
            pts[96 * i:96 * i + 48] = _fp48(p[0])
            pts[96 * i + 48:96 * i + 96] = _fp48(p[1])
        ks[32 * i:32 * i + 32] = (k % _R).to_bytes(32, "big")
    out = ctypes.create_string_buffer(96)
    out_inf = ctypes.c_uint8(0)
    _lib.bls381_g1_msm(out, ctypes.byref(out_inf), bytes(pts), bytes(infs),
                       bytes(ks), n)
    if out_inf.value:
        return None
    raw = out.raw
    return (int.from_bytes(raw[:48], "big"), int.from_bytes(raw[48:], "big"))


def g1_mul(point, k: int):
    return g1_msm([point], [k])


def fp_sqrt(x: int):
    """sqrt mod p for 0 <= x < p, or None when x is not a QR."""
    out = ctypes.create_string_buffer(48)
    if _lib.bls381_fp_sqrt(out, x.to_bytes(48, "big")) != 1:
        return None
    return int.from_bytes(out.raw, "big")


def g1_decompress(b: bytes):
    """Decode one compressed G1 point (canonical + on-curve checks, sqrt
    in native code; NO subgroup check — bls12381.g1_decompress layers the
    GLV membership test on top). Returns the affine int tuple, None for
    canonical infinity; raises ValueError on invalid encodings."""
    if len(b) != 48:
        raise ValueError("bad G1 encoding length")
    out = ctypes.create_string_buffer(96)
    rc = _lib.bls381_g1_decompress(out, bytes(b))
    if rc == 2:
        return None
    if rc != 1:
        raise ValueError("invalid G1 encoding")
    raw = out.raw
    return (int.from_bytes(raw[:48], "big"), int.from_bytes(raw[48:], "big"))


def g1_mul_nonorder(point, k: int):
    """[k]P without reducing k mod R (order/cofactor checks; k < 2^256)."""
    if point is None or k == 0:
        return None
    pts = _fp48(point[0]) + _fp48(point[1])
    out = ctypes.create_string_buffer(96)
    out_inf = ctypes.c_uint8(0)
    _lib.bls381_g1_msm(out, ctypes.byref(out_inf), pts, b"\x00",
                       k.to_bytes(32, "big"), 1)
    if out_inf.value:
        return None
    raw = out.raw
    return (int.from_bytes(raw[:48], "big"), int.from_bytes(raw[48:], "big"))


def g2_mul_nonorder(point, k: int):
    if point is None or k == 0:
        return None
    (x0, x1), (y0, y1) = point
    pts = _fp48(x0) + _fp48(x1) + _fp48(y0) + _fp48(y1)
    out = ctypes.create_string_buffer(192)
    out_inf = ctypes.c_uint8(0)
    _lib.bls381_g2_msm(out, ctypes.byref(out_inf), pts, b"\x00",
                       k.to_bytes(32, "big"), 1)
    if out_inf.value:
        return None
    raw = out.raw
    return ((int.from_bytes(raw[:48], "big"),
             int.from_bytes(raw[48:96], "big")),
            (int.from_bytes(raw[96:144], "big"),
             int.from_bytes(raw[144:], "big")))


def g2_msm(points: Sequence, scalars: Sequence[int]):
    n = len(points)
    pts = bytearray(192 * n)
    infs = bytearray(n)
    ks = bytearray(32 * n)
    for i, (q, k) in enumerate(zip(points, scalars)):
        if q is None:
            infs[i] = 1
        else:
            (x0, x1), (y0, y1) = q
            off = 192 * i
            pts[off:off + 48] = _fp48(x0)
            pts[off + 48:off + 96] = _fp48(x1)
            pts[off + 96:off + 144] = _fp48(y0)
            pts[off + 144:off + 192] = _fp48(y1)
        ks[32 * i:32 * i + 32] = (k % _R).to_bytes(32, "big")
    out = ctypes.create_string_buffer(192)
    out_inf = ctypes.c_uint8(0)
    _lib.bls381_g2_msm(out, ctypes.byref(out_inf), bytes(pts), bytes(infs),
                       bytes(ks), n)
    if out_inf.value:
        return None
    raw = out.raw
    return ((int.from_bytes(raw[:48], "big"),
             int.from_bytes(raw[48:96], "big")),
            (int.from_bytes(raw[96:144], "big"),
             int.from_bytes(raw[144:], "big")))


def g2_mul(point, k: int):
    return g2_msm([point], [k])


_R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
