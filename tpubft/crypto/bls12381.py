"""BLS12-381 reference implementation in pure Python (CPU backend + golden model).

Plays the role RELIC plays in the reference (threshsign/src/bls/relic/ —
SURVEY.md §2.2): field/curve arithmetic, hashing to the curve, BLS signatures,
threshold (Shamir) key generation, Lagrange interpolation, and pairing-based
verification. The reference uses BN-P254; we use BLS12-381 (the modern curve,
and the one BASELINE.md's north star names for the TPU MSM).

Convention: "min-sig" — signatures/hashes in G1 (cheap shares + G1 MSM on
TPU), public keys in G2. Verify: e(sig, -g2) * e(H(m), pk) == 1.

This module is deliberately written with Python ints for clarity and
correctness; the batched TPU implementation lives in tpubft/ops/ and is
tested against this one.
"""
from __future__ import annotations

import hashlib
import secrets
from typing import List, Optional, Sequence, Tuple

# ---------------- curve constants ----------------

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001  # group order
X_PARAM = -0xD201000000010000        # BLS parameter x (negative)
H_EFF_G1 = 0xD201000000010001        # 1 - x : effective G1 cofactor multiplier

G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GEN = (
    (0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
     0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E),
    (0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
     0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE),
)
B1 = 4          # G1: y^2 = x^3 + 4
B2 = (4, 4)     # G2: y^2 = x^3 + 4(1+u)


# ---------------- Fp ----------------

def fp_inv(a: int) -> int:
    return pow(a, P - 2, P)


def fp_sqrt(a: int) -> Optional[int]:
    """p ≡ 3 (mod 4) → candidate a^((p+1)/4); native modexp when built
    (the Python pow dominates hash-to-curve and decompress otherwise)."""
    from tpubft.crypto import bls_native
    if bls_native.available():
        return bls_native.fp_sqrt(a % P)
    c = pow(a, (P + 1) // 4, P)
    return c if c * c % P == a % P else None


# ---------------- Fp2 = Fp[u]/(u^2+1) ----------------
# elements are tuples (c0, c1) = c0 + c1*u

def fp2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def fp2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def fp2_neg(a):
    return ((-a[0]) % P, (-a[1]) % P)


def fp2_mul(a, b):
    # Karatsuba: (a0+a1 u)(b0+b1 u) = (a0b0 - a1b1) + (a0b1 + a1b0) u
    t0 = a[0] * b[0] % P
    t1 = a[1] * b[1] % P
    t2 = (a[0] + a[1]) * (b[0] + b[1]) % P
    return ((t0 - t1) % P, (t2 - t0 - t1) % P)


def fp2_sqr(a):
    # (a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
    t0 = (a[0] + a[1]) % P
    t1 = (a[0] - a[1]) % P
    t2 = a[0] * a[1] % P
    return (t0 * t1 % P, 2 * t2 % P)


def fp2_mul_scalar(a, k: int):
    return (a[0] * k % P, a[1] * k % P)


def fp2_conj(a):
    return (a[0], (-a[1]) % P)


def fp2_inv(a):
    t = fp_inv((a[0] * a[0] + a[1] * a[1]) % P)
    return (a[0] * t % P, (-a[1] * t) % P)


def fp2_sqrt(a) -> Optional[Tuple[int, int]]:
    """Sqrt in Fp2 via the p ≡ 3 mod 4 complex method (used for G2 decompress)."""
    if a == (0, 0):
        return (0, 0)
    # candidate = a^((p^2+7)/16)-style shortcut does not apply; use generic:
    # alpha = a^((p-3)/4) ... use the simple algorithm: c = a^((p^2+7)/16)? For
    # p^2 ≡ 9 mod 16. Simplest reliable route: solve via Fp norm equation.
    # norm = a0^2 + a1^2 must be QR in Fp: n = sqrt(norm)
    norm = (a[0] * a[0] + a[1] * a[1]) % P
    n = fp_sqrt(norm)
    if n is None:
        return None
    for sign in (1, -1):
        # x0^2 = (a0 + n)/2  (try both signs of n)
        t = (a[0] + sign * n) % P * fp_inv(2) % P
        x0 = fp_sqrt(t)
        if x0 is None or x0 == 0:
            continue
        x1 = a[1] * fp_inv(2 * x0 % P) % P
        cand = (x0, x1)
        if fp2_sqr(cand) == (a[0] % P, a[1] % P):
            return cand
    return None


FP2_ONE = (1, 0)
FP2_ZERO = (0, 0)
FP2_U_PLUS_1 = (1, 1)


# ---------------- Fp6 = Fp2[v]/(v^3 - (u+1)) ----------------
# elements: (c0, c1, c2) with ci in Fp2

def fp6_add(a, b):
    return tuple(fp2_add(x, y) for x, y in zip(a, b))


def fp6_sub(a, b):
    return tuple(fp2_sub(x, y) for x, y in zip(a, b))


def fp6_neg(a):
    return tuple(fp2_neg(x) for x in a)


def _mul_by_xi(a):  # multiply Fp2 element by xi = u+1
    return fp2_mul(a, FP2_U_PLUS_1)


def fp6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = fp2_mul(a0, b0)
    t1 = fp2_mul(a1, b1)
    t2 = fp2_mul(a2, b2)
    c0 = fp2_add(t0, _mul_by_xi(fp2_sub(fp2_mul(fp2_add(a1, a2), fp2_add(b1, b2)), fp2_add(t1, t2))))
    c1 = fp2_add(fp2_sub(fp2_mul(fp2_add(a0, a1), fp2_add(b0, b1)), fp2_add(t0, t1)), _mul_by_xi(t2))
    c2 = fp2_add(fp2_sub(fp2_mul(fp2_add(a0, a2), fp2_add(b0, b2)), fp2_add(t0, t2)), t1)
    return (c0, c1, c2)


def fp6_sqr(a):
    return fp6_mul(a, a)


def fp6_inv(a):
    a0, a1, a2 = a
    c0 = fp2_sub(fp2_sqr(a0), _mul_by_xi(fp2_mul(a1, a2)))
    c1 = fp2_sub(_mul_by_xi(fp2_sqr(a2)), fp2_mul(a0, a1))
    c2 = fp2_sub(fp2_sqr(a1), fp2_mul(a0, a2))
    t = fp2_inv(fp2_add(fp2_mul(a0, c0),
                        _mul_by_xi(fp2_add(fp2_mul(a2, c1), fp2_mul(a1, c2)))))
    return (fp2_mul(c0, t), fp2_mul(c1, t), fp2_mul(c2, t))


FP6_ZERO = (FP2_ZERO, FP2_ZERO, FP2_ZERO)
FP6_ONE = (FP2_ONE, FP2_ZERO, FP2_ZERO)


# ---------------- Fp12 = Fp6[w]/(w^2 - v) ----------------
# elements: (c0, c1) with ci in Fp6

FP12_ONE = (FP6_ONE, FP6_ZERO)


def fp12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = fp6_mul(a0, b0)
    t1 = fp6_mul(a1, b1)
    # v * t1 : multiply Fp6 element by v (shift with xi wrap)
    vt1 = (_mul_by_xi(t1[2]), t1[0], t1[1])
    c0 = fp6_add(t0, vt1)
    c1 = fp6_sub(fp6_mul(fp6_add(a0, a1), fp6_add(b0, b1)), fp6_add(t0, t1))
    return (c0, c1)


def fp12_sqr(a):
    return fp12_mul(a, a)


def fp12_conj(a):
    return (a[0], fp6_neg(a[1]))


def fp12_inv(a):
    a0, a1 = a
    t1 = fp6_sqr(a1)
    vt1 = (_mul_by_xi(t1[2]), t1[0], t1[1])
    t = fp6_inv(fp6_sub(fp6_sqr(a0), vt1))
    return (fp6_mul(a0, t), fp6_neg(fp6_mul(a1, t)))


def fp12_pow(a, e: int):
    if e < 0:
        return fp12_pow(fp12_inv(a), -e)
    result = FP12_ONE
    base = a
    while e:
        if e & 1:
            result = fp12_mul(result, base)
        base = fp12_sqr(base)
        e >>= 1
    return result


# ---------------- G1 (affine/jacobian over Fp) ----------------
# Points: None = infinity, else (x, y) affine.

def g1_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - (x * x % P * x + B1)) % P == 0


def g1_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        # doubling
        lam = 3 * x1 * x1 % P * fp_inv(2 * y1 % P) % P
    else:
        lam = (y2 - y1) * fp_inv((x2 - x1) % P) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def g1_neg(pt):
    if pt is None:
        return None
    return (pt[0], (-pt[1]) % P)


def g1_mul_py(pt, k: int):
    k %= R
    result = None
    add = pt
    while k:
        if k & 1:
            result = g1_add(result, add)
        add = g1_add(add, add)
        k >>= 1
    return result


def g1_mul(pt, k: int):
    from tpubft.crypto import bls_native
    if bls_native.available():
        return bls_native.g1_mul(pt, k)
    return g1_mul_py(pt, k)


def g1_msm_py(points: Sequence, scalars: Sequence[int]):
    """Pure-Python MSM (golden model). Must stay independent of the
    native engine — it is the differential oracle the engine is tested
    against, so it composes g1_mul_py, never the routed g1_mul."""
    acc = None
    for pt, k in zip(points, scalars):
        acc = g1_add(acc, g1_mul_py(pt, k))
    return acc


def g1_msm(points: Sequence, scalars: Sequence[int]):
    """Multi-scalar multiplication sum_i [k_i] P_i (the hot accumulate
    op); native engine when available."""
    from tpubft.crypto import bls_native
    if bls_native.available():
        return bls_native.g1_msm(points, scalars)
    return g1_msm_py(points, scalars)


# ---------------- G2 (affine over Fp2) ----------------

def g2_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return fp2_sub(fp2_sqr(y), fp2_add(fp2_mul(fp2_sqr(x), x), B2)) == FP2_ZERO


def g2_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if fp2_add(y1, y2) == FP2_ZERO:
            return None
        lam = fp2_mul(fp2_mul_scalar(fp2_sqr(x1), 3), fp2_inv(fp2_mul_scalar(y1, 2)))
    else:
        lam = fp2_mul(fp2_sub(y2, y1), fp2_inv(fp2_sub(x2, x1)))
    x3 = fp2_sub(fp2_sub(fp2_sqr(lam), x1), x2)
    y3 = fp2_sub(fp2_mul(lam, fp2_sub(x1, x3)), y1)
    return (x3, y3)


def g2_neg(pt):
    if pt is None:
        return None
    return (pt[0], fp2_neg(pt[1]))


def g2_mul_py(pt, k: int):
    k %= R
    result = None
    add = pt
    while k:
        if k & 1:
            result = g2_add(result, add)
        add = g2_add(add, add)
        k >>= 1
    return result


def g2_mul(pt, k: int):
    from tpubft.crypto import bls_native
    if bls_native.available():
        return bls_native.g2_mul(pt, k)
    return g2_mul_py(pt, k)


# ---------------- pairing (ate, Miller loop + final exponentiation) ----------------

def _untwist(pt):
    """Embed a G2 point (Fp2 coords) into E(Fp12) via the untwist map
    x' = x / w^2, y' = y / w^3 (D-type twist, w^2 = v). Built with generic
    Fp12 ops — this is the correctness-reference path, not the fast path."""
    x, y = pt
    W = (FP6_ZERO, FP6_ONE)                 # w
    W2 = fp12_mul(W, W)
    W3 = fp12_mul(W2, W)
    x12 = fp12_mul(_fp2_to_fp12(x), fp12_inv(W2))
    y12 = fp12_mul(_fp2_to_fp12(y), fp12_inv(W3))
    return (x12, y12)


def _fp2_to_fp12(a):
    return ((a, FP2_ZERO, FP2_ZERO), FP6_ZERO)


def _fp12_pt_add(p1, p2):
    """Affine addition on E(Fp12): y^2 = x^3 + 4."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if fp12_add(y1, y2) == _FP12_ZERO:
            return None
        lam = fp12_mul(fp12_scalar(fp12_sqr(x1), 3), fp12_inv(fp12_scalar(y1, 2)))
    else:
        lam = fp12_mul(fp12_sub(y2, y1), fp12_inv(fp12_sub(x2, x1)))
    x3 = fp12_sub(fp12_sub(fp12_sqr(lam), x1), x2)
    y3 = fp12_sub(fp12_mul(lam, fp12_sub(x1, x3)), y1)
    return (x3, y3)


_FP12_ZERO = (FP6_ZERO, FP6_ZERO)


def fp12_add(a, b):
    return (fp6_add(a[0], b[0]), fp6_add(a[1], b[1]))


def fp12_sub(a, b):
    return (fp6_sub(a[0], b[0]), fp6_sub(a[1], b[1]))


def fp12_scalar(a, k: int):
    return (tuple(fp2_mul_scalar(c, k) for c in a[0]),
            tuple(fp2_mul_scalar(c, k) for c in a[1]))


def _fp12_line(p1, p2, q):
    """Line through p1,p2 on E(Fp12) (or tangent if equal) evaluated at q."""
    x1, y1 = p1
    x2, y2 = p2
    xq, yq = q
    if x1 == x2 and y1 == y2:
        lam = fp12_mul(fp12_scalar(fp12_sqr(x1), 3), fp12_inv(fp12_scalar(y1, 2)))
    elif x1 == x2:
        # vertical line
        return fp12_sub(xq, x1)
    else:
        lam = fp12_mul(fp12_sub(y2, y1), fp12_inv(fp12_sub(x2, x1)))
    return fp12_sub(fp12_sub(yq, y1), fp12_mul(lam, fp12_sub(xq, x1)))


def miller_loop(q_g2, p_g1):
    """f_{|x|, Q}(P) over E(Fp12), textbook double-and-add Miller loop."""
    if q_g2 is None or p_g1 is None:
        return FP12_ONE
    Q = _untwist(q_g2)
    Pt = (_int_to_fp12(p_g1[0]), _int_to_fp12(p_g1[1]))
    T = Q
    f = FP12_ONE
    n = -X_PARAM  # positive loop count
    for i in reversed(range(n.bit_length() - 1)):
        f = fp12_mul(fp12_sqr(f), _fp12_line(T, T, Pt))
        T = _fp12_pt_add(T, T)
        if (n >> i) & 1:
            f = fp12_mul(f, _fp12_line(T, Q, Pt))
            T = _fp12_pt_add(T, Q)
    # x < 0: conjugate (valid up to final exponentiation since exponent
    # contains the factor p^6 - 1 and conj = inverse for unitary results)
    return fp12_conj(f)


def _int_to_fp12(a: int):
    return (((a % P, 0), FP2_ZERO, FP2_ZERO), FP6_ZERO)


def final_exponentiation(f):
    """f^((p^12-1)/r) — direct exponentiation (reference impl, not fast)."""
    return fp12_pow(f, (P ** 12 - 1) // R)


def pairing(p_g1, q_g2):
    """e(P, Q) for P in G1, Q in G2."""
    return final_exponentiation(miller_loop(q_g2, p_g1))


def pairing_check_py(pairs: Sequence[Tuple[object, object]]) -> bool:
    """Pure-Python multi-pairing product check (golden model)."""
    f = FP12_ONE
    for p_g1, q_g2 in pairs:
        f = fp12_mul(f, miller_loop(q_g2, p_g1))
    return final_exponentiation(f) == FP12_ONE


def pairing_check(pairs: Sequence[Tuple[object, object]]) -> bool:
    """prod e(Pi, Qi) == 1 — the multi-pairing product check. Routed to
    the native engine (tpubft/native/bls12381.cpp, the RELIC role) when
    it builds; the pure-Python path is the differential-tested fallback."""
    from tpubft.crypto import bls_native
    if bls_native.available():
        return bls_native.pairing_check(pairs)
    return pairing_check_py(pairs)


# ---------------- hash to G1 (try-and-increment, internal ciphersuite) ----------------

DST_G1 = b"TPUBFT-V01-CS01-with-BLS12381G1_XMD:SHA-256_TAI_"


def hash_to_g1(msg: bytes):
    """Deterministic hash to a G1 point (try-and-increment + cofactor clear).

    Not RFC 9380 SSWU (that is planned for the TPU kernel path); this is an
    internal ciphersuite — both sign and verify use it consistently.
    """
    ctr = 0
    while True:
        h = hashlib.sha256(DST_G1 + ctr.to_bytes(4, "big") + msg).digest()
        x = int.from_bytes(h + hashlib.sha256(b"x2" + h).digest()[:16], "big") % P
        rhs = (x * x % P * x + B1) % P
        y = fp_sqrt(rhs)
        if y is not None:
            # choose canonical sign: smaller y
            if y > P - y:
                y = P - y
            pt = (x, y)
            # clear cofactor: multiply by (1 - x_param) = h_eff
            pt = g1_mul_nonorder(pt, H_EFF_G1)
            if pt is not None:
                return pt
        ctr += 1


def g1_mul_nonorder_py(pt, k: int):
    """Scalar mul without reducing k mod R (for cofactor clearing)."""
    result = None
    add = pt
    while k:
        if k & 1:
            result = g1_add(result, add)
        add = g1_add(add, add)
        k >>= 1
    return result


def g1_mul_nonorder(pt, k: int):
    from tpubft.crypto import bls_native
    if bls_native.available():
        return bls_native.g1_mul_nonorder(pt, k)
    return g1_mul_nonorder_py(pt, k)


# ---------------- serialization ----------------

G1_LEN = 48      # compressed
G2_LEN = 96      # compressed


def g1_compress(pt) -> bytes:
    """ZCash-style compressed encoding: 381-bit x + flag bits in top byte."""
    if pt is None:
        return bytes([0xC0] + [0] * 47)
    x, y = pt
    flags = 0x80  # compressed
    if y > (P - 1) // 2:
        flags |= 0x20
    b = bytearray(x.to_bytes(48, "big"))
    b[0] |= flags
    return bytes(b)


def g1_decompress(b: bytes, check_subgroup: bool = True):
    """Decode a compressed G1 point. Network-facing: enforces canonical
    encoding (single byte-representation per point) and, by default, membership
    in the order-R subgroup — required for BLS soundness (G1 cofactor ~2^125).
    The membership test is the fast GLV endomorphism check
    (g1_in_subgroup); a probabilistic BATCH check would be unsound here
    because the cofactor has small prime factors (3, 11, ...)."""
    if len(b) != 48:
        raise ValueError("bad G1 encoding length")
    from tpubft.crypto import bls_native
    if bls_native.available():
        pt = bls_native.g1_decompress(b)        # canonical+curve, fast sqrt
    else:
        flags = b[0]
        if not flags & 0x80:
            raise ValueError("uncompressed G1 not supported")
        if flags & 0x40:
            if b != bytes([0xC0]) + b"\x00" * 47:
                raise ValueError("non-canonical G1 infinity encoding")
            return None
        x = int.from_bytes(bytes([b[0] & 0x1F]) + b[1:], "big")
        if x >= P:
            raise ValueError("G1 x out of range")
        y = fp_sqrt((x * x % P * x + B1) % P)
        if y is None:
            raise ValueError("not on curve")
        if (y > (P - 1) // 2) != bool(flags & 0x20):
            y = P - y
        pt = (x, y)
    if pt is not None and check_subgroup and not g1_in_subgroup(pt):
        raise ValueError("G1 point not in order-R subgroup")
    return pt


# GLV endomorphism subgroup test (the blst/Scott fast check): on the
# order-R subgroup the endomorphism phi(x,y) = (beta*x, y) acts as
# multiplication by lambda = x_param^2 - 1 (a root of T^2+T+1 mod R);
# on every cofactor component the eigenvalues differ, so
#   phi(P) == [lambda]P  <=>  P is in the subgroup.
# One ~127-bit scalar mul instead of the full 255-bit [R]P check.
# beta is the cube root of unity matching this orientation (verified
# against the [R]P test on generator and cofactor points in
# tests/test_bls12381.py).
_G1_BETA = 0x1A0111EA397FE699EC02408663D4DE85AA0D857D89759AD4897D29650FB85F9B409427EB4F49FFFD8BFD00000000AAAC
_G1_LAMBDA = 0xD201000000010000 ** 2 - 1


def g1_in_subgroup(pt) -> bool:
    """Fast deterministic order-R membership test for on-curve points."""
    if pt is None:
        return True
    phi = (pt[0] * _G1_BETA % P, pt[1])
    return g1_mul_nonorder(pt, _G1_LAMBDA) == phi


def g2_compress(pt) -> bytes:
    if pt is None:
        return bytes([0xC0] + [0] * 95)
    (x0, x1), (y0, y1) = pt
    flags = 0x80
    # lexicographic "greater" on (y1, y0), ZCash convention
    greater = (y1 > (P - 1) // 2) if y1 else (y0 > (P - 1) // 2)
    if greater:
        flags |= 0x20
    b = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    b[0] |= flags
    return bytes(b)


def g2_decompress(b: bytes, check_subgroup: bool = True):
    if len(b) != 96:
        raise ValueError("bad G2 encoding length")
    flags = b[0]
    if not flags & 0x80:
        raise ValueError("uncompressed G2 not supported")
    if flags & 0x40:
        if b != bytes([0xC0]) + b"\x00" * 95:
            raise ValueError("non-canonical G2 infinity encoding")
        return None
    x1 = int.from_bytes(bytes([b[0] & 0x1F]) + b[1:48], "big")
    x0 = int.from_bytes(b[48:], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("G2 x out of range")
    x = (x0, x1)
    rhs = fp2_add(fp2_mul(fp2_sqr(x), x), B2)
    y = fp2_sqrt(rhs)
    if y is None:
        raise ValueError("not on curve")
    y0, y1 = y
    greater = (y1 > (P - 1) // 2) if y1 else (y0 > (P - 1) // 2)
    if greater != bool(flags & 0x20):
        y = fp2_neg(y)
    pt = (x, y)
    if check_subgroup and g2_mul_nonorder(pt, R) is not None:
        raise ValueError("G2 point not in order-R subgroup")
    return pt


def g2_mul_nonorder_py(pt, k: int):
    """Scalar mul without reducing k mod R (subgroup checks)."""
    result = None
    add = pt
    while k:
        if k & 1:
            result = g2_add(result, add)
        add = g2_add(add, add)
        k >>= 1
    return result


def g2_mul_nonorder(pt, k: int):
    from tpubft.crypto import bls_native
    if bls_native.available():
        return bls_native.g2_mul_nonorder(pt, k)
    return g2_mul_nonorder_py(pt, k)


# ---------------- BLS signatures (min-sig: sig in G1, pk in G2) ----------------

def keygen(seed: Optional[bytes] = None) -> Tuple[int, Tuple]:
    if seed is not None:
        sk = int.from_bytes(hashlib.sha512(b"bls-keygen" + seed).digest(), "big") % (R - 1) + 1
    else:
        sk = secrets.randbelow(R - 1) + 1
    return sk, g2_mul(G2_GEN, sk)


def sign(sk: int, msg: bytes):
    return g1_mul(hash_to_g1(msg), sk)


def verify(pk_g2, msg: bytes, sig_g1) -> bool:
    if sig_g1 is None or not g1_is_on_curve(sig_g1):
        return False
    # e(sig, g2) == e(H(m), pk)  ⇔  e(sig, -g2) * e(H(m), pk) == 1
    return pairing_check([(sig_g1, g2_neg(G2_GEN)), (hash_to_g1(msg), pk_g2)])


# ---------------- Shamir threshold + Lagrange ----------------

def threshold_keygen(k: int, n: int, seed: Optional[bytes] = None):
    """k-of-n Shamir sharing of a BLS secret. Returns
    (master_pk_g2, share_pks_g2[n], secret_shares[n])."""
    if seed is not None:
        coeffs = [int.from_bytes(hashlib.sha512(b"thr" + seed + i.to_bytes(4, "big")).digest(),
                                 "big") % (R - 1) + 1 for i in range(k)]
    else:
        coeffs = [secrets.randbelow(R - 1) + 1 for _ in range(k)]
    master_pk = g2_mul(G2_GEN, coeffs[0])
    shares = []
    for i in range(1, n + 1):
        v = 0
        for j, c in enumerate(coeffs):
            v = (v + c * pow(i, j, R)) % R
        shares.append(v)
    share_pks = [g2_mul(G2_GEN, s) for s in shares]
    return master_pk, share_pks, shares


def lagrange_coeffs_at_zero(ids: Sequence[int]) -> List[int]:
    """L_i(0) mod R for the signer-id set (reference:
    threshsign/src/bls/relic/BlsThresholdAccumulator.cpp:42
    computeLagrangeCoeff).

    Optimized for large signer sets (n=1000 scale): the shared numerator
    Π(-j) is computed once; per-i denominators accumulate the SMALL
    integer differences (i-j) in machine-size chunks before each modular
    reduction; and all k inversions collapse into ONE modexp via
    Montgomery batch inversion. ~10x over the naive per-i modexp loop at
    k=667."""
    k = len(ids)
    if k == 0:
        return []
    # fail loud on degenerate id sets: an id ≡ 0 mod R zeroes the
    # batched products (silently-infinite combined signature), and
    # duplicates make the interpolation meaningless
    if len(set(i % R for i in ids)) != k or any(i % R == 0 for i in ids):
        raise ValueError("signer ids must be distinct and nonzero mod R")
    num_total = 1
    for j in ids:
        num_total = num_total * (R - j) % R          # Π (0 - j)
    # den_i = Π_{j != i} (i - j); |i - j| is small, so bundle ~5 factors
    # per big-int modmul
    terms = []
    for i in ids:
        den = 1
        small = 1
        nsmall = 0
        for j in ids:
            if j == i:
                continue
            small *= i - j
            nsmall += 1
            if nsmall == 5:
                den = den * small % R
                small, nsmall = 1, 0
        if nsmall:
            den = den * small % R
        # fold the numerator's surplus (0 - i) factor into the inversion
        terms.append(den * (R - i) % R)
    # batch inversion: one modexp total
    prefix = [1] * (k + 1)
    for t in range(k):
        prefix[t + 1] = prefix[t] * terms[t] % R
    inv_all = pow(prefix[k], R - 2, R)
    coeffs = [0] * k
    for t in range(k - 1, -1, -1):
        coeffs[t] = num_total * (inv_all * prefix[t] % R) % R
        inv_all = inv_all * terms[t] % R
    return coeffs


def combine_shares(ids: Sequence[int], shares_g1: Sequence) -> object:
    """Lagrange-weighted MSM of signature shares → combined signature.

    The hot op the TPU backend shards (reference FastMultExp.cpp:27)."""
    coeffs = lagrange_coeffs_at_zero(ids)
    return g1_msm(shares_g1, coeffs)


# ---------------- batch share verification (aggregation tree) ----------------

def g2_msm_py(points: Sequence, scalars: Sequence[int]):
    """Pure-Python golden model — composes g2_mul_py, never the routed
    g2_mul (same independence rule as g1_msm_py)."""
    acc = None
    for pt, k in zip(points, scalars):
        acc = g2_add(acc, g2_mul_py(pt, k))
    return acc


def g2_msm(points: Sequence, scalars: Sequence[int]):
    from tpubft.crypto import bls_native
    if bls_native.available():
        return bls_native.g2_msm(points, scalars)
    return g2_msm_py(points, scalars)


def _rlc_scalars(n: int, context: bytes) -> List[int]:
    """Deterministic 128-bit random-linear-combination coefficients. A
    forged share survives the combined check only with probability
    2^-128 per coefficient choice; deriving them from the share data
    itself (Fiat-Shamir style) means the adversary committed to the
    shares before learning the coefficients."""
    out = []
    for i in range(n):
        h = hashlib.sha256(b"bls-rlc" + context + i.to_bytes(4, "big"))
        out.append(int.from_bytes(h.digest()[:16], "big") | 1)
    return out


def batch_verify_shares(pks_g2: Sequence, h_g1, shares_g1: Sequence) -> bool:
    """One pairing check for a whole batch of shares over ONE message
    point: e(Σ z_i·s_i, -g2) · e(H, Σ z_i·pk_i) == 1 with random z_i
    (the role of the reference's aggregated root check,
    BlsBatchVerifier.cpp:44). Sound up to 2^-128 per batch."""
    if not shares_g1:
        return True
    if any(s is None or not g1_is_on_curve(s) for s in shares_g1):
        return False
    # bind the full statement (message point + every pk + every share)
    # into the coefficient transcript, per standard batch-verify practice
    ctx = (g1_compress(h_g1)
           + b"".join(g2_compress(p) for p in pks_g2)
           + b"".join(g1_compress(s) for s in shares_g1))
    zs = _rlc_scalars(len(shares_g1), ctx)
    agg_sig = g1_msm(shares_g1, zs)
    agg_pk = g2_msm(pks_g2, zs)
    return pairing_check([(agg_sig, g2_neg(G2_GEN)), (h_g1, agg_pk)])


class BlsBatchVerifier:
    """Binary aggregation tree over shares: verify the aggregate first,
    descend only into failing halves — b bad shares cost O(b·log n)
    pairing checks instead of n (reference BlsBatchVerifier::batchVerify
    / batchVerifyRecursive, threshsign/src/bls/relic/BlsBatchVerifier.cpp:
    44,84)."""

    def __init__(self, pks_g2: Sequence, h_g1):
        self._pks = list(pks_g2)
        self._h = h_g1
        self.checks = 0                 # pairing-check count (observability)

    def batch_verify(self, shares_g1: Sequence) -> List[bool]:
        out = [False] * len(shares_g1)
        self._recurse(list(range(len(shares_g1))), list(shares_g1), out)
        return out

    def _recurse(self, idxs: List[int], shares: List, out: List[bool]) -> None:
        if not idxs:
            return
        self.checks += 1
        if batch_verify_shares([self._pks[i] for i in idxs], self._h,
                               [shares[i] for i in idxs]):
            for i in idxs:
                out[i] = True
            return
        if len(idxs) == 1:
            out[idxs[0]] = False
            return
        mid = len(idxs) // 2
        self._recurse(idxs[:mid], shares, out)
        self._recurse(idxs[mid:], shares, out)
