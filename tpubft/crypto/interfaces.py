"""Crypto plugin interfaces — the boundary the TPU backend slots into.

Mirrors the reference's scheme-agnostic API:
  ISigner/IVerifier           — util/include/crypto_utils.hpp:41-55
  IThresholdSigner            — threshsign/include/threshsign/IThresholdSigner.h:19
  IThresholdVerifier          — threshsign/include/threshsign/IThresholdVerifier.h:23
  IThresholdAccumulator       — threshsign/include/threshsign/IThresholdAccumulator.h:22
  Cryptosystem                — threshsign/include/threshsign/ThresholdSignaturesTypes.h:41

Design deltas from the reference (TPU-first):
  * verifiers additionally expose `verify_batch` so backends can vectorize;
    the CPU backends loop, the TPU backend vmaps.
  * accumulators expose `get_pending_batch`/`absorb_batch_result` so share
    verification can be deferred to a batched TPU dispatch instead of being
    verified share-by-share inline.
"""
from __future__ import annotations

import abc
from typing import Dict, List, Optional, Sequence, Tuple


class ISigner(abc.ABC):
    @abc.abstractmethod
    def sign(self, data: bytes) -> bytes: ...

    @property
    @abc.abstractmethod
    def signature_length(self) -> int: ...


class IVerifier(abc.ABC):
    @abc.abstractmethod
    def verify(self, data: bytes, sig: bytes) -> bool: ...

    def verify_batch(self, items: Sequence[Tuple[bytes, bytes]]) -> List[bool]:
        """Default: sequential. TPU backend overrides with a vmapped kernel."""
        return [self.verify(d, s) for d, s in items]

    @property
    @abc.abstractmethod
    def signature_length(self) -> int: ...


class IThresholdSigner(abc.ABC):
    """Signs a share of a threshold signature with this replica's key share."""

    @abc.abstractmethod
    def sign_share(self, data: bytes) -> bytes: ...

    @property
    @abc.abstractmethod
    def signer_id(self) -> int: ...


class IThresholdAccumulator(abc.ABC):
    """Collects shares for one (digest) instance until threshold is reached.

    Reference semantics (IThresholdAccumulator.h): add shares (optionally with
    share verification), set the expected digest, extract the combined
    signature once >= threshold valid shares are present.
    """

    @abc.abstractmethod
    def set_expected_digest(self, digest: bytes) -> None: ...

    @abc.abstractmethod
    def add(self, share_id: int, share: bytes) -> int:
        """Add a share; returns number of shares accumulated."""

    @abc.abstractmethod
    def has_threshold(self) -> bool: ...

    @abc.abstractmethod
    def get_full_signed_data(self) -> bytes:
        """Combine shares into the threshold signature (Lagrange + MSM)."""

    @abc.abstractmethod
    def identify_bad_shares(self) -> List[int]:
        """Verify shares individually, return ids of invalid shares
        (reference: re-accumulation with share verification,
        CollectorOfThresholdSignatures.hpp:363-401)."""

    def add_partial(self, partial: bytes) -> int:
        """Absorb a PARTIAL AGGREGATE produced by an interior node of the
        share-aggregation overlay: a self-describing blob carrying the
        contributor bitmap plus the aggregated share, so the root can
        fold whole subtrees in at once while keeping per-contributor
        accounting (a forged partial bisects to the guilty subtree via
        its bitmap). Only schemes whose shares sum meaningfully without
        per-signer weighting support this — Shamir threshold shares do
        NOT (Lagrange coefficients depend on the final contributor set),
        which is why aggregation mode requires a multisig scheme."""
        raise NotImplementedError(
            "scheme does not support partial aggregation")


class IThresholdVerifier(abc.ABC):
    @abc.abstractmethod
    def new_accumulator(self, with_share_verification: bool) -> IThresholdAccumulator: ...

    @abc.abstractmethod
    def verify(self, data: bytes, sig: bytes) -> bool:
        """Verify a combined threshold signature."""

    def verify_batch_certs(self, items) -> list:
        """[(data, sig)] -> verdicts. Backends with an aggregated check
        (BLS random-linear-combination: ONE pairing check for the whole
        batch) override this; the default is the per-cert loop."""
        return [self.verify(d, s) for d, s in items]

    def combine_batch(self, jobs: Sequence[Tuple[bytes, Dict[int, bytes]]]
                      ) -> List[Tuple[bool, bytes, List[int]]]:
        """Fused cross-slot combine: jobs of (digest, {share_id: share})
        -> one (ok, combined_sig, bad_share_ids) per job. The default is
        the reference SignaturesProcessingJob strategy per job —
        accumulate WITHOUT share verification, combine, verify the
        combined signature, and only on failure identify bad shares.
        Batch-capable backends override this to fold every job's
        combine into one device call and every job's combined-signature
        check into one aggregated verification; overrides MUST return
        verdicts identical to this loop (a bad share fails only its own
        job), which the fused-combine equivalence tests pin down."""
        out: List[Tuple[bool, bytes, List[int]]] = []
        for digest, shares in jobs:
            acc = self.new_accumulator(with_share_verification=False)
            acc.set_expected_digest(digest)
            for sid, share in shares.items():
                acc.add(sid, share)
            combined = acc.get_full_signed_data()
            if self.verify(digest, combined):
                out.append((True, combined, []))
            else:
                out.append((False, b"", acc.identify_bad_shares()))
        return out

    @property
    def supports_partial_aggregation(self) -> bool:
        """True when this scheme's accumulators implement `add_partial`
        (the share-aggregation overlay requires it)."""
        return False

    def share_weight(self, share: bytes) -> int:
        """How many contributors one entry in a share dict represents.
        1 for a raw share; partial-aggregation schemes override this to
        return the contributor-bitmap popcount so quorum accounting
        counts signers, not datagrams."""
        return 1

    @property
    @abc.abstractmethod
    def threshold(self) -> int: ...

    @property
    @abc.abstractmethod
    def total_signers(self) -> int: ...


class IThresholdFactory(abc.ABC):
    @abc.abstractmethod
    def new_signer(self, signer_id: int, secret_share) -> IThresholdSigner: ...

    @abc.abstractmethod
    def new_verifier(self, threshold: int, total: int, public_key,
                     share_public_keys) -> IThresholdVerifier: ...

    @abc.abstractmethod
    def keygen(self, threshold: int, total: int, seed: Optional[bytes] = None): ...


class Cryptosystem:
    """Named registry of threshold schemes (ThresholdSignaturesTypes.h:30-41).

    Holds key material for one "era" and builds signers/verifiers for the
    three commit-path quorums (CryptoManager.hpp:109-111). Types:
      "multisig-ed25519"  — n independent Ed25519 sigs, concatenated multisig
      "threshold-bls"     — BLS12-381 threshold signatures (k-of-n, Shamir)
      "multisig-bls"      — BLS12-381 multisig (aggregate of identified shares)
    """

    _FACTORIES: Dict[str, "IThresholdFactory"] = {}

    @classmethod
    def register_type(cls, type_name: str, factory: IThresholdFactory) -> None:
        cls._FACTORIES[type_name] = factory

    @classmethod
    def factory(cls, type_name: str) -> IThresholdFactory:
        if type_name not in cls._FACTORIES:
            # Lazy registration of built-ins.
            from tpubft.crypto import systems
            systems.register_builtin(type_name)
        return cls._FACTORIES[type_name]

    def __init__(self, type_name: str, threshold: int, num_signers: int,
                 seed: Optional[bytes] = None):
        self.type_name = type_name
        self.threshold_ = threshold
        self.num_signers = num_signers
        fac = self.factory(type_name)
        keys = fac.keygen(threshold, num_signers, seed=seed)
        self.public_key, self.share_public_keys, self.secret_shares = keys
        self._factory = fac

    def create_threshold_signer(self, signer_id: int) -> IThresholdSigner:
        """signer_id is 1-based, as in the reference."""
        return self._factory.new_signer(signer_id, self.secret_shares[signer_id - 1])

    def create_threshold_verifier(self, threshold: Optional[int] = None) -> IThresholdVerifier:
        return self._factory.new_verifier(
            threshold or self.threshold_, self.num_signers,
            self.public_key, self.share_public_keys)
