"""Crypto backend resolution: cpu | tpu | auto.

The reference selects its crypto engine statically (RELIC/Crypto++ at
build time); here the analogous choice is which side of the plugin
boundary executes — host OpenSSL-style verifiers or the batched device
kernels. "auto" resolves to "tpu" exactly when an accelerator device is
actually reachable, probed in a SUBPROCESS because device init on this
class of host can hang indefinitely when the accelerator transport is
down (observed with the tunneled-TPU plugin) — a hung replica at boot is
far worse than a slow probe.

Resolution order for "auto":
  1. TPUBFT_CRYPTO_BACKEND env var ("cpu"/"tpu") — operator override.
  2. JAX_PLATFORMS forcing cpu — tests / CPU-mesh runs.
  3. The in-process jax config forcing cpu (jax.config.update is the
     only RELIABLE way to force CPU on hosts whose accelerator plugin
     overrides the env var — tests/conftest.py does exactly that, and
     the probe must respect it or every test session pays a full probe
     timeout against a dead tunnel).
  4. Cached probe result (per process).
  5. Subprocess device probe with a hard timeout.
"""
from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional

_probe_cache: Optional[str] = None


def _probe_device(timeout_s: float = 60.0) -> str:
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print('PLATFORM=' + jax.devices()[0].platform)"],
            capture_output=True, timeout=timeout_s, text=True)
        for line in r.stdout.splitlines():
            if line.startswith("PLATFORM="):
                plat = line.split("=", 1)[1].strip()
                return "tpu" if plat in ("tpu", "axon") else "cpu"
    except (subprocess.TimeoutExpired, OSError):
        pass
    return "cpu"


def resolve_backend(requested: str) -> str:
    """Map a configured crypto_backend to a concrete one."""
    global _probe_cache
    if requested != "auto":
        return requested
    env = os.environ.get("TPUBFT_CRYPTO_BACKEND")
    if env in ("cpu", "tpu"):
        return env
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return "cpu"
    if _jax_config_forces_cpu():
        return "cpu"
    if _probe_cache is None:
        _probe_cache = _probe_device()
    return _probe_cache


def _jax_config_forces_cpu() -> bool:
    try:
        import jax
        plats = jax.config.jax_platforms       # reading does not init
        return bool(plats) and str(plats).strip().lower() == "cpu"
    except Exception:  # noqa: BLE001 — config introspection best-effort
        return False
