"""Self-hosted scalar signature engine — no OpenSSL, stdlib only.

Pure-Python Ed25519 (RFC 8032) and ECDSA over secp256k1 / secp256r1
(RFC 6979 deterministic nonces, SHA-256) for the host-side sign /
keygen / single-verify paths. The batched device kernels
(tpubft/ops/ed25519.py, ops/ecdsa.py) stay the hot verification plane;
this module is what makes them the PRIMARY engine rather than an
accelerator bolted onto a third-party dependency: the whole crypto
stack now lives in-repo, and `cryptography` (OpenSSL) is a soft
optional speedup probed at runtime by tpubft/crypto/cpu.py.

Byte compatibility contracts (locked by tests/test_crypto_scalar.py):
  * Ed25519 keys/sigs are RFC 8032 raw encodings (32B pk, 64B sig) —
    identical to the OpenSSL backend and the kernel verifiers;
  * ECDSA pubkeys are SEC1 uncompressed (0x04||x||y, 65B), signatures
    fixed-width raw r||s (64B), hash SHA-256 — the wire formats the
    existing keyfiles and kernels already use;
  * seed → private-key derivations reproduce the historical formulas
    (sha256("ed25519-keygen"+seed); sha512("ecdsa-keygen"+seed) folded
    into [1, n-1]), so keyfiles written by tpubft.tools.keygen before
    this engine existed still load and sign identically.

The group math is plain python ints: extended twisted-Edwards
coordinates for ed25519 (same add-2008-hwcd-3 / dbl-2008-hwcd formulas
as the device kernel in ops/ed25519.py), Jacobian coordinates for the
short-Weierstrass curves (parameters mirrored from ops/ecdsa.CURVES).
Fixed-base multiplications walk cached 2^i·G tables so signing and
keygen cost ~128 group additions, not a full double-and-add ladder.
This is NOT constant-time — neither was the OpenSSL-via-python path
for batch shapes — and replica keys here already assume a trusted host.
"""
from __future__ import annotations

import contextlib
import functools
import hashlib
import hmac
import os
import threading
from typing import (Dict, Iterator, List, NamedTuple, Optional, Sequence,
                    Tuple)

# ---------------------------------------------------------------------------
# Ed25519 (RFC 8032)
# ---------------------------------------------------------------------------

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = -121665 * pow(121666, -1, P) % P
_K2D = 2 * D % P
SQRT_M1 = pow(2, (P - 1) // 4, P)
BASE_X = 15112221349535400772501151409588531511454012693041857206046113283949847762202
BASE_Y = 46316835694926478169428394003475163141307993866256225615783033603165251855960

# extended coordinates (X, Y, Z, T) with x = X/Z, y = Y/Z, T = XY/Z
_EXT_IDENT = (0, 1, 1, 0)


def _ext_add(p, q):
    """Unified extended addition (add-2008-hwcd-3, a=-1, k=2d) — the
    int-scalar twin of ops/ed25519.point_add."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = t1 * t2 % P * _K2D % P
    d = 2 * z1 * z2 % P
    e, f, g, h = b - a, d - c, d + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def _ext_double(p):
    """Dedicated doubling (dbl-2008-hwcd, a=-1) — twin of point_dbl."""
    x, y, z, _ = p
    a = x * x % P
    b = y * y % P
    c = 2 * z * z % P
    e = ((x + y) * (x + y) - a - b) % P
    g = (b - a) % P
    h = (-a - b) % P
    f = (g - c) % P
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def _ext_neg(p):
    x, y, z, t = p
    return (P - x if x else 0, y, z, P - t if t else 0)


@functools.lru_cache(maxsize=1)
def _base_comb_table():
    """Comb table for fixed-base mults: tab[j][d] = [d·16^j]B for
    j in 0..63, d in 0..15 — a 256-bit scalar mult becomes ≤64 additions
    with zero doublings. ~1k point ops to build, built once."""
    tab = []
    win = (BASE_X, BASE_Y, 1, BASE_X * BASE_Y % P)
    for _ in range(64):
        row = [_EXT_IDENT, win]
        for _ in range(14):
            row.append(_ext_add(row[-1], win))
        tab.append(row)
        # 16^(j+1)·B = 15·16^j·B + 16^j·B
        win = _ext_add(row[-1], row[1])
    return tab


def _mul_base(k: int):
    """[k]B via the cached comb table (≤64 additions, no doublings)."""
    acc = _EXT_IDENT
    tab = _base_comb_table()
    j = 0
    while k:
        d = k & 15
        if d:
            acc = _ext_add(acc, tab[j][d])
        k >>= 4
        j += 1
    return acc


def _ext_mul(k: int, pt):
    """[k]P, 4-bit fixed-window ladder (variable base: verify only) —
    15 table adds + 4 doublings and ≤1 add per window."""
    row = [_EXT_IDENT, pt]
    for _ in range(14):
        row.append(_ext_add(row[-1], pt))
    acc = _EXT_IDENT
    started = False
    for shift in range((max(k.bit_length(), 1) + 3) // 4 * 4 - 4, -1, -4):
        if started:
            acc = _ext_double(_ext_double(_ext_double(_ext_double(acc))))
        d = (k >> shift) & 15
        if d:
            acc = _ext_add(acc, row[d])
            started = True
    return acc


def _compress(pt) -> bytes:
    x, y, z, _ = pt
    zi = pow(z, -1, P)
    x, y = x * zi % P, y * zi % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _decompress(b32: bytes):
    """Canonical RFC 8032 decoding: reject y >= p and x=0 with sign=1 —
    the same strictness as the device kernel's host prechecks."""
    y = int.from_bytes(b32, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    if y >= P:
        return None
    y2 = y * y % P
    u = (y2 - 1) % P
    v = (D * y2 + 1) % P
    # x = sqrt(u/v) via the (p-5)/8 exponent trick
    x = u * pow(v, 3, P) % P * pow(u * pow(v, 7, P) % P, (P - 5) // 8, P) % P
    vx2 = v * x % P * x % P
    if vx2 != u:
        if vx2 != P - u:
            return None
        x = x * SQRT_M1 % P
    if x == 0 and sign:
        return None
    if (x & 1) != sign:
        x = P - x
    return (x, y, 1, x * y % P)


def _clamp(b32: bytes) -> int:
    a = int.from_bytes(b32, "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


def ed25519_seed_to_private(seed: bytes) -> bytes:
    """Historical keyfile derivation — must never change: existing
    keygen'd key material depends on it."""
    return hashlib.sha256(b"ed25519-keygen" + seed).digest()


def ed25519_public_key(sk: bytes) -> bytes:
    h = hashlib.sha512(sk).digest()
    return _compress(_mul_base(_clamp(h[:32])))


def ed25519_sign(sk: bytes, msg: bytes, pk: Optional[bytes] = None) -> bytes:
    """RFC 8032 deterministic signature — byte-identical to OpenSSL's.
    `pk` (the signer's own public key) is recomputed when not supplied;
    long-lived signers pass their cached copy."""
    h = hashlib.sha512(sk).digest()
    a = _clamp(h[:32])
    prefix = h[32:]
    if pk is None:
        pk = _compress(_mul_base(a))
    r = int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little") % L
    rb = _compress(_mul_base(r))
    k = int.from_bytes(hashlib.sha512(rb + pk + msg).digest(), "little") % L
    s = (r + k * a) % L
    return rb + s.to_bytes(32, "little")


def ed25519_sign_batch(sk: bytes, msgs: Sequence[bytes],
                       pk: Optional[bytes] = None) -> List[bytes]:
    """RFC 8032 deterministic signatures for a batch of messages under
    ONE key — byte-identical to `ed25519_sign` per item. The comb walks
    stay per-item (≤64 cached-table adds each — already cheap), but the
    R-point affine compressions share ONE Montgomery batch inversion
    (`_batch_inv`) instead of paying a full field inversion per
    signature, the same amortization the batched verifier's residue
    paths lean on. Key-derivation hashing and the public-key compress
    are hoisted out of the loop."""
    if not msgs:
        return []
    h = hashlib.sha512(sk).digest()
    a = _clamp(h[:32])
    prefix = h[32:]
    if pk is None:
        pk = _compress(_mul_base(a))
    rs: List[int] = []
    pts = []
    for msg in msgs:
        r = int.from_bytes(hashlib.sha512(prefix + msg).digest(),
                           "little") % L
        rs.append(r)
        pts.append(_mul_base(r))
    invs = _batch_inv([pt[2] for pt in pts], P)
    out: List[bytes] = []
    for msg, r, pt, zi in zip(msgs, rs, pts, invs):
        x, y = pt[0] * zi % P, pt[1] * zi % P
        rb = (y | ((x & 1) << 255)).to_bytes(32, "little")
        k = int.from_bytes(hashlib.sha512(rb + pk + msg).digest(),
                           "little") % L
        s = (r + k * a) % L
        out.append(rb + s.to_bytes(32, "little"))
    return out


def ed25519_verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    """Strict cofactorless verify: s < L, canonical A and R encodings,
    encode([s]B - [k]A) == R — the same equation and strictness as the
    batched kernel (ops/ed25519.verify_kernel), so scalar and device
    verdicts can never diverge."""
    if len(sig) != 64 or len(pk) != 32:
        return False
    sig, pk = bytes(sig), bytes(pk)
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False                    # malleability: reject s >= L
    a_pt = _decompress(pk)
    if a_pt is None:
        return False
    k = int.from_bytes(hashlib.sha512(sig[:32] + pk + msg).digest(),
                       "little") % L
    q = _ext_add(_mul_base(s), _ext_mul(k, _ext_neg(a_pt)))
    # a non-canonical R encoding can never equal a canonical compress
    return _compress(q) == sig[:32]


# ---------------------------------------------------------------------------
# ECDSA over short-Weierstrass curves (SHA-256, RFC 6979 nonces)
# ---------------------------------------------------------------------------

# Parameters mirror ops/ecdsa.CURVES (cross-checked by
# tests/test_crypto_scalar.py) — duplicated so this module stays
# importable with zero heavyweight deps (ops/ecdsa pulls in jax).
CURVES = {
    "secp256k1": dict(
        p=0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F,
        a=0, b=7,
        gx=0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798,
        gy=0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8,
        n=0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141),
    "secp256r1": dict(
        p=0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF,
        a=-3, b=0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B,
        gx=0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296,
        gy=0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5,
        n=0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551),
}

_JAC_IDENT = (0, 1, 0)


def _jac_double(pt, p: int, a: int):
    x, y, z = pt
    if z == 0:
        return _JAC_IDENT
    ys = y * y % p
    s = 4 * x * ys % p
    z2 = z * z % p
    m = (3 * x * x + a * z2 % p * z2) % p
    x3 = (m * m - 2 * s) % p
    y3 = (m * (s - x3) - 8 * ys * ys) % p
    z3 = 2 * y * z % p
    return (x3, y3, z3)


def _jac_add(q, r, p: int, a: int):
    if q[2] == 0:
        return r
    if r[2] == 0:
        return q
    z1z1 = q[2] * q[2] % p
    z2z2 = r[2] * r[2] % p
    u1 = q[0] * z2z2 % p
    u2 = r[0] * z1z1 % p
    s1 = q[1] * z2z2 % p * r[2] % p
    s2 = r[1] * z1z1 % p * q[2] % p
    if u1 == u2:
        if s1 != s2:
            return _JAC_IDENT           # P + (-P)
        return _jac_double(q, p, a)
    h = (u2 - u1) % p
    rr = (s2 - s1) % p
    h2 = h * h % p
    h3 = h * h2 % p
    v = u1 * h2 % p
    x3 = (rr * rr - h3 - 2 * v) % p
    y3 = (rr * (v - x3) - s1 * h3) % p
    z3 = h * q[2] % p * r[2] % p
    return (x3, y3, z3)


def _jac_to_affine(pt, p: int) -> Optional[Tuple[int, int]]:
    x, y, z = pt
    if z == 0:
        return None
    zi = pow(z, -1, p)
    zi2 = zi * zi % p
    return (x * zi2 % p, y * zi2 % p * zi % p)


@functools.lru_cache(maxsize=None)
def _g_table(curve_name: str):
    """2^i·G in Jacobian coords — fixed-base mult for sign/keygen."""
    cv = CURVES[curve_name]
    p, a = cv["p"], cv["a"]
    tab = []
    pt = (cv["gx"], cv["gy"], 1)
    for _ in range(256):
        tab.append(pt)
        pt = _jac_double(pt, p, a)
    return tab


def _mul_g(k: int, curve_name: str):
    cv = CURVES[curve_name]
    p, a = cv["p"], cv["a"]
    acc = _JAC_IDENT
    tab = _g_table(curve_name)
    i = 0
    while k:
        if k & 1:
            acc = _jac_add(acc, tab[i], p, a)
        k >>= 1
        i += 1
    return acc


def _jac_mul(k: int, affine, cv):
    p, a = cv["p"], cv["a"]
    acc = _JAC_IDENT
    base = (affine[0], affine[1], 1)
    for i in range(k.bit_length() - 1, -1, -1):
        acc = _jac_double(acc, p, a)
        if (k >> i) & 1:
            acc = _jac_add(acc, base, p, a)
    return acc


def ecdsa_seed_to_private(seed: bytes, curve_name: str) -> int:
    """Historical keyfile derivation — must never change (see
    ed25519_seed_to_private)."""
    n = CURVES[curve_name]["n"]
    v = int.from_bytes(hashlib.sha512(b"ecdsa-keygen" + seed).digest(), "big")
    return v % (n - 1) + 1


def ecdsa_random_private(curve_name: str) -> int:
    n = CURVES[curve_name]["n"]
    return int.from_bytes(os.urandom(48), "big") % (n - 1) + 1


def ecdsa_public_key(d: int, curve_name: str) -> bytes:
    """SEC1 uncompressed point: 0x04 || x || y (65 bytes)."""
    aff = _jac_to_affine(_mul_g(d, curve_name), CURVES[curve_name]["p"])
    assert aff is not None, "private value is a multiple of the order"
    return b"\x04" + aff[0].to_bytes(32, "big") + aff[1].to_bytes(32, "big")


def _rfc6979_nonces(x: int, h1: bytes, q: int) -> Iterator[int]:
    """RFC 6979 §3.2 deterministic nonce stream (HMAC-SHA256), qlen=256."""
    qlen = (q.bit_length() + 7) // 8

    def bits2int(b: bytes) -> int:
        v = int.from_bytes(b, "big")
        extra = len(b) * 8 - q.bit_length()
        return v >> extra if extra > 0 else v

    bx = x.to_bytes(qlen, "big") + (bits2int(h1) % q).to_bytes(qlen, "big")
    v = b"\x01" * 32
    k = b"\x00" * 32
    k = hmac.new(k, v + b"\x00" + bx, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + bx, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        t = b""
        while len(t) < qlen:
            v = hmac.new(k, v, hashlib.sha256).digest()
            t += v
        cand = bits2int(t)
        if 1 <= cand < q:
            yield cand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def ecdsa_sign(d: int, msg: bytes, curve_name: str) -> bytes:
    """Deterministic ECDSA (RFC 6979, SHA-256), raw r||s output. The
    OpenSSL path signs with a random nonce — both verify identically;
    determinism here buys reproducible tests and no RNG dependence."""
    cv = CURVES[curve_name]
    n = cv["n"]
    h1 = hashlib.sha256(msg).digest()
    z = int.from_bytes(h1, "big") % n
    for k in _rfc6979_nonces(d, h1, n):
        aff = _jac_to_affine(_mul_g(k, curve_name), cv["p"])
        if aff is None:
            continue
        r = aff[0] % n
        if r == 0:
            continue
        s = pow(k, -1, n) * (z + r * d) % n
        if s == 0:
            continue
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")
    raise AssertionError("unreachable: RFC 6979 stream exhausted")


def ecdsa_on_curve(x: int, y: int, curve_name: str) -> bool:
    cv = CURVES[curve_name]
    p = cv["p"]
    if not (0 <= x < p and 0 <= y < p):
        return False
    return (y * y - (x * x * x + cv["a"] * x + cv["b"])) % p == 0


# ---------------------------------------------------------------------------
# Batched ECDSA verification (the degraded-mode hot path)
#
# The per-item `ecdsa_verify` below pays a full generic double-and-add
# ladder plus a fresh pow(s, -1, n) per signature (~30/s-class on the
# bench container through the verifier stack).  `ecdsa_verify_batch`
# amortizes everything that can be shared across a batch:
#
#   * ONE Montgomery batch inversion for every s^-1 (and one more per
#     comb column for the affine-addition denominators, so the whole
#     group walk runs in affine coordinates — ~6 mulmods per point add
#     instead of ~16 for a Jacobian add);
#   * a precomputed fixed-base comb table for G shared module-wide
#     (tab[j][d] = [d * 2^(w*j)]G, so [u1]G is ~32 table additions with
#     zero doublings);
#   * a per-principal comb table for each public key Q, built lazily
#     and graduated: a cheap 4-bit comb on first contact, upgraded to
#     an 8-bit comb once the principal is hot (BFT clients re-sign for
#     their whole session, so the build cost amortizes to noise);
#   * a per-principal decoded-pubkey memo — SEC1 decode + on-curve
#     check paid once per key, not once per retransmitted verify.
#
# All items walk their comb columns in lockstep: each column step
# gathers one affine addition per item, batch-inverts all denominators
# in one Montgomery pass (one pow per column for the whole batch), and
# applies the additions.  Verdicts are byte-identical to the scalar
# loop (locked by tests/test_ecdsa_batch.py three-way vectors).
# ---------------------------------------------------------------------------

# comb widths / cache sizing (env-tunable, read once at import; see
# docs/OPERATIONS.md "ECDSA verification tuning")
_COMB_G_WIDTH = max(1, min(8, int(os.environ.get(
    "TPUBFT_ECDSA_COMB_G", "8"))))
_COMB_Q_COLD_WIDTH = 4
_COMB_Q_HOT_WIDTH = 8
# lifetime verifies after which a principal's comb is rebuilt hot
_COMB_HOT_AFTER = max(1, int(os.environ.get(
    "TPUBFT_ECDSA_COMB_HOT_AFTER", "192")))
_PK_CACHE_MAX = max(4, int(os.environ.get(
    "TPUBFT_ECDSA_PK_CACHE", "256")))
# hot (8-bit) tables are ~2MB each — cap how many stay resident
_HOT_COMB_MAX = max(1, int(os.environ.get(
    "TPUBFT_ECDSA_HOT_COMBS", "24")))


# ---- GLV endomorphism split (secp256k1) ------------------------------
# phi(x, y) = (beta*x, y) equals [lam]P on secp256k1 (beta^3 = 1 mod p,
# lam^3 = 1 mod n), so any scalar k splits as k = k1 + k2*lam (mod n)
# with |k1|, |k2| ~ sqrt(n) via the standard lattice basis
# (a1, b1), (a2, b2) — libsecp256k1's constants. The batched verify
# walks BOTH half-scalars over the same ~17 comb columns (width 8)
# instead of 32, sharing one batch inversion per column; see
# _ecdsa_verify_batch. secp256r1 has no such endomorphism and keeps the
# full-length walk.
_GLV_PARAMS = {
    "secp256k1": dict(
        beta=0x7AE96A2B657C07106E64479EAC3434E99CF0497512F58995C1396C28719501EE,
        lam=0x5363AD4CC05C30E0A5261C028812645A122E22EA20816678DF02967C1B23BD72,
        a1=0x3086D221A7D46BCDE86C90E49284EB15,
        b1=-0xE4437ED6010E88286F547FA90ABFE4C3,
        a2=0x114CA50F7A8E2F3F657C1108D9D44CFD8,
        b2=0x3086D221A7D46BCDE86C90E49284EB15,
    ),
}
# decomposition magnitude rail: reduced scalars always split below
# ~2^128.5; the walk guards at 2^132 and routes a (mathematically
# unreachable) violator through the plain per-item verify instead
_GLV_MAX = 1 << 132


def _glv_enabled() -> bool:
    """Read per call (not at import) so the equivalence tests can pin
    GLV on vs off inside one process; the comb tables serve both paths
    unchanged (full 256-bit rows, the GLV walk just stops early)."""
    return os.environ.get("TPUBFT_ECDSA_GLV", "1") != "0"


def _glv_max_walk() -> int:
    """GLV pays while the per-column batch inversion is the dominant
    serial cost. Each item trades 64 comb additions (32 G + 32 Q at
    width 8) for 68 (2 x 17 + 2 x 17: the half-scalar column count
    ceilings at 17, since |k_i| can exceed 2^128), so past ~32 lockstep
    items the four extra additions outweigh the halved inversion count
    and the full-length walk takes over. The host engine is the
    small-batch / breaker-open path (the device kernel owns large
    batches), so the gated regime is the common one."""
    return int(os.environ.get("TPUBFT_ECDSA_GLV_MAX_B", "32"))


def _glv_cols(width: int) -> int:
    """Comb columns a half-scalar walk needs at this width."""
    return (132 + width - 1) // width


def _glv_split(k: int, glv: dict, n: int) -> Tuple[int, bool, int, bool]:
    """k -> (|k1|, k1<0, |k2|, k2<0) with k1 + k2*lam ≡ k (mod n)."""
    c1 = (glv["b2"] * k + (n >> 1)) // n
    c2 = (-glv["b1"] * k + (n >> 1)) // n
    k1 = k - c1 * glv["a1"] - c2 * glv["a2"]
    k2 = -c1 * glv["b1"] - c2 * glv["b2"]
    return abs(k1), k1 < 0, abs(k2), k2 < 0


def _batch_inv(values: Sequence[int], m: int) -> List[int]:
    """Montgomery's trick: invert every element mod m with ONE pow.
    All values must be nonzero mod m (callers screen them)."""
    k = len(values)
    prefix = [1] * (k + 1)
    acc = 1
    for i, v in enumerate(values):
        acc = acc * v % m
        prefix[i + 1] = acc
    inv = pow(acc, -1, m)
    out = [0] * k
    for i in range(k - 1, -1, -1):
        out[i] = inv * prefix[i] % m
        inv = inv * values[i] % m
    return out


def _jac_batch_to_affine(pts: Sequence, p: int) -> List[Optional[Tuple[int, int]]]:
    """Jacobian -> affine for a whole list with one batch inversion."""
    live = [(i, pt) for i, pt in enumerate(pts) if pt[2] != 0]
    out: List[Optional[Tuple[int, int]]] = [None] * len(pts)
    if not live:
        return out
    invs = _batch_inv([pt[2] for _, pt in live], p)
    for (i, pt), zi in zip(live, invs):
        zi2 = zi * zi % p
        out[i] = (pt[0] * zi2 % p, pt[1] * zi2 % p * zi % p)
    return out


def _build_comb(x: int, y: int, width: int, curve_name: str,
                nbits: int = 256) -> List[List[Optional[Tuple[int, int]]]]:
    """Comb table rows[j][d] = [d * 2^(width*j)](x, y) in AFFINE coords
    (d in 1..2^width-1; index 0 unused).  Affine entries make every
    lockstep addition a mixed add with a batch-shared inversion."""
    cv = CURVES[curve_name]
    p, a = cv["p"], cv["a"]
    cols = (nbits + width - 1) // width
    base = (x, y, 1)
    jac_rows = []
    for _ in range(cols):
        row = [base]
        for _ in range(2, 1 << width):
            row.append(_jac_add(row[-1], base, p, a))
        jac_rows.append(row)
        for _ in range(width):
            base = _jac_double(base, p, a)
    flat = [pt for row in jac_rows for pt in row]
    aff = _jac_batch_to_affine(flat, p)
    out: List[List[Optional[Tuple[int, int]]]] = []
    i = 0
    for _ in range(cols):
        out.append([None] + aff[i:i + (1 << width) - 1])
        i += (1 << width) - 1
    return out


@functools.lru_cache(maxsize=None)
def _g_comb(curve_name: str):
    cv = CURVES[curve_name]
    return _build_comb(cv["gx"], cv["gy"], _COMB_G_WIDTH, curve_name)


class _PubkeyEntry:
    """Per-principal cache slot: decoded point + graduated comb."""
    __slots__ = ("pt", "verifies", "comb", "width")

    def __init__(self, pt: Optional[Tuple[int, int]]):
        self.pt = pt
        self.verifies = 0
        self.comb: Optional[list] = None
        self.width = 0


def _make_stats_lock():
    try:
        from tpubft.utils.racecheck import make_lock
        return make_lock("scalar.ecdsa_cache")
    except Exception:  # pragma: no cover — bootstrap fallback
        import threading
        return threading.Lock()


_cache_lock = _make_stats_lock()
# (curve, pk bytes) -> _PubkeyEntry, LRU-bounded (hits move-to-end so a
# busy principal's hot comb is never evicted by insertion age)
from collections import OrderedDict as _OrderedDict
_pk_cache: "_OrderedDict[Tuple[str, bytes], _PubkeyEntry]" = _OrderedDict()
_HOST_SIZES_KEEP = 256
_hot_combs: List[Tuple[str, bytes]] = []

_SINK_KEYS = ("hits", "misses", "evictions", "comb_evictions",
              "comb_builds", "host_batches", "host_items", "host_ns")


class StatsSink:
    """Attributed counter sink with an ATOMIC drain: increments and the
    drain-and-reset swap serialize on the sink's own lock, so two
    replicas' SigManagers (or a writer racing a concurrent drain — the
    event recorded on one side of the swap lands in exactly one drain,
    never both, never neither) can't lose or double-count updates.
    `host_ns` carries the batched engine's wall time — the autotuner's
    host-tier cost sensor next to the kernel profiler's device tier."""

    __slots__ = ("_mu", "_d", "_sizes")

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._d = {k: 0 for k in _SINK_KEYS}
        self._sizes: List[int] = []

    def add(self, key: str, amount: int = 1) -> None:
        with self._mu:
            self._d[key] += amount

    def note_host_batch(self, size: int, elapsed_ns: int = 0) -> None:
        with self._mu:
            self._d["host_batches"] += 1
            self._d["host_items"] += size
            self._d["host_ns"] += elapsed_ns
            self._sizes.append(size)
            del self._sizes[:-_HOST_SIZES_KEEP]

    def drain(self) -> Dict[str, object]:
        """Atomic drain-and-reset: one lock section swaps the counters
        out, so a concurrent writer's increment is either in this drain
        or the next — never torn across both."""
        with self._mu:
            out: Dict[str, object] = dict(self._d)
            out["host_sizes"] = self._sizes
            self._d = {k: 0 for k in _SINK_KEYS}
            self._sizes = []
        return out


# module-level fallback sink: engine users outside an attribute_stats
# scope (standalone benches, direct cpu.EcdsaVerifier callers) land
# here; consume_decode_stats drains it
_module_sink = StatsSink()

# thread-local stats attribution: a SigManager wraps its verification in
# `attribute_stats(sink)` so events recorded on ITS thread land in ITS
# sink — exact per-replica metrics in multi-replica processes, where the
# engine (and its caches) is shared module state.  Without a sink,
# events fall through to the module sink above.
_TLS = threading.local()


def new_stats_sink() -> StatsSink:
    return StatsSink()


@contextlib.contextmanager
def attribute_stats(sink: StatsSink):
    prev = getattr(_TLS, "sink", None)
    _TLS.sink = sink
    try:
        yield sink
    finally:
        _TLS.sink = prev


def _sink() -> StatsSink:
    sink = getattr(_TLS, "sink", None)
    return sink if sink is not None else _module_sink


def _stat(key: str, amount: int = 1) -> None:
    _sink().add(key, amount)


def _note_host_batch(size: int, elapsed_ns: int = 0) -> None:
    _sink().note_host_batch(size, elapsed_ns)


def _pk_entry(pk: bytes, curve_name: str) -> _PubkeyEntry:
    """SEC1-uncompressed decode + on-curve check, memoized per key: a
    retransmitting client pays the decode once per key, not per verify
    (hits surface as `pubkey_memo_hits` on signature_manager)."""
    key = (curve_name, bytes(pk))
    with _cache_lock:
        e = _pk_cache.get(key)
        if e is not None:
            _pk_cache.move_to_end(key)
    if e is not None:
        _stat("hits")
        return e
    _stat("misses")
    pt: Optional[Tuple[int, int]] = None
    if len(pk) == 65 and pk[0] == 0x04:
        x = int.from_bytes(pk[1:33], "big")
        y = int.from_bytes(pk[33:], "big")
        if ecdsa_on_curve(x, y, curve_name):
            pt = (x, y)
    e = _PubkeyEntry(pt)
    with _cache_lock:
        cur = _pk_cache.get(key)
        if cur is not None:
            return cur                      # racing first decoders share
        _pk_cache[key] = e
        evicted = comb_evicted = 0
        while len(_pk_cache) > _PK_CACHE_MAX:
            old, _ = _pk_cache.popitem(last=False)
            evicted += 1
            if old in _hot_combs:
                _hot_combs.remove(old)
                comb_evicted += 1
    if evicted:
        # eviction telemetry: a high rate here with a falling decode
        # hit-rate means the live principal population outruns
        # TPUBFT_ECDSA_PK_CACHE — the bounded-LRU health signal at
        # million-principal scale (per-shard admission routing exists
        # to keep each worker's slice of the population inside this)
        _stat("evictions", evicted)
        if comb_evicted:
            _stat("comb_evictions", comb_evicted)
    return e


def reset_ecdsa_caches() -> None:
    """Drop every cached pubkey entry and comb table (test/bench
    isolation: a sweep measuring cold-vs-warm tiers must not inherit
    another row's cache residency or hot-slot occupancy)."""
    with _cache_lock:
        _pk_cache.clear()
        _hot_combs.clear()


def consume_decode_stats() -> Dict[str, object]:
    """Drain-and-reset the module-level (unattributed) sink: decode-memo
    counters plus recent host batch sizes/time. Atomic per sink
    (StatsSink.drain) — concurrent drains can't double-count, and a
    racing writer's increment lands in exactly one drain."""
    return _module_sink.drain()


def _q_comb(entry: _PubkeyEntry, key: Tuple[str, bytes], batch: int):
    """Graduated per-principal comb: 4-bit on first contact, rebuilt
    8-bit once the principal crosses _COMB_HOT_AFTER lifetime verifies
    (bounded by _HOT_COMB_MAX resident hot tables)."""
    curve_name = key[0]
    with _cache_lock:
        entry.verifies += batch
        # prune ghosts: a key evicted from _pk_cache while its comb was
        # still building would otherwise hold a hot slot forever
        _hot_combs[:] = [k for k in _hot_combs if k in _pk_cache]
        want_hot = (entry.verifies >= _COMB_HOT_AFTER
                    and entry.width < _COMB_Q_HOT_WIDTH
                    and len(_hot_combs) < _HOT_COMB_MAX)
        if entry.comb is not None and not want_hot:
            return entry.comb, entry.width
    width = _COMB_Q_HOT_WIDTH if want_hot else _COMB_Q_COLD_WIDTH
    comb = _build_comb(entry.pt[0], entry.pt[1], width, curve_name)
    _stat("comb_builds")
    with _cache_lock:
        _hot_combs[:] = [k for k in _hot_combs if k in _pk_cache]
        if key not in _pk_cache:
            # evicted while building: hand the caller the table for this
            # batch but don't let an uncached key occupy a hot slot
            entry.comb, entry.width = comb, width
            return entry.comb, entry.width
        if width >= _COMB_Q_HOT_WIDTH \
                and len(_hot_combs) >= _HOT_COMB_MAX \
                and key not in _hot_combs:
            # lost the cap race to a concurrent upgrade (the check above
            # ran before the build released the lock): discard this
            # build so resident hot tables respect TPUBFT_ECDSA_HOT_COMBS.
            # A comb-less entry keeps it anyway — never leave a decoded
            # key rebuilding per batch — which can transiently exceed
            # the cap by the number of racing first-contact threads.
            if entry.comb is None:
                entry.comb, entry.width = comb, width
                _hot_combs.append(key)
            return entry.comb, entry.width
        if width > entry.width:
            entry.comb, entry.width = comb, width
            if width >= _COMB_Q_HOT_WIDTH and key not in _hot_combs:
                _hot_combs.append(key)
        return entry.comb, entry.width


def _digit_columns(k: int, width: int) -> Tuple[int, ...]:
    """LSB-first base-2^width digits of a 256-bit scalar."""
    b = k.to_bytes(32, "little")
    if width == 8:
        return tuple(b)
    if width == 4:
        out = []
        for byte in b:
            out.append(byte & 15)
            out.append(byte >> 4)
        return tuple(out)
    return tuple((k >> (width * j)) & ((1 << width) - 1)
                 for j in range((256 + width - 1) // width))


class EcdsaBatchPrecheck(NamedTuple):
    """Shared admission result: the ONE precheck both the host batch
    engine and the device kernels' host prep consume (ops/ecdsa
    adapts its item order onto this), so the four verification paths
    cannot drift on what they admit."""
    live: List[int]                      # indices that passed admission
    r: List[int]                         # per-index r (0 when invalid)
    u1: Dict[int, int]                   # e/s mod n for live indices
    u2: Dict[int, int]                   # r/s mod n for live indices
    entries: List[Optional[_PubkeyEntry]]  # decoded-pubkey cache slots


def ecdsa_precheck_batch(items: Sequence[Tuple[bytes, bytes, bytes]],
                         curve_name: str) -> EcdsaBatchPrecheck:
    """Admission identical to `ecdsa_verify` (shape, 0 < r,s < n,
    on-curve pubkey via the per-principal memo) plus u1/u2 scalars with
    ONE Montgomery batch inversion for every s^-1.
    items: (pubkey, message, sig) triples."""
    n = CURVES[curve_name]["n"]
    B = len(items)
    live: List[int] = []
    rs = [0] * B
    ss = [0] * B
    es = [0] * B
    entries: List[Optional[_PubkeyEntry]] = [None] * B
    for i, (pk, msg, sig) in enumerate(items):
        if len(sig) != 64:
            continue
        sig = bytes(sig)
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if not (0 < r < n and 0 < s < n):
            continue
        entry = _pk_entry(pk, curve_name)
        if entry.pt is None:
            continue
        rs[i], ss[i] = r, s
        es[i] = int.from_bytes(hashlib.sha256(msg).digest(), "big") % n
        entries[i] = entry
        live.append(i)
    u1: Dict[int, int] = {}
    u2: Dict[int, int] = {}
    if live:
        winv = _batch_inv([ss[i] for i in live], n)
        for i, w in zip(live, winv):
            u1[i] = es[i] * w % n
            u2[i] = rs[i] * w % n
    return EcdsaBatchPrecheck(live, rs, u1, u2, entries)


# a cold principal's comb build (~6ms for 4-bit) only beats the plain
# per-item ladder once it serves this many verifies
_COMB_MIN_GROUP = 3


def ecdsa_verify_batch(items: Sequence[Tuple[bytes, bytes, bytes]],
                       curve_name: str) -> List[bool]:
    """Batched ECDSA verify: items are (pubkey, message, raw r||s sig)
    triples (pubkeys may all differ).  Verdict-identical to calling
    `ecdsa_verify` per item, ~10x faster at batch 256 on the bench
    container (see benchmarks/RESULTS.md). Batch shape AND wall time
    land in the attributed stats sink (`host_ns`) — the autotuner's
    host-tier cost sensor for the device/host crossover."""
    if not items:
        return []
    import time as _time
    t0 = _time.monotonic_ns()
    try:
        return _ecdsa_verify_batch(items, curve_name)
    finally:
        _note_host_batch(len(items), _time.monotonic_ns() - t0)


def _ecdsa_verify_batch(items: Sequence[Tuple[bytes, bytes, bytes]],
                        curve_name: str) -> List[bool]:
    cv = CURVES[curve_name]
    p, n, a = cv["p"], cv["n"], cv["a"]
    B = len(items)
    out = [False] * B
    chk = ecdsa_precheck_batch(items, curve_name)
    rs, u1, u2 = chk.r, chk.u1, chk.u2
    if not chk.live:
        return out
    # ---- per-principal combs (one build/bump per distinct key) ----
    by_key: Dict[Tuple[str, bytes], List[int]] = {}
    for i in chk.live:
        by_key.setdefault((curve_name, bytes(items[i][0])), []).append(i)
    qcomb = {}
    qwidth = {}
    walk: List[int] = []
    for key, idxs in by_key.items():
        entry = chk.entries[idxs[0]]
        with _cache_lock:
            cold_small = (entry.comb is None
                          and entry.verifies + len(idxs) < _COMB_MIN_GROUP)
            if cold_small:
                entry.verifies += len(idxs)
        if cold_small:
            # a comb build for 1-2 cold items costs more than the plain
            # ladder it replaces: verify directly (verifies still
            # accumulate, so a recurring principal graduates to a comb)
            for i in idxs:
                pk, msg, sig = items[i]
                out[i] = ecdsa_verify(pk, msg, sig, curve_name)
            continue
        comb, width = _q_comb(entry, key, len(idxs))
        for i in idxs:
            qcomb[i] = comb
            qwidth[i] = width
        walk.extend(idxs)
    if not walk:
        return out
    # ---- lockstep affine comb walk ----
    # steps: (shared_row_or_None, per_item_rows_or_None, idxs, digits,
    #         phis, negs) — phis (per-entry, GLV only) routes the
    #         gathered entry through the secp256k1 endomorphism
    #         (x, y) -> (beta*x mod p, y) (the [lam]P half-scalar
    #         stream, reusing the same comb rows); negs flags per-item
    #         sign flips (y -> p - y at gather) for negative
    #         half-scalars
    steps = []
    g_rows = _g_comb(curve_name)
    glv = (_GLV_PARAMS.get(curve_name)
           if _glv_enabled() and len(walk) <= _glv_max_walk() else None)
    if glv is not None:
        # GLV split (ISSUE 17 satellite): u = s1*|k1| + s2*|k2|*lam
        # (mod n) with |k1|, |k2| < 2^~128.5, so the walk is
        # _glv_cols(width) columns instead of the full 256-bit run.
        # Both half-scalars of one column share a single step — and so
        # a single _batch_inv — by accumulating into two independent
        # lanes (item i: lane A at slot i, lane B at slot B+i; adds
        # across lanes have no serial dependency, unlike two adds into
        # one accumulator). The walk length (= the count of per-column
        # modular inversions, the serial cost here) halves and each
        # surviving inversion amortizes over twice the additions; a
        # final batched merge add folds lane B into lane A.
        splits = {}
        bounded = []
        for i in walk:
            s = (_glv_split(u1[i], glv, n) + _glv_split(u2[i], glv, n))
            if max(s[0], s[2], s[4], s[6]) >= _GLV_MAX:
                # magnitude rail (unreachable for reduced scalars):
                # verdict via the plain per-item path, never a wrong
                # answer from truncated digits
                pk_i, msg_i, sig_i = items[i]
                out[i] = ecdsa_verify(pk_i, msg_i, sig_i, curve_name)
                continue
            splits[i] = s
            bounded.append(i)
        walk = bounded
        if not walk:
            return out
        lane_b = [B + i for i in walk]
        both = walk + lane_b
        g_phis = [False] * len(walk) + [True] * len(walk)
        g_negs = ([splits[i][1] for i in walk]
                  + [splits[i][3] for i in walk])
        da = {i: _digit_columns(splits[i][0], _COMB_G_WIDTH)
              for i in walk}
        db = {i: _digit_columns(splits[i][2], _COMB_G_WIDTH)
              for i in walk}
        for j in range(_glv_cols(_COMB_G_WIDTH)):
            steps.append((g_rows[j], None, both,
                          [da[i][j] for i in walk]
                          + [db[i][j] for i in walk], g_phis, g_negs))
        for width in (_COMB_Q_HOT_WIDTH, _COMB_Q_COLD_WIDTH):
            sub = [i for i in walk if qwidth[i] == width]
            if not sub:
                continue
            sub_both = sub + [B + i for i in sub]
            q_phis = [False] * len(sub) + [True] * len(sub)
            q_negs = ([splits[i][5] for i in sub]
                      + [splits[i][7] for i in sub])
            qa = {i: _digit_columns(splits[i][4], width) for i in sub}
            qb = {i: _digit_columns(splits[i][6], width) for i in sub}
            for j in range(_glv_cols(width)):
                rows_j = [qcomb[i][j] for i in sub]
                steps.append((None, rows_j + rows_j, sub_both,
                              [qa[i][j] for i in sub]
                              + [qb[i][j] for i in sub],
                              q_phis, q_negs))
    else:
        g_digs = {i: _digit_columns(u1[i], _COMB_G_WIDTH) for i in walk}
        for j, row in enumerate(g_rows):
            steps.append((row, None, walk,
                          [g_digs[i][j] for i in walk], None, None))
        for width in (_COMB_Q_HOT_WIDTH, _COMB_Q_COLD_WIDTH):
            sub = [i for i in walk if qwidth[i] == width]
            if not sub:
                continue
            digs = {i: _digit_columns(u2[i], width) for i in sub}
            for j in range(len(qcomb[sub[0]])):
                steps.append((None, [qcomb[i][j] for i in sub], sub,
                              [digs[i][j] for i in sub], None, None))
    beta = glv["beta"] if glv is not None else 0
    lanes = 2 * B if glv is not None else B
    ax = [0] * lanes
    ay = [0] * lanes
    inf = [True] * lanes
    for shared_row, rows, idxs, digs, phis, negs in steps:
        denoms: List[int] = []
        dap = denoms.append
        acts: List[Tuple[int, int, int, int]] = []
        aap = acts.append
        for t, i in enumerate(idxs):
            d = digs[t]
            if not d:
                continue
            e = shared_row[d] if shared_row is not None else rows[t][d]
            if phis is not None:
                if phis[t]:
                    e = (beta * e[0] % p, e[1])
                if negs[t]:
                    e = (e[0], p - e[1])
            if inf[i]:
                ax[i], ay[i] = e
                inf[i] = False
                continue
            dx = e[0] - ax[i]
            if dx:
                dap(dx)
                aap((i, e[0], e[1], 0))
            elif e[1] == ay[i]:
                # doubling (2-torsion is impossible on these curves, so
                # 2*y is never 0 here)
                dap(2 * ay[i])
                aap((i, e[0], e[1], 1))
            else:
                inf[i] = True               # P + (-P)
        if not denoms:
            continue
        invs = _batch_inv(denoms, p)
        for (i, ex, ey, dbl), invd in zip(acts, invs):
            x1 = ax[i]
            y1 = ay[i]
            if dbl:
                lam = (3 * x1 * x1 + a) * invd % p
                x3 = (lam * lam - 2 * x1) % p
            else:
                lam = (ey - y1) * invd % p
                x3 = (lam * lam - x1 - ex) % p
            ay[i] = (lam * (x1 - x3) - y1) % p
            ax[i] = x3
    if glv is not None:
        # fold lane B (the [lam]-stream accumulator) into lane A with
        # one final batched affine add
        denoms = []
        acts = []
        for i in walk:
            ib = B + i
            if inf[ib]:
                continue
            if inf[i]:
                ax[i], ay[i] = ax[ib], ay[ib]
                inf[i] = False
                continue
            dx = ax[ib] - ax[i]
            if dx:
                denoms.append(dx)
                acts.append((i, ax[ib], ay[ib], 0))
            elif ay[ib] == ay[i]:
                denoms.append(2 * ay[i])
                acts.append((i, ax[ib], ay[ib], 1))
            else:
                inf[i] = True               # A + (-A)
        if denoms:
            invs = _batch_inv(denoms, p)
            for (i, ex, ey, dbl), invd in zip(acts, invs):
                x1 = ax[i]
                y1 = ay[i]
                if dbl:
                    lam = (3 * x1 * x1 + a) * invd % p
                    x3 = (lam * lam - 2 * x1) % p
                else:
                    lam = (ey - y1) * invd % p
                    x3 = (lam * lam - x1 - ex) % p
                ay[i] = (lam * (x1 - x3) - y1) % p
                ax[i] = x3
    for i in walk:
        # x(T) mod n == r covers the r+n wrap case by construction
        out[i] = (not inf[i]) and ax[i] % n == rs[i]
    return out


def ecdsa_verify(pk: bytes, msg: bytes, sig: bytes, curve_name: str) -> bool:
    """Standard ECDSA verify with the same admission checks as the
    batched kernel's host precheck (ops/ecdsa.prepare_batch): shapes,
    0 < r,s < n, pubkey on curve; then x([u1]G + [u2]Q) ≡ r (mod n)."""
    cv = CURVES[curve_name]
    p, n = cv["p"], cv["n"]
    if len(sig) != 64 or len(pk) != 65 or pk[0] != 0x04:
        return False
    sig, pk = bytes(sig), bytes(pk)
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:], "big")
    x = int.from_bytes(pk[1:33], "big")
    y = int.from_bytes(pk[33:], "big")
    if not (0 < r < n and 0 < s < n):
        return False
    if not ecdsa_on_curve(x, y, curve_name):
        return False
    z = int.from_bytes(hashlib.sha256(msg).digest(), "big") % n
    w = pow(s, -1, n)
    u1, u2 = z * w % n, r * w % n
    pt = _jac_add(_mul_g(u1, curve_name), _jac_mul(u2, (x, y), cv),
                  p, cv["a"])
    aff = _jac_to_affine(pt, p)
    if aff is None:
        return False                    # R' is the identity
    return aff[0] % n == r
