"""TPU-backed crypto plugin implementations.

This is the backend the whole project exists for: the reference verifies
every signature one-at-a-time on CPU threads behind its plugin boundaries
(`IVerifier` — util/include/crypto_utils.hpp:41-55, consumed by
SigManager.cpp:197; `IThresholdVerifier`/`IThresholdAccumulator` —
threshsign/include/threshsign/IThresholdVerifier.h:23,
IThresholdAccumulator.h:22). Here the same boundaries are implemented by
batched JAX kernels:

  * TpuEd25519Verifier       — per-principal IVerifier over the windowed
                               batch kernel (tpubft/ops/ed25519.py);
  * verify_batch_items       — cross-principal one-kernel-call batch used
                               by SigManager.verify_batch (the PrePrepare
                               client-sig flood path);
  * TpuMultisigEd25519Verifier — combined-multisig verification as ONE
                               device batch instead of k sequential share
                               verifies;
  * TpuBlsThresholdVerifier  — BLS threshold accumulator whose combine
                               runs the Lagrange+MSM on device
                               (tpubft/ops/bls12_381.py), the counterpart
                               of fastMultExp (FastMultExp.cpp:27).

Selected via ReplicaConfig.crypto_backend == "tpu"; everything constructs
through the same factories as the CPU backend, so consensus code never
branches on the backend.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple

from tpubft.crypto import bls12381 as bls
from tpubft.crypto.interfaces import IVerifier
from tpubft.crypto.systems import (BlsMultisigVerifier,
                                   BlsThresholdAccumulator,
                                   BlsThresholdVerifier,
                                   MultisigEd25519Verifier)


def verify_batch_items(items: Sequence[Tuple[bytes, bytes, bytes]]
                       ) -> List[bool]:
    """One kernel call over ed25519 (pubkey, data, sig) triples —
    principals may all differ. Used by the multisig share paths (replica
    shares are always the replica scheme)."""
    from tpubft.ops import ed25519 as ops
    return [bool(x) for x in
            ops.verify_batch([(d, s, pk) for pk, d, s in items])]


import functools


@functools.lru_cache(maxsize=1)
def _platform_default_crossover() -> int:
    """Platform half of the crossover default — the expensive
    jax.devices() probe cannot change after process start, so it
    resolves once."""
    import jax
    return 1 if jax.devices()[0].platform != "cpu" else 1 << 30


# runtime override of the ECDSA device/host crossover — the autotuner's
# actuator (tpubft/tuning/wiring.py drives it from measured `ecdsa`
# kernel batch stats vs the batched-host timing counters). Process-wide
# like the device itself: all replicas of one process share one
# accelerator, so the last-configured value wins (same doctrine as the
# breaker's configure()). None = fall through to the env knob/platform
# default below.
_crossover_override: Optional[int] = None


def set_ecdsa_crossover(b: Optional[int]) -> None:
    """Set (or with None, clear) the runtime ECDSA device/host
    crossover. Takes precedence over TPUBFT_ECDSA_CROSSOVER_B."""
    global _crossover_override
    _crossover_override = None if b is None else max(1, int(b))


def ecdsa_crossover() -> int:
    """The effective crossover (override > env > platform default) —
    the autotuner seeds its knob default from this. The static tiers
    (env/platform) scale DOWN by the healthy mesh width: d chips
    amortize the RLC launch at ~1/d the batch, so the device tier wins
    sooner. The autotuner override is exempt — its policy already
    measures the mesh-backed per-item cost, so dividing again would
    double-count the mesh."""
    base = _ecdsa_device_crossover()
    if _crossover_override is not None or base <= 1:
        return base
    from tpubft.ops import dispatch
    return max(1, base // max(1, dispatch.mesh_shards()))


def _ecdsa_device_crossover() -> int:
    """Minimum ECDSA sub-batch size that rides the device RLC kernel;
    smaller groups verify through the batched host engine
    (crypto/scalar.ecdsa_verify_batch). The runtime override (autotuner)
    wins, then TPUBFT_ECDSA_CROSSOVER_B as exported by
    `benchmarks/bench_msm_crossover.py --ecdsa` (env read stays
    per-call: tests flip it at runtime); unset, the default prefers the
    device on real accelerators and the batched host on the XLA-CPU
    fallback (where the kernel is ~100x slower than the comb walk —
    BENCH_r05's 30-34/s cliff)."""
    import os
    if _crossover_override is not None:
        return _crossover_override
    v = os.environ.get("TPUBFT_ECDSA_CROSSOVER_B")
    if v is not None:
        try:
            return int(v)
        except ValueError:
            # a malformed knob must not poison every verify batch (the
            # caller's degrade-never-fail wrapper would reroute forever
            # with only a cryptic per-batch traceback)
            import logging
            logging.getLogger("tpubft.crypto").warning(
                "ignoring non-integer TPUBFT_ECDSA_CROSSOVER_B=%r", v)
    return _platform_default_crossover()


def verify_batch_mixed(items: Sequence[Tuple[str, bytes, bytes, bytes]]
                       ) -> List[bool]:
    """SigManager's cross-principal batch entry: (scheme, pubkey, data,
    sig) tuples, one device dispatch per scheme present. This is how the
    secp256k1/P-256 client-auth mix of BASELINE configs 3/5 rides the
    device: EdDSA through the windowed ed25519 kernel, ECDSA through the
    RLC batch kernel (tpubft/ops/ecdsa.rlc_verify_batch — one MSM-shaped
    launch per flush, the batched counterpart of the reference's
    per-message ECDSAVerifier, crypto_utils.hpp:57-73). ECDSA groups
    below the measured device crossover verify on the batched host
    engine instead of paying a losing kernel dispatch."""
    groups = {}
    for i, (scheme, pk, data, sig) in enumerate(items):
        groups.setdefault(scheme, []).append(i)
    out = [False] * len(items)
    for scheme, idxs in groups.items():
        sub = [items[i] for i in idxs]
        if scheme == "ed25519":
            verdicts = verify_batch_items([(pk, d, s)
                                           for _, pk, d, s in sub])
        elif scheme in ("ecdsa-secp256k1", "secp256k1",
                        "ecdsa-secp256r1", "secp256r1", "ecdsa-p256"):
            curve = ("secp256k1" if "k1" in scheme else "secp256r1")
            if len(sub) >= ecdsa_crossover():
                from tpubft.ops import ecdsa as ops_ecdsa
                rlc_items = [(d, s, pk) for _, pk, d, s in sub]

                def _local_rlc(items=rlc_items, curve=curve):
                    return [bool(x) for x in
                            ops_ecdsa.rlc_verify_batch(curve, items)]
                # offload tier first: a helper eats the verdict storm,
                # the replica pays ONE re-fold launch instead of the
                # bisection descent; None = no lease (pool inactive /
                # at capacity / helpers down) -> local path unchanged
                from tpubft.offload import pool as offload
                verdicts = offload.ecdsa_via_offload(curve, rlc_items,
                                                     _local_rlc)
                if verdicts is None:
                    verdicts = _local_rlc()
            else:
                from tpubft.crypto import scalar as _scalar
                verdicts = _scalar.ecdsa_verify_batch(
                    [(pk, d, s) for _, pk, d, s in sub], curve)
        else:                       # unknown scheme: CPU fallback
            from tpubft.crypto.cpu import make_verifier
            verdicts = []
            for _, pk, d, s in sub:
                try:
                    verdicts.append(make_verifier(scheme, pk).verify(d, s))
                except Exception:
                    verdicts.append(False)
        for i, ok in zip(idxs, verdicts):
            out[i] = ok
    return out


class TpuEd25519Verifier(IVerifier):
    """IVerifier bound to one public key, batch-first. Single verify() is
    a batch of one (pays one device dispatch — callers on the hot path go
    through SigManager.verify_batch / BatchVerifier instead)."""

    def __init__(self, public_key_bytes: bytes):
        self.public_key_bytes = public_key_bytes

    def verify(self, data: bytes, sig: bytes) -> bool:
        return self.verify_batch([(data, sig)])[0]

    def verify_batch(self, items: Sequence[Tuple[bytes, bytes]]
                     ) -> List[bool]:
        try:
            from tpubft.ops import ed25519 as ops
            return [bool(x) for x in ops.verify_batch(
                [(d, s, self.public_key_bytes) for d, s in items])]
        except Exception:  # noqa: BLE001 — device loss (or an OPEN
            # breaker fast-fail) degrades to the host verifier; the
            # breaker recorded the failure at the kernel seam
            from tpubft.crypto.cpu import make_verifier
            v = make_verifier("ed25519", self.public_key_bytes)
            return [v.verify(d, s) for d, s in items]

    @property
    def signature_length(self) -> int:
        return 64


class TpuMultisigEd25519Verifier(MultisigEd25519Verifier):
    """Multisig verifier whose combined-signature check and bad-share
    identification run as one device batch (k shares -> one dispatch).
    Below `min_device_batch` shares the check stays on the CPU verifiers:
    a k=3 certificate is latency-critical and too small to amortize a
    device dispatch."""

    def __init__(self, threshold: int, total: int,
                 share_public_keys: Sequence[bytes],
                 min_device_batch: int = 1):
        super().__init__(threshold, total, share_public_keys)
        self._share_pk_bytes = list(share_public_keys)
        self.min_device_batch = min_device_batch

    def verify(self, data: bytes, sig: bytes) -> bool:
        if self.threshold < self.min_device_batch:
            return super().verify(data, sig)
        entries = self._parse_vector(data, sig)
        if entries is None:
            return False
        try:
            return all(verify_batch_items(entries))
        except Exception:  # noqa: BLE001 — device loss: the host
            # multisig check is byte-identical, just serial
            return super().verify(data, sig)

    def verify_share_batch(self, items: Sequence[Tuple[int, bytes, bytes]]
                           ) -> List[bool]:
        """[(share_id, data, share)] -> verdicts, one device dispatch."""
        if len(items) < self.min_device_batch:
            return [self.verify_share(i, d, s) for i, d, s in items]
        entries = []
        ok_shape = []
        for share_id, data, share in items:
            if 1 <= share_id <= self.total_signers:
                entries.append((self._share_pk_bytes[share_id - 1], data,
                                share))
                ok_shape.append(True)
            else:
                ok_shape.append(False)
        try:
            verdicts = iter(verify_batch_items(entries))
        except Exception:  # noqa: BLE001 — degrade to per-share host
            return [self.verify_share(i, d, s) for i, d, s in items]
        return [next(verdicts) if shaped else False for shaped in ok_shape]

    def verify_batch_certs(self, items) -> List[bool]:
        """Cross-cert batching for the multisig vector: every cert's
        share signatures across the whole flush verify in ONE ed25519
        device batch (k_1+...+k_m sigs, one dispatch) instead of m
        sequential k-verify loops. Presence of this override routes
        multisig certs through the replica's CertBatchVerifier."""
        parsed: List[Optional[List[Tuple[bytes, bytes, bytes]]]] = []
        entries: List[Tuple[bytes, bytes, bytes]] = []
        for data, sig in items:
            one = self._parse_vector(data, sig)
            parsed.append(one)
            if one is not None:
                entries.extend(one)
        if not entries:
            return [False] * len(items)
        if len(entries) < self.min_device_batch:
            # a near-empty flush is latency-critical and too small to
            # amortize a dispatch: host loop (same doctrine as verify)
            return [self.verify(d, s) for d, s in items]
        try:
            verdicts = iter(verify_batch_items(entries))
        except Exception:  # noqa: BLE001 — device loss: serial host check
            return [self.verify(d, s) for d, s in items]
        out = []
        for one in parsed:
            if one is None:
                out.append(False)
            else:
                # materialize BEFORE all(): a short-circuit would leave
                # this cert's unconsumed verdicts on the shared iterator
                # and misattribute them to every later cert in the flush
                vs = [next(verdicts) for _ in one]
                out.append(all(vs))
        return out

    def _parse_vector(self, data: bytes, sig: bytes
                      ) -> Optional[List[Tuple[bytes, bytes, bytes]]]:
        """Structural multisig-vector checks (threshold met, unique
        in-range signers, exact length) -> (pk, data, share) entries,
        or None when the vector can't be valid. Mirrors
        MultisigEd25519Verifier.verify's parse exactly."""
        try:
            (k,) = struct.unpack_from("<H", sig, 0)
            if k < self.threshold:
                return None
            off = 2
            entries = []
            seen = set()
            for _ in range(k):
                (i,) = struct.unpack_from("<H", sig, off)
                off += 2
                share = sig[off:off + 64]
                off += 64
                if i in seen or not 1 <= i <= self.total_signers:
                    return None
                seen.add(i)
                entries.append((self._share_pk_bytes[i - 1], data, share))
            if off != len(sig):
                return None
            return entries
        except (struct.error, IndexError):
            return None

    def combine_batch(self, jobs) -> List[Tuple[bool, bytes, List[int]]]:
        """Fused cross-slot combine for the multisig vector: combining
        is concatenation (host, trivial) — the cost is verification, so
        every job's shares across the flush ride ONE ed25519 device
        batch. Verdicts (including bad-share identification and its
        dict-order listing) are identical to the per-job loop."""
        entries = []
        index = []                     # (job, sid) per entry
        for j, (digest, shares) in enumerate(jobs):
            for sid in shares:         # dict order, like the accumulator
                if 1 <= sid <= self.total_signers:
                    entries.append((self._share_pk_bytes[sid - 1], digest,
                                    shares[sid]))
                    index.append((j, sid))
        if len(entries) < self.min_device_batch:
            return super().combine_batch(jobs)   # host loop (see verify)
        try:
            flat = verify_batch_items(entries) if entries else []
        except Exception:  # noqa: BLE001 — device loss: per-job host loop
            return super().combine_batch(jobs)
        ok_by_job: List[Dict[int, bool]] = [{} for _ in jobs]
        for (j, sid), good in zip(index, flat):
            ok_by_job[j][sid] = bool(good)
        out: List[Tuple[bool, bytes, List[int]]] = []
        for j, (digest, shares) in enumerate(jobs):
            verdicts = ok_by_job[j]
            chosen = sorted(shares)[: self.threshold]
            ok = (len(chosen) >= self.threshold
                  and all(verdicts.get(sid, False) for sid in chosen))
            if ok:
                from tpubft.crypto.systems import pack_multisig_vector
                out.append((True, pack_multisig_vector(chosen, shares),
                            []))
            else:
                out.append((False, b"", [sid for sid in shares
                                         if not verdicts.get(sid, False)]))
        return out


class TpuBlsThresholdAccumulator(BlsThresholdAccumulator):
    """BLS accumulator combining on device: Lagrange coefficients on host
    (tiny), the [λ_i]·share_i MSM on the TPU (ops/bls12_381.msm) — the
    role of fastMultExp in BlsThresholdAccumulator.cpp:42-56.

    Combine-path selection is by quorum size: below the measured
    crossover (TPUBFT_MSM_CROSSOVER_K, benchmarks/bench_msm_crossover.py)
    the host Pippenger MSM beats a device dispatch, so small quorums stay
    on the CPU path even under the tpu backend."""

    def get_full_signed_data(self) -> bytes:
        import os
        k = self._verifier.threshold
        crossover = int(os.environ.get("TPUBFT_MSM_CROSSOVER_K", "128"))
        if len(self._shares) < crossover and k < crossover:
            return super().get_full_signed_data()
        try:
            from tpubft.ops import bls12_381 as dev
            ids = sorted(self._shares)[:k]
            # shares are affine (x, y) int tuples — the device MSM's
            # native input
            combined = dev.combine_shares(ids,
                                          [self._shares[i] for i in ids])
            return bls.g1_compress(combined)
        except Exception:  # noqa: BLE001 — device loss: the host
            # Pippenger combine produces the identical signature
            return super().get_full_signed_data()


class TpuBlsThresholdVerifier(BlsThresholdVerifier):
    def new_accumulator(self, with_share_verification: bool
                        ) -> TpuBlsThresholdAccumulator:
        return TpuBlsThresholdAccumulator(self, with_share_verification)

    def _combine_segments(self, segments, digests=None) -> List:
        """Fused-combine with backend tiering: offload (leased to a
        verified helper, ISSUE 20) -> device -> host. A lease only
        happens when the pool is active AND the caller supplied the
        slot digests the soundness check binds to; any failed/evicted
        lease re-runs on the local tiers inside this same call, so the
        returned points are byte-identical with offload on or off."""
        if digests is not None:
            from tpubft.offload import pool as offload
            leased = offload.combine_via_offload(
                segments, digests, self._master_pk,
                lambda: self._combine_segments_local(segments))
            if leased is not None:
                return leased
        return self._combine_segments_local(segments)

    def _combine_segments_local(self, segments) -> List:
        """Device path: every slot's Lagrange-weighted MSM in ONE
        segmented `msm_batch_kernel` launch (combine_batch's whole
        flush pays one `bls_msm` dispatch instead of one per slot).
        Below the measured crossover the host Pippenger path wins even
        fused — same knob as the per-slot accumulator."""
        import os
        total = sum(len(ids) for ids, _ in segments)
        crossover = int(os.environ.get("TPUBFT_MSM_CROSSOVER_K", "128"))
        # a fused flush amortizes the dispatch across all segments, so
        # it clears the crossover on the SUM of shares, not per slot
        if total < crossover or not any(ids for ids, _ in segments):
            return super()._combine_segments(segments)
        try:
            from tpubft.ops import bls12_381 as dev
            return dev.combine_shares_batch(
                [(ids, pts) for ids, pts in segments])
        except Exception:  # noqa: BLE001 — device loss: the host
            # per-segment combine produces identical signatures
            return super()._combine_segments(segments)


class TpuBlsMultisigVerifier(BlsMultisigVerifier):
    """Multisig-BLS with the unweighted sums on device: every segment's
    Σ share_i rides the SAME segmented multi-MSM kernel the threshold
    scheme's Lagrange combine uses (`ops/bls12_381.msm_batch` under
    `device_section("bls_msm")`), with all-ones scalars — a new call
    shape, not a new kernel. Serves both the fused `combine_batch` flush
    (root of the aggregation overlay) and `aggregate_partials` (interior
    nodes), so one flush is one launch in both roles."""

    def _sum_segments(self, segments, meta=None) -> List:
        if meta is not None and any(m is not None for m in meta):
            from tpubft.offload import pool as offload
            leased = offload.sum_via_offload(
                segments, meta, self,
                lambda: self._sum_segments_local(segments))
            if leased is not None:
                return leased
        return self._sum_segments_local(segments)

    def _sum_segments_local(self, segments) -> List:
        import os
        total = sum(len(pts) for pts in segments)
        crossover = int(os.environ.get("TPUBFT_MSM_CROSSOVER_K", "128"))
        # fused flush: clear the crossover on the SUM across segments
        if total < crossover or not any(segments):
            return super()._sum_segments(segments)
        try:
            from tpubft.ops import bls12_381 as dev
            live = [i for i, pts in enumerate(segments) if pts]
            sums = dev.msm_batch([(segments[i], [1] * len(segments[i]))
                                  for i in live])
            out = [None] * len(segments)
            for i, pt in zip(live, sums):
                out[i] = pt
            return out
        except Exception:  # noqa: BLE001 — device loss: the host
            # sequential sums produce identical points
            return super()._sum_segments(segments)


def make_threshold_verifier(type_name: str, threshold: int, total: int,
                            public_key, share_public_keys,
                            min_device_batch: int = 1):
    """TPU-flavored counterpart of Cryptosystem.create_threshold_verifier
    (ThresholdSignaturesTypes.cpp:183): same key material, device-backed
    verification."""
    if type_name == "multisig-ed25519":
        return TpuMultisigEd25519Verifier(threshold, total,
                                          share_public_keys,
                                          min_device_batch)
    if type_name == "threshold-bls":
        return TpuBlsThresholdVerifier(threshold, total, public_key,
                                       share_public_keys)
    if type_name == "multisig-bls":
        return TpuBlsMultisigVerifier(threshold, total, share_public_keys)
    raise ValueError(f"no TPU backend for cryptosystem {type_name!r}")
