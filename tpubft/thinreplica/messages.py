"""Thin-replica wire protocol (reference proto/thin_replica.proto),
length-framed over TCP: u32le frame length + id byte + codec body."""
from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass, field
from typing import List, Tuple

from tpubft.utils import serialize as ser


@dataclass
class ReadStateRequest:
    ID = 1
    key_prefix: bytes = b""
    SPEC = [("key_prefix", "bytes")]


@dataclass
class ReadStateHashRequest:
    ID = 2
    block_id: int = 0
    key_prefix: bytes = b""
    SPEC = [("block_id", "u64"), ("key_prefix", "bytes")]


@dataclass
class SubscribeRequest:
    ID = 3
    block_id: int = 1           # first block wanted
    key_prefix: bytes = b""
    hashes_only: bool = False
    SPEC = [("block_id", "u64"), ("key_prefix", "bytes"),
            ("hashes_only", "bool")]


@dataclass
class UnsubscribeRequest:
    ID = 4
    SPEC = []


@dataclass
class Update:
    ID = 5
    block_id: int = 0
    kv: List[Tuple[bytes, bytes]] = field(default_factory=list)
    SPEC = [("block_id", "u64"),
            ("kv", ("list", ("pair", "bytes", "bytes")))]


@dataclass
class UpdateHash:
    ID = 6
    block_id: int = 0
    digest: bytes = b""
    SPEC = [("block_id", "u64"), ("digest", "bytes")]


@dataclass
class StateDone:
    """End of the ReadState snapshot stream; carries the state hash."""
    ID = 7
    block_id: int = 0
    digest: bytes = b""
    SPEC = [("block_id", "u64"), ("digest", "bytes")]


@dataclass
class ProtocolError:
    ID = 8
    reason: str = ""
    SPEC = [("reason", "str")]


@dataclass
class ReadProofRequest:
    """Merkle proof for a block_merkle key AS OF a retained block
    (reference versioned sparse_merkle proofs via the kvbc adapter)."""
    ID = 9
    block_id: int = 0
    category: str = ""
    key: bytes = b""
    SPEC = [("block_id", "u64"), ("category", "str"), ("key", "bytes")]


@dataclass
class ProofReply:
    ID = 10
    block_id: int = 0
    root: bytes = b""           # category root anchored in that block
    value_hash: bytes = b""     # b"" = key absent at that block
    bitmap: bytes = b""         # sparse_merkle.Proof compressed path
    siblings: List[bytes] = field(default_factory=list)
    # the value itself when the server still holds it at the proven
    # hash (b"" otherwise) — untrusted: the client binds it to
    # value_hash, which the verified audit path proves
    value: bytes = b""
    SPEC = [("block_id", "u64"), ("root", "bytes"),
            ("value_hash", "bytes"), ("bitmap", "bytes"),
            ("siblings", ("list", "bytes")), ("value", "bytes")]


@dataclass
class AnchorRequest:
    """Ask the server for its newest quorum-certified checkpoint anchor:
    the f+1 matching signed CheckpointMsgs plus the raw block row whose
    digest is the certified state digest. The CLIENT verifies the cert
    signatures and the digest binding — the server is untrusted."""
    ID = 11
    SPEC = []


@dataclass
class AnchorReply:
    ID = 12
    ckpt_seq: int = 0           # consensus seqnum of the checkpoint
    block_id: int = 0           # chain height the certified digest binds
    block_raw: bytes = b""      # encoded Block row; sha256 == cert digest
    certs: List[bytes] = field(default_factory=list)  # packed CheckpointMsg
    SPEC = [("ckpt_seq", "u64"), ("block_id", "u64"),
            ("block_raw", "bytes"), ("certs", ("list", "bytes"))]


@dataclass
class BlockRequest:
    """Raw block row for hash-chain verification (the client walks
    parent digests from a certified anchor; the bytes prove themselves)."""
    ID = 13
    block_id: int = 0
    SPEC = [("block_id", "u64")]


@dataclass
class BlockReply:
    ID = 14
    block_id: int = 0
    raw: bytes = b""            # b"" = missing (ahead or pruned)
    SPEC = [("block_id", "u64"), ("raw", "bytes")]


_TYPES = {cls.ID: cls for cls in
          (ReadStateRequest, ReadStateHashRequest, SubscribeRequest,
           UnsubscribeRequest, Update, UpdateHash, StateDone,
           ProtocolError, ReadProofRequest, ProofReply,
           AnchorRequest, AnchorReply, BlockRequest, BlockReply)}


def pack(msg) -> bytes:
    body = bytes([msg.ID]) + ser.encode_msg(msg)
    return struct.pack("<I", len(body)) + body


def unpack_body(body: bytes):
    if not body or body[0] not in _TYPES:
        raise ser.SerializeError(f"unknown TRS msg id {body[:1]!r}")
    return ser.decode_msg(body[1:], _TYPES[body[0]])


def update_hash(block_id: int, kv: List[Tuple[bytes, bytes]]) -> bytes:
    """Canonical per-block update digest (reference kvbc_app_filter
    event-group hashing): order-independent over the kv set."""
    h = hashlib.sha256()
    h.update(struct.pack("<Q", block_id))
    for k, v in sorted(kv):
        h.update(struct.pack("<I", len(k)) + k)
        h.update(struct.pack("<I", len(v)) + v)
    return h.digest()
