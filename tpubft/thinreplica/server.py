"""ThinReplicaServer — serves state reads + live update subscriptions.

Rebuild of the reference's ThinReplicaImpl
(/root/reference/thin-replica-server/include/thin-replica-server/
thin_replica_impl.hpp:98) + subscription_buffer.hpp: one TCP listener,
one handler thread per connection; live updates arrive from the
blockchain's commit stream into per-subscriber bounded buffers; history
is read from the chain so a subscriber can start at any block and roll
forward into the live stream without gaps.

Serving-plane wiring (the read-scaling tier):

  * the live feed rides the blockchain's RUN listener — one publish hop
    per sealed execution run (the coalesced durable apply), not one per
    block, so the read tier's cost on the write pipeline stays constant
    as accumulation deepens;
  * every proof request is answered with the block-anchored merkle root
    + audit path; the digest-authenticated trust chain up to f+1 signed
    checkpoint certificates is served via AnchorRequest/BlockRequest
    (`anchor_fn` — wired by the consensus replica). The server remains
    untrusted: clients verify everything;
  * observability: the `thinreplica` metrics component
    (trs_overflows / trs_dropped_subscribers / push + read counters)
    and the trs_subscribe / trs_push / trs_proof flight events.
"""
from __future__ import annotations

import hashlib
import queue
import socket
import struct
import threading
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

from tpubft.kvbc import categories as cat
from tpubft.kvbc.blockchain import KeyValueBlockchain
from tpubft.thinreplica import messages as tm
from tpubft.utils import flight
from tpubft.utils.logging import get_logger
from tpubft.utils.metrics import Component
from tpubft.utils.racecheck import make_lock

log = get_logger("thinreplica")


@dataclass
class FilterSpec:
    """kvbc_app_filter equivalent: which updates are client-visible."""
    category: str = "kv"
    key_prefix: bytes = b""

    def filter_updates(self, updates: cat.BlockUpdates
                       ) -> List[Tuple[bytes, bytes]]:
        out = []
        hit = updates.categories.get(self.category)
        if hit is None:
            return out
        _type, cu = hit
        for k in sorted(cu.kv):
            v = cu.kv[k]
            if v is not None and k.startswith(self.key_prefix):
                out.append((k, v))
        return out


class _Subscriber:
    """SubUpdateBuffer: bounded queue of RUNS; overflow drops the
    subscriber (it re-subscribes and catches up from history)."""

    def __init__(self, start_block: int, maxsize: int = 1024) -> None:
        self.q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self.next_block = start_block
        self.dead = False

    def push(self, item) -> bool:
        """True = enqueued; False = buffer full (caller marks dead and
        accounts for the drop — this used to be a silent loss)."""
        try:
            self.q.put_nowait(item)
            return True
        except queue.Full:
            self.dead = True
            return False


class ThinReplicaServer:
    def __init__(self, blockchain: KeyValueBlockchain,
                 filter_spec: Optional[FilterSpec] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 sub_buffer: int = 1024,
                 aggregator=None,
                 anchor_fn: Optional[Callable[[], Optional[tuple]]] = None
                 ) -> None:
        self.bc = blockchain
        self.filter = filter_spec or FilterSpec()
        self._sub_buffer = max(1, sub_buffer)
        # () -> (ckpt_seq, block_id, [packed CheckpointMsg...]) or None;
        # provided by the consensus replica (thread-safe snapshot)
        self._anchor_fn = anchor_fn
        self._subs: List[_Subscriber] = []
        # make_lock (not raw): the subscriber list crosses the commit
        # thread (exec lane / dispatcher) and connection handlers —
        # the lint's static-race pass and the runtime lock-order graph
        # must both see it
        self._subs_lock = make_lock("trs.subs")
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]
        self._running = False
        self._accept_thread: Optional[threading.Thread] = None
        # --- metrics (the serving plane's day-one observability) ---
        self.metrics = Component("thinreplica", aggregator)
        self.m_subscribers = self.metrics.register_gauge("trs_subscribers")
        self.m_pushed_runs = self.metrics.register_counter(
            "trs_pushed_runs")
        self.m_pushed_blocks = self.metrics.register_counter(
            "trs_pushed_blocks")
        self.m_overflows = self.metrics.register_counter("trs_overflows")
        self.m_dropped_subs = self.metrics.register_counter(
            "trs_dropped_subscribers")
        self.m_reads = self.metrics.register_counter("trs_reads")
        self.m_proofs = self.metrics.register_counter("trs_proofs")
        self.m_anchors = self.metrics.register_counter("trs_anchors")
        blockchain.add_run_listener(self._on_run)

    # ---- lifecycle ----
    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._sock.listen(16)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"trs-accept-{self.port}")
        self._accept_thread.start()

    def stop(self) -> None:
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass

    # ---- commit-path feed (exec-lane / dispatcher thread) ----
    def _on_run(self, items) -> None:
        """One sealed run (N blocks, one atomic commit) → ONE buffer
        push per subscriber. Filtering happens once, here, instead of
        per subscriber."""
        batch = [(bid, self.filter.filter_updates(bu))
                 for bid, bu in items]
        dropped = 0
        with self._subs_lock:
            live = []
            for sub in self._subs:
                if sub.dead:
                    continue
                if not sub.push(batch):
                    # overflow: the subscriber is too slow for the live
                    # stream — drop it (it re-subscribes and catches up
                    # from history) and tell the operator how far behind
                    # it was so buffers can be sized
                    dropped += 1
                    self.m_overflows.inc()
                    log.warning(
                        "trs subscriber overflow: lag=%d blocks "
                        "(next wanted %d, head %d, buffer %d runs); "
                        "dropping — it must re-subscribe",
                        max(0, batch[-1][0] - sub.next_block),
                        sub.next_block, batch[-1][0], self._sub_buffer)
                    continue
                live.append(sub)
            self._subs = live
            self.m_subscribers.set(len(live))
        if dropped:
            self.m_dropped_subs.inc(dropped)
        self.m_pushed_runs.inc()
        self.m_pushed_blocks.inc(len(batch))
        flight.record(flight.EV_TRS_PUSH, seq=batch[-1][0],
                      arg=len(batch))

    # ---- connection handling ----
    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True, name="trs-conn").start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            # request/reply messages PIPELINE on one connection (the
            # read-serving hot path must not pay a TCP handshake per
            # read); streaming requests take the connection over and
            # close it when the stream ends
            while True:
                body = self._read_frame(conn)
                if body is None:
                    return
                req = tm.unpack_body(body)
                if isinstance(req, tm.ReadStateRequest):
                    self._serve_read_state(conn, req.key_prefix)
                    return
                if isinstance(req, tm.SubscribeRequest):
                    self._serve_subscription(conn, req)
                    return
                if isinstance(req, tm.ReadStateHashRequest):
                    self._serve_state_hash(conn, req)
                elif isinstance(req, tm.ReadProofRequest):
                    self._serve_proof(conn, req)
                elif isinstance(req, tm.AnchorRequest):
                    self._serve_anchor(conn)
                elif isinstance(req, tm.BlockRequest):
                    self._serve_block(conn, req)
                else:
                    conn.sendall(tm.pack(
                        tm.ProtocolError(reason="bad request")))
                    return
        except Exception:  # noqa: BLE001 — connection teardown
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _read_frame(conn: socket.socket) -> Optional[bytes]:
        hdr = b""
        while len(hdr) < 4:
            chunk = conn.recv(4 - len(hdr))
            if not chunk:
                return None
            hdr += chunk
        (n,) = struct.unpack("<I", hdr)
        if n > 1 << 22:
            return None
        body = b""
        while len(body) < n:
            chunk = conn.recv(n - len(body))
            if not chunk:
                return None
            body += chunk
        return body

    # ---- ReadState / ReadStateHash ----
    def _state_snapshot(self, key_prefix: bytes
                        ) -> Tuple[int, List[Tuple[bytes, bytes]]]:
        block_id = self.bc.last_block_id
        fam_hits = []
        db = self.bc._db
        fam = cat._fam(self.filter.category, "latest")
        for k, raw in db.range_iter(fam, start=key_prefix):
            if not k.startswith(key_prefix):
                break
            fam_hits.append((k, raw[8:]))
        return block_id, fam_hits

    def _state_at_block(self, key_prefix: bytes, at_block: int
                        ) -> List[Tuple[bytes, bytes]]:
        """Historical state from the versioned_kv history family — lets a
        hash server answer for the DATA server's snapshot height even
        while the cluster keeps committing (reference: block-id'd state
        reads)."""
        db = self.bc._db
        fam = cat._fam(self.filter.category, "hist")
        best: dict = {}
        for k, raw in db.range_iter(fam):
            klen = int.from_bytes(k[:2], "big")
            key = k[2:2 + klen]
            if not key.startswith(key_prefix):
                continue
            block = ~int.from_bytes(k[2 + klen:2 + klen + 8],
                                    "big") & 0xFFFFFFFFFFFFFFFF
            if block > at_block or key in best:
                continue  # hist keys are newest-first per key
            best[key] = None if raw[:1] == b"\x00" else raw[1:]
        return sorted((k, v) for k, v in best.items() if v is not None)

    def _serve_read_state(self, conn: socket.socket,
                          key_prefix: bytes) -> None:
        self.m_reads.inc()
        block_id, kv = self._state_snapshot(key_prefix)
        for pair in kv:
            conn.sendall(tm.pack(tm.Update(block_id=block_id, kv=[pair])))
        conn.sendall(tm.pack(tm.StateDone(
            block_id=block_id, digest=tm.update_hash(block_id, kv))))

    def _serve_state_hash(self, conn: socket.socket,
                          req: tm.ReadStateHashRequest) -> None:
        self.m_reads.inc()
        if req.block_id and req.block_id != self.bc.last_block_id:
            if req.block_id > self.bc.last_block_id:
                conn.sendall(tm.pack(tm.ProtocolError(reason="ahead")))
                return
            kv = self._state_at_block(req.key_prefix, req.block_id)
            conn.sendall(tm.pack(tm.StateDone(
                block_id=req.block_id,
                digest=tm.update_hash(req.block_id, kv))))
            return
        block_id, kv = self._state_snapshot(req.key_prefix)
        conn.sendall(tm.pack(tm.StateDone(
            block_id=block_id, digest=tm.update_hash(block_id, kv))))

    def _serve_proof(self, conn: socket.socket,
                     req: tm.ReadProofRequest) -> None:
        """Versioned merkle proof (reference sparse_merkle historical
        versions): audit path for key@block plus the root anchored in
        that block's category digests. The CLIENT verifies — this server
        is untrusted; the root gains authority from an f+1 cross-server
        match or from the signed checkpoint anchor's hash chain."""
        bid = req.block_id or self.bc.last_block_id
        if bid > self.bc.last_block_id:
            conn.sendall(tm.pack(tm.ProtocolError(reason="ahead")))
            return
        if bid < self.bc.genesis_block_id:
            conn.sendall(tm.pack(tm.ProtocolError(reason="pruned")))
            return
        try:
            proof = self.bc.prove_at(req.category, req.key, bid)
            root = self.bc.merkle_root_at(req.category, bid) or b""
            vh = self.bc.merkle_value_hash_at(req.category, req.key, bid)
        except Exception:  # noqa: BLE001 — malformed request data
            conn.sendall(tm.pack(tm.ProtocolError(reason="bad proof req")))
            return
        # ship the value alongside the proof when the LATEST value still
        # hashes to the proven value_hash (one round trip for
        # read+verify); a key overwritten since `bid` yields proof-only
        value = b""
        if vh:
            hit = self.bc.get_latest(req.category, req.key,
                                     cat_type=cat.BLOCK_MERKLE)
            if hit is not None \
                    and hashlib.sha256(hit[1]).digest() == vh:
                value = hit[1]
        self.m_proofs.inc()
        flight.record(flight.EV_TRS_PROOF, seq=bid)
        conn.sendall(tm.pack(tm.ProofReply(
            block_id=bid, root=root, value_hash=vh or b"",
            bitmap=proof.bitmap, siblings=proof.siblings, value=value)))

    # ---- checkpoint anchor + raw blocks (digest-auth trust chain) ----
    def _serve_anchor(self, conn: socket.socket) -> None:
        anchor = self._anchor_fn() if self._anchor_fn is not None else None
        if anchor is None:
            conn.sendall(tm.pack(tm.ProtocolError(reason="no anchor")))
            return
        ckpt_seq, block_id, certs = anchor
        raw = self.bc.get_raw_block(block_id)
        if raw is None:
            # the anchored block was pruned (or this replica lags its
            # own anchor after a restart): the client falls back to the
            # f+1 root-quorum path until the next checkpoint certifies
            conn.sendall(tm.pack(tm.ProtocolError(reason="pruned")))
            return
        self.m_anchors.inc()
        conn.sendall(tm.pack(tm.AnchorReply(
            ckpt_seq=ckpt_seq, block_id=block_id, block_raw=raw,
            certs=list(certs))))

    def _serve_block(self, conn: socket.socket,
                     req: tm.BlockRequest) -> None:
        raw = (self.bc.get_raw_block(req.block_id)
               if 1 <= req.block_id <= self.bc.last_block_id else None)
        conn.sendall(tm.pack(tm.BlockReply(block_id=req.block_id,
                                           raw=raw or b"")))

    # ---- subscriptions ----
    def _block_kv(self, block_id: int,
                  key_prefix: bytes) -> Optional[List[Tuple[bytes, bytes]]]:
        blk = self.bc.get_block(block_id)
        if blk is None:
            return None
        updates = cat.decode_block_updates(blk.updates_blob)
        kv = self.filter.filter_updates(updates)
        return [(k, v) for k, v in kv if k.startswith(key_prefix)]

    def _serve_subscription(self, conn: socket.socket,
                            req: tm.SubscribeRequest) -> None:
        sub = _Subscriber(start_block=max(req.block_id, 1),
                          maxsize=self._sub_buffer)
        with self._subs_lock:
            self._subs.append(sub)
            self.m_subscribers.set(len(self._subs))
        flight.record(flight.EV_TRS_SUBSCRIBE, seq=sub.next_block)
        try:
            next_block = sub.next_block
            # history first (catch-up), then drain the live buffer;
            # blocks older than genesis are gone (pruned) — resume at it
            next_block = max(next_block, self.bc.genesis_block_id or 1)
            while self._running and not sub.dead:
                if next_block <= self.bc.last_block_id:
                    kv = self._block_kv(next_block, req.key_prefix)
                    if kv is None:
                        break
                    self._emit(conn, req, next_block, kv)
                    next_block += 1
                    sub.next_block = next_block
                    continue
                try:
                    batch = sub.q.get(timeout=0.5)
                except queue.Empty:
                    continue
                for block_id, kv in batch:
                    if block_id < next_block:
                        continue   # already served from history
                    if block_id > next_block:
                        # gap (an earlier run was consumed as history
                        # before we enqueued): the outer loop's history
                        # branch fills it on the next pass
                        break
                    kv = [(k, v) for k, v in kv
                          if k.startswith(req.key_prefix)]
                    self._emit(conn, req, block_id, kv)
                    next_block += 1
                    sub.next_block = next_block
        finally:
            sub.dead = True
            with self._subs_lock:
                if sub in self._subs:
                    self._subs.remove(sub)
                self.m_subscribers.set(len(self._subs))

    def _emit(self, conn: socket.socket, req: tm.SubscribeRequest,
              block_id: int, kv: List[Tuple[bytes, bytes]]) -> None:
        if req.hashes_only:
            conn.sendall(tm.pack(tm.UpdateHash(
                block_id=block_id, digest=tm.update_hash(block_id, kv))))
        else:
            conn.sendall(tm.pack(tm.Update(block_id=block_id, kv=kv)))
